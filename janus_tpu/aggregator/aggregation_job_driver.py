"""Aggregation job stepping (leader) — the north-star hot path.

The analog of ``AggregationJobDriver`` (reference:
aggregator/src/aggregator/aggregation_job_driver.rs:59-1046): steps leased
aggregation jobs through init (leader prepare → PUT init request to helper)
and continue (evaluate stored ping-pong transitions → POST continue
request), merges the helper's responses, and commits everything through the
AggregationJobWriter.  The per-report leader prepare loop the reference
ships to rayon (:449) is ONE batched device launch via the backend seam.

Abandonment: after ``maximum_attempts_before_failure`` lease attempts the
job is abandoned with a best-effort DELETE to the helper (reference
:977-1026); errors are classified retryable vs fatal (:1030-1045).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.retries import HttpRetryPolicy, retry_http_request
from ..datastore import (
    AggregationJob,
    AggregationJobState,
    Datastore,
    Lease,
    ReportAggregation,
    ReportAggregationState,
)
from ..datastore.task import AggregatorTask
from ..messages import (
    AggregationJobContinueReq,
    AggregationJobInitializeReq,
    AggregationJobResp,
    AggregationJobStep,
    Duration,
    PartialBatchSelector,
    PrepareContinue,
    PrepareError,
    PrepareInit,
    PrepareResp,
    PrepareStepResult,
    ReportShare,
    ReportMetadata,
)
from ..vdaf import pingpong as pp
from ..vdaf.backend import make_backend
from ..vdaf.prio3 import Prio3, VdafError
from .aggregation_job_writer import AggregationJobWriter

logger = logging.getLogger("janus_tpu.aggregation_job_driver")


class JobStepError(Exception):
    def __init__(self, detail: str, retryable: bool):
        super().__init__(detail)
        self.retryable = retryable


@dataclass
class DriverConfig:
    batch_aggregation_shard_count: int = 8
    maximum_attempts_before_failure: int = 10
    vdaf_backend: str = "oracle"
    http_retry: HttpRetryPolicy = field(default_factory=HttpRetryPolicy)


class AggregationJobDriver:
    def __init__(
        self,
        datastore: Datastore,
        session_factory,
        config: Optional[DriverConfig] = None,
    ):
        self.datastore = datastore
        self._session_factory = session_factory
        self._session = None
        self.config = config or DriverConfig()
        self._backends: Dict[bytes, object] = {}

    def _get_session(self):
        """One shared connection-pooled session per driver (the analog of the
        reference's shared reqwest client)."""
        if self._session is None or self._session.closed:
            self._session = self._session_factory()
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    # ------------------------------------------------------------------
    async def step_aggregation_job(self, lease: Lease) -> None:
        """Stepper callback for the JobDriver
        (reference: aggregation_job_driver.rs:126 step_aggregation_job)."""
        from ..core.metrics import GLOBAL_METRICS, Timer

        if lease.lease_attempts > self.config.maximum_attempts_before_failure:
            await self.abandon_aggregation_job(lease)
            return
        outcome = "success"
        with Timer() as timer:
            try:
                await self._step(lease)
            except JobStepError as e:
                if e.retryable:
                    outcome = "retried"
                    logger.warning("retryable step failure: %s", e)
                    await self.datastore.run_tx_async(
                        "release_agg_job",
                        lambda tx: tx.release_aggregation_job(lease),
                    )
                else:
                    outcome = "abandoned"
                    logger.error("fatal step failure: %s", e)
                    await self.abandon_aggregation_job(lease)
        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.job_steps.labels(
                job_type="aggregation", outcome=outcome
            ).observe(timer.seconds)
            if outcome != "success":
                GLOBAL_METRICS.step_failures.labels(type=outcome).inc()

    async def _step(self, lease: Lease) -> None:
        acq = lease.leased
        # tx1: load task, job, report aggregations (reference :169-220)
        def load(tx):
            task = tx.get_aggregator_task(acq.task_id)
            job = tx.get_aggregation_job(acq.task_id, acq.aggregation_job_id)
            ras = tx.get_report_aggregations_for_aggregation_job(
                acq.task_id, acq.aggregation_job_id
            )
            return task, job, ras

        task, job, ras = await self.datastore.run_tx_async("step_agg_job_1", load)
        if task is None or job is None:
            raise JobStepError("job or task vanished", retryable=False)
        if job.state != AggregationJobState.IN_PROGRESS:
            await self.datastore.run_tx_async(
                "release_done", lambda tx: tx.release_aggregation_job(lease)
            )
            return
        vdaf = task.vdaf_instance()

        start_ras = [ra for ra in ras if ra.state == ReportAggregationState.START_LEADER]
        waiting_ras = [
            ra for ra in ras if ra.state == ReportAggregationState.WAITING_LEADER
        ]
        if start_ras:
            await self._step_init(lease, task, vdaf, job, ras, start_ras)
        elif waiting_ras:
            await self._step_continue(lease, task, vdaf, job, ras, waiting_ras)
        else:
            # nothing to do; close the job out
            job = job.with_state(AggregationJobState.FINISHED)
            writer = AggregationJobWriter(
                task,
                vdaf,
                batch_aggregation_shard_count=self.config.batch_aggregation_shard_count,
                initial_write=False,
            )
            writer.put(job, [], {})

            def tx_fn(tx):
                writer.write(tx)
                tx.release_aggregation_job(lease)

            await self.datastore.run_tx_async("step_agg_job_2", tx_fn)

    # ------------------------------------------------------------------
    def _backend_for(self, task: AggregatorTask, vdaf):
        key = task.task_id.data
        b = self._backends.get(key)
        if b is None and isinstance(vdaf, Prio3):
            try:
                b = make_backend(vdaf, self.config.vdaf_backend)
            except VdafError:
                b = make_backend(vdaf, "oracle")
            self._backends[key] = b
        return b

    def _leader_prep_init(self, task, vdaf, job, start_ras):
        """Batched leader prepare (device launch for Prio3;
        reference mirror: aggregation_job_driver.rs:397-428 on rayon)."""
        try:
            agg_param = vdaf.decode_agg_param(job.aggregation_parameter)
        except VdafError:
            return {
                ra.report_id.data: PrepareError.INVALID_MESSAGE for ra in start_ras
            }
        outcomes: Dict[bytes, object] = {}  # report_id -> (state, msg) | PrepareError
        rows = []
        for ra in start_ras:
            try:
                public_parts = vdaf.decode_public_share(ra.public_share or b"")
                input_share = vdaf.decode_input_share(0, ra.leader_input_share)
            except (VdafError, Exception):
                outcomes[ra.report_id.data] = PrepareError.INVALID_MESSAGE
                continue
            rows.append((ra, public_parts, input_share))

        backend = self._backend_for(task, vdaf)
        if backend is not None:
            prep_in = [
                (ra.report_id.data, public, share) for ra, public, share in rows
            ]
            prep_out = backend.prep_init_batch(task.vdaf_verify_key, 0, prep_in)
            for (ra, _pub, _sh), outcome in zip(rows, prep_out):
                if isinstance(outcome, VdafError):
                    outcomes[ra.report_id.data] = PrepareError.VDAF_PREP_ERROR
                    continue
                state, share = outcome
                msg = pp.PingPongMessage(
                    pp.PingPongMessage.INITIALIZE,
                    prep_share=vdaf.ping_pong_encode_prep_share(share),
                )
                outcomes[ra.report_id.data] = (pp.PingPongContinued(state, 0), msg)
        else:
            for ra, public, share in rows:
                try:
                    state, msg = pp.leader_initialized(
                        vdaf,
                        task.vdaf_verify_key,
                        agg_param,
                        ra.report_id.data,
                        public,
                        share,
                    )
                    outcomes[ra.report_id.data] = (state, msg)
                except (VdafError, pp.PingPongError):
                    outcomes[ra.report_id.data] = PrepareError.VDAF_PREP_ERROR
        return outcomes

    async def _step_init(self, lease, task, vdaf, job, all_ras, start_ras):
        loop = asyncio.get_running_loop()
        outcomes = await loop.run_in_executor(
            None, lambda: self._leader_prep_init(task, vdaf, job, start_ras)
        )
        prepare_inits = []
        states: Dict[bytes, pp.PingPongContinued] = {}
        failed: Dict[bytes, PrepareError] = {}
        for ra in start_ras:
            outcome = outcomes[ra.report_id.data]
            if isinstance(outcome, PrepareError):
                failed[ra.report_id.data] = outcome
                continue
            state, msg = outcome
            states[ra.report_id.data] = state
            prepare_inits.append(
                PrepareInit(
                    ReportShare(
                        ReportMetadata(ra.report_id, ra.time),
                        ra.public_share or b"",
                        ra.helper_encrypted_input_share,
                    ),
                    msg,
                )
            )

        if task.query_type.kind == "FixedSize":
            pbs = PartialBatchSelector.new_fixed_size(job.partial_batch_identifier)
        else:
            pbs = PartialBatchSelector.new_time_interval()
        req = AggregationJobInitializeReq(
            aggregation_parameter=job.aggregation_parameter,
            partial_batch_selector=pbs,
            prepare_inits=prepare_inits,
        )
        resp = await self._send_to_helper(
            task,
            "PUT",
            f"aggregation_jobs/{job.aggregation_job_id}",
            req.get_encoded(),
            AggregationJobInitializeReq.MEDIA_TYPE,
        )
        await self._process_helper_resp(
            lease, task, vdaf, job, all_ras, states, failed, resp
        )

    async def _step_continue(self, lease, task, vdaf, job, all_ras, waiting_ras):
        """Evaluate stored transitions, send continue, process responses
        (reference: :527-626)."""
        states: Dict[bytes, pp.PingPongContinued] = {}
        failed: Dict[bytes, PrepareError] = {}
        finished_now: Dict[bytes, Sequence[int]] = {}
        conts = []
        for ra in waiting_ras:
            try:
                trans = pp.PingPongTransition.decode(vdaf, ra.leader_prep_transition)
                state, msg = trans.evaluate(vdaf)
            except (VdafError, pp.PingPongError):
                failed[ra.report_id.data] = PrepareError.VDAF_PREP_ERROR
                continue
            conts.append(PrepareContinue(ra.report_id, msg))
            if isinstance(state, pp.PingPongFinished):
                finished_now[ra.report_id.data] = state.out_share
            else:
                states[ra.report_id.data] = state

        # The wire step is the leader's CURRENT step: after init the leader
        # job is at step 1 while the helper is at 0, and the helper requires
        # req.step == helper_step + 1 — i.e. exactly the leader's step.
        wire_step = AggregationJobStep(int(job.step))
        req = AggregationJobContinueReq(wire_step, conts)
        resp = await self._send_to_helper(
            task,
            "POST",
            f"aggregation_jobs/{job.aggregation_job_id}",
            req.get_encoded(),
            AggregationJobContinueReq.MEDIA_TYPE,
        )
        await self._process_helper_resp(
            lease,
            task,
            vdaf,
            job,
            all_ras,
            states,
            failed,
            resp,
            finished_now=finished_now,
            next_step=AggregationJobStep(int(wire_step) + 1),
        )

    # ------------------------------------------------------------------
    async def _process_helper_resp(
        self,
        lease,
        task,
        vdaf,
        job,
        all_ras,
        states: Dict[bytes, pp.PingPongContinued],
        failed: Dict[bytes, PrepareError],
        resp: AggregationJobResp,
        *,
        finished_now: Optional[Dict[bytes, Sequence[int]]] = None,
        next_step: Optional[AggregationJobStep] = None,
    ) -> None:
        """Merge helper PrepareResps into report aggregations
        (reference: :629-793 process_response_from_helper)."""
        finished_now = finished_now or {}
        by_id = {pr.report_id.data: pr for pr in resp.prepare_resps}
        new_ras: List[ReportAggregation] = []
        out_shares: Dict[bytes, Sequence[int]] = {}
        for ra in all_ras:
            rid = ra.report_id.data
            if ra.state in (
                ReportAggregationState.FINISHED,
                ReportAggregationState.FAILED,
            ):
                continue  # already terminal; no update needed
            if rid in failed:
                new_ras.append(ra.failed(failed[rid]))
                continue
            pr = by_id.get(rid)
            if pr is None:
                new_ras.append(ra.failed(PrepareError.REPORT_DROPPED))
                continue
            if pr.result.variant == PrepareStepResult.REJECT:
                new_ras.append(ra.failed(pr.result.error))
                continue
            if rid in finished_now:
                if pr.result.variant != PrepareStepResult.FINISHED:
                    new_ras.append(ra.failed(PrepareError.VDAF_PREP_ERROR))
                    continue
                new_ras.append(ra.with_state(ReportAggregationState.FINISHED))
                out_shares[rid] = finished_now[rid]
                continue
            if pr.result.variant != PrepareStepResult.CONTINUE:
                new_ras.append(ra.failed(PrepareError.VDAF_PREP_ERROR))
                continue
            state = states.get(rid)
            if state is None:
                new_ras.append(ra.failed(PrepareError.VDAF_PREP_ERROR))
                continue
            try:
                value = pp.continued(
                    vdaf, True, state, pr.result.message,
                    vdaf.decode_agg_param(job.aggregation_parameter),
                )
            except (VdafError, pp.PingPongError):
                new_ras.append(ra.failed(PrepareError.VDAF_PREP_ERROR))
                continue
            if value.out_share is not None:
                new_ras.append(ra.with_state(ReportAggregationState.FINISHED))
                out_shares[rid] = value.out_share
            else:
                new_ras.append(
                    ra.with_state(
                        ReportAggregationState.WAITING_LEADER,
                        leader_prep_transition=value.transition.encode(vdaf),
                    )
                )

        any_waiting = any(
            ra.state == ReportAggregationState.WAITING_LEADER for ra in new_ras
        )
        job = job.with_step(
            next_step if next_step is not None else AggregationJobStep(int(job.step) + 1)
        )
        job = job.with_state(
            AggregationJobState.IN_PROGRESS
            if any_waiting
            else AggregationJobState.FINISHED
        )

        writer = AggregationJobWriter(
            task,
            vdaf,
            batch_aggregation_shard_count=self.config.batch_aggregation_shard_count,
            initial_write=False,
            backend=self._backend_for(task, vdaf),
        )
        writer.put(job, new_ras, out_shares)

        def tx_fn(tx):
            writer.write(tx)
            tx.release_aggregation_job(lease)

        await self.datastore.run_tx_async("step_agg_job_2", tx_fn)

    # ------------------------------------------------------------------
    async def abandon_aggregation_job(self, lease: Lease) -> None:
        """reference: :977-1026 (abandon + best-effort helper DELETE)"""
        acq = lease.leased

        def tx_fn(tx):
            task = tx.get_aggregator_task(acq.task_id)
            job = tx.get_aggregation_job(acq.task_id, acq.aggregation_job_id)
            if job is not None and job.state == AggregationJobState.IN_PROGRESS:
                tx.update_aggregation_job(job.with_state(AggregationJobState.ABANDONED))
            tx.release_aggregation_job(lease)
            return task

        task = await self.datastore.run_tx_async("abandon_agg_job", tx_fn)
        if task is not None:
            try:
                await self._send_to_helper(
                    task,
                    "DELETE",
                    f"aggregation_jobs/{acq.aggregation_job_id}",
                    None,
                    None,
                    expect_body=False,
                )
            except Exception:
                logger.warning("best-effort helper DELETE failed", exc_info=True)

    # ------------------------------------------------------------------
    async def _send_to_helper(
        self,
        task: AggregatorTask,
        method: str,
        resource: str,
        body: Optional[bytes],
        media_type: Optional[str],
        expect_body: bool = True,
    ) -> Optional[AggregationJobResp]:
        """HTTPS to the peer aggregator with retry/backoff
        (reference: aggregator.rs:3200 send_request_to_helper)."""
        url = (
            task.peer_aggregator_endpoint.rstrip("/")
            + f"/tasks/{task.task_id}/{resource}"
        )
        headers = {}
        if media_type:
            headers["Content-Type"] = media_type
        if task.aggregator_auth_token is not None:
            name, value = task.aggregator_auth_token.request_authentication()
            headers[name] = value
        try:
            status, resp_body, _ = await retry_http_request(
                self._get_session(),
                method,
                url,
                data=body,
                headers=headers,
                policy=self.config.http_retry,
            )
        except Exception as e:
            raise JobStepError(f"helper request failed: {e}", retryable=True)
        if status >= 400:
            # 4xx = fatal (bad request will not heal); 5xx = retryable
            # (reference: aggregation_job_driver.rs:1030 error classification)
            raise JobStepError(
                f"helper returned {status}: {resp_body[:200]!r}",
                retryable=status >= 500,
            )
        if not expect_body:
            return None
        return AggregationJobResp.get_decoded(resp_body)
