"""Generic lease-based job driver loop.

The analog of ``JobDriver`` (reference:
aggregator/src/binary_utils/job_driver.rs:26-266): periodically acquires
leases on incomplete jobs (with jitter on the discovery interval),
steps them concurrently under a semaphore bound, applies a per-job timeout
derived from the lease expiry minus a clock-skew allowance, and drains
gracefully on stop.  Crash recovery is inherent: an expired lease makes the
job re-acquirable by any replica (SURVEY.md §5 failure detection).
"""

from __future__ import annotations

import asyncio
import logging
import random
import zlib
from typing import Awaitable, Callable, List, Optional

from ..core.time import Clock
from ..datastore.models import Lease
from ..messages import Duration

logger = logging.getLogger("janus_tpu.job_driver")


def step_retry_delay(
    attempts: int,
    initial_s: float,
    max_s: float,
    multiplier: float = 2.0,
    jitter_key: Optional[bytes] = None,
) -> Duration:
    """Exponential lease-backoff for a retryable step failure: attempt 1
    waits ``initial_s``, doubling up to ``max_s``.  Shared by both job
    drivers so every retryable failure redelivers on the same curve
    (reference analog: collection_job_driver.rs RetryStrategy :723-792,
    generalized to aggregation).  Clamped to >= 1s: Duration is integral
    seconds, and truncating a sub-second delay to 0 would mean immediate
    redelivery — the hot loop this backoff exists to prevent.

    ``jitter_key`` (the job id) spreads the base delay over [base, 2x
    base) with a seed stable per (job, attempt): every job released
    during a partition would otherwise sit on the SAME backoff schedule
    and re-acquire in one wave the moment the link heals — a thundering
    herd aimed at a helper that just recovered.  Stable seeding keeps
    redelivery times reproducible for a given chaos seed while distinct
    jobs land at distinct offsets; the jittered delay may exceed
    ``max_s`` by up to 2x, which is the point at the cap (every job AT
    the cap must still spread)."""
    # exponent clamped: peer-unhealthy releases can push attempts into
    # the thousands during a long partition, and float ** overflows past
    # ~2**1024 — 2**64 already exceeds any real max_s
    base = min(initial_s * multiplier ** min(max(0, attempts - 1), 64), max_s)
    if jitter_key:
        rng = random.Random((zlib.crc32(jitter_key) << 8) ^ attempts)
        base = base * (1.0 + rng.random())
    return Duration(max(1, round(base)))


def heal_grace_s(retry_max_delay_s: float) -> float:
    """Heal-grace window for the ceiling guards, shared by both drivers:
    long enough for every job released during the partition to cycle
    back through acquisition at least once — step_retry_delay's max
    jittered backoff is 2x the max delay, and the extra 1x is headroom
    for discovery-poll and worker-queue latency (a boundary job must
    not miss the window by one poll interval and abandon)."""
    return 3.0 * retry_max_delay_s


async def peer_partition_state(datastore, task_id, grace_s: float) -> str:
    """Ceiling-time partition classification shared by BOTH job drivers:
    is the task's peer ``suspect`` (inside its dwell — release, don't
    abandon), ``healed`` (probing, or back healthy within ``grace_s`` —
    the inflated delivery count is partition debris, let the job take
    its delivery: a PROBING peer's delivery IS the half-open probe, and
    without it a fleet whose every job is past-ceiling could never heal),
    or ``healthy`` (the ceiling's normal verdict applies)?  Lookup
    failures report ``healthy`` — fall through to the normal verdict
    rather than wedge the ceiling on a sick datastore.  The common
    no-partition case short-circuits on the in-memory tracker without
    touching the datastore."""
    from ..core import peer_health
    from ..core.peer_health import PEER_SUSPECT, PEER_PROBING

    tracker = peer_health.tracker()
    if not tracker.partition_signal(grace_s):
        return "healthy"
    try:
        task = await datastore.run_tx_async(
            "ceiling_peer_check", lambda tx: tx.get_aggregator_task(task_id)
        )
    except Exception:
        # the lookup only maps task_id -> peer URL, and partition_signal
        # already confirmed SOME peer is partitioned: fail toward the
        # cheap, reversible verdict (release) — failing "healthy" here
        # would abandon exactly the jobs this guard protects whenever
        # the datastore is contended by the same redelivery churn
        return "suspect"
    if task is None:
        return "healthy"
    url = task.peer_aggregator_endpoint
    state = tracker.state(url)
    if state == PEER_SUSPECT:
        return "suspect"
    if state == PEER_PROBING or tracker.recently_healed(url, grace_s):
        return "healed"
    return "healthy"


async def partition_excused(datastore, task_id, retry_max_delay_s: float) -> bool:
    """Budget-exhaustion excuse shared by both drivers: is the task's
    peer partitioned (suspect/probing) or healed within the grace?  A
    job whose lease_attempts were inflated by clean partition releases
    must not be abandoned by the max_step_attempts comparison on its
    first post-heal hiccup — the count is partition debris, not failure
    history.  Cheap in the common case (peer_partition_state
    short-circuits on the in-memory tracker)."""
    return (
        await peer_partition_state(
            datastore, task_id, heal_grace_s(retry_max_delay_s)
        )
        != "healthy"
    )


def suspect_task_ids(tx, job_type: str = "job") -> Optional[List[bytes]]:
    """Task ids whose peer is currently SUSPECT — the peer-health-aware
    acquisition filter (runs INSIDE the acquirer's transaction, sharing
    it).  During a partition, a suspect peer's jobs used to be acquired
    and immediately released by the step's peer gate, burning one lease
    tx round trip per job per discovery poll; filtering them at the
    acquire query spares that churn while the dwell lasts.  PROBING peers
    are deliberately NOT filtered: a probing peer's job delivery IS the
    half-open probe that can heal the partition.  The no-partition common
    case pays one in-memory check and touches nothing.

    Fleet extension (ISSUE 16 satellite): suspects advertised by OTHER
    fleet members' heartbeat rows are honored beside the in-memory
    tracker, so a replica that never talked to a partitioned peer also
    skips its tasks.  Empty set when fleet mode is off."""
    from ..core import peer_health
    from ..core.fleet import fleet_shared_suspects
    from ..core.peer_health import PEER_SUSPECT, origin_of

    tracker = peer_health.tracker()
    shared = fleet_shared_suspects(tx)
    if not shared and not tracker.partition_signal(0.0):
        return None
    ids = [
        task_id
        for task_id, url in tx.get_task_peer_index()
        # strictly SUSPECT (tracker.is_suspect would also match probing)
        if url
        and (tracker.state(url) == PEER_SUSPECT or origin_of(url) in shared)
    ]
    if not ids:
        return None
    from ..core.metrics import GLOBAL_METRICS

    if GLOBAL_METRICS.registry is not None:
        GLOBAL_METRICS.job_acquisition_suspect_filtered.labels(
            job_type=job_type
        ).inc()
    return ids


def acquisition_exclusions(tx, job_type: str = "job") -> Optional[List[bytes]]:
    """The full acquisition filter both driver binaries thread into
    ``acquire_incomplete_*_jobs(exclude_task_ids=...)``: suspect-peer
    tasks (above) unioned with tasks the fleet router routes to another
    replica.  Fleet off -> reduces to suspect_task_ids exactly."""
    from ..core.fleet import fleet_router

    ids = suspect_task_ids(tx, job_type) or []
    router = fleet_router()
    if router is not None:
        seen = set(ids)
        for task_id in router.not_owned_task_ids(tx) or []:
            if task_id not in seen:
                seen.add(task_id)
                ids.append(task_id)
    return ids or None


def helper_request_deadline(lease, datastore):
    """Monotonic deadline for one peer exchange, shared by BOTH job
    drivers: 80% of the remaining lease (floor 1s), so a blackholed peer
    ALWAYS hands the step back in time to RELEASE the lease in-band —
    never leaving it to expire into the reaper (the partition soak
    asserts ``janus_job_leases_expired_total`` stays zero).  None when
    there is no lease/datastore context (unit tests, best-effort
    cleanup calls)."""
    if lease is None or datastore is None:
        return None
    import time as _time

    remaining = lease.lease_expiry.seconds - datastore.clock.now().seconds
    return _time.monotonic() + max(1.0, 0.8 * remaining)


class JobDriver:
    def __init__(
        self,
        clock: Clock,
        acquirer: Callable[[Duration, int], Awaitable[List[Lease]]],
        stepper: Callable[[Lease], Awaitable[None]],
        *,
        job_discovery_interval: float = 1.0,
        max_concurrent_job_workers: int = 10,
        worker_lease_duration: Duration = Duration(600),
        worker_lease_clock_skew_allowance: Duration = Duration(60),
        reaper: Optional[Callable[[], Awaitable[int]]] = None,
        lease_reap_interval: float = 10.0,
        job_type: str = "job",
    ):
        self.clock = clock
        self.acquirer = acquirer
        self.stepper = stepper
        self.job_discovery_interval = job_discovery_interval
        self.max_concurrent_job_workers = max_concurrent_job_workers
        self.worker_lease_duration = worker_lease_duration
        self.worker_lease_clock_skew_allowance = worker_lease_clock_skew_allowance
        #: Expired-lease reaper (crash recovery): an async callable that
        #: clears the lease tokens of jobs whose lease expired WITHOUT
        #: release (their holder died or wedged) and returns the count —
        #: each one is counted into janus_job_leases_expired_total.  The
        #: jobs were already re-acquirable (acquisition scans on expiry);
        #: reaping makes the death visible and the redelivery prompt.
        self.reaper = reaper
        self.lease_reap_interval = lease_reap_interval
        self.job_type = job_type
        self._last_reap = 0.0
        self._inflight: set = set()

    async def _maybe_reap(self) -> None:
        import time as _time

        if self.reaper is None:
            return
        now = _time.monotonic()
        if now - self._last_reap < self.lease_reap_interval:
            return
        self._last_reap = now
        try:
            count = await self.reaper()
        except Exception:
            logger.exception("lease reaper pass failed")
            return
        if not count:
            return
        logger.warning(
            "reaped %d expired %s lease(s) (holder died or wedged); "
            "redelivering",
            count,
            self.job_type,
        )
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.job_leases_expired.labels(job_type=self.job_type).inc(
                count
            )

    async def run(self, stop: asyncio.Event) -> None:
        """Run until ``stop`` is set, then drain in-flight steppers
        (reference: job_driver.rs:100-149)."""
        from ..datastore.datastore import DatastoreUnavailable

        sem = asyncio.Semaphore(self.max_concurrent_job_workers)
        acquire_failures = 0
        while not stop.is_set():
            await self._maybe_reap()
            free = self.max_concurrent_job_workers - len(self._inflight)
            leases: List[Lease] = []
            if free > 0:
                try:
                    leases = await self.acquirer(self.worker_lease_duration, free)
                    acquire_failures = 0
                except DatastoreUnavailable as e:
                    # Brownout idle-backoff (ISSUE 17): consecutive
                    # acquisition failures stretch the discovery sleep
                    # multiplicatively (capped) instead of polling a
                    # struggling database on the normal cadence.  One
                    # line per miss — the health tracker and metrics
                    # carry the detail.
                    acquire_failures += 1
                    logger.warning(
                        "job acquisition failed, datastore unavailable "
                        "(%d consecutive; backing off): %s",
                        acquire_failures,
                        e,
                    )
                except Exception:
                    acquire_failures += 1
                    logger.exception("job acquisition failed")
            for lease in leases:
                task = asyncio.ensure_future(self._step(sem, lease))
                self._inflight.add(task)
                task.add_done_callback(self._inflight.discard)
            # jittered discovery sleep (reference: job_driver.rs discovery
            # interval w/ jitter); cut short if stop is requested.
            delay = self.job_discovery_interval * (0.5 + random.random())
            if acquire_failures:
                delay = min(
                    delay * (2 ** min(acquire_failures, 5)),
                    max(self.job_discovery_interval, 60.0),
                )
            try:
                await asyncio.wait_for(stop.wait(), timeout=delay)
            except asyncio.TimeoutError:
                pass
        if self._inflight:
            await asyncio.gather(*list(self._inflight), return_exceptions=True)

    async def _step(self, sem: asyncio.Semaphore, lease: Lease) -> None:
        from ..core.metrics import GLOBAL_METRICS
        from ..core.trace import trace_scope, trace_span

        async with sem:
            # per-job timeout: remaining lease minus skew allowance
            # (reference: job_driver.rs:222-247)
            timeout = max(
                1.0,
                lease.lease_expiry.seconds
                - self.clock.now().seconds
                - self.worker_lease_clock_skew_allowance.seconds,
            )
            leased = lease.leased
            if GLOBAL_METRICS.registry is not None:
                GLOBAL_METRICS.job_age_at_acquire.labels(
                    job_type=self.job_type
                ).observe(getattr(leased, "age_seconds", 0.0))
            job_id = getattr(leased, "aggregation_job_id", None) or getattr(
                leased, "collection_job_id", None
            )
            # Per-outcome accounting: on wall time alone, a fleet spinning
            # on timeouts/retries is indistinguishable from a healthy one.
            outcome = "ok"
            # Bind the job's persisted trace context for the whole step:
            # every log line, chrome-trace span, and outbound traceparent
            # from this replica joins the job's cross-process timeline.
            with trace_scope(
                trace_id=getattr(leased, "trace_id", None),
                task_id=leased.task_id,
                job_id=job_id,
            ), trace_span(
                "job_step",
                job_type=type(leased).__name__,
                attempts=lease.lease_attempts,
            ):
                try:
                    await asyncio.wait_for(self.stepper(lease), timeout=timeout)
                except asyncio.TimeoutError:
                    outcome = "timeout"
                    logger.warning("job step timed out; lease will expire naturally")
                except Exception as e:
                    # steppers normally classify internally; anything that
                    # reaches here is either an escaped JobStepError (duck-
                    # typed on .retryable) or an unclassified failure
                    outcome = (
                        "retryable" if getattr(e, "retryable", False) else "fatal"
                    )
                    logger.exception("job step failed")
            if GLOBAL_METRICS.registry is not None:
                GLOBAL_METRICS.job_steps_total.labels(
                    job_type=type(lease.leased).__name__, outcome=outcome
                ).inc()
