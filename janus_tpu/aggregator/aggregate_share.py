"""Aggregate-share computation from sharded batch aggregations.

The analog of ``compute_aggregate_share`` (reference:
aggregator/src/aggregator/aggregate_share.rs:21-118): merge every shard
accumulator covering the batch, cross-checking report count and checksum.
This host-side merge is the small tail of the sharded accumulation whose bulk
runs on device (`BatchedPrio3.aggregate` / psum over the mesh).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..core.report_id import checksum_combined
from ..core.time import interval_merge
from ..datastore import BatchAggregation, Transaction
from ..datastore.query_type import strategy_for
from ..datastore.task import AggregatorTask
from ..messages import Interval, ReportIdChecksum


def compute_aggregate_share(
    task: AggregatorTask,
    vdaf,
    tx: Transaction,
    collection_identifier: bytes,
    aggregation_parameter: bytes,
) -> Tuple[Optional[List[int]], int, ReportIdChecksum, Interval]:
    """Merge all batch-aggregation shards covered by the collection
    identifier.  Returns (aggregate_share_vec | None, report_count,
    checksum, client_timestamp_interval)."""
    strategy = strategy_for(task)
    field = vdaf.field_for_agg_param(vdaf.decode_agg_param(aggregation_parameter))
    share: Optional[List[int]] = None
    count = 0
    checksum = ReportIdChecksum.zero()
    interval = Interval.EMPTY
    for ident in strategy.batch_identifiers_for_collection_identifier(
        task, collection_identifier
    ):
        for ba in tx.get_batch_aggregations_for_batch(
            task.task_id, ident, aggregation_parameter
        ):
            if ba.aggregate_share is not None:
                vec = field.decode_vec(ba.aggregate_share)
                share = vec if share is None else field.vec_add(share, vec)
            count += ba.report_count
            checksum = checksum_combined(checksum, ba.checksum)
            interval = interval_merge(interval, ba.client_timestamp_interval)
    return share, count, checksum, interval
