"""Aggregator service: role logic, write combiners, job machinery.

The analog of the reference's ``aggregator`` crate (reference:
aggregator/src/aggregator.rs and friends).
"""

from .aggregate_share import compute_aggregate_share
from .aggregation_job_creator import AggregationJobCreator, CreatorConfig
from .aggregation_job_driver import AggregationJobDriver, DriverConfig
from .aggregation_job_writer import AggregationJobWriter, merge_batch_aggregations
from .aggregator import Aggregator, Config, TaskAggregator
from .collection_job_driver import (
    CollectionDriverConfig,
    CollectionJobDriver,
    NoDifferentialPrivacy,
)
from .error import AggregatorError, ReportRejection
from .garbage_collector import GarbageCollector, GcConfig
from .http_handlers import aggregator_app
from .job_driver import JobDriver
from .report_writer import ReportWriteBatcher

__all__ = [n for n in dir() if not n.startswith("_")]
