"""Aggregation job creation (leader).

The analog of ``AggregationJobCreator`` + ``BatchCreator`` (reference:
aggregator/src/aggregator/aggregation_job_creator.rs:67-981,
batch_creator.rs:32-517): periodically claims unaggregated reports, groups
them into aggregation jobs of [min, max] size — per batch interval for
TimeInterval tasks, via outstanding-batch filling for FixedSize tasks —
moves each report's payload into its StartLeader report aggregation, and
scrubs the client report.  Metadata-only: no VDAF compute happens here.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..core.time import time_to_batch_interval_start
from ..core.trace import emit_span, new_trace_id
from ..datastore import (
    AggregationJob,
    AggregationJobState,
    Datastore,
    ReportAggregation,
    ReportAggregationState,
    Transaction,
)
from ..datastore.task import AggregatorTask
from ..messages import (
    AggregationJobId,
    AggregationJobStep,
    BatchId,
    Duration,
    Interval,
    ReportMetadata,
    Role,
    Time,
)
from .aggregation_job_writer import AggregationJobWriter

logger = logging.getLogger("janus_tpu.aggregation_job_creator")


@dataclass
class CreatorConfig:
    """reference: aggregation_job_creator.rs config fields"""

    min_aggregation_job_size: int = 10
    max_aggregation_job_size: int = 256
    reports_per_round: int = 5000
    batch_aggregation_shard_count: int = 8
    #: Write-behind ingest (ISSUE 18): every run_once pre-pass
    #: materializes report-journal rows at least this old into
    #: client_reports before claiming — the crash-replay + migration
    #: handoff for journaled replicas (a cohort staged on a dead replica
    #: becomes ordinary claimable reports here).  The grace keeps the
    #: creator from stealing seconds-old rows the upload replica's own
    #: staged consumer is about to pack zero-copy; stealing is safe
    #: (the row delete linearizes it), just wasteful.
    journal_replay_min_age_s: float = 5.0


class AggregationJobCreator:
    def __init__(self, datastore: Datastore, config: Optional[CreatorConfig] = None):
        self.datastore = datastore
        self.config = config or CreatorConfig()

    async def run_once(self) -> int:
        """One creation pass over every leader task; returns jobs created."""
        # Report-journal replay pre-pass (ISSUE 18): ACKed-but-
        # unmaterialized reports from journaled-ingest replicas become
        # claimable client_reports rows.  One indexed probe when the
        # journal is empty; failure-tolerant — a wedged replay must not
        # stop classic creation.
        try:
            _consumed, materialized = await self.datastore.run_tx_async(
                "report_journal_replay",
                lambda tx: tx.materialize_report_journal_rows(
                    self.config.reports_per_round,
                    min_age_s=self.config.journal_replay_min_age_s,
                ),
            )
            if materialized:
                from ..core.metrics import GLOBAL_METRICS

                if GLOBAL_METRICS.registry is not None:
                    GLOBAL_METRICS.ingest_journal_replayed.inc(materialized)
                logger.info("replayed %d report-journal rows", materialized)
        except Exception:
            logger.exception("report-journal replay pre-pass failed")
        tasks = await self.datastore.run_tx_async(
            "creator_tasks", lambda tx: tx.get_aggregator_tasks()
        )
        created = 0
        for task in tasks:
            if task.role != Role.LEADER:
                continue
            try:
                count, job_spans = await self.datastore.run_tx_async(
                    "create_aggregation_jobs",
                    lambda tx, task=task: self.create_jobs_for_task(tx, task),
                )
                created += count
                # Trace LINK point (ISSUE 9), emitted only AFTER the
                # transaction commits: the tx function re-runs on retryable
                # conflicts, and a span written mid-attempt would link
                # upload traces to phantom jobs that never committed.
                for span in job_spans:
                    emit_span("job_create", "job", **span)
            except Exception:
                logger.exception("job creation failed for task %s", task.task_id)
        return created

    # -- per-task creation (one transaction) ----------------------------
    def create_jobs_for_task(
        self, tx: Transaction, task: AggregatorTask
    ) -> Tuple[int, List[dict]]:
        vdaf = task.vdaf_instance()
        if getattr(vdaf, "REQUIRES_AGG_PARAM", False):
            # VDAFs with a real aggregation parameter (Poplar1) get their
            # jobs from collection requests, not from this periodic creator
            # (the reference gates this path behind test-util:
            # aggregation_job_creator.rs:741).
            logger.debug("skipping agg-param task %s", task.task_id)
            return 0, []
        metas = tx.get_unaggregated_client_reports_for_task(
            task.task_id, self.config.reports_per_round
        )
        if not metas:
            return 0, []
        if task.query_type.kind == "TimeInterval":
            jobs, leftover = self._group_time_interval(task, metas)
        else:
            jobs, leftover = self._group_fixed_size(tx, task, metas)

        # leftover reports go back to the unaggregated pool
        # (reference: aggregation_job_creator.rs:607-717)
        if leftover:
            tx.mark_reports_unaggregated(task.task_id, [m.report_id for m in leftover])

        writer = AggregationJobWriter(
            task,
            vdaf,
            batch_aggregation_shard_count=self.config.batch_aggregation_shard_count,
            initial_write=True,
        )
        count = 0
        job_spans: List[dict] = []
        for batch_id, group in jobs:
            t_job = time.monotonic()
            job_id = AggregationJobId.random()
            start = min(m.time.seconds for m in group)
            end = max(m.time.seconds for m in group) + 1
            job = AggregationJob(
                task_id=task.task_id,
                aggregation_job_id=job_id,
                aggregation_parameter=b"",
                partial_batch_identifier=batch_id,
                client_timestamp_interval=Interval(Time(start), Duration(end - start)),
                state=AggregationJobState.IN_PROGRESS,
                step=AggregationJobStep(0),
                # Trace mint point (ISSUE 5): the job's whole cross-process
                # pipeline — every driver step on any replica, the helper's
                # handling, log lines and chrome-trace spans — joins on
                # this persisted id.
                trace_id=new_trace_id(),
            )
            ras = []
            upload_traces = set()
            for ord_, meta in enumerate(group):
                # move payload from client_reports into the StartLeader row,
                # then scrub (reference: :718-731)
                report = tx.get_client_report(task.task_id, meta.report_id)
                if report is None:
                    continue
                if report.trace_id:
                    upload_traces.add(report.trace_id)
                ras.append(
                    ReportAggregation(
                        task_id=task.task_id,
                        aggregation_job_id=job_id,
                        report_id=meta.report_id,
                        time=meta.time,
                        ord=ord_,
                        state=ReportAggregationState.START_LEADER,
                        public_share=report.public_share,
                        leader_extensions=report.leader_extensions,
                        leader_input_share=report.leader_input_share,
                        helper_encrypted_input_share=report.helper_encrypted_input_share,
                    )
                )
                tx.scrub_client_report(task.task_id, meta.report_id)
            if not ras:
                continue
            writer.put(job, ras)
            # The job's creation span carries the upload trace ids of the
            # reports it packs, stitching client ingress (upload-minted
            # traces) onto the job's cross-process timeline — one view
            # from upload through prepare to collection.  Collected here,
            # EMITTED by run_once after the transaction commits: spans are
            # not transactional, so a mid-attempt emit would survive a
            # retried/rolled-back attempt as a phantom job.
            job_spans.append(
                dict(
                    start_s=t_job,
                    dur_s=time.monotonic() - t_job,
                    trace_id=job.trace_id,
                    task_id=str(task.task_id),
                    job_id=str(job_id),
                    reports=len(ras),
                    links=sorted(upload_traces),
                )
            )
            count += 1
        writer.write(tx)
        return count, job_spans

    def _group_time_interval(
        self, task: AggregatorTask, metas: List[ReportMetadata]
    ) -> Tuple[List[Tuple[Optional[BatchId], List[ReportMetadata]]], List[ReportMetadata]]:
        """Group by batch interval, then chunk into [min, max]-sized jobs
        (reference: aggregation_job_creator.rs:563-741)."""
        by_interval: Dict[int, List[ReportMetadata]] = {}
        for m in metas:
            start = time_to_batch_interval_start(m.time, task.time_precision).seconds
            by_interval.setdefault(start, []).append(m)
        jobs: List[Tuple[Optional[BatchId], List[ReportMetadata]]] = []
        leftover: List[ReportMetadata] = []
        for group in by_interval.values():
            for i in range(0, len(group), self.config.max_aggregation_job_size):
                chunk = group[i : i + self.config.max_aggregation_job_size]
                if len(chunk) >= self.config.min_aggregation_job_size:
                    jobs.append((None, chunk))
                else:
                    leftover.extend(chunk)
        return jobs, leftover

    # -- staged-cohort consumption (ISSUE 18: the zero-copy path) --------
    async def run_staged_once(self, plane) -> int:
        """One consumption pass over the ingest plane's staged cohorts
        (core/ingest.py IngestPlane.take_staged): pack journaled reports
        into aggregation jobs from their IN-MEMORY payloads — no
        client_reports read-back.  Returns jobs created.  Reports the
        pass cannot consume (race lost, cohort below min size) simply
        stay journaled and fall to the materializer."""
        created = 0
        for task_id, _shape, reports in plane.take_staged():
            try:
                count, packed, job_spans = await self.datastore.run_tx_async(
                    "staged_aggregation_jobs",
                    lambda tx, task_id=task_id, reports=reports: (
                        self._staged_jobs_tx(tx, task_id, reports)
                    ),
                )
                created += count
                from ..core.metrics import GLOBAL_METRICS

                if packed and GLOBAL_METRICS.registry is not None:
                    GLOBAL_METRICS.ingest_staged_total.labels(path="direct").inc(
                        packed
                    )
                # emitted only AFTER the commit, exactly like run_once
                for span in job_spans:
                    emit_span("job_create", "job", **span)
            except Exception:
                logger.exception("staged job creation failed for task %s", task_id)
        return created

    def _staged_jobs_tx(self, tx: Transaction, task_id, reports):
        task = tx.get_aggregator_task(task_id)
        if task is None:
            return 0, 0, []
        return self.create_jobs_from_staged(tx, task, reports)

    def create_jobs_from_staged(
        self, tx: Transaction, task: AggregatorTask, reports
    ) -> Tuple[int, int, List[dict]]:
        """Pack a staged cohort (LeaderStoredReports with live payloads)
        into aggregation jobs inside ``tx``; returns (jobs, reports
        packed, job spans).  TimeInterval tasks only — the ingest plane
        stages nothing else.

        Exactly-once per report is two writes in THIS transaction, in
        order: consume the journal row (``delete_report_journal_row`` —
        losing the delete means the materializer or a replaying replica
        owns the report, so we must write NOTHING for it), then insert
        the born-scrubbed client_reports tombstone
        (``put_scrubbed_client_report`` — losing that insert means a
        synchronous-path duplicate already materialized a row whose
        owner will pack it).  Only a report that wins both is packed."""
        vdaf = task.vdaf_instance()
        by_report = {r.report_id.data: r for r in reports}
        metas = [ReportMetadata(r.report_id, r.time) for r in reports]
        # leftovers (below min job size) are NOT consumed: their journal
        # rows are still outstanding, so the materializer/replay routes
        # them through the classic path instead of stranding them
        jobs, _leftover = self._group_time_interval(task, metas)
        writer = AggregationJobWriter(
            task,
            vdaf,
            batch_aggregation_shard_count=self.config.batch_aggregation_shard_count,
            initial_write=True,
        )
        count = 0
        packed = 0
        job_spans: List[dict] = []
        for batch_id, group in jobs:
            t_job = time.monotonic()
            job_id = AggregationJobId.random()
            ras = []
            upload_traces = set()
            for meta in group:
                report = by_report[meta.report_id.data]
                if not tx.delete_report_journal_row(task.task_id, meta.report_id):
                    continue  # consumed elsewhere: not ours to pack
                if not tx.put_scrubbed_client_report(
                    task.task_id, meta.report_id, meta.time, report.trace_id
                ):
                    continue  # duplicate already materialized: its owner packs it
                if report.trace_id:
                    upload_traces.add(report.trace_id)
                ras.append(
                    ReportAggregation(
                        task_id=task.task_id,
                        aggregation_job_id=job_id,
                        report_id=meta.report_id,
                        time=meta.time,
                        ord=len(ras),
                        state=ReportAggregationState.START_LEADER,
                        public_share=report.public_share,
                        leader_extensions=report.leader_extensions,
                        leader_input_share=report.leader_input_share,
                        helper_encrypted_input_share=report.helper_encrypted_input_share,
                    )
                )
            if not ras:
                continue
            start = min(ra.time.seconds for ra in ras)
            end = max(ra.time.seconds for ra in ras) + 1
            job = AggregationJob(
                task_id=task.task_id,
                aggregation_job_id=job_id,
                aggregation_parameter=b"",
                partial_batch_identifier=batch_id,
                client_timestamp_interval=Interval(Time(start), Duration(end - start)),
                state=AggregationJobState.IN_PROGRESS,
                step=AggregationJobStep(0),
                trace_id=new_trace_id(),
            )
            writer.put(job, ras)
            job_spans.append(
                dict(
                    start_s=t_job,
                    dur_s=time.monotonic() - t_job,
                    trace_id=job.trace_id,
                    task_id=str(task.task_id),
                    job_id=str(job_id),
                    reports=len(ras),
                    links=sorted(upload_traces),
                )
            )
            count += 1
            packed += len(ras)
        if count:
            writer.write(tx)
        return count, packed, job_spans

    def _group_fixed_size(
        self, tx: Transaction, task: AggregatorTask, metas: List[ReportMetadata]
    ) -> Tuple[List[Tuple[Optional[BatchId], List[ReportMetadata]]], List[ReportMetadata]]:
        """Incremental batch filling via the headroom-priority BatchCreator
        (reference: batch_creator.rs:32-517 — see batch_creator.py)."""
        from .batch_creator import BatchCreator

        creator = BatchCreator(
            tx,
            task,
            self.config.min_aggregation_job_size,
            self.config.max_aggregation_job_size,
        )
        for m in metas:
            creator.add_report(m)
        return creator.finish()
