"""Batched upload writer + the batched HPKE-open stage.

The analog of ``ReportWriteBatcher`` (reference:
aggregator/src/aggregator/report_writer.rs:39-246): uploaded reports from all
tasks are funneled into one background batcher that commits up to
``max_batch_size`` of them in a single datastore transaction (or after
``max_batch_write_delay`` elapses), fanning per-report results back to the
waiting upload handlers.  In-batch duplicates by (task, report id) are
resolved to a single write.  Rejected uploads increment the task's sharded
upload counters (reference: report_writer.rs:324 TaskUploadCounters).

ISSUE 14 adds the front door's OTHER batcher: :class:`UploadOpenBatcher`
applies the same size/delay pattern to the expensive half of upload
validation — the HPKE open.  Concurrent uploads' opens queue here, flush
as ONE ``core/hpke_batch.open_batch`` call on a worker thread (per-report
KEM off the event loop, all AES-GCM bodies as one vectorized pass), and
its bounded queue is the admission-control point: past the depth or
delay budget, :meth:`UploadOpenBatcher.admit` sheds with the
DAP-retryable 503 + Retry-After instead of letting the event loop
drown (counted in ``janus_upload_shed_total``, visible in /statusz).
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
import time
from typing import Dict, List, Optional, Tuple

from ..datastore import Datastore, LeaderStoredReport, TaskUploadCounter, TxConflict
from ..messages import TaskId
from .error import ReportRejection, UploadShed


class ReportWriteBatcher:
    def __init__(
        self,
        datastore: Datastore,
        max_batch_size: int = 100,
        max_batch_write_delay: float = 0.25,
        counter_shard_count: int = 8,
    ):
        self.datastore = datastore
        self.max_batch_size = max_batch_size
        self.max_batch_write_delay = max_batch_write_delay
        self.counter_shard_count = counter_shard_count
        #: (report, waiter, enqueue-monotonic) — the timestamp feeds
        #: janus_report_upload_to_commit_seconds and the upload_commit span
        self._queue: List[Tuple[object, asyncio.Future, float]] = []
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        #: flush generation (ISSUE 14 satellite): a call_later-scheduled
        #: _flush can interleave with a size-triggered _flush_locked — by
        #: the time the timer task wins the lock, its cohort was already
        #: flushed and a NEW cohort's timer may be armed.  The stale task
        #: must neither cancel that live timer nor flush the new cohort
        #: early, so each armed timer carries the generation it was armed
        #: for and a fired flush whose generation has moved on is a no-op.
        self._flush_gen = 0
        self._lock = asyncio.Lock()

    # ------------------------------------------------------------------
    async def write_report(self, report: LeaderStoredReport) -> None:
        """Enqueue a validated report; resolves when its batch commits.
        Raises ReportRejection if the store rejected it.

        Upload trace (ISSUE 9): a report arriving without a trace id
        adopts the caller's bound trace context or mints a fresh one, so
        EVERY persisted report carries a 32-hex upload trace — including
        writes from paths that bypass handle_upload (load generators,
        soaks seeding through the real writer)."""
        if report.trace_id is None:
            from ..core.trace import current_trace, new_trace_id

            report = dataclasses.replace(
                report,
                trace_id=current_trace().get("trace_id") or new_trace_id(),
            )
        fut = asyncio.get_running_loop().create_future()
        async with self._lock:
            self._queue.append((report, fut, time.monotonic()))
            if len(self._queue) >= self.max_batch_size:
                await self._flush_locked()
            elif self._flush_handle is None:
                loop = asyncio.get_running_loop()
                gen = self._flush_gen
                self._flush_handle = loop.call_later(
                    self.max_batch_write_delay,
                    lambda: asyncio.ensure_future(self._flush(gen)),
                )
        await fut

    async def write_rejection(self, task_id: TaskId, rejection: ReportRejection) -> None:
        """Record a rejected upload in the task's sharded counters."""
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.upload_outcomes.labels(decision=rejection.category).inc()
        shard = random.randrange(self.counter_shard_count)
        counter = TaskUploadCounter(task_id, **{rejection.category: 1})

        def tx_fn(tx):
            tx.increment_task_upload_counter(task_id, shard, counter)

        await self.datastore.run_tx_async("upload_rejection", tx_fn)

    async def _flush(self, gen: Optional[int] = None) -> None:
        async with self._lock:
            if gen is not None and gen != self._flush_gen:
                # stale timer: its cohort was already size-flushed while
                # this task waited on the lock.  Returning (instead of
                # flushing) keeps it from cancelling the NEW cohort's
                # timer and draining that cohort before its delay.
                return
            await self._flush_locked()

    async def _flush_locked(self) -> None:
        self._flush_gen += 1
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        batch, self._queue = self._queue, []
        if not batch:
            return
        # In-batch dedup by (task, report id): first wins, dups succeed as
        # idempotent uploads (reference: report_writer.rs:159-237).
        seen: Dict[bytes, int] = {}
        unique: List[Tuple[object, List[asyncio.Future], float]] = []
        for report, fut, enqueued in batch:
            key = report.task_id.data + report.report_id.data
            if key in seen:
                unique[seen[key]][1].append(fut)
            else:
                seen[key] = len(unique)
                unique.append((report, [fut], enqueued))

        def tx_fn(tx):
            outcomes = []
            shard = random.randrange(self.counter_shard_count)
            for report, _futs, _enq in unique:
                try:
                    tx.put_client_report(report)
                    tx.increment_task_upload_counter(
                        report.task_id,
                        shard,
                        TaskUploadCounter(report.task_id, report_success=1),
                    )
                    outcomes.append(None)
                except TxConflict:
                    # duplicate upload: idempotent success
                    outcomes.append(None)
            return outcomes

        from ..core import faults
        from ..core.metrics import GLOBAL_METRICS

        try:
            # Failure-domain boundary: an injected flush fault impersonates
            # a batch-commit failure — fanned to every waiting upload
            # handler exactly like a real one (clients retry the upload).
            await faults.fire_async("report_writer.flush")
            outcomes = await self.datastore.run_tx_async("upload_batch", tx_fn)
        except Exception as e:  # commit failed: fan the error to every waiter
            for _report, futs, _enq in unique:
                for fut in futs:
                    if not fut.done():
                        fut.set_exception(e)
            return
        from ..core.trace import emit_span

        have_metrics = GLOBAL_METRICS.registry is not None
        now_s = self.datastore.now().seconds if have_metrics else 0
        committed = time.monotonic()
        accepted = 0
        for (report, futs, enqueued), outcome in zip(unique, outcomes):
            if outcome is None:
                if have_metrics:
                    accepted += 1
                    # Freshness SLO input: report age at commit (client
                    # timestamp -> writer commit) per accepted report.
                    GLOBAL_METRICS.report_commit_age.observe(
                        max(0.0, float(now_s - report.time.seconds))
                    )
                    # Front-door SLO input (ISSUE 9): how long the batcher
                    # held the report before it was durable.
                    GLOBAL_METRICS.upload_to_commit.observe(
                        max(0.0, committed - enqueued)
                    )
                # Per-report CHILD span stamped with the UPLOAD's trace id
                # (the flush_share pattern): the client-ingress hop of the
                # merged timeline, enqueue -> batch commit.
                emit_span(
                    "upload_commit",
                    "upload",
                    enqueued,
                    committed - enqueued,
                    trace_id=report.trace_id,
                    task_id=str(report.task_id),
                )
            for fut in futs:
                if fut.done():
                    continue
                if outcome is None:
                    fut.set_result(None)
                else:
                    fut.set_exception(outcome)
        if have_metrics:
            GLOBAL_METRICS.upload_outcomes.labels(decision="accepted").inc(accepted)


# ---------------------------------------------------------------------------
# the batched HPKE-open stage (ISSUE 14 tentpole)


#: The process's front-door open batcher, registered at construction so
#: /statusz can render queue depth / shed counts without holding the
#: Aggregator (one aggregator binary per process; tests that build
#: several see the most recent, which is the serving one).
_FRONTDOOR: Optional["UploadOpenBatcher"] = None


def frontdoor_stats() -> Optional[dict]:
    """The /statusz "upload" section (None when no opener exists —
    driver/creator binaries)."""
    return _FRONTDOOR.stats() if _FRONTDOOR is not None else None


class UploadOpenBatcher:
    """Size/delay batcher for upload HPKE opens + the front door's
    admission-control point.

    ``open()`` enqueues one report's open; a batch flushes when
    ``max_batch_size`` opens are pending or ``max_batch_delay`` elapses,
    as ONE ``hpke_batch.open_batch`` call on a worker thread — the KEM
    leaves the event loop, the AES-GCM bodies fuse into one vectorized
    pass, and per-report error slots keep one malformed ciphertext from
    touching its batchmates.  Multiple flushes may be in flight at once
    (the lock covers only queue surgery, never crypto).

    ``admit()`` is the load-shedding gate: callers invoke it BEFORE any
    per-upload work; past ``max_queue`` pending opens, or once the oldest
    pending open has waited ``shed_delay_s``, it raises
    :class:`UploadShed` (503 + Retry-After).  Both signals mean the open
    stage is not keeping up — refusing new work with a retryable error is
    strictly cheaper than queueing it to time out."""

    def __init__(
        self,
        max_batch_size: int = 64,
        max_batch_delay: float = 0.005,
        max_queue: int = 1024,
        shed_delay_s: float = 2.0,
    ):
        self.max_batch_size = max_batch_size
        self.max_batch_delay = max_batch_delay
        self.max_queue = max_queue
        self.shed_delay_s = shed_delay_s
        #: (request 4-tuple, waiter, enqueue-monotonic, report ident)
        self._queue: List[Tuple[tuple, asyncio.Future, float, Optional[tuple]]] = []
        #: detached-but-unresolved batches: seq -> (rows, oldest enqueue).
        #: Admission control MUST count these — the staging queue drains
        #: into flight at max_batch_size/max_batch_delay granularity, so
        #: on its own it can never reach a real queue bound while a slow
        #: open stage piles work up on the thread pool.
        self._inflight: Dict[int, Tuple[int, float]] = {}
        self._batch_seq = 0
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        self._flush_gen = 0
        self._lock = asyncio.Lock()
        self._sheds = {"queue_full": 0, "queue_delay": 0}
        self._batches = 0
        self._opened = 0
        self._bisections = 0
        self._quarantined = 0
        global _FRONTDOOR
        _FRONTDOOR = self

    # -- admission control ----------------------------------------------
    def queue_depth(self) -> int:
        """Opens pending anywhere in the front door: staged + in flight.
        The DEPTH bound must count detached-but-unresolved batches — the
        staging queue drains into flight at batch-size granularity, so
        on its own it could never reach a real bound while a slow open
        stage piles work up on the thread pool."""
        return len(self._queue) + sum(n for n, _enq in self._inflight.values())

    def oldest_wait_s(self) -> float:
        """Age of the oldest STAGED open.  Deliberately excludes
        in-flight batches: their age spikes transiently on one-off costs
        (a cold XLA compile of a new pow2 kernel shape) that the depth
        bound already covers — a staged entry aging past budget, by
        contrast, means flushes have stopped being picked up at all
        (event-loop or timer starvation), which is exactly the collapse
        the delay shed exists to catch."""
        return time.monotonic() - self._queue[0][2] if self._queue else 0.0

    def admit(self) -> None:
        """Raise :class:`UploadShed` when the front door is past budget;
        counted per reason in janus_upload_shed_total."""
        reason = None
        if self.max_queue > 0 and self.queue_depth() >= self.max_queue:
            reason = "queue_full"
        elif self.shed_delay_s > 0 and self.oldest_wait_s() > self.shed_delay_s:
            reason = "queue_delay"
        if reason is None:
            return
        from ..core.metrics import GLOBAL_METRICS

        self._sheds[reason] += 1
        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.upload_sheds.labels(reason=reason).inc()
        raise UploadShed(f"upload front door over {reason} budget; retry")

    # -- the open stage --------------------------------------------------
    async def open(self, keypair, info, ciphertext, aad, ident=None) -> bytes:
        """Resolve to the plaintext when this report's batch opens;
        raises HpkeError on a per-report decrypt failure.  ``ident`` is an
        optional (task_hex, report_id_bytes) pair carried alongside the
        request so a poison row isolated by bisection can be recorded in
        the quarantine ledger under its report identity."""
        fut = asyncio.get_running_loop().create_future()
        async with self._lock:
            self._queue.append(
                ((keypair, info, ciphertext, aad), fut, time.monotonic(), ident)
            )
            self._publish_depth()
            if len(self._queue) >= self.max_batch_size:
                await self._flush_locked()
            elif self._flush_handle is None:
                loop = asyncio.get_running_loop()
                gen = self._flush_gen
                self._flush_handle = loop.call_later(
                    self.max_batch_delay,
                    lambda: asyncio.ensure_future(self._flush(gen)),
                )
        return await fut

    async def _flush(self, gen: Optional[int] = None) -> None:
        async with self._lock:
            if gen is not None and gen != self._flush_gen:
                return  # stale timer (see ReportWriteBatcher._flush)
            await self._flush_locked()

    async def _flush_locked(self) -> None:
        """Detach the pending cohort and launch its open off-lock: the
        lock guards queue surgery only, so several batches can be in
        flight on the thread pool at once."""
        self._flush_gen += 1
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        batch, self._queue = self._queue, []
        if not batch:
            self._publish_depth()
            return
        seq = self._batch_seq
        self._batch_seq += 1
        self._inflight[seq] = (len(batch), batch[0][2])
        self._publish_depth()
        asyncio.ensure_future(self._run_batch(batch, seq))

    async def _run_batch(self, batch, seq: int) -> None:
        from ..core.metrics import GLOBAL_METRICS

        requests = [item for item, _fut, _enq, _ident in batch]
        t0 = time.monotonic()
        try:
            loop = asyncio.get_running_loop()
            try:
                results = await loop.run_in_executor(
                    None, _open_batch_worker, requests
                )
            except Exception:
                # batch-LEVEL failure: bisect the cohort on the thread
                # pool to isolate the poison row(s) — O(log B) extra
                # passes, not B serial opens — while rejecting nothing
                # the inline path would accept (a failing singleton
                # falls through to the inline open, errors as values)
                results, offenders = await loop.run_in_executor(
                    None, _open_bisect_worker, requests
                )
                self._note_offenders(batch, offenders)
            took = time.monotonic() - t0
            self._batches += 1
            self._opened += len(batch)
            if GLOBAL_METRICS.registry is not None:
                GLOBAL_METRICS.upload_open_batch_rows.observe(len(batch))
                GLOBAL_METRICS.upload_open_seconds.labels(backend="batched").observe(took)
        except BaseException as e:
            # nothing above should throw, but a stranded upload handler
            # (future never resolved) is the one unacceptable outcome
            for _item, fut, _enq, _ident in batch:
                if not fut.done():
                    fut.set_exception(
                        e if isinstance(e, Exception) else RuntimeError(str(e))
                    )
            raise
        finally:
            self._inflight.pop(seq, None)
            self._publish_depth()
        for (_item, fut, _enq, _ident), result in zip(batch, results):
            if fut.done():
                continue
            if isinstance(result, Exception):
                fut.set_exception(result)
            else:
                fut.set_result(result)

    def _note_offenders(self, batch, offenders) -> None:
        """Record bisection-isolated poison rows in the quarantine ledger
        under their report identity (when the caller supplied one)."""
        from ..core import quarantine

        quarantine.note_bisection()
        self._bisections += 1
        for idx, err in offenders:
            item, _fut, _enq, ident = batch[idx]
            task_hex, report_id = ident if ident is not None else (None, None)
            quarantine.record(
                "upload_open",
                task=task_hex,
                report_id=report_id,
                error=err,
                payload=item[2],  # the ciphertext
            )
            self._quarantined += 1

    def _publish_depth(self) -> None:
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.upload_queue_depth.set(self.queue_depth())

    # -- introspection ---------------------------------------------------
    def stats(self) -> dict:
        return {
            "queue_depth": self.queue_depth(),
            "staged": len(self._queue),
            "inflight": sum(n for n, _enq in self._inflight.values()),
            "oldest_wait_s": round(self.oldest_wait_s(), 4),
            "max_queue": self.max_queue,
            "shed_delay_s": self.shed_delay_s,
            "sheds": dict(self._sheds),
            "batches": self._batches,
            "opened": self._opened,
            "bisections": self._bisections,
            "quarantined": self._quarantined,
        }


def _open_batch_worker(requests):
    """Thread-pool body of one open batch; the ``upload.open`` fault
    point lets chaos wedge the open stage (delay mode backs the queue up
    into sheds; error mode exercises the per-report fallback)."""
    from ..core import faults
    from ..core.hpke_batch import open_batch

    faults.fire("upload.open")
    return open_batch(requests)


def _open_bisect_worker(requests):
    """Batch-level failure fallback: bisect the cohort to isolate the
    poison row(s) instead of re-running the FULL batch inline serially (a
    healthy 499-report cohort must not pay 499 serial opens for one
    poison row).  The bisection attempt is ``open_batch`` WITHOUT the
    ``upload.open`` fault hook — an injected transient must heal on the
    full-cohort retry, not quarantine healthy reports.  A singleton that
    still fails the batch path gets the per-report inline open (errors as
    values), so nothing the inline path would accept is ever rejected.
    Returns (results, offenders) where offenders is [(index, error)]."""
    from ..core.hpke_batch import _open_one, open_batch
    from ..core.quarantine import bisect_batch

    outcome = bisect_batch(requests, open_batch)
    results = [None] * len(requests)
    for i, r in outcome.results.items():
        results[i] = r
    offenders = []
    for i, err in outcome.offenders:
        one = _open_one(*requests[i])
        results[i] = one
        if isinstance(one, Exception):
            offenders.append((i, err))
    return results, offenders
