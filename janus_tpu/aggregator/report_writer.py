"""Batched upload writer.

The analog of ``ReportWriteBatcher`` (reference:
aggregator/src/aggregator/report_writer.rs:39-246): uploaded reports from all
tasks are funneled into one background batcher that commits up to
``max_batch_size`` of them in a single datastore transaction (or after
``max_batch_write_delay`` elapses), fanning per-report results back to the
waiting upload handlers.  In-batch duplicates by (task, report id) are
resolved to a single write.  Rejected uploads increment the task's sharded
upload counters (reference: report_writer.rs:324 TaskUploadCounters).
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
import time
from typing import Dict, List, Optional, Tuple

from ..datastore import Datastore, LeaderStoredReport, TaskUploadCounter, TxConflict
from ..messages import TaskId
from .error import ReportRejection


class ReportWriteBatcher:
    def __init__(
        self,
        datastore: Datastore,
        max_batch_size: int = 100,
        max_batch_write_delay: float = 0.25,
        counter_shard_count: int = 8,
    ):
        self.datastore = datastore
        self.max_batch_size = max_batch_size
        self.max_batch_write_delay = max_batch_write_delay
        self.counter_shard_count = counter_shard_count
        #: (report, waiter, enqueue-monotonic) — the timestamp feeds
        #: janus_report_upload_to_commit_seconds and the upload_commit span
        self._queue: List[Tuple[object, asyncio.Future, float]] = []
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        self._lock = asyncio.Lock()

    # ------------------------------------------------------------------
    async def write_report(self, report: LeaderStoredReport) -> None:
        """Enqueue a validated report; resolves when its batch commits.
        Raises ReportRejection if the store rejected it.

        Upload trace (ISSUE 9): a report arriving without a trace id
        adopts the caller's bound trace context or mints a fresh one, so
        EVERY persisted report carries a 32-hex upload trace — including
        writes from paths that bypass handle_upload (load generators,
        soaks seeding through the real writer)."""
        if report.trace_id is None:
            from ..core.trace import current_trace, new_trace_id

            report = dataclasses.replace(
                report,
                trace_id=current_trace().get("trace_id") or new_trace_id(),
            )
        fut = asyncio.get_running_loop().create_future()
        async with self._lock:
            self._queue.append((report, fut, time.monotonic()))
            if len(self._queue) >= self.max_batch_size:
                await self._flush_locked()
            elif self._flush_handle is None:
                loop = asyncio.get_running_loop()
                self._flush_handle = loop.call_later(
                    self.max_batch_write_delay,
                    lambda: asyncio.ensure_future(self._flush()),
                )
        await fut

    async def write_rejection(self, task_id: TaskId, rejection: ReportRejection) -> None:
        """Record a rejected upload in the task's sharded counters."""
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.upload_outcomes.labels(decision=rejection.category).inc()
        shard = random.randrange(self.counter_shard_count)
        counter = TaskUploadCounter(task_id, **{rejection.category: 1})

        def tx_fn(tx):
            tx.increment_task_upload_counter(task_id, shard, counter)

        await self.datastore.run_tx_async("upload_rejection", tx_fn)

    async def _flush(self) -> None:
        async with self._lock:
            await self._flush_locked()

    async def _flush_locked(self) -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        batch, self._queue = self._queue, []
        if not batch:
            return
        # In-batch dedup by (task, report id): first wins, dups succeed as
        # idempotent uploads (reference: report_writer.rs:159-237).
        seen: Dict[bytes, int] = {}
        unique: List[Tuple[object, List[asyncio.Future], float]] = []
        for report, fut, enqueued in batch:
            key = report.task_id.data + report.report_id.data
            if key in seen:
                unique[seen[key]][1].append(fut)
            else:
                seen[key] = len(unique)
                unique.append((report, [fut], enqueued))

        def tx_fn(tx):
            outcomes = []
            shard = random.randrange(self.counter_shard_count)
            for report, _futs, _enq in unique:
                try:
                    tx.put_client_report(report)
                    tx.increment_task_upload_counter(
                        report.task_id,
                        shard,
                        TaskUploadCounter(report.task_id, report_success=1),
                    )
                    outcomes.append(None)
                except TxConflict:
                    # duplicate upload: idempotent success
                    outcomes.append(None)
            return outcomes

        from ..core import faults
        from ..core.metrics import GLOBAL_METRICS

        try:
            # Failure-domain boundary: an injected flush fault impersonates
            # a batch-commit failure — fanned to every waiting upload
            # handler exactly like a real one (clients retry the upload).
            await faults.fire_async("report_writer.flush")
            outcomes = await self.datastore.run_tx_async("upload_batch", tx_fn)
        except Exception as e:  # commit failed: fan the error to every waiter
            for _report, futs, _enq in unique:
                for fut in futs:
                    if not fut.done():
                        fut.set_exception(e)
            return
        from ..core.trace import emit_span

        have_metrics = GLOBAL_METRICS.registry is not None
        now_s = self.datastore.now().seconds if have_metrics else 0
        committed = time.monotonic()
        accepted = 0
        for (report, futs, enqueued), outcome in zip(unique, outcomes):
            if outcome is None:
                if have_metrics:
                    accepted += 1
                    # Freshness SLO input: report age at commit (client
                    # timestamp -> writer commit) per accepted report.
                    GLOBAL_METRICS.report_commit_age.observe(
                        max(0.0, float(now_s - report.time.seconds))
                    )
                    # Front-door SLO input (ISSUE 9): how long the batcher
                    # held the report before it was durable.
                    GLOBAL_METRICS.upload_to_commit.observe(
                        max(0.0, committed - enqueued)
                    )
                # Per-report CHILD span stamped with the UPLOAD's trace id
                # (the flush_share pattern): the client-ingress hop of the
                # merged timeline, enqueue -> batch commit.
                emit_span(
                    "upload_commit",
                    "upload",
                    enqueued,
                    committed - enqueued,
                    trace_id=report.trace_id,
                    task_id=str(report.task_id),
                )
            for fut in futs:
                if fut.done():
                    continue
                if outcome is None:
                    fut.set_result(None)
                else:
                    fut.set_exception(outcome)
        if have_metrics:
            GLOBAL_METRICS.upload_outcomes.labels(decision="accepted").inc(accepted)
