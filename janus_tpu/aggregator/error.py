"""Aggregator error taxonomy → DAP problem documents.

The analog of the reference's error enum + report rejection reasons
(reference: aggregator/src/aggregator/error.rs:220, problem_details.rs).
Each error carries the DapProblemType it maps to at the HTTP boundary.
"""

from __future__ import annotations

from typing import Optional

from ..messages.problem_type import DapProblemType


class AggregatorError(Exception):
    """Base; ``problem`` is None for internal (500) errors."""

    problem: Optional[DapProblemType] = None
    status = 500
    #: seconds for a Retry-After header on the response (None = no
    #: header).  The leader's retry_http_request honors it — capped at
    #: its policy's max interval — so helper-side backpressure shapes
    #: the peer's backoff instead of blind exponential sleeps.
    retry_after: Optional[int] = None

    def __init__(self, detail: str = ""):
        super().__init__(detail)
        self.detail = detail


class ServiceUnavailable(AggregatorError):
    """Transient capacity exhaustion (device executor backpressure): the
    peer should retry — 503 lands in the leader's retryable (>= 500)
    classification, so the lease machinery redelivers the job."""

    status = 503
    retry_after = 1


class UploadShed(ServiceUnavailable):
    """Front-door load shedding (ISSUE 14): the bounded upload queue is
    past its depth or delay budget, so this report is refused BEFORE any
    datastore or crypto work with the DAP-retryable 503 + Retry-After —
    overload becomes client retry pressure instead of event-loop
    collapse.  Counted in janus_upload_shed_total."""


class UnrecognizedTask(AggregatorError):
    problem = DapProblemType.UNRECOGNIZED_TASK
    status = 404


class UnrecognizedAggregationJob(AggregatorError):
    problem = DapProblemType.UNRECOGNIZED_AGGREGATION_JOB
    status = 404


class UnrecognizedCollectionJob(AggregatorError):
    problem = None
    status = 404


class UnauthorizedRequest(AggregatorError):
    problem = DapProblemType.UNAUTHORIZED_REQUEST
    status = 403


class InvalidMessage(AggregatorError):
    problem = DapProblemType.INVALID_MESSAGE
    status = 400


class UnsupportedExtension(AggregatorError):
    problem = DapProblemType.INVALID_MESSAGE
    status = 400


class StepMismatch(AggregatorError):
    problem = DapProblemType.STEP_MISMATCH
    status = 400


class RoundMismatch(AggregatorError):
    problem = DapProblemType.STEP_MISMATCH
    status = 400


class OutdatedHpkeConfig(AggregatorError):
    problem = DapProblemType.OUTDATED_CONFIG
    status = 400


class ReportRejectedError(AggregatorError):
    problem = DapProblemType.REPORT_REJECTED
    status = 400


class ReportTooEarly(AggregatorError):
    problem = DapProblemType.REPORT_TOO_EARLY
    status = 400


class BatchInvalid(AggregatorError):
    problem = DapProblemType.BATCH_INVALID
    status = 400


class InvalidBatchSize(AggregatorError):
    problem = DapProblemType.INVALID_BATCH_SIZE
    status = 400


class BatchMismatch(AggregatorError):
    problem = DapProblemType.BATCH_MISMATCH
    status = 400


class QueryMismatch(AggregatorError):
    problem = DapProblemType.BATCH_INVALID
    status = 400


class BatchQueriedTooManyTimes(AggregatorError):
    problem = DapProblemType.BATCH_QUERIED_TOO_MANY_TIMES
    status = 400


class BatchOverlap(AggregatorError):
    problem = DapProblemType.BATCH_OVERLAP
    status = 400


class ForbiddenMutation(AggregatorError):
    """Idempotency violation: same id, different request content
    (reference: aggregator/src/aggregator/error.rs ForbiddenMutation)."""

    problem = None
    status = 409


class DeletedCollectionJob(AggregatorError):
    problem = None
    status = 204


class ReportRejection(Exception):
    """Upload-path rejection with its counter category
    (reference: aggregator/src/aggregator/error.rs:220 ReportRejectionReason)."""

    # categories match TaskUploadCounter columns
    INTERVAL_COLLECTED = "interval_collected"
    DECODE_FAILURE = "report_decode_failure"
    DECRYPT_FAILURE = "report_decrypt_failure"
    EXPIRED = "report_expired"
    OUTDATED_KEY = "report_outdated_key"
    TOO_EARLY = "report_too_early"
    TASK_EXPIRED = "task_expired"

    def __init__(self, category: str, detail: str = ""):
        super().__init__(detail)
        self.category = category
        self.detail = detail

    def to_error(self) -> AggregatorError:
        if self.category == self.TOO_EARLY:
            return ReportTooEarly(self.detail)
        if self.category == self.OUTDATED_KEY:
            return OutdatedHpkeConfig(self.detail)
        return ReportRejectedError(self.detail)
