"""Taskprov: in-band task provisioning.

The analog of the reference's taskprov support (reference:
aggregator_core/src/taskprov.rs:17,97; aggregator.rs:722 opt-in): a client
or peer advertises an encoded ``TaskConfig`` (dap-taskprov header); the
aggregator derives the task id as SHA-256 of the encoded config, checks the
advertising peer is a configured ``PeerAggregator``, derives the VDAF verify
key from the peer's ``verify_key_init``, and provisions the task on the fly.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional

from ..core.auth_tokens import AuthenticationToken, AuthenticationTokenHash
from ..core.hpke import HpkeKeypair
from ..datastore.task import AggregatorTask, TaskQueryType, vdaf_verify_key_length
from ..messages import Duration, HpkeConfig, Role, TaskId, Time
from ..messages.taskprov import TaskConfig, TaskprovQuery
from ..xof import XofTurboShake128


@dataclass(frozen=True)
class PeerAggregator:
    """Pre-shared configuration for a taskprov peer
    (reference: aggregator_core/src/taskprov.rs:97)."""

    endpoint: str
    role: Role  # the PEER's role
    # Secret hygiene: VerifyKeyInit seeds every task's verify key — never in
    # logs (reference: aggregator_core/src/taskprov.rs:17 wraps it in a
    # Debug-redacting newtype).
    verify_key_init: bytes = field(repr=False)  # 32 bytes
    collector_hpke_config: HpkeConfig
    report_expiry_age: Optional[Duration] = None
    tolerable_clock_skew: Duration = Duration(60)
    aggregator_auth_token: Optional[AuthenticationToken] = None
    aggregator_auth_token_hash: Optional[AuthenticationTokenHash] = None
    collector_auth_token_hash: Optional[AuthenticationTokenHash] = None


def taskprov_task_id(encoded_task_config: bytes) -> TaskId:
    """task_id = SHA-256(TaskConfig) (draft-wang-ppm-dap-taskprov)."""
    return TaskId(hashlib.sha256(encoded_task_config).digest())


def derive_vdaf_verify_key(
    verify_key_init: bytes, task_id: TaskId, length: int
) -> bytes:
    """Per-task verify key from the peer's VerifyKeyInit
    (reference: aggregator_core/src/taskprov.rs:17 VerifyKeyInit).

    All 32 bytes of the init feed the derivation (as the binder, with a
    fixed all-zero XOF seed), so the full keyspace is preserved.
    """
    if len(verify_key_init) != 32:
        raise ValueError("verify_key_init must be 32 bytes")
    return XofTurboShake128(
        b"\x00" * 16, b"dap-taskprov verify key", verify_key_init + task_id.data
    ).next(length)


def taskprov_task(
    encoded_task_config: bytes,
    peer: PeerAggregator,
    own_role: Role,
    hpke_keys: List[HpkeKeypair],
    config: Optional[TaskConfig] = None,
) -> AggregatorTask:
    """Build the AggregatorTask a taskprov advertisement describes."""
    if config is None:
        config = TaskConfig.get_decoded(encoded_task_config)
    task_id = taskprov_task_id(encoded_task_config)
    q = config.query_config
    if q.query.variant == TaskprovQuery.TIME_INTERVAL:
        query_type = TaskQueryType.time_interval()
    elif q.query.variant == TaskprovQuery.FIXED_SIZE:
        query_type = TaskQueryType.fixed_size(max_batch_size=q.query.max_batch_size)
    else:
        raise ValueError("reserved taskprov query type")
    vdaf = config.vdaf_config.vdaf_type.to_instance()
    return AggregatorTask(
        task_id=task_id,
        peer_aggregator_endpoint=peer.endpoint,
        query_type=query_type,
        vdaf=vdaf,
        role=own_role,
        vdaf_verify_key=derive_vdaf_verify_key(
            peer.verify_key_init, task_id, vdaf_verify_key_length(vdaf)
        ),
        min_batch_size=q.min_batch_size,
        time_precision=q.time_precision,
        task_expiration=config.task_expiration,
        report_expiry_age=peer.report_expiry_age,
        tolerable_clock_skew=peer.tolerable_clock_skew,
        aggregator_auth_token=peer.aggregator_auth_token,
        aggregator_auth_token_hash=peer.aggregator_auth_token_hash,
        collector_auth_token_hash=peer.collector_auth_token_hash,
        collector_hpke_config=peer.collector_hpke_config,
        hpke_keys=hpke_keys,
    )
