"""Incremental fixed-size batch filling with headroom-priority ordering.

The analog of the reference's ``BatchCreator`` (reference:
aggregator/src/aggregator/batch_creator.rs:32-517): reports are routed into
the *most-full* unfilled outstanding batch first (a max-heap on the batch's
potential size), so batches complete as early as possible; new batches are
opened only when every open batch is saturated and enough reports remain.
Two passes share one engine:

* assignment (``greedy=False``): jobs are cut only at full
  ``max_aggregation_job_size`` (or the batch's remaining headroom).
* finish (``greedy=True``): remaining reports form smaller jobs down to
  ``min_aggregation_job_size`` — or even below it when that is exactly what
  completes a batch's ``min_batch_size`` (batch_creator.rs:207-249).

Batches whose CONFIRMED size already meets ``min_batch_size`` at load time
are marked filled and never reconsidered (batch_creator.rs:128-143); the
fixed-size collection path selects by confirmed size independently.
"""

from __future__ import annotations

import heapq
import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..messages import BatchId, ReportMetadata, Time


@dataclass
class _OpenBatch:
    batch_id: BatchId
    new_max_size: int  # potential size incl. reports assigned this pass
    stale: bool = False


@dataclass
class _Bucket:
    heap: List[Tuple[int, int, _OpenBatch]] = field(default_factory=list)
    reports: List[ReportMetadata] = field(default_factory=list)


class BatchCreator:
    """One task's fixed-size batch filling for a single creation pass."""

    def __init__(
        self,
        tx,
        task,
        min_aggregation_job_size: int,
        max_aggregation_job_size: int,
    ):
        self.tx = tx
        self.task = task
        self.min_job = min_aggregation_job_size
        self.max_job = max_aggregation_job_size
        self.min_batch = task.min_batch_size
        # Without an explicit max, aim for batches of exactly min_batch_size
        # (reference: batch_creator.rs:88-94 / draft-ietf-ppm-dap-09 §4.1.2).
        self.effective_max = task.query_type.max_batch_size or task.min_batch_size
        self.btws = task.query_type.batch_time_window_size
        self.buckets: Dict[Optional[int], _Bucket] = {}
        self.jobs: List[Tuple[BatchId, List[ReportMetadata]]] = []
        self._tiebreak = itertools.count()

    # -- bucket plumbing -------------------------------------------------
    def _bucket_key(self, m: ReportMetadata) -> Optional[int]:
        if self.btws is None:
            return None
        return m.time.seconds - m.time.seconds % self.btws.seconds

    def _load_bucket(self, key: Optional[int]) -> _Bucket:
        bucket = self.buckets.get(key)
        if bucket is not None:
            return bucket
        bucket = _Bucket()
        bucket_time = Time(key) if key is not None else None
        for ob in self.tx.get_unfilled_outstanding_batches(self.task.task_id, bucket_time):
            if ob.size_min >= self.min_batch:
                # Enough confirmed aggregations: retire it from filling.
                self.tx.mark_outstanding_batch_filled(self.task.task_id, ob.batch_id)
                continue
            self._push(bucket, _OpenBatch(ob.batch_id, ob.size_max))
        self.buckets[key] = bucket
        return bucket

    def _push(self, bucket: _Bucket, ob: _OpenBatch) -> None:
        heapq.heappush(bucket.heap, (-ob.new_max_size, next(self._tiebreak), ob))

    def _pop_largest(self, bucket: _Bucket) -> Optional[_OpenBatch]:
        while bucket.heap:
            _, _, ob = heapq.heappop(bucket.heap)
            if not ob.stale:
                return ob
        return None

    # -- the engine ------------------------------------------------------
    def add_report(self, meta: ReportMetadata) -> None:
        key = self._bucket_key(meta)
        bucket = self._load_bucket(key)
        bucket.reports.append(meta)
        self._process(key, bucket, greedy=False)

    def _cut_job(self, batch: _OpenBatch, bucket: _Bucket, size: int) -> None:
        take, bucket.reports = bucket.reports[:size], bucket.reports[size:]
        self.jobs.append((batch.batch_id, take))
        batch.stale = True
        updated = _OpenBatch(batch.batch_id, batch.new_max_size + size)
        self._push(bucket, updated)

    def _process(self, key: Optional[int], bucket: _Bucket, greedy: bool) -> None:
        while True:
            while True:
                if not bucket.reports:
                    return
                largest = self._pop_largest(bucket)
                if largest is None:
                    break
                if largest.new_max_size >= self.effective_max:
                    continue  # saturated: discard from consideration
                if greedy:
                    desired = min(
                        len(bucket.reports),
                        self.max_job,
                        self.effective_max - largest.new_max_size,
                    )
                    completes_batch = (
                        largest.new_max_size < self.min_batch
                        and largest.new_max_size + desired >= self.min_batch
                    )
                    if desired >= self.min_job or completes_batch:
                        self._cut_job(largest, bucket, desired)
                        continue
                    self._push(bucket, largest)
                    return
                else:
                    desired = min(
                        self.max_job, self.effective_max - largest.new_max_size
                    )
                    if len(bucket.reports) >= desired:
                        self._cut_job(largest, bucket, desired)
                        continue
                    self._push(bucket, largest)
                    return

            # Every open batch is saturated (or none exist): open a new one
            # if enough reports remain for the pass's job-size threshold.
            threshold = self.min_job if greedy else self.max_job
            desired = min(len(bucket.reports), self.max_job, self.effective_max)
            if desired >= threshold and desired > 0:
                batch_id = BatchId.random()
                bucket_time = Time(key) if key is not None else None
                self.tx.put_outstanding_batch(self.task.task_id, batch_id, bucket_time)
                nb = _OpenBatch(batch_id, 0)
                self._cut_job(nb, bucket, desired)
                continue
            return

    def finish(self) -> Tuple[List[Tuple[BatchId, List[ReportMetadata]]], List[ReportMetadata]]:
        """Greedy pass over every bucket; returns (jobs, leftover reports)."""
        leftover: List[ReportMetadata] = []
        for key, bucket in self.buckets.items():
            self._process(key, bucket, greedy=True)
            leftover.extend(bucket.reports)
            bucket.reports = []
        return self.jobs, leftover
