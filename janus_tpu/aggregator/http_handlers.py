"""DAP HTTP API over aiohttp.

The analog of the trillium router (reference:
aggregator/src/aggregator/http_handlers.rs:283-357): all DAP routes, CORS
preflight for browser clients, RFC 7807 problem documents on errors, and
bearer/DAP-Auth-Token extraction.  Routes:

    GET    /hpke_config?task_id=...
    PUT    /tasks/:task_id/reports
    PUT    /tasks/:task_id/aggregation_jobs/:aggregation_job_id
    POST   /tasks/:task_id/aggregation_jobs/:aggregation_job_id
    DELETE /tasks/:task_id/aggregation_jobs/:aggregation_job_id
    PUT    /tasks/:task_id/collection_jobs/:collection_job_id
    POST   /tasks/:task_id/collection_jobs/:collection_job_id
    DELETE /tasks/:task_id/collection_jobs/:collection_job_id
    POST   /tasks/:task_id/aggregate_shares
"""

from __future__ import annotations

import json
import logging
from typing import Optional

from aiohttp import web

from ..core.auth_tokens import DAP_AUTH_HEADER, AuthenticationToken
from ..datastore.datastore import DatastoreUnavailable
from ..messages import (
    AggregateShare,
    AggregationJobId,
    AggregationJobResp,
    CollectionJobId,
    HpkeConfigList,
    Report,
    TaskId,
)
from ..messages.codec import CodecError
from ..messages.problem_type import problem_document
from .aggregator import Aggregator
from .error import AggregatorError, DeletedCollectionJob

logger = logging.getLogger("janus_tpu.http")

PROBLEM_CONTENT_TYPE = "application/problem+json"


def _extract_auth(request: web.Request) -> Optional[AuthenticationToken]:
    """Bearer header first, then DAP-Auth-Token
    (reference: core/src/auth_tokens.rs)."""
    auth = request.headers.get("Authorization")
    if auth and auth.startswith("Bearer "):
        try:
            return AuthenticationToken.new_bearer(auth[len("Bearer ") :])
        except ValueError:
            return None
    dap = request.headers.get(DAP_AUTH_HEADER)
    if dap:
        try:
            return AuthenticationToken.new_dap_auth(dap)
        except ValueError:
            return None
    return None


def _problem(err: AggregatorError, task_id: Optional[TaskId]) -> web.Response:
    headers = (
        {"Retry-After": str(err.retry_after)}
        if err.retry_after is not None
        else None
    )
    if err.problem is None:
        return web.Response(
            status=err.status, text=err.detail or "", headers=headers
        )
    doc = problem_document(err.problem, task_id=task_id, detail=err.detail or None)
    return web.Response(
        status=err.status,
        content_type=PROBLEM_CONTENT_TYPE,
        text=json.dumps(doc),
        headers=headers,
    )


def _wire(body: bytes, media_type: str, status: int = 200) -> web.Response:
    return web.Response(status=status, body=body, content_type=media_type)


async def _maybe_taskprov(request: web.Request, task_id: TaskId) -> None:
    """In-band task provisioning (reference: aggregator.rs:722).  Upload and
    hpke_config requests are client-originated and cannot carry the peer
    token; everything else must."""
    taskprov_header = request.headers.get("dap-taskprov")
    if not taskprov_header:
        return
    from ..messages.dap import _unb64url

    aggregator = request.app["aggregator"]
    try:
        encoded = _unb64url(taskprov_header)
    except Exception:
        from .error import InvalidMessage

        raise InvalidMessage("malformed dap-taskprov header")
    client_route = request.path.endswith("/reports") or request.path.endswith(
        "/hpke_config"
    )
    await aggregator.ensure_taskprov_task(
        task_id,
        encoded,
        _extract_auth(request),
        require_peer_auth=not client_route,
    )


def _route(handler):
    """Wrap a handler: task-id parsing, error → problem-document mapping,
    per-route request metrics, and trace-context adoption — the peer's
    ``traceparent`` header (W3C trace id, sent by the leader's drivers) is
    bound for the request so helper-side logs and chrome-trace spans join
    the job's cross-process timeline (reference: http_handlers.rs error
    mapping + instrumented spans + :225-281 route counters)."""
    import time as _time

    from ..core.metrics import GLOBAL_METRICS
    from ..core.trace import parse_traceparent, trace_scope, trace_span

    async def wrapped(request: web.Request) -> web.Response:
        t0 = _time.monotonic()
        route = request.match_info.route.resource
        route_name = route.canonical if route else request.path
        with trace_scope(
            trace_id=parse_traceparent(request.headers.get("traceparent"))
        ), trace_span(
            "http_request", cat="http", method=request.method, route=route_name
        ):
            resp = await _wrapped_inner(request)
        GLOBAL_METRICS.observe_http(
            route_name,
            resp.status,
            _time.monotonic() - t0,
        )
        return resp

    async def _wrapped_inner(request: web.Request) -> web.Response:
        task_id = None
        try:
            if "task_id" in request.match_info:
                try:
                    task_id = TaskId.from_str(request.match_info["task_id"])
                except Exception:
                    from .error import InvalidMessage

                    raise InvalidMessage("malformed task id")
                from ..core.trace import bind_trace

                bind_trace(task_id=task_id)
                # in-band task provisioning (reference: aggregator.rs:722)
                await _maybe_taskprov(request, task_id)
            return await handler(request, task_id)
        except DeletedCollectionJob:
            return web.Response(status=204)
        except AggregatorError as err:
            return _problem(err, task_id)
        except CodecError as err:
            from .error import InvalidMessage

            return _problem(InvalidMessage(str(err)), task_id)
        except DatastoreUnavailable as err:
            # Datastore unreachable / retries exhausted is a TRANSIENT
            # infrastructure failure, not a protocol error: answer with
            # the DAP-retryable 503 (+ Retry-After) so the leader's
            # lease machinery redelivers — a split-brain window (helper
            # HTTP up, helper datastore down) must not 500 jobs into
            # their failure budget.  Scoped to the retries-exhausted
            # subclass: permanent DatastoreErrors (missing rows, schema
            # mismatch) would retry forever under a 503.
            logger.warning("datastore unavailable in %s: %s", request.path, err)
            return web.Response(
                status=503,
                text="datastore unavailable",
                headers={"Retry-After": "5"},
            )
        except Exception:
            logger.exception("internal error in %s", request.path)
            return web.Response(status=500, text="internal error")

    return wrapped


def aggregator_app(aggregator: Aggregator) -> web.Application:
    """Build the DAP service (reference: http_handlers.rs:283
    aggregator_handler)."""

    @_route
    async def hpke_config(request: web.Request, _tid) -> web.Response:
        task_id = None
        if "task_id" in request.query:
            try:
                task_id = TaskId.from_str(request.query["task_id"])
            except Exception:
                from .error import InvalidMessage

                raise InvalidMessage("malformed task id")
            await _maybe_taskprov(request, task_id)
        config_list = await aggregator.handle_hpke_config(task_id)
        return _wire(config_list.get_encoded(), HpkeConfigList.MEDIA_TYPE)

    @_route
    async def upload(request: web.Request, task_id) -> web.Response:
        body = await request.read()
        report = Report.get_decoded(body)
        await aggregator.handle_upload(task_id, report)
        return web.Response(status=201)

    @_route
    async def aggregation_job_put(request: web.Request, task_id) -> web.Response:
        job_id = AggregationJobId.from_str(request.match_info["aggregation_job_id"])
        body = await request.read()
        resp = await aggregator.handle_aggregate_init(
            task_id, job_id, body, _extract_auth(request)
        )
        return _wire(resp.get_encoded(), AggregationJobResp.MEDIA_TYPE)

    @_route
    async def aggregation_job_post(request: web.Request, task_id) -> web.Response:
        job_id = AggregationJobId.from_str(request.match_info["aggregation_job_id"])
        body = await request.read()
        resp = await aggregator.handle_aggregate_continue(
            task_id, job_id, body, _extract_auth(request)
        )
        return _wire(resp.get_encoded(), AggregationJobResp.MEDIA_TYPE)

    @_route
    async def aggregation_job_delete(request: web.Request, task_id) -> web.Response:
        job_id = AggregationJobId.from_str(request.match_info["aggregation_job_id"])
        await aggregator.handle_aggregate_delete(task_id, job_id, _extract_auth(request))
        return web.Response(status=204)

    @_route
    async def collection_job_put(request: web.Request, task_id) -> web.Response:
        job_id = CollectionJobId.from_str(request.match_info["collection_job_id"])
        body = await request.read()
        await aggregator.handle_create_collection_job(
            task_id, job_id, body, _extract_auth(request)
        )
        return web.Response(status=201)

    @_route
    async def collection_job_post(request: web.Request, task_id) -> web.Response:
        job_id = CollectionJobId.from_str(request.match_info["collection_job_id"])
        collection = await aggregator.handle_get_collection_job(
            task_id, job_id, _extract_auth(request)
        )
        if collection is None:
            return web.Response(
                status=202,
                headers={"Retry-After": str(aggregator.config.collection_job_retry_after)},
            )
        from ..messages import Collection

        return _wire(collection.get_encoded(), Collection.MEDIA_TYPE)

    @_route
    async def collection_job_delete(request: web.Request, task_id) -> web.Response:
        job_id = CollectionJobId.from_str(request.match_info["collection_job_id"])
        await aggregator.handle_delete_collection_job(
            task_id, job_id, _extract_auth(request)
        )
        return web.Response(status=204)

    @_route
    async def aggregate_shares(request: web.Request, task_id) -> web.Response:
        body = await request.read()
        share = await aggregator.handle_aggregate_share(
            task_id, body, _extract_auth(request)
        )
        return _wire(share.get_encoded(), AggregateShare.MEDIA_TYPE)

    async def healthz(_request: web.Request) -> web.Response:
        return web.Response(text="ok")

    async def metrics(_request: web.Request) -> web.Response:
        from ..core.metrics import GLOBAL_METRICS

        return web.Response(
            body=GLOBAL_METRICS.export(), content_type="text/plain"
        )

    async def cors_preflight(_request: web.Request) -> web.Response:
        # reference: http_handlers.rs CORS preflight for upload from browsers
        return web.Response(
            status=204,
            headers={
                "Access-Control-Allow-Origin": "*",
                "Access-Control-Allow-Methods": "PUT, POST, GET",
                "Access-Control-Allow-Headers": "content-type",
            },
        )

    app = web.Application(client_max_size=64 * 1024 * 1024)
    app["aggregator"] = aggregator
    app.add_routes(
        [
            web.get("/hpke_config", hpke_config),
            web.get("/healthz", healthz),
            web.get("/metrics", metrics),
            web.put("/tasks/{task_id}/reports", upload),
            web.options("/tasks/{task_id}/reports", cors_preflight),
            web.put(
                "/tasks/{task_id}/aggregation_jobs/{aggregation_job_id}",
                aggregation_job_put,
            ),
            web.post(
                "/tasks/{task_id}/aggregation_jobs/{aggregation_job_id}",
                aggregation_job_post,
            ),
            web.delete(
                "/tasks/{task_id}/aggregation_jobs/{aggregation_job_id}",
                aggregation_job_delete,
            ),
            web.put(
                "/tasks/{task_id}/collection_jobs/{collection_job_id}",
                collection_job_put,
            ),
            web.post(
                "/tasks/{task_id}/collection_jobs/{collection_job_id}",
                collection_job_post,
            ),
            web.delete(
                "/tasks/{task_id}/collection_jobs/{collection_job_id}",
                collection_job_delete,
            ),
            web.post("/tasks/{task_id}/aggregate_shares", aggregate_shares),
        ]
    )
    return app
