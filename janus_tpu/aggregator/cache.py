"""Refreshed config caches: global HPKE keypairs and taskprov peers.

The reference keeps request-path config data out of the database hot path
with periodically-refreshed caches (reference: aggregator/src/cache.rs:24-208
— GlobalHpkeKeypairCache with a refresh task, PeerAggregatorCache).  Same
design here: a TTL snapshot served synchronously, plus an asyncio refresh
loop started lazily on first use so steady-state requests never wait on a
transaction.  A refresh failure keeps serving the previous snapshot (stale
config beats an outage, matching the reference's error-tolerant refresher).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Awaitable, Callable, Generic, List, Optional, TypeVar

logger = logging.getLogger(__name__)

T = TypeVar("T")


class RefreshingCache(Generic[T]):
    """TTL snapshot + lazy background refresh loop."""

    def __init__(
        self,
        fetch: Callable[[], Awaitable[T]],
        refresh_interval: float,
        name: str,
    ):
        self._fetch = fetch
        self._interval = refresh_interval
        self._name = name
        self._value: Optional[T] = None
        self._fetched_at: float = float("-inf")
        self._task: Optional[asyncio.Task] = None
        self._lock = asyncio.Lock()

    async def get(self) -> T:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._refresh_loop())
        if self._fetched_at == float("-inf"):
            async with self._lock:
                if self._fetched_at == float("-inf"):  # double-checked
                    self._value = await self._fetch()
                    self._fetched_at = time.monotonic()
        return self._value

    async def _refresh_loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self._interval)
                try:
                    self._value = await self._fetch()
                    self._fetched_at = time.monotonic()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    logger.warning(
                        "%s cache refresh failed; serving stale snapshot",
                        self._name,
                        exc_info=True,
                    )
        except asyncio.CancelledError:
            pass

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def invalidate(self) -> None:
        """Force the next get() to fetch.  For in-process embedders and
        tests; the management API usually runs in a separate process, where
        the refresh interval is the propagation delay (as in the
        reference)."""
        self._fetched_at = float("-inf")


class GlobalHpkeKeypairCache(RefreshingCache[List[object]]):
    """Active global HPKE keypairs (reference: cache.rs:24-120)."""

    def __init__(self, datastore, refresh_interval: float = 60.0):
        super().__init__(
            lambda: datastore.run_tx_async(
                "cache_global_hpke", lambda tx: tx.get_global_hpke_keypairs()
            ),
            refresh_interval,
            "global-hpke-keypair",
        )

    async def active_keypairs(self):
        return [kp for kp in await self.get() if kp.state.value == "Active"]

    async def active_configs(self):
        return [kp.config for kp in await self.active_keypairs()]


class PeerAggregatorCache(RefreshingCache[List[object]]):
    """Taskprov peer aggregators (reference: cache.rs:150-208)."""

    def __init__(self, datastore, refresh_interval: float = 60.0):
        super().__init__(
            lambda: datastore.run_tx_async(
                "cache_taskprov_peers", lambda tx: tx.get_taskprov_peer_aggregators()
            ),
            refresh_interval,
            "peer-aggregator",
        )

    async def peers(self):
        return await self.get()
