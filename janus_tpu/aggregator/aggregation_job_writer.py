"""Transactional write-combiner for aggregation jobs.

The analog of ``AggregationJobWriter`` (reference:
aggregator/src/aggregator/aggregation_job_writer.rs:35-861): writes an
aggregation job plus its report aggregations in one transaction, accumulating
every Finished report's output share into a randomly-chosen shard of the
batch's ``batch_aggregations`` accumulator — the write-contention sharding the
TPU path later merges with ``lax.psum`` (SURVEY.md §2.3 P4).  Reports whose
batch has already been collected are failed with BatchCollected.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.report_id import checksum_combined, checksum_updated_with
from ..core.time import interval_merge, time_to_batch_interval
from ..datastore import (
    AggregationJob,
    AggregationJobState,
    BatchAggregation,
    BatchAggregationState,
    ReportAggregation,
    ReportAggregationState,
    Transaction,
    TxConflict,
)
from ..datastore.query_type import strategy_for
from ..datastore.task import AggregatorTask
from ..messages import Interval, PrepareError, ReportIdChecksum


class AggregationJobWriter:
    """Collects job + report-aggregation writes, then commits them with
    batch-aggregation accumulation inside the caller's transaction.

    ``initial_write=True`` is the creation path (jobs counted into
    aggregation_jobs_created); False is the update path (terminal jobs
    counted into aggregation_jobs_terminated), mirroring the reference's
    InitialWrite/UpdateWrite strategies.

    ``out_shares`` maps a finished report's id bytes to its VDAF output-share
    vector; shares are accumulated here and never persisted per report
    (the reference does the same: out shares exist only inside this write).
    """

    def __init__(
        self,
        task: AggregatorTask,
        vdaf,
        batch_aggregation_shard_count: int = 8,
        initial_write: bool = True,
        backend=None,
        accumulator_deltas: Optional[
            Dict[bytes, Tuple[Sequence[int], frozenset]]
        ] = None,
        journal_entries: Optional[Dict[bytes, frozenset]] = None,
    ):
        self.task = task
        self.vdaf = vdaf
        self.shard_count = batch_aggregation_shard_count
        self.initial_write = initial_write
        #: Device backend (TpuBackend/MeshBackend) for on-device out-share
        #: accumulation; None falls back to host field adds.
        self.backend = backend
        #: Pre-drained device-resident deltas (executor/accumulator.py):
        #: batch identifier -> (field vector, report ids it covers).  Rows
        #: whose out_share is a ResidentRef are summed by these instead of
        #: host vectors; the rid set is checked against the reports that
        #: survive the in-tx BatchCollected gate (mismatch raises
        #: StaleAccumulatorDelta — the delta must never merge a report the
        #: tx is failing).
        self.accumulator_deltas = accumulator_deltas or {}
        #: Deferred drains (accumulator.drain_interval_s > 0): batch
        #: identifier -> report ids whose out shares STAY resident on
        #: device past this tx.  The writer persists one accumulator-
        #: journal row per (job, identifier) in the same transaction and
        #: merges NO share for those rows now — the cadence drain (or a
        #: crash-recovery replay) merges them later against the row.
        self.journal_entries = journal_entries or {}
        self._jobs: List[
            Tuple[AggregationJob, List[ReportAggregation], Dict[bytes, Sequence[int]]]
        ] = []

    def put(
        self,
        job: AggregationJob,
        report_aggregations: List[ReportAggregation],
        out_shares: Optional[Dict[bytes, Sequence[int]]] = None,
    ):
        self._jobs.append((job, report_aggregations, out_shares or {}))

    # ------------------------------------------------------------------
    def write(self, tx: Transaction) -> Dict[bytes, PrepareError]:
        """Write everything; returns {report_id.data: error} for reports that
        were failed during the write (batch already collected)."""
        strategy = strategy_for(self.task)
        failures: Dict[bytes, PrepareError] = {}
        collected: Dict[bytes, bool] = {}

        def ident_for(job: AggregationJob, ra: ReportAggregation) -> bytes:
            if job.partial_batch_identifier is not None:
                return job.partial_batch_identifier.get_encoded()
            return strategy.to_batch_identifier(self.task, ra.time)

        def is_collected(ident: bytes, param: bytes) -> bool:
            if ident not in collected:
                bas = tx.get_batch_aggregations_for_batch(
                    self.task.task_id, ident, param
                )
                collected[ident] = any(
                    ba.state != BatchAggregationState.AGGREGATING for ba in bas
                )
            return collected[ident]

        for job, ras, out_shares in self._jobs:
            # Fail reports aimed at collected batches
            # (reference: aggregation_job_writer.rs:591-698).
            checked: List[ReportAggregation] = []
            for ra in ras:
                if ra.state != ReportAggregationState.FAILED and is_collected(
                    ident_for(job, ra), job.aggregation_parameter
                ):
                    ra = ra.failed(PrepareError.BATCH_COLLECTED)
                    failures[ra.report_id.data] = PrepareError.BATCH_COLLECTED
                    out_shares.pop(ra.report_id.data, None)
                checked.append(ra)
            ras = checked

            if self.initial_write:
                tx.put_aggregation_job(job)
                for ra in ras:
                    tx.put_report_aggregation(ra)
            else:
                tx.update_aggregation_job(job)
                for ra in ras:
                    tx.update_report_aggregation(ra)

            if self.journal_entries:
                self._write_journal(tx, job, failures)
            self._accumulate(tx, job, ras, out_shares, ident_for)
        return failures

    def _write_journal(self, tx, job, failures) -> None:
        """Persist the deferred-drain journal rows IN this transaction.
        A journaled report that was failed by the in-tx collected check
        would leave the resident delta counting a report the tx rejects —
        abort via StaleAccumulatorDelta (the caller discards the bucket
        and the step redelivers, exactly like the drained-delta race)."""
        from ..executor.accumulator import StaleAccumulatorDelta

        for ident, rids in self.journal_entries.items():
            dropped = [r for r in rids if r in failures]
            if dropped:
                raise StaleAccumulatorDelta(
                    f"batch {ident!r}: {len(dropped)} journaled report(s) "
                    "failed in-tx (batch collected)"
                )
            tx.put_accumulator_journal_entry(
                self.task.task_id,
                ident,
                job.aggregation_parameter,
                job.aggregation_job_id,
                sorted(rids),
            )

    # ------------------------------------------------------------------
    def _sum_shares(self, field, shares: List[Sequence[int]]) -> List[int]:
        """Sum out-share vectors: on-device (cross-shard all-reduce on a
        MeshBackend — the collective replacing the reference's DB shard
        merge) when a device backend is attached and the batch is worth a
        launch; host field adds otherwise."""
        backend = self.backend
        if backend is not None and hasattr(backend, "aggregate_batch") and len(shares) > 1:
            import numpy as np

            jf = backend.bp.jf
            limbs = jf.to_limbs([x for sh in shares for x in sh]).reshape(
                len(shares), -1, jf.n
            )
            return backend.aggregate_batch(limbs, np.ones(len(shares), dtype=bool))
        acc: Optional[List[int]] = None
        for sh in shares:
            acc = list(sh) if acc is None else field.vec_add(acc, sh)
        return acc

    # ------------------------------------------------------------------
    def _resolve_shares(self, field, ident, shares, rids) -> Optional[List[int]]:
        """Sum one batch's finished shares, mixing host vectors with a
        pre-drained device-resident delta (ResidentRef rows).  Rows named
        by a deferred-drain journal entry contribute NOTHING here (their
        delta stays on device; the journal row written in this tx is what
        guarantees it is merged later).  Returns None when every share is
        deferred — the batch row carries count/checksum only for now."""
        from ..executor.accumulator import ResidentRef, StaleAccumulatorDelta

        host_rows = [s for s in shares if not isinstance(s, ResidentRef)]
        ref_rids = {
            rid for rid, s in zip(rids, shares) if isinstance(s, ResidentRef)
        }
        journaled = ref_rids & set(self.journal_entries.get(ident, frozenset()))
        need_drained = ref_rids - journaled
        if not need_drained:
            return self._sum_shares(field, host_rows) if host_rows else None
        delta, covered = self.accumulator_deltas.get(ident, (None, frozenset()))
        if delta is None or set(covered) != need_drained:
            raise StaleAccumulatorDelta(
                f"batch {ident!r}: drained delta covers {len(covered)} "
                f"report(s), tx needs exactly {len(need_drained)}"
            )
        if not host_rows:
            return list(delta)
        return field.vec_add(list(delta), self._sum_shares(field, host_rows))

    # ------------------------------------------------------------------
    def _accumulate(self, tx, job, ras, out_shares, ident_for) -> None:
        """Merge finished out-shares into per-batch shard accumulators and
        update the created/terminated job counters the collection readiness
        gate relies on (reference: collection_job_driver.rs:124-262)."""
        by_batch: Dict[bytes, List[ReportAggregation]] = {}
        for ra in ras:
            if (
                ra.state == ReportAggregationState.FINISHED
                and ra.report_id.data in out_shares
            ):
                by_batch.setdefault(ident_for(job, ra), []).append(ra)

        # Job-level counters land on every batch the job touched; for a job
        # with no finished reports, on the batch of its interval start.
        job_batches = set(by_batch)
        if job.partial_batch_identifier is not None:
            job_batches.add(job.partial_batch_identifier.get_encoded())
        elif not job_batches:
            job_batches.add(
                time_to_batch_interval(
                    job.client_timestamp_interval.start, self.task.time_precision
                ).get_encoded()
            )

        field = self.vdaf.field_for_agg_param(
            self.vdaf.decode_agg_param(job.aggregation_parameter)
        )
        terminal = job.state in (
            AggregationJobState.FINISHED,
            AggregationJobState.ABANDONED,
        )
        for ident in job_batches:
            finished = by_batch.get(ident, [])
            shard = random.randrange(self.shard_count)
            agg_share: Optional[List[int]] = None
            count = 0
            checksum = ReportIdChecksum.zero()
            interval = Interval.EMPTY
            for ra in finished:
                count += 1
                checksum = checksum_updated_with(checksum, ra.report_id)
                interval = interval_merge(
                    interval,
                    time_to_batch_interval(ra.time, self.task.time_precision),
                )
            if finished:
                agg_share = self._resolve_shares(
                    field, ident, [out_shares[ra.report_id.data] for ra in finished],
                    [ra.report_id.data for ra in finished],
                )
            delta = BatchAggregation(
                task_id=self.task.task_id,
                batch_identifier=ident,
                aggregation_parameter=job.aggregation_parameter,
                ord=shard,
                state=BatchAggregationState.AGGREGATING,
                aggregate_share=field.encode_vec(agg_share)
                if agg_share is not None
                else None,
                report_count=count,
                checksum=checksum,
                client_timestamp_interval=interval,
                aggregation_jobs_created=1 if self.initial_write else 0,
                aggregation_jobs_terminated=1
                if (not self.initial_write and terminal)
                else 0,
            )
            upsert_batch_aggregation(tx, field, delta)


def upsert_batch_aggregation(tx: Transaction, field, delta: BatchAggregation) -> None:
    """Merge ``delta`` into its shard row, creating it if absent (the one
    upsert shared by the writer's accumulate path and the deferred-drain /
    journal-replay share merges — they must never diverge)."""
    existing = tx.get_batch_aggregation(
        delta.task_id, delta.batch_identifier, delta.aggregation_parameter, delta.ord
    )
    if existing is not None:
        tx.update_batch_aggregation(merge_batch_aggregations(field, existing, delta))
        return
    try:
        tx.put_batch_aggregation(delta)
    except TxConflict:
        fresh = tx.get_batch_aggregation(
            delta.task_id, delta.batch_identifier, delta.aggregation_parameter, delta.ord
        )
        tx.update_batch_aggregation(merge_batch_aggregations(field, fresh, delta))


def merge_share_delta(
    tx: Transaction,
    task: AggregatorTask,
    field,
    batch_identifier: bytes,
    aggregation_parameter: bytes,
    vector: Sequence[int],
    shard_count: int = 8,
) -> None:
    """Merge a share-ONLY delta into one random shard of a batch's
    accumulator — the deferred-drain / journal-replay write: the covered
    reports' count, checksum and interval were already committed by their
    jobs' writer transactions; only the aggregate share was left resident
    on device."""
    delta = BatchAggregation(
        task_id=task.task_id,
        batch_identifier=batch_identifier,
        aggregation_parameter=aggregation_parameter,
        ord=random.randrange(shard_count),
        state=BatchAggregationState.AGGREGATING,
        aggregate_share=field.encode_vec(list(vector)),
        report_count=0,
        checksum=ReportIdChecksum.zero(),
        client_timestamp_interval=Interval.EMPTY,
        aggregation_jobs_created=0,
        aggregation_jobs_terminated=0,
    )
    upsert_batch_aggregation(tx, field, delta)


def merge_batch_aggregations(
    field, base: BatchAggregation, add: BatchAggregation
) -> BatchAggregation:
    """Pointwise merge of two shard accumulators (same batch/param/ord)."""
    share_a = field.decode_vec(base.aggregate_share) if base.aggregate_share else None
    share_b = field.decode_vec(add.aggregate_share) if add.aggregate_share else None
    if share_a is None:
        merged = share_b
    elif share_b is None:
        merged = share_a
    else:
        merged = field.vec_add(share_a, share_b)
    return BatchAggregation(
        task_id=base.task_id,
        batch_identifier=base.batch_identifier,
        aggregation_parameter=base.aggregation_parameter,
        ord=base.ord,
        state=base.state,
        aggregate_share=field.encode_vec(merged) if merged is not None else None,
        report_count=base.report_count + add.report_count,
        checksum=checksum_combined(base.checksum, add.checksum),
        client_timestamp_interval=interval_merge(
            base.client_timestamp_interval, add.client_timestamp_interval
        ),
        aggregation_jobs_created=base.aggregation_jobs_created
        + add.aggregation_jobs_created,
        aggregation_jobs_terminated=base.aggregation_jobs_terminated
        + add.aggregation_jobs_terminated,
    )
