"""Collection job stepping (leader).

The analog of ``CollectionJobDriver`` (reference:
aggregator/src/aggregator/collection_job_driver.rs:43-650): a leased
collection job steps through a readiness gate (no unaggregated reports in
scope AND every started aggregation job terminated), marks the batch
Collected (writing empty fence shards so concurrent aggregation writers
fail fast), computes the leader share from the shard accumulators, applies
the differential-privacy hook, requests the helper's encrypted aggregate
share, and stores the Finished job.  Not-ready jobs are released with a
stepped retry delay (reference RetryStrategy :723-792).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import List, Optional

from ..core.report_id import checksum_combined
from ..core.retries import HttpRetryPolicy, retry_http_request
from ..datastore import (
    BatchAggregation,
    BatchAggregationState,
    CollectionJobState,
    Datastore,
    Lease,
)
from ..datastore.datastore import DatastoreError, DatastoreUnavailable
from ..datastore.query_type import strategy_for
from ..datastore.task import AggregatorTask
from ..messages import (
    AggregateShare,
    AggregateShareReq,
    BatchId,
    BatchSelector,
    Duration,
    Interval,
    ReportIdChecksum,
)
from .aggregate_share import compute_aggregate_share
from .aggregation_job_writer import merge_batch_aggregations
from .error import InvalidBatchSize

logger = logging.getLogger("janus_tpu.collection_job_driver")


# Strategy types live in core.dp (ZCdpDiscreteGaussian discrete-Gaussian
# noise + the no-op); re-exported here for compatibility with earlier API.
from ..core.dp import NoDifferentialPrivacy, dp_strategy_from_dict  # noqa: E402


@dataclass
class CollectionDriverConfig:
    maximum_attempts_before_failure: int = 10
    #: Uniform retryable-failure budget (mirrors DriverConfig
    #: .max_step_attempts): a failed helper exchange releases the lease
    #: with exponential backoff and abandons once lease_attempts reaches
    #: this, instead of redelivering forever.
    max_step_attempts: int = 10
    #: Readiness-poll backoff: a NOT-READY job (reports still
    #: aggregating) re-polls on this curve (reference RetryStrategy
    #: :723-792).  Distinct from the failure backoff below — polling an
    #: unready batch is normal operation, not a failure.
    retry_initial_delay: Duration = Duration(5)
    retry_max_delay: Duration = Duration(300)
    #: Retryable-FAILURE backoff (helper exchange failed): the
    #: aggregation driver's curve, shared via step_retry_delay.
    step_retry_initial_delay: Duration = Duration(1)
    step_retry_max_delay: Duration = Duration(300)
    http_retry: HttpRetryPolicy = field(default_factory=HttpRetryPolicy)
    #: shard layout for journal-replay share merges — must match the
    #: writers' batch_aggregation_shard_count
    batch_aggregation_shard_count: int = 8
    #: (Peer-health gating thresholds live on the PROCESS-WIDE tracker —
    #: see DriverConfig's note; binaries apply them once at startup.)


class CollectionJobDriver:
    def __init__(
        self,
        datastore: Datastore,
        session_factory,
        config: Optional[CollectionDriverConfig] = None,
        dp_strategy=None,
    ):
        self.datastore = datastore
        self._session_factory = session_factory
        self._session = None
        self.config = config or CollectionDriverConfig()
        # None => per-task dispatch from the VDAF instance's dp_strategy.
        self._dp_override = dp_strategy

    def _get_session(self):
        if self._session is None or self._session.closed:
            self._session = self._session_factory()
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    # ------------------------------------------------------------------
    async def step_collection_job(self, lease: Lease) -> None:
        """Stepper entry: runs the step, classifying a mid-step
        ``DatastoreUnavailable`` as brownout pressure — release with
        jittered backoff WITHOUT consuming the attempt budget, exactly
        the peer_unhealthy treatment (ISSUE 17 tentpole layer 3)."""
        try:
            await self._step_collection_job(lease)
        except DatastoreUnavailable as e:
            acq = lease.leased
            logger.warning(
                "datastore unavailable mid-step for collection job %s — "
                "releasing without consuming the attempt budget: %s",
                acq.collection_job_id,
                e,
            )
            try:
                await self._release_retryable(lease, peer_unhealthy=True)
            except DatastoreError:
                logger.warning(
                    "release of collection job %s failed too (datastore "
                    "still browned out); lease expiry redelivers it",
                    acq.collection_job_id,
                )

    async def _step_collection_job(self, lease: Lease) -> None:
        import time as _time

        t_step = _time.monotonic()
        acq = lease.leased
        if lease.lease_attempts > self.config.maximum_attempts_before_failure:
            # Entry-ceiling partition guard (shared classification with
            # the aggregation driver): a delivery count inflated by
            # clean peer-unhealthy releases must not abandon the job
            # while the peer is still unreachable — and within the heal
            # grace the job gets its post-heal delivery instead of an
            # entry abandonment.  Brownout excuse first (in-memory): a
            # datastore brownout inflates the count the same way.
            from ..core.db_health import tracker as db_tracker
            from .job_driver import heal_grace_s, peer_partition_state

            if db_tracker().brownout_signal(
                heal_grace_s(self.config.step_retry_max_delay.seconds)
            ):
                await self._release_retryable(lease, peer_unhealthy=True)
                return
            verdict = await peer_partition_state(
                self.datastore,
                acq.task_id,
                heal_grace_s(self.config.step_retry_max_delay.seconds),
            )
            if verdict == "suspect":
                await self._release_retryable(lease, peer_unhealthy=True)
                return
            if verdict != "healed":
                await self.abandon_collection_job(lease)
                return
            # healed: fall through — this delivery is the job's chance
        else:
            # Early peer gate (mirrors the aggregation driver's
            # _gate_peer): the helper exchange sits at the END of this
            # step, after the journal replay and the aggregate-share
            # recomputation — a suspect peer inside its dwell would
            # waste all of that per delivery.  Cheap: the in-memory
            # partition_signal short-circuits the task lookup in the
            # common no-partition case.
            from .job_driver import peer_partition_state as _pps

            if await _pps(self.datastore, acq.task_id, 0.0) == "suspect":
                await self._release_retryable(lease, peer_unhealthy=True)
                return

        # Guaranteed drain-before-collection: outstanding accumulator-
        # journal rows name FINISHED reports whose out shares are still
        # resident in some (possibly dead) replica's device accumulator.
        # Re-derive them on the bit-exact CPU oracle from the retained
        # report_aggregations payloads and merge them now — the readiness
        # gate below refuses to collect while any row is outstanding, so
        # an aggregate can never be computed without these shares.
        try:
            await self._replay_outstanding_journal(acq)
        except DatastoreUnavailable:
            # brownout, not a replay problem: classify at the wrapper
            # (release without consuming the budget)
            raise
        except Exception as e:
            logger.warning("accumulator journal replay failed: %s", e)
            await self._release_retryable(lease)
            return

        def tx1(tx):
            task = tx.get_aggregator_task(acq.task_id)
            job = tx.get_collection_job(
                acq.task_id, acq.collection_job_id, acq.query_type
            )
            if task is None or job is None:
                return None
            if job.state != CollectionJobState.START:
                tx.release_collection_job(lease)
                return None
            vdaf = task.vdaf_instance()
            if not self._ready(tx, task, job):
                # stepped retry delay (reference: :255-262, :723-792)
                attempts = tx.increment_collection_job_step_attempts(
                    acq.task_id, acq.collection_job_id
                )
                delay = min(
                    self.config.retry_initial_delay.seconds * (2 ** (attempts - 1)),
                    self.config.retry_max_delay.seconds,
                )
                tx.release_collection_job(lease, Duration(delay))
                return None
            # mark batch aggregations Collected + fence (reference: :283-316)
            strategy = strategy_for(task)
            for ident in strategy.batch_identifiers_for_collection_identifier(
                task, job.batch_identifier
            ):
                for ba in tx.get_batch_aggregations_for_batch(
                    acq.task_id, ident, job.aggregation_parameter
                ):
                    if ba.state == BatchAggregationState.AGGREGATING:
                        tx.update_batch_aggregation(
                            BatchAggregation(
                                task_id=ba.task_id,
                                batch_identifier=ba.batch_identifier,
                                aggregation_parameter=ba.aggregation_parameter,
                                ord=ba.ord,
                                state=BatchAggregationState.COLLECTED,
                                aggregate_share=ba.aggregate_share,
                                report_count=ba.report_count,
                                checksum=ba.checksum,
                                client_timestamp_interval=ba.client_timestamp_interval,
                                aggregation_jobs_created=ba.aggregation_jobs_created,
                                aggregation_jobs_terminated=ba.aggregation_jobs_terminated,
                            )
                        )
            share, count, checksum, interval = compute_aggregate_share(
                task, vdaf, tx, job.batch_identifier, job.aggregation_parameter
            )
            return task, job, vdaf, share, count, checksum, interval

        loaded = await self.datastore.run_tx_async("step_collection_job_1", tx1)
        if loaded is None:
            return
        task, job, vdaf, share, count, checksum, interval = loaded

        if share is None or count < task.min_batch_size:
            logger.warning(
                "collection job %s batch too small (%d < %d); abandoning",
                acq.collection_job_id,
                count,
                task.min_batch_size,
            )
            await self.abandon_collection_job(lease)
            return

        # DP noise (reference: :338-344 add_noise_to_agg_share): the
        # strategy comes from the task's VDAF instance description unless
        # the driver was constructed with an explicit override.
        strategy = self._dp_override or dp_strategy_from_dict(
            task.vdaf.get("dp_strategy")
        )
        share = strategy.add_noise_to_agg_share(vdaf, share, count)

        # request the helper's encrypted aggregate share (reference: :347-377)
        if task.query_type.kind == "TimeInterval":
            batch_selector = BatchSelector.new_time_interval(
                Interval.get_decoded(job.batch_identifier)
            )
        else:
            batch_selector = BatchSelector.new_fixed_size(
                BatchId.get_decoded(job.batch_identifier)
            )
        req = AggregateShareReq(
            batch_selector=batch_selector,
            aggregation_parameter=job.aggregation_parameter,
            report_count=count,
            checksum=checksum,
        )
        url = (
            task.peer_aggregator_endpoint.rstrip("/")
            + f"/tasks/{task.task_id}/aggregate_shares"
        )
        # Peer-health gate (ISSUE 11): a suspect helper inside its dwell
        # means this exchange is doomed — release with backoff without
        # burning the attempt (and without consuming the failure budget).
        from ..core import peer_health
        from ..core.retries import is_transport_error

        tracker = peer_health.tracker()
        if not tracker.allow(url):
            logger.warning(
                "peer %s is suspect; releasing collection job without an "
                "attempt",
                peer_health.origin_of(url),
            )
            await self._release_retryable(lease, peer_unhealthy=True)
            return
        headers = {"Content-Type": AggregateShareReq.MEDIA_TYPE}
        if task.aggregator_auth_token is not None:
            name, value = task.aggregator_auth_token.request_authentication()
            headers[name] = value
        from ..core.trace import inject_traceparent

        inject_traceparent(headers)
        # lease-derived deadline: a blackholed helper must hand the step
        # back in time to RELEASE the lease, never leave it to the reaper
        from .job_driver import helper_request_deadline

        deadline = helper_request_deadline(lease, self.datastore)
        try:
            status, body, _ = await retry_http_request(
                self._get_session(),
                "POST",
                url,
                data=req.get_encoded(),
                headers=headers,
                policy=self.config.http_retry,
                deadline=deadline,
            )
        except Exception as e:
            logger.warning("helper aggregate-share request failed: %s", e)
            await self._release_retryable(
                lease,
                peer_unhealthy=is_transport_error(e) and tracker.is_suspect(url),
            )
            return
        if status >= 400:
            logger.warning("helper aggregate-share returned %d", status)
            await self._release_retryable(lease)
            return
        helper_share = AggregateShare.get_decoded(body)

        # Chaos seam (ISSUE 20): the canary's wrong-answer fence.  A
        # corrupt-mode spec on this point mangles the encoded leader
        # aggregate share right before it is sealed into the finished
        # job — a fault no transport/lease/health signal can see; only a
        # known-plaintext probe verifying the collected sum catches it.
        from ..core import faults

        leader_share_bytes = faults.corrupt_bytes(
            "collection.aggregate_share",
            vdaf.field_for_agg_param(
                vdaf.decode_agg_param(job.aggregation_parameter)
            ).encode_vec(share),
            target=str(task.task_id),
        )
        finished = job.finished(
            report_count=count,
            client_timestamp_interval=interval,
            leader_aggregate_share=leader_share_bytes,
            helper_aggregate_share=helper_share.encrypted_aggregate_share,
        )

        def tx2(tx):
            tx.update_collection_job(finished)
            # scrub batch aggregations (reference: :380-463)
            strategy = strategy_for(task)
            for ident in strategy.batch_identifiers_for_collection_identifier(
                task, job.batch_identifier
            ):
                for ba in tx.get_batch_aggregations_for_batch(
                    task.task_id, ident, job.aggregation_parameter
                ):
                    if ba.state == BatchAggregationState.COLLECTED:
                        tx.update_batch_aggregation(ba.scrubbed())
            tx.release_collection_job(lease)

        await self.datastore.run_tx_async("step_collection_job_2", tx2)

        # Pipeline-freshness SLO: end-to-end age of the collected batch —
        # collection finish minus its earliest client timestamp, the "how
        # old is a report by the time it lands in an aggregate" histogram.
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None and interval is not None:
            GLOBAL_METRICS.collection_e2e.observe(
                max(0.0, float(self.datastore.now().seconds - interval.start.seconds))
            )

        # Trace LINK point (ISSUE 9): close the merged timeline's far end.
        # The collection-finish span links the collected reports' upload
        # trace ids (persisted on client_reports; they survive scrubbing),
        # so trace_merge stitches client ingress -> prepare -> collection
        # into ONE critical path even though the collection job's own
        # trace id was minted independently of any upload's.
        collected_batch_id = (
            BatchId.get_decoded(job.batch_identifier)
            if task.query_type.kind != "TimeInterval"
            else None
        )
        await self._emit_collection_finish_span(
            task, interval, collected_batch_id, count, t_step
        )

    # ------------------------------------------------------------------
    async def _emit_collection_finish_span(
        self, task, interval, batch_id, report_count, t_step
    ) -> None:
        """Emit the collection-finish span with upload-trace links;
        failure-tolerant and bounded (at most 512 linked ids) — tracing
        must never fail a finished collection, and with no span consumer
        active (no chrome tracer, no OTLP sink) the link query is skipped
        entirely: the collection hot path pays nothing for tracing that
        is off.  Linked ids come from the reports AGGREGATED into this
        batch (``batch_id`` scopes fixed-size tasks), so overlapping
        collections never chain-merge each other's traces."""
        import time as _time

        from ..core.trace import emit_span, tracing_active

        if (interval is None and batch_id is None) or not tracing_active():
            return
        try:
            trace_ids = await self.datastore.run_tx_async(
                "collect_trace_links",
                lambda tx: tx.get_aggregated_report_trace_ids(
                    task.task_id,
                    interval=interval if batch_id is None else None,
                    batch_id=batch_id,
                    limit=512,
                ),
            )
        except Exception:
            logger.exception("collection trace-link lookup failed")
            trace_ids = []
        emit_span(
            "collection_finish",
            "collection",
            t_step,
            _time.monotonic() - t_step,
            task_id=str(task.task_id),
            reports=report_count,
            links=trace_ids,
        )

    # ------------------------------------------------------------------
    async def _replay_outstanding_journal(self, acq) -> None:
        """Consume every accumulator-journal row covering this collection's
        batches: oracle-recompute the named reports' out shares from their
        retained report_aggregations payloads and merge ONE vector per row
        into the batch's shard accumulators.  Row deletion and the merge
        share a transaction, so a row is merged exactly once even when the
        owning replica's cadence drain races this replay (the loser of the
        DELETE drops its vector)."""
        # cheap pre-check first: in the common (non-deferred) deployment
        # the journal is always empty, and this one indexed COUNT is all
        # the hot path pays — the task/job reload below runs only when
        # there is actually something to replay
        if not await self.datastore.run_tx_async(
            "collect_journal_probe",
            lambda tx: tx.count_accumulator_journal_entries(acq.task_id),
        ):
            return

        def load(tx):
            task = tx.get_aggregator_task(acq.task_id)
            job = tx.get_collection_job(
                acq.task_id, acq.collection_job_id, acq.query_type
            )
            if task is None or job is None:
                return None
            strategy = strategy_for(task)
            entries = []
            for ident in strategy.batch_identifiers_for_collection_identifier(
                task, job.batch_identifier
            ):
                entries.extend(
                    e
                    for e in tx.get_accumulator_journal_entries(acq.task_id, ident)
                    if e.aggregation_parameter == job.aggregation_parameter
                )
            return task, entries

        loaded = await self.datastore.run_tx_async("collect_journal_scan", load)
        if loaded is None or not loaded[1]:
            return
        task, entries = loaded
        vdaf = task.vdaf_instance()
        for entry in entries:
            await self._replay_journal_entry(task, vdaf, entry)

    async def _replay_journal_entry(self, task, vdaf, entry) -> None:
        from ..core import faults
        from ..vdaf.backend import OracleBackend
        from .aggregation_job_writer import merge_share_delta

        await faults.fire_async("accumulator.replay")
        ras = await self.datastore.run_tx_async(
            "replay_load_ras",
            lambda tx: tx.get_report_aggregations_for_aggregation_job(
                task.task_id, entry.aggregation_job_id
            ),
        )
        by_rid = {ra.report_id.data: ra for ra in ras}
        rows = []
        for rid in entry.report_ids:
            ra = by_rid.get(rid)
            if ra is None or ra.leader_input_share is None:
                # the replay window was violated (payload scrubbed or row
                # GC'd under an outstanding journal entry) — shares are
                # unrecoverable; fail LOUDLY, never silently drop
                raise RuntimeError(
                    f"journal entry for job {entry.aggregation_job_id} names "
                    f"report {rid.hex()} without a replayable payload"
                )
            rows.append(ra)
        agg_param = vdaf.decode_agg_param(entry.aggregation_parameter)
        field = vdaf.field_for_agg_param(agg_param)

        def recompute():
            prep_in = [
                (
                    ra.report_id.data,
                    vdaf.decode_public_share(ra.public_share or b""),
                    vdaf.decode_input_share(0, ra.leader_input_share),
                )
                for ra in rows
            ]
            if getattr(vdaf, "REQUIRES_AGG_PARAM", False):
                # Agg-param VDAFs (Poplar1): replay at the journal row's
                # OWN parameter — the row carries it precisely so two tree
                # levels can never cross — re-walking each report's IDPF
                # share and summing the prefix-value vectors the FINISHED
                # verdict already vouched for (the sketch verified before
                # the row was journaled).
                total = None
                for nonce, public, share in prep_in:
                    state, _sh = vdaf.prep_init(
                        task.vdaf_verify_key, 0, agg_param, nonce, public, share
                    )
                    out = list(state.y_flat)
                    total = out if total is None else field.vec_add(total, out)
                return total
            oracle = OracleBackend(vdaf)
            total = None
            for outcome in oracle.prep_init_batch(
                task.vdaf_verify_key, 0, prep_in
            ):
                if not isinstance(outcome, tuple):
                    # a report that already prepared successfully cannot
                    # re-reject on the bit-exact oracle; treat as data loss
                    raise RuntimeError(f"oracle replay rejected a report: {outcome}")
                state, _share = outcome
                total = (
                    list(state.out_share)
                    if total is None
                    else field.vec_add(total, state.out_share)
                )
            return total

        # task cost scope (core/costs.py): the crash-recovery replay's CPU
        # time attributes to the task with path="oracle" via the oracle's
        # _observe_prepare hook
        from ..core import costs

        total = await asyncio.get_running_loop().run_in_executor(
            None,
            lambda: costs.run_in_task_scope(task.task_id.data, recompute),
        )

        def tx_fn(tx):
            # exactly-once hinges on the DELETE: whoever consumes the row
            # merges the shares, in the same transaction
            if not tx.delete_accumulator_journal_entry(
                task.task_id,
                entry.batch_identifier,
                entry.aggregation_parameter,
                entry.aggregation_job_id,
            ):
                return False
            if total is not None:
                merge_share_delta(
                    tx,
                    task,
                    field,
                    entry.batch_identifier,
                    entry.aggregation_parameter,
                    total,
                    shard_count=self.config.batch_aggregation_shard_count,
                )
            return True

        merged = await self.datastore.run_tx_async("journal_replay", tx_fn)
        if merged:
            logger.warning(
                "oracle-replayed %d report(s) of job %s from the datastore "
                "journal (owner never drained — crashed or raced)",
                len(entry.report_ids),
                entry.aggregation_job_id,
            )
            from ..core.metrics import GLOBAL_METRICS

            if GLOBAL_METRICS.registry is not None:
                GLOBAL_METRICS.accumulator_journal_consumed.labels(
                    path="replay"
                ).inc()

    # ------------------------------------------------------------------
    async def _release_retryable(
        self, lease: Lease, peer_unhealthy: bool = False
    ) -> None:
        """Retryable-failure budget + exponential lease-backoff (the
        aggregation driver's curve, shared via step_retry_delay): release
        for redelivery, or abandon once the budget is spent.  Partition
        pressure (``peer_unhealthy`` — the peer-health tracker has the
        helper suspect) never consumes the budget: the job releases with
        jittered backoff for as long as the partition lasts."""
        from ..core.db_health import tracker as db_tracker
        from .job_driver import (
            heal_grace_s,
            partition_excused,
            step_retry_delay,
        )

        if (
            lease.lease_attempts >= self.config.max_step_attempts
            and not peer_unhealthy
            # attempts inflated by a datastore brownout are the
            # database's doing (in-memory check, evaluated first)
            and not db_tracker().brownout_signal(
                heal_grace_s(self.config.step_retry_max_delay.seconds)
            )
            # attempts inflated by a partition must not abandon the
            # post-heal delivery on its first ordinary hiccup
            and not await partition_excused(
                self.datastore,
                lease.leased.task_id,
                self.config.step_retry_max_delay.seconds,
            )
        ):
            logger.error(
                "collection step failure exhausted its %d-attempt budget; "
                "abandoning",
                self.config.max_step_attempts,
            )
            await self.abandon_collection_job(lease)
            return
        delay = step_retry_delay(
            lease.lease_attempts,
            self.config.step_retry_initial_delay.seconds,
            self.config.step_retry_max_delay.seconds,
            # per-job jitter: heal-time reacquisitions spread out instead
            # of thundering-herding the freshly recovered helper
            jitter_key=lease.leased.collection_job_id.data,
        )
        await self.datastore.run_tx_async(
            "release_coll_job", lambda tx: tx.release_collection_job(lease, delay)
        )

    def _ready(self, tx, task: AggregatorTask, job) -> bool:
        """Readiness gate (reference: :124-262): no unaggregated reports in
        scope and all created aggregation jobs terminated."""
        vdaf = task.vdaf_instance()
        if task.query_type.kind == "TimeInterval" and not getattr(
            vdaf, "REQUIRES_AGG_PARAM", False
        ):
            # agg-param VDAFs never mark reports aggregated (they are reused
            # across levels); their jobs are all created with the collection
            # request, so created==terminated alone gates readiness
            interval = Interval.get_decoded(job.batch_identifier)
            if tx.count_unaggregated_client_reports_for_interval(
                task.task_id, interval
            ):
                return False
        strategy = strategy_for(task)
        for ident in strategy.batch_identifiers_for_collection_identifier(
            task, job.batch_identifier
        ):
            # Deferred-drain fence: an outstanding accumulator-journal row
            # means counted reports whose shares are not yet merged —
            # collecting now would compute a wrong aggregate.  The
            # pre-step replay consumes these; re-checking INSIDE the
            # readiness transaction closes the race with a job committing
            # a new row between the replay and this step.
            if tx.count_accumulator_journal_entries_for_batch(
                task.task_id, ident, job.aggregation_parameter
            ):
                return False
            # counters are sharded: a job's created/terminated increments may
            # land on different shards, so compare per-batch sums
            # (reference: models.rs:1421 counters summed over shards)
            created = terminated = 0
            for ba in tx.get_batch_aggregations_for_batch(
                task.task_id, ident, job.aggregation_parameter
            ):
                created += ba.aggregation_jobs_created
                terminated += ba.aggregation_jobs_terminated
            if created != terminated:
                return False
        return True

    async def abandon_collection_job(self, lease: Lease) -> None:
        """reference: :568-629"""
        acq = lease.leased

        def tx_fn(tx):
            job = tx.get_collection_job(
                acq.task_id, acq.collection_job_id, acq.query_type
            )
            if job is not None and job.state == CollectionJobState.START:
                tx.update_collection_job(job.with_state(CollectionJobState.ABANDONED))
            tx.release_collection_job(lease)

        await self.datastore.run_tx_async("abandon_collection_job", tx_fn)
