"""Device executor: process-wide continuous cross-job batching.

See service.py for the design.  Importing this package does NOT import
jax — control-plane processes can hold an ExecutorConfig (and the
overload / circuit-breaker error types for retry classification) without
pulling in the device stack.
"""

from .service import (
    CircuitBreaker,
    CircuitOpenError,
    DeviceExecutor,
    ExecutorConfig,
    ExecutorOverloadedError,
    bucket_label,
    get_global_executor,
    reset_global_executor,
    shape_label,
)

__all__ = [
    "CircuitBreaker",
    "CircuitOpenError",
    "DeviceExecutor",
    "ExecutorConfig",
    "ExecutorOverloadedError",
    "bucket_label",
    "get_global_executor",
    "reset_global_executor",
    "shape_label",
]
