"""Device executor: process-wide continuous cross-job batching.

See service.py for the design.  Importing this package does NOT import
jax — control-plane processes can hold an ExecutorConfig (and the
overload / circuit-breaker error types for retry classification) without
pulling in the device stack.
"""

from .accumulator import (
    AccumulatorConfig,
    AccumulatorError,
    AccumulatorUnavailable,
    DeviceAccumulatorStore,
    ResidentRef,
    StaleAccumulatorDelta,
)
from .service import (
    KIND_COMBINE,
    KIND_POPLAR_INIT,
    KIND_PREP_INIT,
    CircuitBreaker,
    CircuitOpenError,
    DeviceExecutor,
    ExecutorConfig,
    ExecutorOverloadedError,
    bucket_label,
    get_global_executor,
    peek_global_executor,
    reset_global_executor,
    shape_label,
)

__all__ = [
    "AccumulatorConfig",
    "AccumulatorError",
    "AccumulatorUnavailable",
    "CircuitBreaker",
    "CircuitOpenError",
    "DeviceAccumulatorStore",
    "DeviceExecutor",
    "ExecutorConfig",
    "ExecutorOverloadedError",
    "KIND_COMBINE",
    "KIND_POPLAR_INIT",
    "KIND_PREP_INIT",
    "ResidentRef",
    "StaleAccumulatorDelta",
    "bucket_label",
    "get_global_executor",
    "peek_global_executor",
    "reset_global_executor",
    "shape_label",
]
