"""Process-wide device execution service: continuous cross-job batching.

The chip is one wide pipeline; N concurrent aggregation jobs must not
carve it into N narrow, contending ones.  Today each driver step
coalesces only the jobs that happen to land inside its own gather window
(aggregation_job_driver._coalesced_prep_init), so 16 concurrent tasks
still issue many small launches and re-pay dispatch overhead per driver.
This module is the scheduling layer between the protocol logic and the
kernel pool — shaped like an inference-serving continuous batcher:

* ``submit(shape_key, kind, payload) -> result``: every driver (and any
  other producer of prepare work) enqueues into a process-wide service
  that owns the device.
* **Bucketed continuous batching**: submissions are grouped per
  ``(vdaf_shape_key, kind, agg_id, agg_param_key)`` bucket and flushed as
  ONE pow2-padded mega-batch when the bucket reaches ``flush_max_rows``
  or its ``flush_window_s`` deadline expires — whichever comes first.
  The agg-param key is an OPAQUE per-VDAF discriminant of the submission's
  aggregation parameter: Prio3 (no parameter) passes None, Poplar1 passes
  its IDPF tree level — so multi-round heavy-hitter rounds from different
  jobs at the SAME level coalesce into one bulk-AES walk + device sketch
  mega-batch, while two levels of one task can never share a bucket.
* **Compiled-executable cache + warmup**: backends are shape-keyed and
  shared by every submitter, so one compiled graph serves all tasks;
  ``warmup_backend`` precompiles the configured mega-batch shapes before
  traffic arrives (startup, not first-request, pays the compile).
* **Double-buffered host->device staging**: marshal/device_put runs on a
  dedicated staging thread while the previous mega-batch's launch
  occupies the chip (stage k+1 overlaps launch k).
* **Backpressure**: per-bucket queue depth is bounded; a submission that
  would exceed it — or whose deadline expires while queued — is rejected
  with ExecutorOverloadedError, which callers surface as a retryable
  JobStepError (the lease machinery redelivers the job).

Results are byte-identical to per-job launches: the mega-batch is the
same concatenation ``TpuBackend.prep_init_multi`` already performs, with
per-row verify keys (tests/test_multitask.py asserts oracle parity under
concurrent submission).
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..core import costs, faults
from ..core.trace import current_trace, emit_span
from .flight_recorder import FlightRecorder

logger = logging.getLogger("janus_tpu.executor")

#: Submission kinds (the "phase" of the bucket key).
KIND_PREP_INIT = "prep_init"
KIND_COMBINE = "combine"
#: Poplar1 heavy-hitters round-0 prepare: payload is (verify_key,
#: agg_param, reports) and the flush runs ONE bulk-AES IDPF walk + device
#: sketch for every submission in the bucket
#: (Poplar1Backend.prep_init_multi_poplar).  Buckets of this kind carry an
#: agg-param key (the tree LEVEL), so different jobs at one level coalesce
#: while levels never share a mega-batch.
KIND_POPLAR_INIT = "poplar_init"


class ExecutorOverloadedError(Exception):
    """Bounded-queue or deadline rejection.

    Retryable by construction: the report rows are still leased in the
    datastore, so the caller maps this to JobStepError(retryable=True)
    and the job is redelivered when the device catches up.
    """


class CircuitOpenError(Exception):
    """The shape's device circuit is open: K consecutive launches failed
    and the breaker has not yet half-open-probed its way back.

    NOT a retryable-overload signal — the device is sick, not busy.  The
    caller's contract is graceful degradation: serve the submission on
    the bit-exact CPU oracle instead (AggregationJobDriver does), so
    aggregation keeps running while the breaker probes for recovery.
    """


#: Circuit states (exported via the janus_executor_circuit_state gauge).
CIRCUIT_CLOSED, CIRCUIT_OPEN, CIRCUIT_HALF_OPEN = 0, 1, 2
_CIRCUIT_STATE_NAMES = {0: "closed", 1: "open", 2: "half_open"}


@dataclass
class ExecutorConfig:
    """Tuning knobs; defaults favor throughput at ~5 ms added latency."""

    enabled: bool = False
    #: Mesh-sharded mega-batches: upgrade every single-chip TpuBackend
    #: this executor caches to the SPMD MeshBackend over the local mesh
    #: (vdaf/backend.py), so staging lands each mega-batch's shards
    #: directly on their chips.  Equivalent to configuring
    #: ``vdaf_backend: mesh`` on every producer; oracle/hybrid/Poplar1
    #: backends pass through untouched.
    mesh: bool = False
    #: flush a bucket as soon as it holds this many rows
    flush_max_rows: int = 16384
    #: deadline from a bucket's first pending submission to its flush
    flush_window_s: float = 0.005
    #: per-bucket bound on queued + in-flight rows; beyond it, submit rejects
    max_queue_rows: int = 131072
    #: default per-submission deadline (queued past it -> rejected);
    #: <= 0 disables deadline rejection
    submit_timeout_s: float = 30.0
    #: pow2 mega-batch size warmup compiles per (backend, agg_id); 0 = off
    warmup_rows: int = 0
    #: run warmup compiles on a dedicated background thread (default) so
    #: backend_for — and therefore the submit path and binary startup —
    #: never blocks behind XLA; while a shape is WARMING, producers route
    #: its submissions to the CPU oracle (or wait on the warm future),
    #: and the breaker never sees the compile.  False = legacy inline
    #: warmup (the first resolver pays the compile synchronously).
    warmup_async: bool = True
    #: pow2 shape canonicalization (vdaf/canonical.py): producers key
    #: device backends by the CANONICAL shape so N task shapes share
    #: O(log N) compiled executables; shapes whose bit-exactness
    #: preconditions fail keep exact-shape compiles.  Read by the job
    #: drivers and the helper aggregator at backend resolution.
    canonical_shapes: bool = True
    #: consecutive launch failures per VDAF shape before its circuit
    #: opens (submits raise CircuitOpenError -> oracle fallback); 0 = off
    breaker_failure_threshold: int = 5
    #: how long an open circuit waits before letting one half-open probe
    #: launch through to test the device
    breaker_reset_timeout_s: float = 30.0
    #: starvation-free flush scheduling: ready flushes dispatch in deficit
    #: round-robin across buckets (deadline-earliest within a bucket)
    #: instead of arrival order, so one hot bucket cannot monopolize the
    #: chip while others hold pending work.  False = legacy FIFO.
    fair_flush: bool = True
    #: deficit-round-robin quantum (rows a bucket may flush per scheduling
    #: round before yielding); a flush larger than the quantum still
    #: dispatches, paying the overshoot out of future rounds
    fair_quota_rows: int = 16384
    #: flight recorder ring size (per-flush records kept in memory for
    #: /statusz "flights" + breaker-trip/slow-flush dumps); >= 1
    flight_recorder_size: int = 256
    #: slow-flush anomaly threshold: a flush whose launch exceeds this
    #: factor × its bucket's rolling p95 dumps the flight ring (rate
    #: limited); <= 0 disables the detector (ring + breaker dumps stay on)
    slow_flush_p95_factor: float = 4.0
    #: device-resident accumulator store (accumulator.AccumulatorConfig);
    #: None or .enabled=False = out shares read back per flush (legacy)
    accumulator: Optional[object] = None
    #: batch bisection quarantine (ISSUE 19): a NON-injected batch-level
    #: launch failure retries the cohort in halves (core/quarantine.py) to
    #: isolate poison rows — healthy rows resolve normally, offenders get
    #: in-band VdafError outcomes and land in the quarantine ledger.  A
    #: poison report costs O(log B) extra passes once, never a wedged
    #: pipeline or a permanently-tripped breaker.  False = legacy fail-all.
    bisection_enabled: bool = True
    #: per-report retry-charge cap during a bisection sieve; a range whose
    #: most-charged row hits the budget is quarantined wholesale
    bisection_per_item_budget: int = 16
    #: repeated NON-injected device failures confined to ONE shape while
    #: another shape on the same breaker domain stays healthy quarantine
    #: that shape bucket to the CPU oracle instead of opening the shared
    #: (mesh-wide) breaker — blast-radius reduction; 0 = off
    bucket_quarantine_threshold: int = 2
    #: how long a quarantined shape bucket routes to the oracle before
    #: device submissions flow again
    bucket_quarantine_s: float = 60.0
    #: a failing shape only quarantines (vs counting against the breaker)
    #: when ANOTHER shape on its breaker domain succeeded within this
    #: window — the proof the mesh itself is healthy
    bucket_quarantine_success_window_s: float = 30.0


class CircuitBreaker:
    """Per-shape-key device health: closed -> (K consecutive launch
    failures) -> open -> (reset timeout) -> half-open, one probe in
    flight -> closed on success, straight back to open on failure.

    Thread-safe: allow() runs on submitter event loops, record_*() on
    flush tasks / the launch thread.
    """

    def __init__(
        self, label: str, failure_threshold: int, reset_timeout_s: float, on_trip=None
    ):
        self.label = label
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.state = CIRCUIT_CLOSED
        self.consecutive_failures = 0
        self.trips = 0
        self._opened_at = 0.0
        self._probing = False
        self._lock = threading.Lock()
        #: called as on_trip(breaker) AFTER the lock is released, once per
        #: closed/half-open -> open transition (the executor hangs the
        #: flight-recorder dump here); exceptions are swallowed — a broken
        #: observer must never keep a sick circuit from opening
        self.on_trip = on_trip

    def allow(self) -> bool:
        """May a new submission enter the device path right now?"""
        if self.failure_threshold <= 0:
            return True
        with self._lock:
            if self.state == CIRCUIT_CLOSED:
                return True
            if self.state == CIRCUIT_OPEN:
                if time.monotonic() - self._opened_at < self.reset_timeout_s:
                    return False
                self._set_state(CIRCUIT_HALF_OPEN)
                self._probing = True
                return True
            # HALF_OPEN: exactly one probe in flight at a time
            if self._probing:
                return False
            self._probing = True
            return True

    def probe_aborted(self) -> None:
        """A flush resolved without touching the device (every submission
        expired in queue): no health signal either way, but the probe slot
        must free up or a half-open breaker wedges."""
        with self._lock:
            self._probing = False

    def is_open_peek(self) -> bool:
        """Side-effect-free open check: True while the circuit is open and
        still inside its reset dwell.  Returns False once the dwell has
        elapsed so the next real submission runs the half-open probe (the
        dwell test mirrors allow(); keep them together)."""
        with self._lock:
            return self.state == CIRCUIT_OPEN and (
                time.monotonic() - self._opened_at < self.reset_timeout_s
            )

    def record_success(self) -> None:
        with self._lock:
            self.consecutive_failures = 0
            self._probing = False
            if self.state != CIRCUIT_CLOSED:
                logger.info("device circuit %s closed (probe succeeded)", self.label)
                self._set_state(CIRCUIT_CLOSED)

    def record_failure(self) -> None:
        if self.failure_threshold <= 0:
            return
        with self._lock:
            self.consecutive_failures += 1
            self._probing = False
            should_open = self.state == CIRCUIT_HALF_OPEN or (
                self.state == CIRCUIT_CLOSED
                and self.consecutive_failures >= self.failure_threshold
            )
            if should_open or self.state == CIRCUIT_OPEN:
                self._opened_at = time.monotonic()
            if should_open:
                self.trips += 1
                logger.warning(
                    "device circuit %s OPEN after %d consecutive launch "
                    "failure(s); falling back to the CPU oracle for %.1fs",
                    self.label,
                    self.consecutive_failures,
                    self.reset_timeout_s,
                )
                self._set_state(CIRCUIT_OPEN)
        if should_open and self.on_trip is not None:
            try:
                self.on_trip(self)
            except Exception:
                logger.exception("circuit on_trip observer failed")

    def _set_state(self, state: int) -> None:
        """Lock held.  Metrics are best-effort (no registry -> no-op)."""
        self.state = state
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.circuit_state.labels(circuit=self.label).set(state)
            GLOBAL_METRICS.circuit_transitions.labels(
                circuit=self.label, state=_CIRCUIT_STATE_NAMES[state]
            ).inc()


@dataclass
class _Submission:
    payload: object
    rows: int
    future: asyncio.Future
    loop: asyncio.AbstractEventLoop
    enqueued: float
    deadline: Optional[float]
    #: set by _finish (under the executor lock) so depth accounting is
    #: idempotent across the flush's normal/reject/exception paths
    finished: bool = False
    #: caller opted into device-resident out shares (accumulator store):
    #: the flush keeps the out-share matrix on device and hands back
    #: ResidentRefs instead of limb vectors
    retain: bool = False
    #: task identity (drivers pass the DAP task id): the per-task DRR
    #: accounting domain WITHIN a bucket — tasks sharing one VDAF shape
    #: share its bucket but not its quantum, so one hot task cannot
    #: starve its shape-mates.  None = unattributed (legacy callers).
    task: Optional[object] = None
    #: submitter's trace context (trace_id/task_id/job_id), captured at
    #: submit time so the flush can emit per-submission child spans — a
    #: job's merged timeline shows its share of each mega-batch flush
    trace_ctx: Optional[dict] = None


class _Bucket:
    """Pending submissions for one (shape_key, kind, agg_id)."""

    def __init__(
        self, key: tuple, backend, kind: str, agg_id: int, label: str, breaker=None
    ):
        self.key = key
        self.backend = backend
        self.kind = kind
        self.agg_id = agg_id
        self.label = label
        #: shared per-shape CircuitBreaker (None when breakers are off)
        self.breaker = breaker
        self.pending: List[_Submission] = []
        self.queued_rows = 0
        self.inflight_rows = 0
        self.timer: Optional[asyncio.TimerHandle] = None
        # plain-Python stats (usable without prometheus; bench reads these)
        self.flushes = 0
        self.flushed_rows = 0
        self.flushed_jobs = 0
        self.rejections = 0
        #: last submit/flush touch — retire_idle_buckets() reaps buckets
        #: idle past the threshold and removes their gauge label sets
        self.last_activity = time.monotonic()

    @property
    def depth_rows(self) -> int:
        return self.queued_rows + self.inflight_rows

    def mean_flush_rows(self) -> float:
        return self.flushed_rows / self.flushes if self.flushes else 0.0


def bucket_label(
    backend, kind: str, agg_id: int, shape_key: tuple = None, agg_param_key=None
) -> str:
    """Compact metric label: circuit/aggregator-side/phase[/level].

    ``shape_key`` appends a stable digest so two parameterizations of the
    same circuit (e.g. Histogram length=4 vs length=1024) never share a
    label — stats() and the per-bucket gauges key on it.  ``agg_param_key``
    (agg-param VDAFs: Poplar1 passes its tree level) renders as an ``L{k}``
    segment so an operator reading /statusz or the ``janus_executor_*``
    series can tell which LEVEL of a heavy-hitters run a bucket serves."""
    vdaf = getattr(backend, "vdaf", None)
    valid = getattr(getattr(vdaf, "flp", None), "valid", None)
    circuit = type(valid).__name__ if valid is not None else type(vdaf).__name__
    label = f"{circuit}/a{agg_id}/{kind}"
    if agg_param_key is not None:
        label += f"/L{agg_param_key}"
    if shape_key is not None:
        label += "#" + _shape_digest(shape_key)
    return label


def _shape_digest(shape_key: tuple) -> str:
    import zlib

    return "%06x" % (zlib.crc32(repr(shape_key).encode()) & 0xFFFFFF)


def shape_label(backend, shape_key: tuple) -> str:
    """Per-shape label (no kind/agg_id): the circuit breaker's identity."""
    vdaf = getattr(backend, "vdaf", None)
    valid = getattr(getattr(vdaf, "flp", None), "valid", None)
    circuit = type(valid).__name__ if valid is not None else type(vdaf).__name__
    return f"{circuit}#{_shape_digest(shape_key)}"


def breaker_domain(shape_key: tuple, backend):
    """The breaker's failure unit: the MESH for mesh backends (its device
    set — one circuit per mesh, shared by every shape launching on it),
    the VDAF shape otherwise."""
    mesh = getattr(backend, "mesh", None)
    if mesh is not None:
        return ("mesh", tuple(str(d) for d in mesh.devices.flat))
    return shape_key


def mesh_label(backend) -> str:
    """Per-mesh breaker label: device count + a stable device-set digest."""
    devs = tuple(str(d) for d in backend.mesh.devices.flat)
    return "mesh[%d]#%s" % (len(devs), _shape_digest(devs))


class DeviceExecutor:
    """The continuous batcher.  One per process (get_global_executor)."""

    def __init__(self, config: Optional[ExecutorConfig] = None):
        self.config = config or ExecutorConfig()
        self._buckets: Dict[tuple, _Bucket] = {}
        self._backends: Dict[tuple, object] = {}
        #: breaker DOMAIN -> breaker.  The domain is the failure unit: the
        #: VDAF shape for single-chip backends, the MESH for mesh backends
        #: (losing a device sickens every shape launching on that mesh, so
        #: they must share one circuit — breaker-per-mesh, not per-process
        #: and not per-shape).
        self._breakers: Dict[object, CircuitBreaker] = {}
        #: shape_key -> its domain's breaker (the circuit_open peek index)
        self._breaker_by_shape: Dict[tuple, CircuitBreaker] = {}
        #: domain -> shape_keys referencing it (retirement bookkeeping)
        self._breaker_shapes: Dict[object, set] = {}
        self._lock = threading.Lock()
        self._stage_pool: Optional[ThreadPoolExecutor] = None
        self._launch_pool: Optional[ThreadPoolExecutor] = None
        #: one dedicated compile thread: warmups serialize (XLA compiles
        #: are CPU-heavy; two at once just slow each other down) and never
        #: touch the stage/launch pools that serve live traffic
        self._warmup_pool: Optional[ThreadPoolExecutor] = None
        #: shape_key -> {state: cold|warming|warm|failed, compile_s,
        #: error, future} — the per-shape compile ledger behind
        #: warming()/wait_warm()/compile_stats() (/statusz surfaces it)
        self._warmup_state: Dict[tuple, dict] = {}
        # Strong refs to in-flight flush tasks: the event loop holds tasks
        # weakly, and a GC'd flush would strand its detached submissions.
        self._flush_tasks: set = set()
        self._closed = False
        # Fair flush scheduler state: per-loop ready queues of detached
        # flushes, dispatched deficit-round-robin across buckets.
        self._ready: Dict[object, Dict[tuple, list]] = {}
        self._ready_seq = 0
        self._rr_cursor: Dict[object, int] = {}
        self._deficit: Dict[tuple, float] = {}
        #: per-(bucket, task) deficit tabs: fairness WITHIN a bucket, so
        #: tasks sharing one VDAF shape cannot starve each other (the
        #: bucket-level tab above keeps fairness ACROSS buckets)
        self._task_deficit: Dict[tuple, float] = {}
        self._dispatchers: Dict[object, object] = {}
        self._slots: Dict[object, asyncio.Semaphore] = {}
        #: dispatched-but-unfinished flushes per loop: the loop's slot
        #: semaphore may only be pruned when this reaches zero, or a new
        #: dispatcher generation would mint fresh permits and break the
        #: two-in-flight double-buffering bound
        self._slot_inflight: Dict[object, int] = {}
        #: per-flush black box (flight_recorder.py): /statusz "flights",
        #: breaker-trip dumps, slow-flush anomaly dumps
        self.flight_recorder = FlightRecorder(
            size=self.config.flight_recorder_size,
            slow_flush_p95_factor=self.config.slow_flush_p95_factor,
        )
        # Device-resident accumulator store (out-share residency).
        acc_cfg = self.config.accumulator
        self.accumulator = None
        #: durable spill target for shutdown(drain=True): called as
        #: sink(bucket_key, vector, journal_entries); registered by the
        #: component that can write the datastore (the job driver).  None
        #: means there is nowhere durable to spill — shutdown falls back
        #: to the logged discard (redelivery / journal replay re-derives).
        self._spill_sink = None
        #: blast-radius quarantine (ISSUE 19): shape_key -> quarantine
        #: expiry (monotonic).  While set, circuit_open() peeks True and
        #: submit() raises CircuitOpenError for the shape — callers serve
        #: from the CPU oracle — WITHOUT the shared breaker tripping.
        self._quarantined_shapes: Dict[tuple, float] = {}
        #: shape_key -> consecutive non-injected launch-failure streak
        self._shape_fail_streak: Dict[tuple, int] = {}
        #: breaker domain -> (monotonic time, shape_key) of last success:
        #: the mesh-health witness the quarantine gate consults
        self._domain_last_success: Dict[object, tuple] = {}
        self._bucket_quarantines = 0
        if acc_cfg is not None and getattr(acc_cfg, "enabled", False):
            from .accumulator import DeviceAccumulatorStore

            self.accumulator = DeviceAccumulatorStore(acc_cfg)

    def set_spill_sink(self, sink) -> None:
        """Register the durable drain target used by shutdown(drain=True)
        (and any explicit drain_accumulator() call)."""
        self._spill_sink = sink

    # -- shape-keyed backend cache --------------------------------------
    def backend_for(self, shape_key: tuple, factory):
        """One backend instance (and its compiled graphs) per VDAF shape,
        shared across every driver in the process.  Newly created backends
        are warmed up (mega-batch executables compiled) when configured.
        With ``config.mesh`` set, single-chip device backends are upgraded
        to the SPMD MeshBackend over the local mesh before caching, so
        every producer's mega-batches shard across the chips."""
        created = False
        with self._lock:
            b = self._backends.get(shape_key)
            if b is None:
                b = factory()
                if self.config.mesh:
                    b = self._meshify(b)
                self._backends[shape_key] = b
                created = True
                if shape_key not in self._warmup_state:
                    self._warmup_state[shape_key] = {
                        "state": "cold",
                        "compile_s": None,
                        "error": None,
                        "future": None,
                        "since": time.monotonic(),
                    }
        if created and self.config.warmup_rows and hasattr(b, "stage_prep_init_multi"):
            self._schedule_warmup(shape_key, b)
        return b

    @staticmethod
    def _meshify(backend):
        """``device_executor.mesh: true`` — upgrade an exact-type
        TpuBackend to MeshBackend (already-mesh, oracle, hybrid, and
        Poplar1 backends pass through: they either have no SPMD launch or
        are mesh-aware already)."""
        from ..vdaf.backend import MeshBackend, TpuBackend

        if type(backend) is TpuBackend:
            # Preserve the field-arithmetic layout AND canonical mode
            # across the upgrade: the mesh backend runs the same per-shard
            # graphs, so an mxu-configured (or bucket-twin) producer must
            # stay that way after meshification.
            return MeshBackend(
                backend.vdaf,
                field_backend=backend.field_backend,
                canonical=backend.canonical,
            )
        return backend

    def cached_backend(self, shape_key: tuple):
        """Peek the shape-keyed backend cache WITHOUT creating (commit
        paths must reuse exactly the backend whose launches minted their
        resident refs — buffer widths must match the retained matrices)."""
        with self._lock:
            return self._backends.get(shape_key)

    # -- background warmup ------------------------------------------------
    def _schedule_warmup(self, shape_key: tuple, backend) -> None:
        """Queue a warmup compile for a freshly created backend.  With
        ``warmup_async`` (the default) the compile runs on the dedicated
        warmup thread and backend_for returns immediately — producers see
        warming() True and drain the shape through the CPU oracle (or
        wait_warm()) until the executable lands.  A FAILED warmup only
        clears the warming flag: the bucket keeps working (the first live
        flush pays the compile, exactly the pre-warmup world) and the
        breaker is untouched — compile trouble is not device sickness."""
        state = self._warmup_state[shape_key]
        if not self.config.warmup_async:
            state.update(state="warming", since=time.monotonic())
            self._do_warmup(shape_key, backend)
            return
        with self._lock:
            if self._warmup_pool is None:
                if self._closed:
                    return
                self._warmup_pool = ThreadPoolExecutor(
                    1, thread_name_prefix="janus-exec-warmup"
                )
            state.update(state="warming", since=time.monotonic())
            state["future"] = self._warmup_pool.submit(
                self._do_warmup, shape_key, backend
            )

    def _do_warmup(self, shape_key: tuple, backend) -> bool:
        from ..core.metrics import GLOBAL_METRICS
        from ..core.trace import trace_span

        state = self._warmup_state[shape_key]
        label = shape_label(backend, shape_key)
        t0 = time.monotonic()
        try:
            with trace_span(
                "compile",
                cat="executor",
                shape=label,
                rows=self.config.warmup_rows,
            ):
                n = self.warmup_backend(backend)
            dt = time.monotonic() - t0
            state.update(
                state="warm", compile_s=round(dt, 3), error=None,
                since=time.monotonic(),
            )
            outcome = "ok"
            if n:
                logger.info(
                    "warmed %d executable(s) for %s (%s) at %d rows in %.1fs",
                    n,
                    type(backend).__name__,
                    label,
                    self.config.warmup_rows,
                    dt,
                )
        except Exception as e:
            dt = time.monotonic() - t0
            state.update(
                state="failed", compile_s=round(dt, 3), error=str(e)[:200],
                since=time.monotonic(),
            )
            outcome = "error"
            logger.exception("executor warmup failed for %s (serving cold)", label)
        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.executor_warmups.labels(outcome=outcome).inc()
            if outcome == "ok":
                GLOBAL_METRICS.executor_compile_seconds.labels(shape=label).observe(dt)
        return outcome == "ok"

    def warming(self, shape_key: tuple) -> bool:
        """True while the shape's warmup compile is still in flight —
        producers route its submissions to the CPU oracle meanwhile (the
        breaker must never count compile-wait as a launch failure, and
        with this peek it never sees one)."""
        st = self._warmup_state.get(shape_key)
        return st is not None and st["state"] == "warming"

    def wait_warm(self, shape_key: tuple, timeout: Optional[float] = None) -> bool:
        """Block until the shape's warmup settles; True iff it is WARM.
        The compile-future face of the cold-task contract (producers that
        prefer waiting a bounded moment over an oracle hop)."""
        st = self._warmup_state.get(shape_key)
        if st is None:
            return False
        fut = st.get("future")
        if fut is not None:
            try:
                fut.result(timeout=timeout)
            except Exception:
                pass
        return st["state"] == "warm"

    def compile_stats(self) -> Dict[str, dict]:
        """Per-shape compile ledger for /statusz: cold (resolved, never
        warmed), warming, warm (last compile_s), or failed (error) — each
        with ``age_s``, the time the shape has sat in its current state
        (a warming age of minutes is a compile an operator should be
        watching; a warm age across a restart window proves the
        persistent cache paid off)."""
        now = time.monotonic()
        with self._lock:
            out = {}
            for shape_key, st in self._warmup_state.items():
                b = self._backends.get(shape_key)
                label = (
                    shape_label(b, shape_key) if b is not None else repr(shape_key)
                )
                out[label] = {
                    "state": st["state"],
                    "compile_s": st["compile_s"],
                    "error": st["error"],
                    "age_s": round(now - st.get("since", now), 1),
                }
            return out

    # -- thread pools ----------------------------------------------------
    def _pools(self) -> Tuple[ThreadPoolExecutor, ThreadPoolExecutor]:
        # One staging + one launch thread: launches serialize on the chip
        # by design; staging of the next mega-batch overlaps the current
        # launch (double buffering).
        with self._lock:
            if self._stage_pool is None:
                self._stage_pool = ThreadPoolExecutor(
                    1, thread_name_prefix="janus-exec-stage"
                )
                self._launch_pool = ThreadPoolExecutor(
                    1, thread_name_prefix="janus-exec-launch"
                )
            return self._stage_pool, self._launch_pool

    # -- submission ------------------------------------------------------
    async def submit(
        self,
        shape_key: tuple,
        kind: str,
        payload,
        *,
        backend,
        agg_id: int = 0,
        deadline_s: Optional[float] = None,
        retain_out_shares: bool = False,
        task_ident: Optional[object] = None,
        agg_param_key: Optional[object] = None,
    ):
        """Enqueue prepare work; resolves when its mega-batch lands.

        kind=KIND_PREP_INIT: payload is (verify_key, report_rows) and the
        result is the per-row List[PrepOutcome].  kind=KIND_COMBINE:
        payload is the prep-share rows and the result is the per-row
        combine outcomes.  kind=KIND_POPLAR_INIT: payload is (verify_key,
        agg_param, report_rows) and the result is the per-row Poplar1
        (state, share) outcomes.  Raises ExecutorOverloadedError on
        backpressure.  ``task_ident`` attributes the rows to a task for
        the per-task fairness quota within the bucket (None =
        unattributed).  ``agg_param_key`` is the opaque agg-param bucket
        discriminant (None for parameter-less VDAFs; Poplar1 passes the
        tree level): submissions coalesce only within one value, so two
        rounds of one task can never share a mega-batch — but different
        JOBS at one level do.
        """
        if kind == KIND_PREP_INIT:
            rows = len(payload[1])
        elif kind == KIND_COMBINE:
            rows = len(payload)
        elif kind == KIND_POPLAR_INIT:
            rows = len(payload[2])
        else:
            raise ValueError(f"unknown submission kind {kind!r}")
        if rows == 0:
            return []
        if self._closed:
            raise ExecutorOverloadedError("executor is shut down")
        breaker = self._breaker_for(shape_key, backend)
        if breaker is not None and not breaker.allow():
            raise CircuitOpenError(
                f"device circuit {breaker.label} is open after "
                f"{breaker.consecutive_failures} consecutive launch failure(s)"
            )
        if self._bucket_quarantined(shape_key):
            # the shape bucket is quarantined to the oracle (ISSUE 19):
            # same caller-visible contract as an open circuit, but scoped
            # to this one shape — the rest of the mesh keeps launching
            raise CircuitOpenError(
                f"shape bucket #{_shape_digest(shape_key)} is quarantined "
                f"to the CPU oracle"
            )
        loop = asyncio.get_running_loop()
        now = time.monotonic()
        timeout = self.config.submit_timeout_s if deadline_s is None else deadline_s
        key = (shape_key, kind, agg_id, agg_param_key)
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = _Bucket(
                    key,
                    backend,
                    kind,
                    agg_id,
                    bucket_label(backend, kind, agg_id, shape_key, agg_param_key),
                    breaker=breaker,
                )
                self._buckets[key] = bucket
            # Backpressure bounds the QUEUE, not the job: a submission
            # larger than the bound is still admitted when nothing is
            # ahead of it (the legacy per-job path handled any size, so
            # rejecting it here would fail the job on every retry).
            if bucket.depth_rows and bucket.depth_rows + rows > self.config.max_queue_rows:
                bucket.rejections += 1
                self._observe_rejection(bucket, "queue_full")
                costs.cost_model().observe_rows(task_ident, "rejected", rows)
                raise ExecutorOverloadedError(
                    f"bucket {bucket.label}: {bucket.depth_rows} rows queued/"
                    f"in flight, +{rows} exceeds max_queue_rows="
                    f"{self.config.max_queue_rows}"
                )
            sub = _Submission(
                payload=payload,
                rows=rows,
                future=loop.create_future(),
                loop=loop,
                enqueued=now,
                # <= 0 disables the deadline (documented in config.py)
                deadline=now + timeout if timeout and timeout > 0 else None,
                retain=retain_out_shares and self.accumulator is not None,
                task=task_ident,
                trace_ctx=current_trace() or None,
            )
            bucket.last_activity = now
            bucket.pending.append(sub)
            bucket.queued_rows += rows
            self._observe_depth(bucket)
            if bucket.queued_rows >= self.config.flush_max_rows:
                subs = self._take_pending(bucket)
            else:
                subs = None
                if bucket.timer is None:
                    bucket.timer = loop.call_later(
                        self.config.flush_window_s,
                        lambda: self._spawn(self._deadline_flush(bucket)),
                    )
        if subs:
            self._enqueue_ready(bucket, subs, trigger="size")
        return await sub.future

    def _breaker_for(self, shape_key: tuple, backend) -> Optional[CircuitBreaker]:
        """One CircuitBreaker per failure DOMAIN (None when disabled).
        Single-chip backends fail per VDAF shape (a bad compile/OOM is
        shape-local), so their domain is the shape: every bucket of it —
        both aggregator sides, both kinds — shares the verdict.  Mesh
        backends fail per MESH (a lost device sickens every shape that
        launches collectives over it), so every mesh-backed shape on one
        mesh shares one breaker: a ``backend.device_lost`` trip opens the
        circuit for ALL of them at once and the drivers serve those jobs
        on the bit-exact CPU oracle until the probe heals the mesh."""
        if self.config.breaker_failure_threshold <= 0:
            return None
        domain = breaker_domain(shape_key, backend)
        with self._lock:
            br = self._breakers.get(domain)
            if br is None:
                label = (
                    mesh_label(backend)
                    if getattr(backend, "mesh", None) is not None
                    else shape_label(backend, shape_key)
                )
                br = CircuitBreaker(
                    label,
                    self.config.breaker_failure_threshold,
                    self.config.breaker_reset_timeout_s,
                    # black box on trip: the ring of recent flushes ships
                    # with the failure as one structured log event
                    on_trip=lambda b: self.flight_recorder.dump(
                        "breaker_trip",
                        detail={
                            "circuit": b.label,
                            "consecutive_failures": b.consecutive_failures,
                            "trips": b.trips,
                        },
                    ),
                )
                self._breakers[domain] = br
            self._breaker_by_shape[shape_key] = br
            self._breaker_shapes.setdefault(domain, set()).add(shape_key)
            return br

    def _spawn(self, coro) -> None:
        """Schedule a flush coroutine, keeping a strong reference until done."""
        task = asyncio.ensure_future(coro)
        self._flush_tasks.add(task)
        task.add_done_callback(self._flush_tasks.discard)

    def _take_pending(self, bucket: _Bucket) -> List[_Submission]:
        """Detach the bucket's pending set for a flush.  Lock held."""
        subs, bucket.pending = bucket.pending, []
        bucket.queued_rows = 0
        for s in subs:
            bucket.inflight_rows += s.rows
        if bucket.timer is not None:
            bucket.timer.cancel()
            bucket.timer = None
        return subs

    async def _deadline_flush(self, bucket: _Bucket) -> None:
        with self._lock:
            bucket.timer = None
            subs = self._take_pending(bucket)
        if subs:
            self._enqueue_ready(bucket, subs, trigger="deadline")

    # -- fair flush scheduling -------------------------------------------
    def _enqueue_ready(self, bucket: _Bucket, subs: List[_Submission], trigger: str):
        """Queue a detached flush for dispatch.  The dispatcher serves
        ready flushes deficit-round-robin ACROSS buckets (one hot bucket
        cannot monopolize the chip) and deadline-earliest WITHIN a bucket;
        a per-loop two-slot semaphore keeps stage k+1 overlapping launch k
        (the double buffering the FIFO path had)."""
        loop = asyncio.get_running_loop()
        min_deadline = min(
            (s.deadline for s in subs if s.deadline is not None), default=float("inf")
        )
        with self._lock:
            ready = self._ready.setdefault(loop, {})
            self._ready_seq += 1
            ready.setdefault(bucket.key, []).append(
                (min_deadline, self._ready_seq, bucket, subs, trigger)
            )
            if loop in self._dispatchers:
                return
            task = asyncio.ensure_future(self._dispatch_loop())
            self._dispatchers[loop] = task
        self._flush_tasks.add(task)
        task.add_done_callback(self._flush_tasks.discard)

    def _pick_next_locked(self, loop):
        """Next ready flush for this loop.  Lock held."""
        ready = self._ready.get(loop)
        if not ready:
            return None
        if not self.config.fair_flush:
            # true legacy FIFO: globally arrival-ordered across buckets
            # (serving dict-first would let a busy first bucket starve the
            # rest, which arrival order never did)
            key = min(ready, key=lambda k: min(e[1] for e in ready[k]))
            entries = ready[key]
            entries.sort(key=lambda e: e[1])
            entry = entries.pop(0)
            if not entries:
                del ready[key]
            if not ready:
                del self._ready[loop]
            return entry[2], entry[3], entry[4]
        quota = max(1, self.config.fair_quota_rows)
        keys = list(ready.keys())
        cursor = self._rr_cursor.get(loop, 0) % len(keys)
        for final_pass in (False, True):
            for i in range(len(keys)):
                key = keys[(cursor + i) % len(keys)]
                entries = ready.get(key)
                if not entries:
                    continue
                entries.sort(key=lambda e: (e[0], e[1]))  # deadline-earliest
                j, task_refill = self._pick_entry_locked(key, entries, quota)
                rows = sum(s.rows for s in entries[j][3])
                # a bucket in deficit debt yields its turn — unless every
                # bucket is in debt, in which case the round refills below
                # and the earliest-cursor bucket proceeds (progress
                # guarantee; the overshoot stays on its tab)
                if final_pass or self._deficit.get(key, quota) >= min(rows, quota):
                    if task_refill:
                        # every entry's tasks are in per-task debt: refill
                        # the bucket's task tabs — only here, at DISPATCH
                        # (a refill on a merely CONSIDERED bucket that the
                        # bucket-level gate then skips would erase a hot
                        # task's debt without any cold task progressing)
                        for e in entries:
                            for s in e[3]:
                                tk = (key, s.task)
                                self._task_deficit[tk] = min(
                                    quota, self._task_deficit.get(tk, 0) + quota
                                )
                    entry = entries.pop(j)
                    if not entries:
                        del ready[key]
                    if not ready:
                        del self._ready[loop]
                    self._deficit[key] = self._deficit.get(key, quota) - rows
                    for s in entry[3]:  # per-task tabs within the bucket
                        tk = (key, s.task)
                        self._task_deficit[tk] = (
                            self._task_deficit.get(tk, quota) - s.rows
                        )
                    self._rr_cursor[loop] = (cursor + i + 1) % len(keys)
                    return entry[2], entry[3], entry[4]
            for k in keys:  # full round found only debtors: refill
                self._deficit[k] = min(quota, self._deficit.get(k, 0) + quota)
        return None

    def _pick_entry_locked(self, key, entries, quota):
        """WITHIN one bucket: deadline-earliest, except that an entry whose
        tasks are all in per-task deficit debt yields to the first entry of
        a task still holding quota — tasks sharing one VDAF shape share its
        bucket but not its quantum, so a task flooding the bucket with
        ready flushes cannot starve its shape-mates (carried over from
        PR 3).  ``entries`` is pre-sorted (deadline, seq); returns
        ``(chosen index, task_refill)``.  PURE — when every entry's tasks
        are in debt it picks the earliest entry (progress guarantee) and
        signals ``task_refill=True`` so the caller refills the bucket's
        task tabs at dispatch time, never on a bucket the bucket-level
        deficit gate then skips."""
        if len(entries) == 1:
            return 0, False
        for j, e in enumerate(entries):
            subs = e[3]
            rows = sum(s.rows for s in subs)
            credit = min(
                self._task_deficit.get((key, s.task), quota) for s in subs
            )
            if credit >= min(rows, quota):
                return j, False
        return 0, True

    async def _dispatch_loop(self) -> None:
        loop = asyncio.get_running_loop()
        me = asyncio.current_task()
        with self._lock:
            sem = self._slots.get(loop)
            if sem is None:
                # two slots: one flush staging while the previous launches
                sem = self._slots[loop] = asyncio.Semaphore(2)
        try:
            while True:
                # slot FIRST, then pick: choosing a flush before a slot is
                # free would pin the scheduling decision while later (and
                # possibly more urgent) buckets become ready
                await sem.acquire()
                with self._lock:
                    item = self._pick_next_locked(loop)
                    if item is None:
                        # exit + deregister atomically: an enqueue that saw
                        # this dispatcher alive must not strand its entry
                        if self._dispatchers.get(loop) is me:
                            del self._dispatchers[loop]
                            self._rr_cursor.pop(loop, None)
                            # the semaphore may only be pruned once no
                            # dispatched flush still holds a permit — a
                            # successor generation must inherit it, not
                            # mint two fresh slots on top of in-flight work
                            if not self._slot_inflight.get(loop):
                                self._slots.pop(loop, None)
                                self._slot_inflight.pop(loop, None)
                        sem.release()
                        return
                    self._slot_inflight[loop] = (
                        self._slot_inflight.get(loop, 0) + 1
                    )
                bucket, subs, trigger = item
                task = asyncio.ensure_future(self._run_flush(bucket, subs, trigger))
                self._flush_tasks.add(task)

                def _done(t, sem=sem, loop=loop):
                    self._flush_tasks.discard(t)
                    with self._lock:
                        left = self._slot_inflight.get(loop, 1) - 1
                        self._slot_inflight[loop] = left
                        if left <= 0 and loop not in self._dispatchers:
                            self._slots.pop(loop, None)
                            self._slot_inflight.pop(loop, None)
                    sem.release()

                task.add_done_callback(_done)
        finally:
            with self._lock:
                # identity check: never unseat a successor dispatcher that
                # registered after this one deregistered itself
                if self._dispatchers.get(loop) is me:
                    del self._dispatchers[loop]

    async def drain(self) -> None:
        """Flush every pending bucket now and wait for results to settle
        (shutdown / end-of-bench barrier) — including flush tasks that
        were already in flight when drain was called."""
        flushes = []
        loop = asyncio.get_running_loop()
        with self._lock:
            # ready-but-undispatched flushes for THIS loop drain directly
            for entries in self._ready.pop(loop, {}).values():
                for _dl, _seq, bucket, subs, _trigger in entries:
                    flushes.append((bucket, subs))
            for bucket in self._buckets.values():
                subs = self._take_pending(bucket)
                if subs:
                    flushes.append((bucket, subs))
        inflight = [t for t in self._flush_tasks if t.get_loop() is loop]
        # cross-loop submissions resolve via call_soon_threadsafe on their
        # own loop; gather here only what belongs to this one
        waiters = [
            s.future for _, subs in flushes for s in subs if s.loop is loop
        ]
        for bucket, subs in flushes:
            await self._run_flush(bucket, subs, trigger="drain")
        if inflight:
            await asyncio.gather(*inflight, return_exceptions=True)
        if waiters:
            await asyncio.gather(*waiters, return_exceptions=True)

    # -- the flush -------------------------------------------------------
    async def _run_flush(
        self, bucket: _Bucket, subs: List[_Submission], trigger: str
    ) -> None:
        from ..core.trace import trace_span

        loop = asyncio.get_running_loop()
        live = self._reject_expired(bucket, subs)
        if not live:
            if bucket.breaker is not None:
                bucket.breaker.probe_aborted()
            return
        rows = sum(s.rows for s in live)
        # Per-submission queue delay (enqueue -> flush dispatch): the
        # ReportWriteBatcher 3-tuple pattern — _Submission carries its
        # enqueue stamp, so the delay is measured here where dispatch
        # actually happens, per submission, not once per flush.
        t_dispatch = time.monotonic()
        queue_delay_max = 0.0
        model = costs.cost_model()
        for s in live:
            delay = max(0.0, t_dispatch - s.enqueued)
            queue_delay_max = max(queue_delay_max, delay)
            model.observe_queue_delay(s.task, delay)
        stage_s = 0.0
        padded_rows = 0
        t_launch = t_dispatch
        #: set the moment the launch is known-good (record_success):
        #: an exception AFTER it (resolve bookkeeping, ref release) must
        #: not re-attribute the measured durations, re-record the flight,
        #: or count a launch failure against a healthy device
        launch_ok = False
        stage_pool, launch_pool = self._pools()
        retain = None
        try:
            # Failure-domain boundary: an injected flush fault is a launch
            # failure to every job in the mega-batch — and to the breaker.
            await faults.fire_async("executor.flush")
            with trace_span(
                "executor_flush",
                cat="executor",
                bucket=bucket.label,
                rows=rows,
                jobs=len(live),
                trigger=trigger,
            ):
                if bucket.kind == KIND_PREP_INIT:
                    requests = [s.payload for s in live]
                    # Device-resident out shares: engaged only when EVERY
                    # submission in the mega-batch opted in (a mixed batch
                    # must not hand ResidentRefs to a caller expecting limb
                    # vectors) and the backend supports retention.
                    if (
                        self.accumulator is not None
                        and all(s.retain for s in live)
                        and getattr(
                            bucket.backend, "supports_resident_out_shares", False
                        )
                    ):
                        retain = self.accumulator
                    t_stage = time.monotonic()
                    staged = await loop.run_in_executor(
                        stage_pool,
                        lambda: bucket.backend.stage_prep_init_multi(
                            bucket.agg_id, requests
                        ),
                    )
                    t_launch = time.monotonic()
                    stage_s = t_launch - t_stage
                    # pad waste: rows the compiled executable computes and
                    # masks away (pow2 canonicalization + mesh-tail
                    # rounding) — invisible on flush_rows, counted here
                    pad_to = getattr(staged, "pad_to", None)
                    if pad_to is not None:
                        padded_rows = max(0, pad_to - rows)

                    def launch():
                        # Deadline re-check AFTER the launch-queue wait —
                        # that queue (one flush at a time on the chip) is
                        # where overload actually parks submissions.  If
                        # every submission expired, skip the device work
                        # entirely; a mixed batch launches as staged
                        # (padding already covers the expired rows).
                        if staged is None:
                            return [[] for _ in live], live
                        still = self._reject_expired(bucket, live)
                        if not still:
                            return None, []
                        if retain is not None:
                            return (
                                bucket.backend.launch_prep_init_multi(
                                    staged, requests, retain_store=retain
                                ),
                                still,
                            )
                        return (
                            bucket.backend.launch_prep_init_multi(
                                staged, requests
                            ),
                            still,
                        )

                    outs, still = await loop.run_in_executor(launch_pool, launch)
                elif bucket.kind == KIND_POPLAR_INIT:
                    # Poplar1 mega-batch: every submission's (verify_key,
                    # agg_param, reports) payload IS a request row for the
                    # multi-request walk — submissions sharing an agg param
                    # (different jobs, one level) run as ONE bulk-AES walk
                    # + ONE device sketch with per-row verify keys.  The
                    # walk (host AES or the jax kernel) is the STAGE half
                    # and the sketch launch the LAUNCH half, on the same
                    # stage/launch threads as prep_init — flush k+1's tree
                    # walk overlaps flush k's sketch launch (the ISSUE 13
                    # double buffering; expired-at-launch rows now pay the
                    # walk, the price of the overlap — their refs release
                    # in the resolution loop).  Device-resident sketches:
                    # when every submission opted in and the backend's walk
                    # is jax, the flush's y matrices are adopted by the
                    # accumulator store and states carry ResidentRefs.
                    if (
                        self.accumulator is not None
                        and all(s.retain for s in live)
                        and getattr(
                            bucket.backend, "supports_resident_sketch", False
                        )
                    ):
                        retain = self.accumulator
                    t_stage = time.monotonic()
                    staged = await loop.run_in_executor(
                        stage_pool,
                        lambda: bucket.backend.stage_poplar_init_multi(
                            bucket.agg_id, [s.payload for s in live]
                        ),
                    )
                    t_launch = time.monotonic()
                    stage_s = t_launch - t_stage

                    def launch():
                        still = self._reject_expired(bucket, live)
                        if not still:
                            return None, []
                        if retain is not None:
                            return (
                                bucket.backend.launch_poplar_init_multi(
                                    staged, retain_store=retain
                                ),
                                still,
                            )
                        return (
                            bucket.backend.launch_poplar_init_multi(staged),
                            still,
                        )

                    outs, still = await loop.run_in_executor(launch_pool, launch)
                else:  # KIND_COMBINE: concatenate rows, launch once, slice
                    concat = [row for s in live for row in s.payload]
                    t_launch = time.monotonic()

                    def launch():
                        still = self._reject_expired(bucket, live)
                        if not still:
                            return None, []
                        flat = bucket.backend.prep_shares_to_prep_batch(concat)
                        outs, start = [], 0
                        for s in live:
                            outs.append(flat[start : start + s.rows])
                            start += s.rows
                        return outs, still

                    outs, still = await loop.run_in_executor(launch_pool, launch)
            if outs is None:
                if bucket.breaker is not None:
                    bucket.breaker.probe_aborted()
                # every submission expired at the launch dequeue: nothing
                # touched the device, but the black box still records it
                self.flight_recorder.record(
                    bucket=bucket.label,
                    trigger=trigger,
                    rows=rows,
                    padded_rows=padded_rows,
                    tasks=[model.label_for(s.task) for s in live],
                    queue_delay_max_s=queue_delay_max,
                    stage_s=stage_s,
                    launch_s=0.0,
                    outcome="expired",
                    breaker_state=self._breaker_state_name(bucket),
                    fault=False,
                )
                return
            if bucket.breaker is not None:
                bucket.breaker.record_success()
            self._note_launch_success(bucket)
            launch_ok = True
            done = time.monotonic()
            launch_s = done - t_launch
            bucket.flushes += 1
            bucket.flushed_rows += rows
            bucket.flushed_jobs += len(live)
            self._observe_flush(bucket, rows, launch_s)
            self._observe_pad(bucket, padded_rows)
            # Per-task cost attribution (ISSUE 12): split the measured
            # stage/launch durations across the flush's submissions
            # proportionally by rows.  Conservation: the per-task shares
            # sum to the measured totals; padding overhead rides with the
            # rows that caused it.
            model.attribute_flush(
                [(s.task, s.rows) for s in live],
                {"stage": stage_s, "launch": launch_s},
                path="device",
            )
            still_set = set(id(s) for s in still)
            for s, out in zip(live, outs):
                if id(s) not in still_set:
                    # rejected at launch dequeue: its result is dropped, so
                    # any ResidentRefs minted for its rows must be released
                    # or the retained flush matrix never frees
                    if retain is not None and out:
                        self._release_dropped_refs(retain, out)
                    continue
                self._finish(bucket, s, done)
                self._observe_wait(bucket, done - s.enqueued)
                model.observe_rows(s.task, "ok", s.rows)
                # Per-submission CHILD span, stamped with the SUBMITTER's
                # trace context: one job's merged Perfetto timeline shows
                # its share of each mega-batch flush (rows of flush_rows),
                # not just an anonymous executor_flush it cannot claim.
                emit_span(
                    "flush_share",
                    "executor",
                    t_launch,
                    launch_s,
                    bucket=bucket.label,
                    rows=s.rows,
                    flush_rows=rows,
                    trigger=trigger,
                    **(s.trace_ctx or {}),
                )
                self._resolve(s, result=out)
            self.flight_recorder.record(
                bucket=bucket.label,
                trigger=trigger,
                rows=rows,
                padded_rows=padded_rows,
                tasks=[model.label_for(s.task) for s in live],
                queue_delay_max_s=queue_delay_max,
                stage_s=stage_s,
                launch_s=launch_s,
                outcome="ok",
                breaker_state=self._breaker_state_name(bucket),
                fault=False,
            )
        except Exception as e:  # surface the launch failure to every job
            done = time.monotonic()
            if (
                not launch_ok
                and self.config.bisection_enabled
                and not isinstance(e, faults.FaultInjectedError)
                and bucket.kind in (KIND_PREP_INIT, KIND_COMBINE)
                and rows >= 2
            ):
                # Batch-level failure that is NOT an injected transient:
                # sieve the cohort for poison rows before condemning the
                # whole flush (and the device) for one bad report.  An
                # injected fault takes the legacy path — chaos soaks
                # assert transient faults heal via retry/breaker, and
                # bisecting them would quarantine healthy reports.
                if await self._bisect_failed_flush(
                    bucket,
                    live,
                    e,
                    trigger,
                    rows,
                    padded_rows,
                    queue_delay_max,
                    model,
                    stage_s,
                    t_launch,
                ):
                    return
                done = time.monotonic()
            if not launch_ok:
                launch_s = max(0.0, done - t_launch)
                # attribute whatever the chip DID spend before failing,
                # then record the flight BEFORE the breaker verdict so a
                # trip's ring dump includes this failing flush.  Error
                # rows count only submissions not already accounted (a
                # launch-dequeue rejection was counted "rejected"; the
                # success loop counted resolved rows "ok").
                model.attribute_flush(
                    [(s.task, s.rows) for s in live],
                    {"stage": stage_s, "launch": launch_s},
                    path="device",
                )
                for s in live:
                    if not s.finished:
                        model.observe_rows(s.task, "error", s.rows)
                self.flight_recorder.record(
                    bucket=bucket.label,
                    trigger=trigger,
                    rows=rows,
                    padded_rows=padded_rows,
                    tasks=[model.label_for(s.task) for s in live],
                    queue_delay_max_s=queue_delay_max,
                    stage_s=stage_s,
                    launch_s=launch_s,
                    outcome="error",
                    breaker_state=self._breaker_state_name(bucket),
                    fault=isinstance(e, faults.FaultInjectedError),
                    error=e,
                )
                self._record_flush_failure(bucket, e)
            else:
                logger.exception(
                    "flush bookkeeping failed after a successful launch "
                    "(bucket %s); unresolved submissions get the error",
                    bucket.label,
                )
            for s in live:
                self._finish(bucket, s, done)
                self._resolve(s, exc=e)

    async def _bisect_failed_flush(
        self,
        bucket: _Bucket,
        live: List[_Submission],
        exc: Exception,
        trigger: str,
        rows: int,
        padded_rows: int,
        queue_delay_max: float,
        model,
        stage_s: float,
        t_launch: float,
    ) -> bool:
        """Sieve a failed mega-batch for poison rows (ISSUE 19).

        Runs the cohort through ``quarantine.bisect_batch`` on the launch
        pool: the full cohort is retried once (an absorbed transient costs
        one extra pass and quarantines nothing), then failing halves split
        until the poison row(s) are isolated within the per-report budget.
        Healthy rows resolve with their real results and the breaker
        records a SUCCESS (the device demonstrably works); offenders get
        in-band VdafError outcomes — the exact value drivers already map
        to PrepareError.VDAF_PREP_ERROR — and land in the quarantine
        ledger under their report identity.

        Returns False (caller runs the legacy fail-all path) when every
        singleton failed — that is the PASS failing, not a poison row —
        or when the sieve itself errored.  Bisection retries never pass
        ``retain_store``: retried rows return host vectors, which every
        caller already handles (mixed batches fall back the same way).
        """
        from ..core import quarantine

        items: List[tuple] = []
        if bucket.kind == KIND_PREP_INIT:
            for si, s in enumerate(live):
                for row in s.payload[1]:
                    items.append((si, row))

            def attempt(subset):
                by_sub: Dict[int, list] = {}
                for si, row in subset:
                    by_sub.setdefault(si, []).append(row)
                reqs = []
                for si in sorted(by_sub):
                    p = live[si].payload
                    # preserve the payload's tail (canonical backends ride
                    # the task vdaf as a third element)
                    reqs.append((p[0], by_sub[si]) + tuple(p[2:]))
                staged = bucket.backend.stage_prep_init_multi(bucket.agg_id, reqs)
                outs = bucket.backend.launch_prep_init_multi(staged, reqs)
                return [o for per_req in outs for o in per_req]

        else:  # KIND_COMBINE
            for si, s in enumerate(live):
                for row in s.payload:
                    items.append((si, row))

            def attempt(subset):
                return bucket.backend.prep_shares_to_prep_batch(
                    [row for _si, row in subset]
                )

        loop = asyncio.get_running_loop()
        _, launch_pool = self._pools()
        try:
            outcome = await loop.run_in_executor(
                launch_pool,
                lambda: quarantine.bisect_batch(
                    items, attempt, self.config.bisection_per_item_budget
                ),
            )
        except Exception:
            logger.exception("bisection sieve failed (bucket %s)", bucket.label)
            return False
        quarantine.note_bisection()
        if outcome.offenders and not outcome.attributable:
            # every singleton failed: the pass is broken (device lost, bad
            # build) — not poison.  Legacy path: fail-all + breaker (or
            # bucket quarantine when the rest of the domain is healthy).
            return False

        from ..vdaf.prio3 import VdafError

        stage = "prep_init" if bucket.kind == KIND_PREP_INIT else "combine"
        poisoned: Dict[int, VdafError] = {}
        for idx, err in outcome.offenders:
            si, row = items[idx]
            report_id = None
            if (
                bucket.kind == KIND_PREP_INIT
                and isinstance(row, tuple)
                and row
                and isinstance(row[0], (bytes, bytearray))
            ):
                report_id = bytes(row[0])
            task = live[si].task
            quarantine.record(
                stage,
                task=(
                    task.hex()
                    if isinstance(task, (bytes, bytearray))
                    else (str(task) if task is not None else None)
                ),
                report_id=report_id,
                error=err,
                payload=row,
            )
            poisoned[idx] = VdafError(
                f"row quarantined by batch bisection: {type(err).__name__}"
            )

        per_sub: List[list] = [[] for _ in live]
        for idx, (si, _row) in enumerate(items):
            if idx in poisoned:
                per_sub[si].append(poisoned[idx])
            else:
                per_sub[si].append(outcome.results[idx])

        done = time.monotonic()
        launch_s = max(0.0, done - t_launch)
        if bucket.breaker is not None:
            # the sieve proved the device healthy — a poison row must
            # never trip the circuit
            bucket.breaker.record_success()
        self._note_launch_success(bucket)
        bucket.flushes += 1
        bucket.flushed_rows += rows
        bucket.flushed_jobs += len(live)
        self._observe_flush(bucket, rows, launch_s)
        self._observe_pad(bucket, padded_rows)
        model.attribute_flush(
            [(s.task, s.rows) for s in live],
            {"stage": stage_s, "launch": launch_s},
            path="device",
        )
        offender_rows: Dict[int, int] = {}
        for idx in poisoned:
            si = items[idx][0]
            offender_rows[si] = offender_rows.get(si, 0) + 1
        for si, s in enumerate(live):
            bad = offender_rows.get(si, 0)
            if s.rows - bad:
                model.observe_rows(s.task, "ok", s.rows - bad)
            if bad:
                model.observe_rows(s.task, "error", bad)
            self._finish(bucket, s, done)
            self._observe_wait(bucket, done - s.enqueued)
            self._resolve(s, result=per_sub[si])
        self.flight_recorder.record(
            bucket=bucket.label,
            trigger=trigger,
            rows=rows,
            padded_rows=padded_rows,
            tasks=[model.label_for(s.task) for s in live],
            queue_delay_max_s=queue_delay_max,
            stage_s=stage_s,
            launch_s=launch_s,
            outcome="bisected",
            breaker_state=self._breaker_state_name(bucket),
            fault=False,
            error=exc,
        )
        logger.warning(
            "bisected failed flush (bucket %s): %d/%d row(s) quarantined "
            "in %d attempt(s)%s",
            bucket.label,
            len(outcome.offenders),
            len(items),
            outcome.attempts,
            " [budget exhausted]" if outcome.exhausted else "",
        )
        return True

    def _note_launch_success(self, bucket: _Bucket) -> None:
        """A launch landed: clear the shape's failure streak and stamp its
        breaker domain's health witness (the quarantine gate's evidence
        that the mesh itself works)."""
        shape_key = bucket.key[0]
        with self._lock:
            self._shape_fail_streak.pop(shape_key, None)
            self._quarantined_shapes.pop(shape_key, None)
            domain = breaker_domain(shape_key, bucket.backend)
            self._domain_last_success[domain] = (time.monotonic(), shape_key)

    def _record_flush_failure(self, bucket: _Bucket, exc: Exception) -> None:
        """Count a launch failure.  Usually the breaker — but repeated
        NON-injected failures confined to ONE shape while another shape on
        the same breaker domain stays demonstrably healthy quarantine that
        shape bucket to the oracle instead (ISSUE 19): a shape-local
        failure (bad compile, pathological input shape) must not open the
        mesh-wide circuit and drag every healthy shape to the oracle with
        it."""
        shape_key = bucket.key[0]
        if self.config.bucket_quarantine_threshold > 0 and not isinstance(
            exc, faults.FaultInjectedError
        ):
            now = time.monotonic()
            quarantined = False
            with self._lock:
                streak = self._shape_fail_streak.get(shape_key, 0) + 1
                self._shape_fail_streak[shape_key] = streak
                domain = breaker_domain(shape_key, bucket.backend)
                last = self._domain_last_success.get(domain)
                domain_healthy = (
                    last is not None
                    and last[1] != shape_key
                    and now - last[0]
                    <= self.config.bucket_quarantine_success_window_s
                )
                if (
                    streak >= self.config.bucket_quarantine_threshold
                    and domain_healthy
                ):
                    self._quarantined_shapes[shape_key] = (
                        now + self.config.bucket_quarantine_s
                    )
                    self._bucket_quarantines += 1
                    quarantined = True
            if quarantined:
                from ..core import quarantine

                quarantine.record(
                    "bucket",
                    task=bucket.label,
                    error=exc,
                    durable=False,
                )
                logger.warning(
                    "quarantined shape bucket %s to the CPU oracle for %.0fs "
                    "after %d shape-local failure(s); breaker %s stays closed",
                    bucket.label,
                    self.config.bucket_quarantine_s,
                    streak,
                    bucket.breaker.label if bucket.breaker else "<none>",
                )
                return
        if bucket.breaker is not None:
            bucket.breaker.record_failure()

    def _bucket_quarantined(self, shape_key: tuple) -> bool:
        """Is the shape bucket inside its quarantine dwell?  Expired
        entries are reaped on the way out (the next submission runs on the
        device and a success clears the streak)."""
        now = time.monotonic()
        with self._lock:
            exp = self._quarantined_shapes.get(shape_key)
            if exp is None:
                return False
            if now >= exp:
                del self._quarantined_shapes[shape_key]
                return False
            return True

    def bucket_quarantine_stats(self) -> dict:
        """The /statusz face of the shape-bucket quarantine."""
        now = time.monotonic()
        with self._lock:
            return {
                "total": self._bucket_quarantines,
                "quarantined": {
                    f"#{_shape_digest(k)}": round(max(0.0, exp - now), 2)
                    for k, exp in self._quarantined_shapes.items()
                },
                "fail_streaks": {
                    f"#{_shape_digest(k)}": v
                    for k, v in self._shape_fail_streak.items()
                },
            }

    @staticmethod
    def _release_dropped_refs(store, outcomes) -> None:
        """Release the ResidentRefs inside a dropped submission's prepare
        outcomes (each is (state, share) or a VdafError).  Prio3 states
        carry the ref as ``out_share``; Poplar1 states as ``y_flat``."""
        from .accumulator import ResidentRef

        refs = []
        for o in outcomes:
            if not isinstance(o, tuple) or not o:
                continue
            ref = getattr(o[0], "out_share", None)
            if not isinstance(ref, ResidentRef):
                ref = getattr(o[0], "y_flat", None)
            if isinstance(ref, ResidentRef):
                refs.append(ref)
        if refs:
            store.release_refs(refs)

    @staticmethod
    def _breaker_state_name(bucket: _Bucket) -> Optional[str]:
        """The bucket's breaker state at record time (flight recorder
        field); None when breakers are disabled."""
        if bucket.breaker is None:
            return None
        return _CIRCUIT_STATE_NAMES.get(bucket.breaker.state)

    def _reject_expired(self, bucket: _Bucket, subs: List[_Submission]):
        """Reject (retryably) every submission whose deadline has passed;
        returns the still-live remainder.  Called when a flush starts and
        again when it reaches the launch thread — the launch queue is
        where submissions wait under chip overload."""
        now = time.monotonic()
        live: List[_Submission] = []
        for s in subs:
            if s.deadline is None or now <= s.deadline:
                live.append(s)
                continue
            self._finish(bucket, s, now)
            bucket.rejections += 1
            self._observe_rejection(bucket, "deadline")
            costs.cost_model().observe_rows(s.task, "rejected", s.rows)
            self._resolve(
                s,
                exc=ExecutorOverloadedError(
                    f"bucket {bucket.label}: queued past its "
                    f"{s.deadline - s.enqueued:.3f}s deadline"
                ),
            )
        return live

    def _finish(self, bucket: _Bucket, s: _Submission, now: float) -> None:
        with self._lock:
            if s.finished:
                return
            s.finished = True
            bucket.last_activity = now
            bucket.inflight_rows -= s.rows
            self._observe_depth(bucket)

    @staticmethod
    def _resolve(s: _Submission, result=None, exc: Optional[Exception] = None):
        """Complete a submission future on ITS loop (cross-loop safe)."""

        def do():
            if s.future.done():
                return
            if exc is not None:
                s.future.set_exception(exc)
            else:
                s.future.set_result(result)

        try:
            if s.loop is asyncio.get_running_loop():
                do()
                return
        except RuntimeError:
            pass
        try:
            s.loop.call_soon_threadsafe(do)
        except RuntimeError:  # submitter's loop already closed
            pass

    # -- warmup ----------------------------------------------------------
    def warmup_backend(self, backend, agg_ids=(0, 1), pad_to: Optional[int] = None) -> int:
        """Precompile the mega-batch executable(s) for one backend.

        Stages a couple of synthetic reports padded to ``pad_to`` (default
        config.warmup_rows) and launches them, so the first real flush
        replays a cached executable instead of paying XLA at peak traffic.
        Returns the number of executables compiled (0 when warmup is off
        or the backend has no device launch path).
        """
        pad_to = pad_to if pad_to is not None else self.config.warmup_rows
        if not pad_to or not hasattr(backend, "stage_prep_init_multi"):
            return 0
        vdaf = backend.vdaf
        meas = _synthetic_measurement(vdaf)
        nonce = b"\x00" * vdaf.NONCE_SIZE
        public, shares = vdaf.shard(meas, nonce, b"\x00" * vdaf.RAND_SIZE)
        vk = b"\x00" * vdaf.VERIFY_KEY_SIZE
        compiled = 0
        for agg_id in agg_ids:
            reports = [(nonce, public, shares[min(agg_id, len(shares) - 1)])]
            staged = backend.stage_prep_init_multi(
                agg_id, [(vk, reports)], pad_to=pad_to
            )
            backend.launch_prep_init_multi(staged, [(vk, reports)])
            compiled += 1
        return compiled

    # -- introspection ---------------------------------------------------
    def stats(self) -> Dict[str, dict]:
        """Per-bucket counters (plain Python; bench + tests read these)."""
        with self._lock:
            return {
                b.label: {
                    "flushes": b.flushes,
                    "flushed_rows": b.flushed_rows,
                    "flushed_jobs": b.flushed_jobs,
                    "mean_flush_rows": round(b.mean_flush_rows(), 2),
                    "rejections": b.rejections,
                    "depth_rows": b.depth_rows,
                }
                for b in self._buckets.values()
            }

    def circuit_open(self, shape_key: tuple) -> bool:
        """PEEK at a shape's circuit without the allow() side effects:
        True while the circuit is open and still inside its reset dwell.
        Job drivers consult this at step entry (alongside circuit_stats())
        to route straight to the CPU oracle instead of paying a
        submit-then-CircuitOpenError round trip per job.  Returns False
        once the dwell has elapsed so the next real submission runs the
        half-open probe that can close the circuit.  Mesh-backed shapes
        share their mesh's breaker, so after a device loss this returns
        True for EVERY shape on that mesh.  A quarantined shape bucket
        (ISSUE 19) also peeks True — same oracle routing, scoped to the
        one shape — until its quarantine dwell expires."""
        if self._bucket_quarantined(shape_key):
            return True
        with self._lock:
            br = self._breaker_by_shape.get(shape_key) or self._breakers.get(
                shape_key
            )
        return br is not None and br.is_open_peek()

    def retire_idle_buckets(self, max_idle_s: float = 600.0) -> int:
        """Reap buckets with no pending/in-flight work that have been idle
        past ``max_idle_s``, removing their ``janus_executor_queue_rows``
        label sets; breakers whose shape no longer has any bucket and whose
        circuit is closed retire with them (their ``janus_executor_
        circuit_state`` series too).  Without this, a retired task's bucket
        gauges report stale values forever and series cardinality only ever
        grows (ISSUE 5 satellite).  Returns the number of buckets retired.
        """
        now = time.monotonic()
        retired: List[str] = []
        retired_circuits: List[str] = []
        with self._lock:
            for key, bucket in list(self._buckets.items()):
                if (
                    not bucket.pending
                    and bucket.depth_rows == 0
                    and bucket.timer is None
                    and now - bucket.last_activity >= max_idle_s
                ):
                    del self._buckets[key]
                    # the scheduler tabs go with the bucket — _deficit and
                    # the per-task _task_deficit entries are keyed by task
                    # cardinality and would otherwise grow for the process
                    # lifetime under task churn
                    self._deficit.pop(key, None)
                    for tk in [t for t in self._task_deficit if t[0] == key]:
                        del self._task_deficit[tk]
                    retired.append(bucket.label)
            live_shapes = {key[0] for key in self._buckets}
            for domain, breaker in list(self._breakers.items()):
                # a breaker retires only when NONE of the shapes in its
                # domain (one for per-shape breakers, many for a mesh's)
                # still has a live bucket, and its circuit is closed
                shapes = self._breaker_shapes.get(domain, {domain})
                if not (shapes & live_shapes) and breaker.state == CIRCUIT_CLOSED:
                    del self._breakers[domain]
                    for sk in self._breaker_shapes.pop(domain, set()):
                        if self._breaker_by_shape.get(sk) is breaker:
                            del self._breaker_by_shape[sk]
                    retired_circuits.append(breaker.label)
        if retired or retired_circuits:
            from ..core.metrics import GLOBAL_METRICS

            if GLOBAL_METRICS.registry is not None:
                for label in retired:
                    # EVERY per-bucket series goes with the bucket —
                    # cardinality must be capped by live traffic, not
                    # history (rejection reasons are a closed set)
                    for metric in (
                        GLOBAL_METRICS.executor_queue_rows,
                        GLOBAL_METRICS.executor_flush_rows,
                        GLOBAL_METRICS.executor_wait_seconds,
                        GLOBAL_METRICS.executor_launch_seconds,
                        GLOBAL_METRICS.executor_pad_rows,
                    ):
                        GLOBAL_METRICS.remove_series(metric, label)
                    for reason in ("queue_full", "deadline"):
                        GLOBAL_METRICS.remove_series(
                            GLOBAL_METRICS.executor_rejections, label, reason
                        )
                for label in retired_circuits:
                    GLOBAL_METRICS.remove_series(
                        GLOBAL_METRICS.circuit_state, label
                    )
            logger.info(
                "retired %d idle executor bucket(s) and %d closed circuit(s)",
                len(retired),
                len(retired_circuits),
            )
        return len(retired)

    def flight_stats(self, n: int = 32) -> dict:
        """The flight recorder's /statusz face: ring stats + the newest
        ``n`` per-flush records, newest first."""
        out = self.flight_recorder.stats()
        out["records"] = self.flight_recorder.snapshot(n)
        return out

    def circuit_stats(self) -> Dict[str, dict]:
        """Per-shape breaker state (plain Python; chaos tests read this)."""
        with self._lock:
            return {
                br.label: {
                    "state": _CIRCUIT_STATE_NAMES[br.state],
                    "trips": br.trips,
                    "consecutive_failures": br.consecutive_failures,
                }
                for br in self._breakers.values()
            }

    def shutdown(self, drain: bool = True) -> None:
        """Stop intake and tear down.  ``drain=True`` (the default — the
        graceful path) first spills every healthy bucket's committed-but-
        unspilled delta through the registered spill sink, so a SIGTERM
        loses nothing; ``drain=False`` is the crash-shaped teardown —
        deltas are dropped loudly and redelivery (un-committed jobs) or
        the persisted journal's oracle replay (committed, deferred-drain
        jobs) re-derives them."""
        self._closed = True
        if self.accumulator is not None:
            if drain and self._spill_sink is not None:
                try:
                    self.accumulator.drain_all(self._spill_sink)
                except Exception:
                    logger.exception("accumulator shutdown drain failed")
            # whatever remains (poisoned buckets, failed sink writes, or
            # drain=False): un-spilled deltas either belong to jobs whose
            # tx never committed (redelivery re-derives them) or carry
            # persisted journal rows (survivors replay them), so drop
            # them loudly without paying a readback per bucket
            try:
                self.accumulator.discard_all()
            except Exception:
                logger.exception("accumulator shutdown teardown failed")
        with self._lock:
            pools = [self._stage_pool, self._launch_pool, self._warmup_pool]
            self._stage_pool = self._launch_pool = self._warmup_pool = None
        for p in pools:
            if p is not None:
                p.shutdown(wait=False)

    # -- metrics ---------------------------------------------------------
    def _observe_depth(self, bucket: _Bucket) -> None:
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.executor_queue_rows.labels(bucket=bucket.label).set(
                bucket.depth_rows
            )

    def _observe_flush(self, bucket: _Bucket, rows: int, launch_s: float) -> None:
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.executor_flush_rows.labels(bucket=bucket.label).observe(rows)
            GLOBAL_METRICS.executor_launch_seconds.labels(
                bucket=bucket.label
            ).observe(launch_s)

    def _observe_pad(self, bucket: _Bucket, padded_rows: int) -> None:
        from ..core.metrics import GLOBAL_METRICS

        if padded_rows > 0 and GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.executor_pad_rows.labels(bucket=bucket.label).inc(
                padded_rows
            )

    def _observe_wait(self, bucket: _Bucket, wait_s: float) -> None:
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.executor_wait_seconds.labels(bucket=bucket.label).observe(
                wait_s
            )

    def _observe_rejection(self, bucket: _Bucket, reason: str) -> None:
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.executor_rejections.labels(
                bucket=bucket.label, reason=reason
            ).inc()


def _synthetic_measurement(vdaf):
    """A valid all-zero measurement for warmup sharding: scalar circuits
    (Count/Sum/Histogram) take 0; vector circuits take [0]*length (the
    fixed-point family sizes by ``entries`` — the all-zero vector has
    norm 0, valid in every family)."""
    flp = vdaf.flp
    try:
        flp.encode(0)
        return 0
    except Exception:
        length = getattr(flp.valid, "length", None)
        if length is None:
            length = getattr(flp.valid, "entries", 1)
        return [0] * length


# -- process-wide instance ---------------------------------------------------

_GLOBAL: Optional[DeviceExecutor] = None
_GLOBAL_LOCK = threading.Lock()


def get_global_executor(config: Optional[ExecutorConfig] = None) -> DeviceExecutor:
    """The one executor that owns this process's chip.  First caller's
    config wins; later callers share the instance (all drivers feed one
    batcher — that is the point)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = DeviceExecutor(config)
        return _GLOBAL


def peek_global_executor() -> Optional[DeviceExecutor]:
    """The process-wide instance if one exists, WITHOUT creating it —
    shutdown paths must never mint an executor just to tear it down."""
    with _GLOBAL_LOCK:
        return _GLOBAL


def reset_global_executor() -> None:
    """Tests only: drop the process-wide instance."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is not None:
            _GLOBAL.shutdown(drain=False)
        _GLOBAL = None
