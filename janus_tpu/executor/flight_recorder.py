"""Executor flight recorder: a black box for the device plane (ISSUE 12).

A bounded in-memory ring of per-flush records — bucket, rows vs padded
rows, participating tasks, queue delay, stage/launch wall time, outcome,
breaker state, whether an injected fault fired — kept cheap enough to run
always-on.  Three read paths:

* the ``flights`` section of ``/statusz`` (the last N records, newest
  first) — what an operator curls when a soak wedges;
* a **breaker-trip dump**: the moment a circuit opens, the whole ring is
  emitted as ONE structured log event, so every chaos failure ships with
  the flushes that led up to it (the post-hoc question "what were the
  last launches doing" has an answer even after the process is gone);
* a **slow-flush anomaly dump**: a flush whose launch exceeds
  ``slow_flush_p95_factor`` × the bucket's rolling p95 dumps the ring
  too (rate-limited — an overloaded chip must not turn the log into a
  dump firehose).

The ring is O(size) bounded, process-local, and deliberately NOT
persisted: a fresh binary starts an empty ring (SIGKILL semantics —
asserted by ``./ci.sh chaos crash``), because the flight recorder answers
"what was THIS incarnation doing", and the durable story (journal,
leases, traces) already survives elsewhere.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

logger = logging.getLogger("janus_tpu.executor.flights")

#: grep-stable marker for the one-line structured dump event (chaos
#: asserts exactly-once on it; keep it unique in the codebase)
DUMP_MARKER = "EXECUTOR-FLIGHT-RECORDER-DUMP"


class FlightRecorder:
    """Bounded ring of per-flush records + anomaly-triggered dumps."""

    #: launch-duration window per bucket feeding the rolling p95
    P95_WINDOW = 64
    #: anomaly detection needs this many samples before it trusts the p95
    MIN_P95_SAMPLES = 16
    #: floor between two slow-flush dumps (breaker trips are never limited)
    SLOW_DUMP_MIN_INTERVAL_S = 30.0

    def __init__(self, size: int = 256, slow_flush_p95_factor: float = 4.0):
        self.size = max(1, size)
        #: k in "launch > k × rolling p95 -> dump"; <= 0 disables the
        #: anomaly detector (the ring and breaker dumps stay on)
        self.slow_flush_p95_factor = slow_flush_p95_factor
        self._ring: deque = deque(maxlen=self.size)
        self._launch_window: Dict[str, deque] = {}
        self._lock = threading.Lock()
        self._seq = 0
        self.recorded_total = 0
        self.dumps: Dict[str, int] = {}
        self._last_slow_dump = 0.0

    # -- recording -------------------------------------------------------
    def record(
        self,
        *,
        bucket: str,
        trigger: str,
        rows: int,
        padded_rows: int,
        tasks: List[str],
        queue_delay_max_s: float,
        stage_s: float,
        launch_s: float,
        outcome: str,
        breaker_state: Optional[str],
        fault: bool,
        error: Optional[str] = None,
    ) -> Optional[dict]:
        """Append one flush record; returns the record.  Runs the
        slow-flush detector against the bucket's rolling p95 BEFORE this
        flush's own sample joins the window (a single huge flush must not
        raise the bar it is judged by)."""
        with self._lock:
            self._seq += 1
            rec = {
                "seq": self._seq,
                "t": round(time.time(), 3),
                "bucket": bucket,
                "trigger": trigger,
                "rows": rows,
                "padded_rows": padded_rows,
                "tasks": sorted(set(tasks)),
                "queue_delay_max_ms": round(queue_delay_max_s * 1000.0, 3),
                "stage_ms": round(stage_s * 1000.0, 3),
                "launch_ms": round(launch_s * 1000.0, 3),
                "outcome": outcome,
                "breaker": breaker_state,
                "fault": fault,
            }
            if error:
                rec["error"] = str(error)[:200]
            self._ring.append(rec)
            self.recorded_total += 1
            window = self._launch_window.get(bucket)
            if window is None:
                window = self._launch_window[bucket] = deque(
                    maxlen=self.P95_WINDOW
                )
            p95 = self._p95_locked(window)
            slow = (
                outcome == "ok"
                and self.slow_flush_p95_factor > 0
                and p95 is not None
                and launch_s > self.slow_flush_p95_factor * p95
            )
            if outcome == "ok":
                window.append(launch_s)
        if slow:
            self.dump(
                "slow_flush",
                detail={
                    "bucket": bucket,
                    "launch_ms": rec["launch_ms"],
                    "rolling_p95_ms": round(p95 * 1000.0, 3),
                    "factor": self.slow_flush_p95_factor,
                },
                rate_limited=True,
            )
        return rec

    def _p95_locked(self, window: deque) -> Optional[float]:
        if len(window) < self.MIN_P95_SAMPLES:
            return None
        ordered = sorted(window)
        return ordered[min(len(ordered) - 1, int(0.95 * len(ordered)))]

    # -- dumps -----------------------------------------------------------
    def dump(
        self, reason: str, detail: Optional[dict] = None, rate_limited: bool = False
    ) -> bool:
        """Emit the whole ring as ONE structured log event.  Breaker trips
        always dump; slow-flush anomalies respect the rate floor so chip
        overload cannot flood the log.  Returns whether a dump fired."""
        now = time.monotonic()
        with self._lock:
            if rate_limited and now - self._last_slow_dump < self.SLOW_DUMP_MIN_INTERVAL_S:
                return False
            if rate_limited:
                self._last_slow_dump = now
            self.dumps[reason] = self.dumps.get(reason, 0) + 1
            payload = {
                "reason": reason,
                "detail": detail or {},
                "flights": list(self._ring),
            }
        logger.warning("%s %s", DUMP_MARKER, json.dumps(payload, sort_keys=True))
        return True

    # -- introspection ---------------------------------------------------
    def snapshot(self, n: int = 32) -> List[dict]:
        """The newest ``n`` records, newest first (statusz "flights")."""
        with self._lock:
            recs = list(self._ring)
        return list(reversed(recs))[: max(0, n)]

    def stats(self) -> dict:
        with self._lock:
            return {
                "ring_size": self.size,
                "recorded": self.recorded_total,
                "dumps": dict(self.dumps),
            }
