"""Device-resident accumulator store: on-chip out-share accumulation.

The steady-state hot path used to read every mega-batch's out shares back
to the host (``launch_prep_init_multi`` materialized a (B, OUTPUT_LEN, n)
limb matrix per flush) and re-merge them through the sharded
``batch_aggregations`` rows.  Accelerator proof-system frameworks (ZK-Flex,
arXiv:2606.03046; Hermes, arXiv:2603.01556) get their throughput by keeping
reduction state resident in accelerator memory and spilling only at epoch
boundaries — the same shape as a KV-cache/optimizer-state manager in a
serving stack.  This module is that manager for Janus out shares:

* **Flush-resident matrices**: with the store attached, a prepare flush
  retains its ``out_share`` mega-batch ON DEVICE and hands each report a
  lightweight :class:`ResidentRef` (flush id + row) instead of the limb
  vector.  The host sees only per-report prepare verdicts; the flush pays
  ZERO device->host out-share readback (``TpuBackend.outshare_readback_rows``
  stays 0 — the acceptance counter).
* **Per-bucket persistent accumulators**: verified rows are psummed into a
  per-``(task, VDAF shape, batch bucket)`` resident buffer
  (:meth:`DeviceAccumulatorStore.commit_rows` — one tiny device launch per
  bucket, no readback).
* **Commit-time spill**: the driver requests :meth:`drain` at job commit; the
  readback is ONE (OUTPUT_LEN,) field vector per bucket — O(OUT) instead of
  O(B*OUT) per flush — handed to ``AggregationJobWriter`` for the existing
  sharded merge.
* **LRU / memory-pressure eviction**: resident bytes are bounded by a
  configurable budget; beyond it the least-recently-used state spills to
  host mirrors (flush matrices to host limb arrays, bucket buffers to host
  field vectors) — correctness is unaffected, only the residency win.
* **Mirror-delta journal**: every ``commit_rows`` appends ``(job, report
  ids)`` to the bucket's journal; the journal is cleared by a successful
  drain.  On a launch failure / CircuitOpenError the bucket is poisoned and
  :meth:`discard` returns the journaled identities so the caller replays
  exactly those reports through the bit-exact CPU oracle path — accumulation
  never double-counts (the poisoned device delta is dropped, never drained)
  and never drops (the journal names every un-spilled report).

The store is jax-free at import: all device arithmetic goes through the
backend seam (``accumulate_rows`` / ``read_accum_buffer`` on TpuBackend),
so control-plane processes and fake-backend tests never pull in the device
stack.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..core import faults

logger = logging.getLogger("janus_tpu.accumulator")


class AccumulatorError(Exception):
    """Base for accumulator-store failures."""


class AccumulatorUnavailable(AccumulatorError):
    """A device accumulate/drain launch failed (or the bucket is poisoned
    from an earlier failure).  The caller's contract is the CPU-oracle
    replay: re-derive the journaled reports' out shares on the oracle and
    hand host vectors to the writer; then :meth:`DeviceAccumulatorStore.discard`
    the bucket so the dead device delta can never double-count."""


class StaleAccumulatorDelta(AccumulatorError):
    """The drained delta no longer matches the reports surviving the
    transactional write (a report was failed in-tx, e.g. BatchCollected,
    after its row was already accumulated).  Raised INSIDE the tx so the
    commit aborts cleanly; the caller surfaces it as a retryable step
    failure — redelivery re-prepares the job and the in-tx check fails the
    report properly, with nothing merged (no double count, no drop)."""


@dataclass(frozen=True)
class ResidentRef:
    """A device-resident out share: row ``row`` of flush ``flush_id``.

    Travels inside ``Prio3PrepareState.out_share`` through the ping-pong
    layer untouched (``prep_next`` returns it verbatim); only the
    store can resolve it back to field elements.
    """

    flush_id: int
    row: int


@dataclass
class AccumulatorConfig:
    """Tuning knobs for the store (``device_executor.accumulator.*``)."""

    enabled: bool = False
    #: resident-byte cap across flush matrices + bucket buffers; beyond it
    #: LRU state spills to host mirrors.  <= 0 disables eviction.
    byte_budget: int = 256 << 20
    #: Deferred drains: 0 (default) drains every bucket at its job's
    #: commit (residency window = one step, nothing survives the tx).
    #: > 0 lets a bucket accumulate across jobs and drains it once it is
    #: this old — each contributing job persists an accumulator-journal
    #: row in its commit tx (datastore ``accumulator_journal``), so a
    #: crash between commit and drain is recoverable: survivors replay
    #: the journaled reports through the CPU oracle from the datastore.
    drain_interval_s: float = 0.0
    #: Dedicated maintenance cadence (binaries background loop): > 0 runs
    #: ``AggregationJobDriver.run_accumulator_maintenance`` every this
    #: many seconds, draining deferred buckets that came due while no
    #: driver commit was around to drain them (an idle task's bucket no
    #: longer waits for the NEXT commit) and rebalancing resident
    #: occupancy.  <= 0 = commit-driven drains only (pre-maintenance
    #: behavior).
    maintenance_interval_s: float = 0.0

    @property
    def deferred(self) -> bool:
        return self.drain_interval_s > 0


class _Flush:
    """One retained prepare mega-batch: the (pad, OUT, n) out-share matrix.

    ``matrix`` is a device array until evicted, then a host ndarray; the
    accumulate launch consumes either (jax device_puts host inputs).
    """

    def __init__(self, flush_id: int, backend, matrix, rows: int, nbytes: int):
        self.flush_id = flush_id
        self.backend = backend
        self.matrix = matrix
        self.rows = rows
        self.nbytes = nbytes
        self.consumed: Set[int] = set()
        self.on_host = False
        self.last_used = time.monotonic()


class _Bucket:
    """Persistent accumulator for one (task, shape, batch-bucket,
    agg-param) — the aggregation parameter (Poplar1's encoded level +
    prefixes; b"" for Prio3) is part of the caller's key tuple, so two
    rounds of one heavy-hitters task can never share a bucket."""

    def __init__(self, key: tuple, backend):
        self.key = key
        #: minting device backend; None for host-vector buckets
        #: (commit_host_rows — Poplar1 sketch deltas), whose only state is
        #: the spilled_host mirror
        self.backend = backend
        #: drain-time field for host-vector buckets (backend is None there)
        self.field = None
        #: device (OUT, n) limb buffer; None until the first commit
        self.buffer = None
        self.buffer_nbytes = 0
        #: host mirror of evicted device state (field ints)
        self.spilled_host: Optional[List[int]] = None
        #: mirror-delta journal: (job_token, frozenset of report ids)
        self.journal: List[Tuple[object, frozenset]] = []
        self.row_count = 0
        self.poisoned = False
        #: set (under oplock) when a drain/discard detaches the bucket: a
        #: commit racing the detach must fail cleanly and replay, never
        #: land rows in a buffer that has already been read
        self.closed = False
        self.last_used = time.monotonic()
        #: first-commit time: deferred drains fire once the bucket is
        #: drain_interval_s old (age of the OLDEST un-drained delta, so no
        #: journal row waits longer than one interval under steady traffic)
        self.created_at = time.monotonic()
        #: serializes device ops against this bucket's buffer (a commit
        #: racing an eviction or drain must never double- or under-count)
        self.oplock = threading.Lock()


class DeviceAccumulatorStore:
    """Process-wide resident out-share state, owned by the DeviceExecutor."""

    def __init__(self, config: Optional[AccumulatorConfig] = None):
        self.config = config or AccumulatorConfig()
        self._flushes: Dict[int, _Flush] = {}
        self._buckets: Dict[tuple, _Bucket] = {}
        self._next_flush_id = 0
        self._lock = threading.Lock()
        # plain-Python counters (bench + tests read these; metrics mirror them)
        self.resident_bytes = 0
        self.retained_rows = 0
        self.spills = 0
        self.evictions = 0
        self.drain_readback_rows = 0

    # -- flush retention -------------------------------------------------
    def retain_flush(self, backend, matrix, rows: int, nbytes: int) -> int:
        """Adopt a flush's device out-share matrix; returns its flush id.

        Eviction runs BEFORE adoption: an eviction failure (injected or
        real) must never fire after state was mutated, or the caller could
        not tell a clean failure from a half-applied one."""
        self._evict_if_needed()
        with self._lock:
            fid = self._next_flush_id
            self._next_flush_id += 1
            self._flushes[fid] = _Flush(fid, backend, matrix, rows, nbytes)
            self.resident_bytes += nbytes
            self.retained_rows += rows
        self._observe()
        return fid

    def release_refs(self, refs: Sequence[ResidentRef]) -> None:
        """Mark rows consumed without accumulating (failed / dropped
        reports); frees a flush matrix once every row is accounted for."""
        with self._lock:
            for ref in refs:
                self._consume_row_locked(ref)
        self._observe()

    def _consume_row_locked(self, ref: ResidentRef) -> None:
        fl = self._flushes.get(ref.flush_id)
        if fl is None:
            return
        fl.consumed.add(ref.row)  # idempotent: replay may re-release rows
        if len(fl.consumed) >= fl.rows:
            del self._flushes[ref.flush_id]
            self.resident_bytes -= fl.nbytes

    # -- accumulation ----------------------------------------------------
    def commit_rows(
        self,
        bucket_key: tuple,
        backend,
        refs: Sequence[ResidentRef],
        *,
        job_token,
        report_ids: Sequence[bytes],
    ) -> None:
        """Psum the referenced rows into the bucket's resident buffer (one
        device launch per source flush, no readback) and journal the delta.

        Raises :class:`AccumulatorUnavailable` on any device failure; the
        bucket is then poisoned and the caller must oracle-replay +
        :meth:`discard`.
        """
        if not refs:
            return
        # evict BEFORE mutating: a mid-eviction failure must leave this
        # commit cleanly un-applied (exactly-once recovery depends on it)
        self._evict_if_needed()
        with self._lock:
            by_flush: Dict[int, List[int]] = {}
            for ref in refs:
                by_flush.setdefault(ref.flush_id, []).append(ref.row)
            sources = []
            for fid, rows in by_flush.items():
                fl = self._flushes.get(fid)
                if fl is None:
                    raise AccumulatorUnavailable(
                        f"flush {fid} no longer resident (evicted past recall)"
                    )
                fl.last_used = time.monotonic()
                sources.append((fl, rows))
            # The MINTING backend (recorded on the flush at retain time) is
            # the accumulation authority: its buffer widths and sharding
            # match the retained matrix by construction, while the caller's
            # backend can diverge after a canonical-twin fallback/recovery
            # (an exact-shape flush committed through the bucket twin — or
            # vice versa — would mismatch widths).  The caller's backend is
            # only the last resort for legacy flushes without one.
            mint = sources[0][0].backend or backend
            bucket = self._buckets.get(bucket_key)
            if bucket is None:
                bucket = _Bucket(bucket_key, mint)
                self._buckets[bucket_key] = bucket
            elif bucket.backend is None:
                # the bucket was opened by a host-vector commit
                # (commit_host_rows — e.g. a Poplar1 oracle-fallback row at
                # the same level); adopt the minting backend so the drain
                # can read the device buffer this commit is about to create
                bucket.backend = mint
            if bucket.poisoned:
                raise AccumulatorUnavailable(
                    f"bucket {bucket_key!r} poisoned by an earlier launch failure"
                )
        with bucket.oplock:
            # re-validate under the op lock: a concurrent drain/discard may
            # have detached this bucket after we looked it up — landing
            # rows in a buffer that was already read would merge them into
            # another job's delta without their journal entry
            if bucket.closed or bucket.poisoned:
                raise AccumulatorUnavailable(
                    f"bucket {bucket_key!r} was drained/poisoned concurrently"
                )
            try:
                for fl, rows in sources:
                    pad = fl.matrix.shape[0]
                    mask = np.zeros(pad, dtype=bool)
                    mask[rows] = True
                    bucket.buffer = (fl.backend or backend).accumulate_rows(
                        bucket.buffer, fl.matrix, mask
                    )
            except Exception as e:
                bucket.poisoned = True
                raise AccumulatorUnavailable(
                    f"accumulate launch failed: {e}"
                ) from e
            # journal under the SAME lock as the buffer update, so a
            # drain's snapshot can never see the delta without its entry
            # agg-param planes (Poplar1 sketch matrices) carry their drain
            # field explicitly — the eviction/drain_all field resolution
            # for backends with no vdaf.flp face
            if bucket.field is None:
                bucket.field = getattr(mint, "accum_field", None)
            with self._lock:
                if bucket.buffer_nbytes == 0:
                    bucket.buffer_nbytes = self._buffer_nbytes(mint)
                    self.resident_bytes += bucket.buffer_nbytes
                bucket.journal.append((job_token, frozenset(report_ids)))
                bucket.row_count += len(refs)
                bucket.last_used = time.monotonic()
                for ref in refs:
                    self._consume_row_locked(ref)
        self._observe()

    def commit_host_rows(
        self,
        bucket_key: tuple,
        field,
        vectors: Sequence[Sequence[int]],
        *,
        job_token,
        report_ids: Sequence[bytes],
    ) -> None:
        """Host-vector twin of :meth:`commit_rows` for VDAFs whose out
        shares are materialized on the host (Poplar1's sketch ``y``
        vectors finish in the ping-pong layer as field ints): sum
        ``vectors`` into the bucket's host mirror and journal the delta
        under the SAME exactly-once fence — deferred drains, cadence
        scans, poisoning, and the datastore journal/replay machinery all
        behave identically to device buckets.  What the store adds for
        these buckets is not PCIe savings but the cross-job level-keyed
        accumulation window: N jobs at one tree level merge as ONE
        datastore vector write, with the persisted journal rows making a
        crash before the drain recoverable.  Host mirrors are off the
        resident-byte budget (same posture as evicted device state)."""
        if not vectors:
            return
        if len(vectors) != len(report_ids):
            raise AccumulatorError("one vector per report id required")
        with self._lock:
            bucket = self._buckets.get(bucket_key)
            if bucket is None:
                bucket = _Bucket(bucket_key, None)
                self._buckets[bucket_key] = bucket
            if bucket.poisoned:
                raise AccumulatorUnavailable(
                    f"bucket {bucket_key!r} poisoned by an earlier failure"
                )
        with bucket.oplock:
            # same re-validation as commit_rows: a concurrent drain/discard
            # may have detached the bucket after the lookup
            if bucket.closed or bucket.poisoned:
                raise AccumulatorUnavailable(
                    f"bucket {bucket_key!r} was drained/poisoned concurrently"
                )
            bucket.field = field
            acc = bucket.spilled_host
            for v in vectors:
                acc = list(v) if acc is None else field.vec_add(acc, v)
            bucket.spilled_host = acc
            with self._lock:
                bucket.journal.append((job_token, frozenset(report_ids)))
                bucket.row_count += len(vectors)
                bucket.last_used = time.monotonic()
        self._observe()

    @staticmethod
    def _buffer_nbytes(backend) -> int:
        explicit = getattr(backend, "accum_buffer_nbytes", None)
        if explicit:
            return int(explicit)
        try:
            flp = backend.vdaf.flp
            # mesh backends keep one (OUT, n) partial-sum row PER DEVICE
            # (accum_buffer_rows = mesh size), so the resident-byte budget
            # must account the whole sharded buffer, not one chip's slice
            rows = getattr(backend, "accum_buffer_rows", 1)
            return rows * flp.OUTPUT_LEN * backend.bp.jf.n * 4
        except Exception:
            return 0

    # -- spill -----------------------------------------------------------
    def drain(self, bucket_key: tuple, field) -> Optional[Tuple[List[int], Set[bytes]]]:
        """Commit-time spill: read back the bucket's resident sum as ONE
        field vector, clear the bucket + journal, and return
        ``(vector, journaled report ids)``.  Returns None when the bucket
        holds nothing."""
        out = self.drain_with_journal(bucket_key, field)
        if out is None:
            return None
        vector, journal = out
        rids: Set[bytes] = set()
        for _job, ids in journal:
            rids |= ids
        return vector, rids

    def drain_with_journal(
        self, bucket_key: tuple, field
    ) -> Optional[Tuple[List[int], List[Tuple[object, frozenset]]]]:
        """Like :meth:`drain`, but returns the per-job journal entries
        ``[(job_token, frozenset(report_ids)), ...]`` instead of the flat
        id set — the deferred-drain transaction consumes the persisted
        ``accumulator_journal`` rows at job granularity, and may only
        merge the vector if EVERY entry's row is still present (a missing
        row means a crash-recovery replay already merged that job's
        shares; merging the vector then would double-count them).
        The named fault point ``accumulator.spill`` fires here so chaos
        runs exercise mid-spill failures."""
        with self._lock:
            bucket = self._buckets.pop(bucket_key, None)
            if bucket is not None:
                self.resident_bytes -= bucket.buffer_nbytes
        if bucket is None:
            return None
        with bucket.oplock:
            # closed stops any concurrent commit that resolved this bucket
            # before the pop: its rows must go to a FRESH bucket (or the
            # caller's replay), never into a buffer we are about to read
            bucket.closed = True
            if bucket.poisoned:
                with self._lock:  # restore for discard()/replay bookkeeping
                    self._buckets[bucket_key] = bucket
                    self.resident_bytes += bucket.buffer_nbytes
                raise AccumulatorUnavailable(f"bucket {bucket_key!r} is poisoned")
            try:
                faults.fire("accumulator.spill")
                vector = bucket.spilled_host
                if bucket.buffer is not None:
                    t0 = time.monotonic()
                    drained = bucket.backend.read_accum_buffer(bucket.buffer)
                    self._attribute_drain(bucket_key, time.monotonic() - t0)
                    with self._lock:
                        self.drain_readback_rows += 1
                    vector = (
                        drained if vector is None else field.vec_add(vector, drained)
                    )
            except Exception as e:
                with self._lock:
                    bucket.poisoned = True
                    self._buckets[bucket_key] = bucket
                    self.resident_bytes += bucket.buffer_nbytes
                raise AccumulatorUnavailable(f"spill readback failed: {e}") from e
            journal = list(bucket.journal)
        with self._lock:
            self.spills += 1
        self._observe(spill_reason="commit")
        if vector is None:
            return None
        return vector, journal

    @staticmethod
    def _attribute_drain(bucket_key: tuple, seconds: float) -> None:
        """Spill/drain cost rows (ISSUE 12): the per-bucket readback is
        device time spent FOR one task — bucket keys are
        ``(role, task, shape, ident, param)``, so the task ident rides in
        slot 1 — attributed under phase="drain" beside the flush-split
        stage/launch seconds.  Best-effort: a malformed legacy key
        attributes to "unattributed" rather than failing the drain."""
        try:
            from ..core import costs

            ident = bucket_key[1] if len(bucket_key) > 1 else None
            costs.cost_model().attribute_direct(
                ident, "drain", "device", seconds
            )
        except Exception:  # pragma: no cover - attribution is never fatal
            logger.debug("drain cost attribution failed", exc_info=True)

    def discard(self, bucket_key: tuple) -> List[Tuple[object, frozenset]]:
        """Drop a (typically poisoned) bucket's device state WITHOUT
        spilling and return its journal so the caller can oracle-replay the
        un-spilled reports.  Dropping before replay is what makes recovery
        exactly-once: the device delta can never be drained later."""
        with self._lock:
            bucket = self._buckets.pop(bucket_key, None)
            if bucket is None:
                return []
            self.resident_bytes -= bucket.buffer_nbytes
        with bucket.oplock:
            # stop any in-flight commit racing the discard: its rows must
            # not land in a buffer nobody will ever drain
            bucket.closed = True
            journal = list(bucket.journal)
        self._observe(spill_reason="discard")
        return journal

    # -- eviction --------------------------------------------------------
    def _evict_if_needed(self) -> None:
        budget = self.config.byte_budget
        if budget <= 0:
            return
        while True:
            with self._lock:
                if self.resident_bytes <= budget:
                    return
                victim = self._pick_victim_locked()
                if victim is None:
                    return
            self._evict(victim)

    def _pick_victim_locked(self):
        """LRU across flush matrices and bucket buffers still on device."""
        candidates: List[Tuple[float, object]] = []
        for fl in self._flushes.values():
            if not fl.on_host:
                candidates.append((fl.last_used, fl))
        for b in self._buckets.values():
            if b.buffer is not None and not b.poisoned:
                candidates.append((b.last_used, b))
        if not candidates:
            return None
        return min(candidates, key=lambda c: c[0])[1]

    def _evict(self, victim) -> None:
        """Spill one LRU item to its host mirror (fault point
        ``accumulator.evict``); device failures poison buckets (flush
        eviction failures poison every bucket lazily via commit_rows)."""
        faults.fire("accumulator.evict")
        if isinstance(victim, _Flush):
            host = np.asarray(victim.matrix)
            with self._lock:
                if self._flushes.get(victim.flush_id) is not victim or victim.on_host:
                    return  # freed or already evicted since the LRU pick
                victim.matrix = host
                victim.on_host = True
                self.resident_bytes -= victim.nbytes
                # the host mirror is off-budget; zero the tab so the
                # final consume-and-free doesn't subtract a second time
                victim.nbytes = 0
                self.evictions += 1
            logger.info(
                "evicted flush %d (%d rows) to host under memory pressure",
                victim.flush_id,
                victim.rows,
            )
        else:  # _Bucket
            with victim.oplock:
                if victim.buffer is None or victim.closed:
                    return  # drained/discarded since the LRU pick
                t0 = time.monotonic()
                drained = victim.backend.read_accum_buffer(victim.buffer)
                self._attribute_drain(victim.key, time.monotonic() - t0)
                field = (
                    victim.field
                    or getattr(victim.backend, "accum_field", None)
                    or victim.backend.vdaf.flp.field
                )
                victim.spilled_host = (
                    drained
                    if victim.spilled_host is None
                    else field.vec_add(victim.spilled_host, drained)
                )
                victim.buffer = None
                with self._lock:
                    # account only while still registered: a concurrent
                    # drain pop already took buffer_nbytes off the books
                    if self._buckets.get(victim.key) is victim:
                        self.resident_bytes -= victim.buffer_nbytes
                    victim.buffer_nbytes = 0
                    self.evictions += 1
            logger.info("evicted bucket %r accumulator to host", victim.key)
        self._observe(evicted=True)

    # -- lifecycle / introspection --------------------------------------
    def rebalance(self) -> dict:
        """Occupancy housekeeping for the maintenance loop: run the LRU
        eviction pass (normally paid inline by the next commit) so memory
        pressure is relieved on cadence instead of on the hot path, and
        return the occupancy snapshot the loop logs.  Bucket placement
        note: every bucket spans the LOCAL mesh (the same ICI domain its
        flush matrices live on), so within one process "rebalancing" is
        budget eviction; spreading buckets across MESHES on multi-slice
        hosts is the ROADMAP follow-on that would land here."""
        self._evict_if_needed()
        return self.stats()

    def due_buckets(self, max_age_s: float) -> List[tuple]:
        """Keys of buckets whose oldest un-drained delta is older than
        ``max_age_s`` — the deferred-drain cadence scan."""
        now = time.monotonic()
        with self._lock:
            return [
                b.key
                for b in self._buckets.values()
                if now - b.created_at >= max_age_s
            ]

    def bucket_keys(self) -> List[tuple]:
        with self._lock:
            return list(self._buckets)

    def drain_all(self, sink) -> None:
        """Drain every bucket into ``sink(key, vector, journal_entries)``
        (callers that can merge the vectors somewhere durable — the
        graceful-shutdown spill); buckets whose drain OR sink fails are
        discarded with a warning — their persisted journal rows, if any,
        make the loss recoverable via the collection-time replay."""
        with self._lock:
            keys = list(self._buckets)
        for key in keys:
            try:
                with self._lock:
                    b = self._buckets.get(key)
                    # host-vector buckets carry their drain field directly;
                    # device buckets derive it from the minting backend
                    field = (
                        None
                        if b is None
                        else (b.field or getattr(
                            getattr(getattr(b.backend, "vdaf", None), "flp", None),
                            "field",
                            None,
                        ))
                    )
                if field is None:
                    continue
                out = self.drain_with_journal(key, field)
                if out is not None:
                    sink(key, out[0], out[1])
            except Exception:
                logger.warning(
                    "drain_all failed for bucket %r; discarding", key, exc_info=True
                )
                self.discard(key)

    def discard_all(self) -> None:
        """Shutdown teardown: drop every resident delta WITHOUT the
        per-bucket readback (there is nowhere durable to put a vector at
        shutdown), logging what is dropped — any delta still resident
        belongs to a job whose tx never committed, so lease redelivery
        re-derives it; nothing is lost, and nothing dies silently."""
        with self._lock:
            keys = list(self._buckets)
        for key in keys:
            journal = self.discard(key)
            if journal:
                rids = set()
                for _job, ids in journal:
                    rids |= ids
                logger.warning(
                    "dropping un-spilled resident delta for bucket %r "
                    "(%d report(s)); the owning job never committed its tx "
                    "and will redeliver",
                    key,
                    len(rids),
                )
        with self._lock:
            self._flushes.clear()
            self._buckets.clear()
            self.resident_bytes = 0
        self._observe()

    def stats(self) -> dict:
        with self._lock:
            return {
                "resident_bytes": self.resident_bytes,
                "flushes_resident": len(self._flushes),
                "buckets": len(self._buckets),
                "retained_rows": self.retained_rows,
                "spills": self.spills,
                "evictions": self.evictions,
                "drain_readback_rows": self.drain_readback_rows,
            }

    def _observe(self, spill_reason: Optional[str] = None, evicted: bool = False):
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is None:
            return
        GLOBAL_METRICS.accumulator_resident_bytes.set(self.resident_bytes)
        GLOBAL_METRICS.accumulator_buckets.set(len(self._buckets))
        if spill_reason is not None:
            GLOBAL_METRICS.accumulator_spills.labels(reason=spill_reason).inc()
        if evicted:
            GLOBAL_METRICS.accumulator_evictions.inc()
