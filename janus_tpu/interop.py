"""Interop test API binaries.

The analog of the reference's ``interop_binaries`` crate (reference:
interop_binaries/src/: janus_interop_{client,aggregator,collector}.rs,
implementing draft-dcook-ppm-dap-interop-test-design): one multi-call app
per role exposing the ``/internal/test/*`` HTTP API so cross-implementation
harnesses can drive client, leader, helper, and collector uniformly.

    client:      ready, upload
    aggregator:  ready, endpoint_for_task, add_task
    collector:   ready, add_task, collection_start, collection_poll

Run: ``python -m janus_tpu.binaries janus_interop_{client,aggregator,
collector}`` — or build the apps in-process for tests.
"""

from __future__ import annotations

import asyncio
import os
import secrets
from typing import Dict, Optional

from aiohttp import web

from .aggregator import (
    Aggregator,
    AggregationJobCreator,
    AggregationJobDriver,
    CollectionJobDriver,
    Config,
    CreatorConfig,
    aggregator_app,
)
from .core.auth_tokens import AuthenticationToken
from .core.hpke import HpkeKeypair
from .core.time import RealClock
from .datastore import AggregatorTask, Crypter, Datastore, TaskQueryType, generate_key
from .messages import (
    Duration,
    FixedSizeQuery,
    HpkeConfig,
    Interval,
    Query,
    Role,
    TaskId,
    Time,
)


from .messages.dap import _b64url as _b64u, _unb64url as _unb64u


def _vdaf_to_instance(vdaf: dict) -> dict:
    """Interop JSON VDAF object -> VdafInstance description.  The interop
    design carries numbers as JSON strings."""
    t = vdaf["type"]
    out = {"type": t}
    for key in ("bits", "length", "chunk_length", "proofs", "rounds"):
        if key in vdaf:
            out[key] = int(vdaf[key])
    return out


def _success(**kw) -> web.Response:
    return web.json_response({"status": "success", **kw})


def _error(detail: str) -> web.Response:
    return web.json_response({"status": "error", "error": detail})


# ---------------------------------------------------------------------------


def interop_client_app() -> web.Application:
    """reference: interop_binaries/src/commands/janus_interop_client.rs"""

    async def ready(_request):
        return web.Response(status=200)

    async def upload(request: web.Request):
        from .client import Client
        from .vdaf.instances import vdaf_from_instance

        body = await request.json()
        try:
            vdaf = vdaf_from_instance(_vdaf_to_instance(body["vdaf"]))
            measurement = body["measurement"]
            if isinstance(measurement, str):
                measurement = int(measurement)
            elif isinstance(measurement, list):
                measurement = [int(x) for x in measurement]
            client = Client(
                task_id=TaskId(_unb64u(body["task_id"])),
                leader_endpoint=body["leader"],
                helper_endpoint=body["helper"],
                vdaf=vdaf,
                time_precision=Duration(int(body["time_precision"])),
            )
            t = Time(int(body["time"])) if body.get("time") else None
            await client.upload(measurement, time=t)
            return _success()
        except Exception as e:
            return _error(str(e))

    app = web.Application()
    app.add_routes(
        [
            web.post("/internal/test/ready", ready),
            web.post("/internal/test/upload", upload),
        ]
    )
    return app


# ---------------------------------------------------------------------------


def interop_aggregator_app(
    datastore: Datastore, aggregator: Aggregator, dap_app: web.Application
) -> web.Application:
    """reference: interop_binaries janus_interop_aggregator.rs — wraps a DAP
    aggregator, adding the /internal/test/* control surface."""

    async def ready(_request):
        return web.Response(status=200)

    async def endpoint_for_task(_request):
        # DAP is served under /dap/ on the same server
        return _success(endpoint="/dap/")

    async def add_task(request: web.Request):
        body = await request.json()
        try:
            role = Role[body["role"].upper()]
            query_kind = int(body.get("query_type", 1))
            if query_kind == 1:
                query_type = TaskQueryType.time_interval()
            else:
                query_type = TaskQueryType.fixed_size(
                    max_batch_size=int(body["max_batch_size"])
                    if body.get("max_batch_size")
                    else None
                )
            leader_token = body["leader_authentication_token"]
            task = AggregatorTask(
                task_id=TaskId(_unb64u(body["task_id"])),
                peer_aggregator_endpoint=body["helper"]
                if role == Role.LEADER
                else body["leader"],
                query_type=query_type,
                vdaf=_vdaf_to_instance(body["vdaf"]),
                role=role,
                vdaf_verify_key=_unb64u(body["vdaf_verify_key"]),
                min_batch_size=int(body["min_batch_size"]),
                time_precision=Duration(int(body["time_precision"])),
                task_expiration=Time(int(body["task_expiration"]))
                if body.get("task_expiration")
                else None,
                aggregator_auth_token=AuthenticationToken.new_bearer(leader_token)
                if role == Role.LEADER
                else None,
                aggregator_auth_token_hash=AuthenticationToken.new_bearer(
                    leader_token
                ).hash()
                if role == Role.HELPER
                else None,
                collector_auth_token_hash=AuthenticationToken.new_bearer(
                    body["collector_authentication_token"]
                ).hash()
                if body.get("collector_authentication_token")
                else None,
                collector_hpke_config=HpkeConfig.get_decoded(
                    _unb64u(body["collector_hpke_config"])
                )
                if body.get("collector_hpke_config")
                else None,
                hpke_keys=[HpkeKeypair.generate(1)],
            )
            await datastore.run_tx_async(
                "interop_add_task", lambda tx: tx.put_aggregator_task(task)
            )
            return _success()
        except Exception as e:
            return _error(str(e))

    app = web.Application()
    app.add_routes(
        [
            web.post("/internal/test/ready", ready),
            web.post("/internal/test/endpoint_for_task", endpoint_for_task),
            web.post("/internal/test/add_task", add_task),
        ]
    )
    # serve the DAP API on the same server under /
    app.add_subapp("/dap/", dap_app)
    return app


# ---------------------------------------------------------------------------


def interop_collector_app() -> web.Application:
    """reference: interop_binaries janus_interop_collector.rs"""
    tasks: Dict[str, dict] = {}
    handles: Dict[str, asyncio.Task] = {}

    async def ready(_request):
        return web.Response(status=200)

    async def add_task(request: web.Request):
        body = await request.json()
        try:
            keypair = HpkeKeypair.generate(137)
            tasks[body["task_id"]] = {
                "config": body,
                "keypair": keypair,
            }
            return _success(
                collector_hpke_config=_b64u(keypair.config.get_encoded())
            )
        except Exception as e:
            return _error(str(e))

    async def collection_start(request: web.Request):
        from .collector import Collector
        from .vdaf.instances import vdaf_from_instance

        body = await request.json()
        try:
            entry = tasks[body["task_id"]]
            cfg = entry["config"]
            vdaf = vdaf_from_instance(_vdaf_to_instance(cfg["vdaf"]))
            collector = Collector(
                task_id=TaskId(_unb64u(body["task_id"])),
                leader_endpoint=cfg["leader"],
                vdaf=vdaf,
                auth_token=AuthenticationToken.new_bearer(
                    cfg["collector_authentication_token"]
                ),
                hpke_keypair=entry["keypair"],
            )
            q = body["query"]
            if int(q["type"]) == 1:
                query = Query.new_time_interval(
                    Interval(
                        Time(int(q["batch_interval_start"])),
                        Duration(int(q["batch_interval_duration"])),
                    )
                )
            else:
                if q.get("subtype") in (1, "1", None) and not q.get("batch_id"):
                    query = Query.new_fixed_size(FixedSizeQuery.current_batch())
                else:
                    from .messages import BatchId

                    query = Query.new_fixed_size(
                        FixedSizeQuery.by_batch_id(BatchId(_unb64u(q["batch_id"])))
                    )
            agg_param = _unb64u(body.get("agg_param", "") or "")
            handle = secrets.token_hex(16)
            handles[handle] = asyncio.ensure_future(
                collector.collect(query, agg_param)
            )
            return _success(handle=handle)
        except Exception as e:
            return _error(str(e))

    async def collection_poll(request: web.Request):
        body = await request.json()
        task = handles.get(body.get("handle", ""))
        if task is None:
            return _error("unknown handle")
        if not task.done():
            return web.json_response({"status": "in progress"})
        try:
            result = task.result()
        except Exception as e:
            return _error(str(e))
        agg = result.aggregate_result
        if isinstance(agg, list):
            agg_json = [str(x) for x in agg]
        else:
            agg_json = str(agg)
        return _success(
            report_count=result.report_count,
            interval_start=result.interval.start.seconds,
            interval_duration=result.interval.duration.seconds,
            result=agg_json,
        )

    app = web.Application()
    app.add_routes(
        [
            web.post("/internal/test/ready", ready),
            web.post("/internal/test/add_task", add_task),
            web.post("/internal/test/collection_start", collection_start),
            web.post("/internal/test/collection_poll", collection_poll),
        ]
    )
    return app


# ---------------------------------------------------------------------------


def run_interop_binary(role: str, port: int = 8080) -> None:
    """Entry for ``python -m janus_tpu.binaries janus_interop_<role>``:
    in-memory datastore + background drivers, the way the reference's
    containerized interop aggregator runs its own migrations + daemons."""
    if role == "client":
        web.run_app(interop_client_app(), port=port)
        return
    if role == "collector":
        web.run_app(interop_collector_app(), port=port)
        return

    import tempfile

    clock = RealClock()
    path = tempfile.mkstemp(suffix=".sqlite3", prefix="janus-interop-")[1]
    datastore = Datastore(path, Crypter([generate_key()]), clock)
    # Backend selectable from the environment so the containerized harness
    # can exercise the device paths (oracle | tpu | mesh).
    backend = os.environ.get("JANUS_TPU_VDAF_BACKEND", "oracle")
    aggregator = Aggregator(
        datastore,
        clock,
        Config(max_upload_batch_write_delay=0.05, vdaf_backend=backend),
    )
    dap_app = aggregator_app(aggregator)

    async def main():
        import aiohttp

        creator = AggregationJobCreator(
            datastore, CreatorConfig(min_aggregation_job_size=1)
        )
        agg_driver = AggregationJobDriver(datastore, aiohttp.ClientSession)
        coll_driver = CollectionJobDriver(datastore, aiohttp.ClientSession)

        async def drive_loop():
            while True:
                try:
                    await creator.run_once()
                    leases = await datastore.run_tx_async(
                        "acq_a",
                        lambda tx: tx.acquire_incomplete_aggregation_jobs(
                            Duration(600), 10
                        ),
                    )
                    for lease in leases:
                        await agg_driver.step_aggregation_job(lease)
                    leases = await datastore.run_tx_async(
                        "acq_c",
                        lambda tx: tx.acquire_incomplete_collection_jobs(
                            Duration(600), 10
                        ),
                    )
                    for lease in leases:
                        await coll_driver.step_collection_job(lease)
                except Exception:
                    import logging

                    logging.getLogger("janus_tpu.interop").exception("drive failed")
                await asyncio.sleep(0.5)

        app = interop_aggregator_app(datastore, aggregator, dap_app)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "0.0.0.0", port)
        await site.start()
        task = asyncio.ensure_future(drive_loop())
        try:
            await asyncio.Event().wait()
        finally:
            task.cancel()

    asyncio.run(main())
