"""Binaries: daemons + ops CLI (reference: aggregator/src/binaries/)."""
