"""Layered configuration: YAML file + environment-variable secrets.

The analog of the reference's config system (reference:
aggregator/src/config.rs:31-199, binary_utils.rs:49,207-238): a
``CommonConfig`` shared by every binary (database, health port, logging),
per-binary sections with defaults, and secrets (datastore keys, auth tokens)
taken from the environment, never the file.
"""

from __future__ import annotations

import base64
import os
from dataclasses import dataclass, field
from typing import List, Optional

import yaml


class ConfigError(Exception):
    pass


def redact_database_url(url: str) -> str:
    """DB location safe for logs: the DSN password is dropped
    (reference: config.rs:115-124 redacts the url in Debug output)."""
    if "://" not in url:
        return url  # SQLite file path: nothing secret
    scheme, _, rest = url.partition("://")
    authority, slash, tail = rest.partition("/")
    # Userinfo lives only in the authority (an '@' in path/query is data),
    # and only a userinfo WITH a password needs redacting.
    if "@" in authority:
        userinfo, _, host = authority.rpartition("@")
        if ":" in userinfo:
            user = userinfo.split(":", 1)[0]
            return f"{scheme}://{user}:REDACTED@{host}{slash}{tail}"
    return url


@dataclass
class DbConfig:
    """reference: config.rs:75 DbConfig"""

    path: str = "janus_tpu.sqlite3"

    def __repr__(self) -> str:
        return f"DbConfig(path={redact_database_url(self.path)!r})"


@dataclass
class FaultInjectionConfig:
    """Deterministic fault injection (core/faults.py).  DEFAULT FULLY
    OFF — when disabled nothing is sampled and every injection point is
    a single boolean check.  Enabling arms named points with per-point
    probability and mode, e.g.::

        fault_injection:
          enabled: true
          seed: 7
          points:
            datastore.tx.begin: {mode: error, probability: 0.05}
            http.request:
              - {mode: error, probability: 0.1}
              - {mode: delay, probability: 0.1, delay_s: 0.05}
            executor.flush: {mode: error, probability: 0.2}
            clock.skew: {mode: skew, probability: 0.2, skew_s: 30}

    Point names and modes are documented in core/faults.py
    (KNOWN_POINTS / MODES).
    """

    enabled: bool = False
    seed: int = 0
    #: point name -> FaultSpec kwargs (one mapping or a list of them)
    points: dict = field(default_factory=dict)

    def install(self) -> None:
        """Arm the process-wide registry (no-op when disabled)."""
        from ..core import faults

        if not self.enabled or not self.points:
            return
        specs = []
        for point, opts in self.points.items():
            for o in opts if isinstance(opts, list) else [opts]:
                specs.append(faults.FaultSpec(point=point, **dict(o)))
        faults.configure(specs, seed=self.seed)


@dataclass
class FleetConfig:
    """Fleet control plane (core/fleet.py).  DEFAULT OFF — when disabled
    no router is installed, no member row is written, and the drivers'
    acquisition filter is bit-for-bit the plain suspect filter.  Enabled,
    each driver binary registers ``replica_id`` with a heartbeat row and
    rendezvous-routes tasks across the live same-role members::

        fleet:
          enabled: true
          replica_id: agg-east-1     # empty -> hostname-pid-nonce
          heartbeat_interval_s: 2.0
          heartbeat_ttl_s: 10.0      # member liveness horizon
          takeover_grace_s: 5.0      # delay before acquiring absorbed tasks
          suspect_staleness_s: 30.0  # shared-suspect advertisement horizon

    TTL tuning: migration latency after a SIGKILL is bounded by
    ``heartbeat_ttl_s + takeover_grace_s``; the TTL must comfortably
    exceed ``heartbeat_interval_s`` (>= 3x) or routine scheduling jitter
    reads as death and causes migration storms.
    """

    enabled: bool = False
    #: Stable identity in the rendezvous domain.  Give restarts the SAME
    #: id (deployment slot name) so a bounced replica re-owns its tasks —
    #: and its warm compile cache — instead of reshuffling the fleet.
    #: Empty = hostname-pid-nonce (unique per process start).
    replica_id: str = ""
    heartbeat_interval_s: float = 2.0
    heartbeat_ttl_s: float = 10.0
    takeover_grace_s: float = 5.0
    suspect_staleness_s: float = 30.0
    #: Migration-storm suppression (ISSUE 17): if MORE than this fraction
    #: of the previously-live same-role members (excluding self) go stale
    #: in one ownership refresh, the staleness is treated as correlated
    #: (datastore brownout) and the router freezes its last-known
    #: ownership view instead of migrating.  0.5 means "more than half
    #: vanished at once"; raise toward 1.0 to suppress only total
    #: blackouts, lower toward 0.0 to make any multi-member loss freeze.
    mass_staleness_fraction: float = 0.5


@dataclass
class DatastoreHealthConfig:
    """Datastore health tracker (core/db_health.py): the brownout
    detector fed by every run_tx retry.  Always on — the thresholds only
    shape when consecutive transient tx failures flip the process-wide
    verdict to SUSPECT (fleet freezes routing, upload front door sheds,
    janitors skip their sweeps)."""

    #: consecutive transient tx failures before SUSPECT
    failure_threshold: int = 3
    #: suspect dwell before transactions count as probes again
    suspect_dwell_s: float = 5.0


@dataclass
class CommonConfig:
    """reference: config.rs:31 CommonConfig"""

    database: DbConfig = field(default_factory=DbConfig)
    health_check_listen_address: str = "127.0.0.1:8000"
    max_transaction_retries: int = 30
    log_level: str = "INFO"
    #: jax.distributed cluster membership, for GANG-SCHEDULED SPMD
    #: deployments whose launcher starts (and restarts) every process
    #: together and runs the same launch sequence in lockstep — with
    #: JANUS_TPU_MESH_SPAN=global the mesh then spans every host's chips,
    #: DCN collectives between hosts (the analog of the reference's
    #: NCCL/MPI multi-node backend).  The ORDINARY lease-driven daemons
    #: must leave this empty: they issue independent per-replica launches
    #: (a cross-host collective would deadlock), their mesh is the local
    #: host's chips, and cross-host scale-out is the N-stateless-replica
    #: shared-datastore model — note initialize() also blocks at a
    #: startup barrier until ALL processes join, which fits a gang
    #: scheduler and not independently-restarting replicas.  Fields
    #: mirror jax.distributed.initialize.
    distributed_coordinator: str = ""  # "host:port"; empty = no cluster
    distributed_num_processes: int = 0
    distributed_process_id: int = -1
    #: Chrome-trace (Trace Event Format) output path for job/launch spans —
    #: load in chrome://tracing or Perfetto (reference: trace.rs:145-156
    #: chrome tracing layer).  Off when empty.
    chrome_trace_path: str = ""
    #: jax.profiler server port for on-demand device captures (0 = off;
    #: reference analog: trace.rs:158-236 always-on tooling sockets).
    profiler_port: int = 0
    #: Deterministic fault injection across the failure domains
    #: (datastore tx, peer HTTP, executor/device launches, clock skew);
    #: fully off by default.
    fault_injection: FaultInjectionConfig = field(default_factory=FaultInjectionConfig)
    #: Status sampler cadence (core/statusz.py): publishes the sampled
    #: queue-depth/freshness gauges (acquirable jobs, outstanding journal
    #: rows + oldest age) and retires idle executor buckets.  <= 0 disables.
    status_sample_interval_s: float = 5.0
    #: Idle threshold for executor-bucket gauge retirement (cardinality
    #: cap); <= 0 keeps every bucket's series forever (pre-ISSUE-5 shape).
    #: The per-task cost series (janus_task_*) retire on the same tick
    #: and threshold.
    executor_bucket_idle_s: float = 600.0
    #: Per-task cost-attribution cardinality cap (core/costs.py): at most
    #: this many live ``task`` label values on the janus_task_* series;
    #: tasks beyond it attribute to task="other" until the sampler-tick
    #: retirement frees idle slots.
    cost_task_cardinality: int = 64
    #: OTLP collector endpoint (core/otlp.py), e.g.
    #: ``http://otel-collector:4318`` — when set, ChromeTracer spans and
    #: the metric registry are exported OTLP/HTTP on the status-sampler
    #: cadence.  Import-gated on the opentelemetry-sdk: without the lib
    #: the exporter is a first-class no-op and /statusz's "otlp" section
    #: says "unavailable".  Empty = no export.
    otlp_endpoint: str = ""
    #: Declarative SLO targets (core/slo.py), evaluated by the status
    #: sampler into janus_slo_burn_rate{slo,window} /
    #: janus_slo_breach_total{slo} and the /statusz "slo" section::
    #:
    #:     slos:
    #:       commit_age:     {objective: 0.99, threshold_s: 60}
    #:       collection_e2e: {objective: 0.95, threshold_s: 600}
    #:
    #: Signals: commit_age, upload_to_commit, job_age_at_acquire,
    #: collection_e2e, first_flush (or any raw janus_* histogram name via
    #: ``signal:``).  Empty = no SLO evaluation.
    slos: dict = field(default_factory=dict)
    #: Fleet-wide persistent XLA compile cache ROOT (utils/jax_setup.py):
    #: when set, every binary points jax's compilation cache at
    #: ``<dir>/<config-digest>`` at startup, so a restarted replica (crash
    #: recovery, rollout) replays its executables instead of re-paying
    #: 37-286 s of compile per VDAF shape.  The digest subdirectory keys
    #: on (JAX_PLATFORMS, XLA_FLAGS, host CPU fingerprint) — a shared
    #: volume is safe across heterogeneous hosts — and the no-cache-on-CPU
    #: guard still applies (XLA:CPU AOT loads are poisoned; see
    #: enable_compile_cache).  Empty = no persistent cache.
    compile_cache_dir: str = ""
    #: Fleet control plane (core/fleet.py): replica membership +
    #: rendezvous task routing for the job drivers; fully off by default.
    fleet: FleetConfig = field(default_factory=FleetConfig)
    #: Datastore health tracker thresholds (core/db_health.py); the
    #: tracker itself is always on.
    db_health: DatastoreHealthConfig = field(default_factory=DatastoreHealthConfig)


@dataclass
class AccumulatorStoreConfig:
    """Device-resident accumulator store (``device_executor.accumulator.*``,
    janus_tpu/executor/accumulator.py).  DEFAULT OFF — enabling keeps each
    flush's out shares resident on device and spills ONE field vector per
    batch bucket at job commit instead of reading every mega-batch back."""

    enabled: bool = False
    #: resident-byte cap (flush matrices + bucket buffers); LRU state
    #: spills to host mirrors beyond it.  <= 0 disables eviction.
    byte_budget: int = 256 << 20
    #: Deferred drains: 0 (default) drains every bucket at job commit;
    #: > 0 accumulates across jobs and drains buckets once they are this
    #: old.  Each contributing job persists an accumulator_journal row in
    #: its commit transaction, so a crashed replica's un-drained deltas
    #: are re-derived from the datastore by the collection-time oracle
    #: replay (guaranteed drain-before-collection).
    drain_interval_s: float = 0.0
    #: Dedicated maintenance loop cadence (aggregation-job-driver binary):
    #: > 0 drains due deferred buckets and rebalances resident occupancy
    #: from a background loop instead of only at committing drivers'
    #: commits, so an idle task's bucket never waits for unrelated
    #: traffic.  <= 0 disables the loop (commit-driven drains only).
    maintenance_interval_s: float = 0.0

    def to_accumulator_config(self):
        from ..executor.accumulator import AccumulatorConfig

        return AccumulatorConfig(
            enabled=self.enabled,
            byte_budget=self.byte_budget,
            drain_interval_s=self.drain_interval_s,
            maintenance_interval_s=self.maintenance_interval_s,
        )


@dataclass
class DeviceExecutorConfig:
    """Process-wide device executor (janus_tpu/executor/): continuous
    cross-job batching of Prio3 prepare.  Default OFF — the per-driver
    gather-window path stays the oracle-verified default; enabling routes
    every driver's prepare through one bucketed continuous batcher that
    owns the chip."""

    enabled: bool = False
    #: Mesh-sharded mega-batches (``device_executor.mesh: true``): every
    #: single-chip TpuBackend the executor caches is upgraded to the SPMD
    #: MeshBackend over the LOCAL mesh (this host's chips), so staging
    #: lands each mega-batch's report shards directly on their devices
    #: and the accumulator keeps per-bucket buffers sharded.  Equivalent
    #: to setting ``vdaf_backend: mesh`` on every producer in the
    #: process.  Lease-driven daemons must keep the default local span —
    #: see the JANUS_TPU_MESH_SPAN caveat on CommonConfig's distributed_*
    #: fields (a cross-host collective from independent replicas would
    #: deadlock).
    mesh: bool = False
    #: flush a bucket once it holds this many rows (pow2-padded launch)
    flush_max_rows: int = 16384
    #: deadline (ms) from a bucket's first pending submission to its flush
    flush_window_ms: float = 5.0
    #: per-bucket queued+in-flight row bound; beyond it submits are
    #: rejected retryably (lease redelivery provides the retry)
    max_queue_rows: int = 131072
    #: per-submission deadline; queued past it -> retryable rejection
    #: (<= 0 disables deadline rejection)
    submit_timeout_s: float = 30.0
    #: mega-batch size to precompile per backend at startup (0 = off)
    warmup_rows: int = 0
    #: run warmup compiles on a background thread (default): backend
    #: resolution and binary startup never block behind XLA, and submits
    #: for a still-warming shape drain through the CPU oracle (or wait
    #: ``warmup_wait_s``).  False = legacy inline warmup.
    warmup_async: bool = True
    #: pow2 shape canonicalization (vdaf/canonical.py): key device
    #: backends by the canonical (bucket-padded) shape so N task shapes
    #: share O(log N) compiled executables, bit-exactly; shapes failing
    #: the parity preconditions keep exact-shape compiles.
    canonical_shapes: bool = True
    #: consecutive launch failures per VDAF shape before its circuit
    #: opens and the driver degrades to the CPU oracle (0 disables)
    breaker_failure_threshold: int = 5
    #: open-circuit dwell before a half-open probe launch tests the device
    breaker_reset_timeout_s: float = 30.0
    #: starvation-free flush scheduling (deficit round-robin across
    #: buckets, deadline-earliest within one); False = legacy FIFO
    fair_flush: bool = True
    #: deficit-round-robin quantum in rows
    fair_quota_rows: int = 16384
    #: flight recorder ring size (per-flush black-box records kept in
    #: memory for /statusz "flights" + breaker-trip/slow-flush dumps)
    flight_recorder_size: int = 256
    #: slow-flush anomaly factor: a flush whose launch exceeds this ×
    #: its bucket's rolling p95 dumps the flight ring (rate-limited);
    #: <= 0 disables the detector
    slow_flush_p95_factor: float = 4.0
    #: device-resident accumulator store (default off)
    accumulator: AccumulatorStoreConfig = field(default_factory=AccumulatorStoreConfig)

    def to_executor_config(self):
        """Build the runtime ExecutorConfig (jax-free import path)."""
        from ..executor import ExecutorConfig

        return ExecutorConfig(
            enabled=self.enabled,
            mesh=self.mesh,
            flush_max_rows=self.flush_max_rows,
            flush_window_s=self.flush_window_ms / 1000.0,
            max_queue_rows=self.max_queue_rows,
            submit_timeout_s=self.submit_timeout_s,
            warmup_rows=self.warmup_rows,
            warmup_async=self.warmup_async,
            canonical_shapes=self.canonical_shapes,
            breaker_failure_threshold=self.breaker_failure_threshold,
            breaker_reset_timeout_s=self.breaker_reset_timeout_s,
            fair_flush=self.fair_flush,
            fair_quota_rows=self.fair_quota_rows,
            flight_recorder_size=self.flight_recorder_size,
            slow_flush_p95_factor=self.slow_flush_p95_factor,
            accumulator=self.accumulator.to_accumulator_config()
            if self.accumulator.enabled
            else None,
        )


@dataclass
class JobDriverConfig:
    """reference: config.rs:172 JobDriverConfig"""

    job_discovery_interval_s: float = 10.0
    max_concurrent_job_workers: int = 10
    worker_lease_duration_s: int = 600
    worker_lease_clock_skew_allowance_s: int = 60
    maximum_attempts_before_failure: int = 10
    #: retryable-failure budget: redeliveries (lease_attempts) a job gets
    #: before a retryable step failure abandons it
    max_step_attempts: int = 10
    #: exponential lease-backoff curve between retryable redeliveries
    retry_initial_delay_s: float = 1.0
    retry_max_delay_s: float = 300.0
    #: expired-lease reaper cadence (crash recovery): clears lease tokens
    #: whose holder died without releasing, counting each into
    #: janus_job_leases_expired_total; <= 0 disables the reaper
    lease_reap_interval_s: float = 10.0
    #: per-attempt HTTP timeout toward the peer aggregator: one hung or
    #: blackholed attempt is cut off here instead of riding aiohttp's
    #: defaults (core/retries.py attempt_timeout); <= 0 disables
    http_attempt_timeout_s: float = 30.0
    #: peer-health gating (core/peer_health.py): consecutive transport
    #: failures before the peer is SUSPECT and lease work stops being
    #: burned on it (jobs release with retryable jittered backoff that
    #: never consumes max_step_attempts); 0 disables gating
    peer_failure_threshold: int = 3
    #: suspect dwell before half-open probes flow toward the peer again
    peer_suspect_dwell_s: float = 10.0


@dataclass
class IngestConfig:
    """Zero-copy ingest plane (core/ingest.py, ISSUE 18).  Mode
    ``synchronous`` (the default) keeps the legacy write path bit-for-bit:
    every upload commits its client_reports row inline via the
    ReportWriteBatcher before the 200 is sent.  Mode ``journaled`` flips
    the front door to the write-behind report journal::

        ingest:
          mode: journaled
          journal_batch_size: 100
          journal_write_delay_ms: 50
          journal_queue_max: 2048
          stage_direct: true
          stage_max_reports: 4096
          staged_consume_interval_ms: 250
          materialize_interval_ms: 1000
          materialize_batch_size: 256

    Durability contract: an upload is ACKed only after its journal row is
    durable — write-behind defers the client_reports MATERIALIZATION (the
    aggregation-visible copy), never the ACK.  Freshly journaled reports
    are additionally staged in-process, pre-bucketed by (task, vdaf
    shape), and the embedded staged consumer packs them straight into
    aggregation jobs without the creator's read-back round-trip.
    """

    #: "synchronous" | "journaled"
    mode: str = "synchronous"
    #: journal-writer flush trigger: rows per flush tx / max delay a
    #: report waits for co-batching before its flush fires anyway
    journal_batch_size: int = 100
    journal_write_delay_ms: int = 50
    #: admission bound on queued+in-flight journal writes: past it the
    #: front door sheds 503s (janus_upload_shed_total{reason="journal"})
    #: instead of queueing unboundedly behind a slow journal writer
    journal_queue_max: int = 2048
    #: hand freshly journaled reports straight to the in-process staged
    #: consumer (false = journal only; the materializer read-back path
    #: carries everything)
    stage_direct: bool = True
    #: staged-buffer bound (reports across all cohorts); beyond it fresh
    #: reports fall back to the read-back path, never unbounded memory
    stage_max_reports: int = 4096
    #: embedded staged-consumer cadence (aggregator binary): how often
    #: staged cohorts are packed into aggregation jobs
    staged_consume_interval_ms: int = 250
    #: background materializer cadence + per-pass row bound: the
    #: write-behind half that folds journal rows into client_reports
    materialize_interval_ms: int = 1000
    materialize_batch_size: int = 256
    #: staged job sizing (mirrors JobCreatorConfig min/max): cohorts
    #: below min stay journaled for the periodic creator to fold in
    staged_min_job_size: int = 10
    staged_max_job_size: int = 256


@dataclass
class CanaryConfig:
    """The canary plane's prober (core/canary.py; ISSUE 20): black-box
    known-plaintext probes through the real upload -> aggregate ->
    collect path, one auto-provisioned task per VDAF family.

        canary:
          leader_endpoint: "http://127.0.0.1:8080"
          helper_endpoint: "http://127.0.0.1:8081"
          leader_task_api: "http://127.0.0.1:9080"
          helper_task_api: "http://127.0.0.1:9081"
          task_api_auth_token: "admin-token"
          families: [prio3_sum, prio3_histogram]
          probe_interval_s: 30
          trace_globs: ["/tmp/traces/*.trace"]
    """

    #: DAP endpoints the probes travel through (the real front doors)
    leader_endpoint: str = ""
    helper_endpoint: str = ""
    #: management APIs (aggregator task_api_listen_address) the prober
    #: provisions its canary tasks against
    leader_task_api: str = ""
    helper_task_api: str = ""
    task_api_auth_token: str = ""
    #: VDAF families to probe (each gets its own canary task); names
    #: resolve through core/canary.py FAMILIES
    families: List[str] = field(default_factory=lambda: ["prio3_sum", "prio3_histogram"])
    #: probe cadence and collection-poll budget
    probe_interval_s: float = 30.0
    poll_interval_s: float = 0.5
    collect_timeout_s: float = 60.0
    #: consecutive probe failures before a family's verdict is "failing"
    #: (one failure = "degraded")
    fail_threshold: int = 2
    #: consecutive 503-shed suppressions before the next shed counts as a
    #: loud upload failure — a front door that never reopens must page
    shed_escalate_after: int = 3
    #: canary-task time precision; each probe cycle aggregates its own
    #: already-closed bucket, walking backward so batches never overlap
    time_precision_s: int = 3600
    #: chrome-trace globs (the replicas' trace files) for per-stage
    #: commit/first-prepare attribution; empty = prober-clock stages only
    trace_globs: List[str] = field(default_factory=list)


@dataclass
class CanaryBinaryConfig:
    common: CommonConfig = field(default_factory=CommonConfig)
    canary: CanaryConfig = field(default_factory=CanaryConfig)


@dataclass
class AggregatorConfig:
    common: CommonConfig = field(default_factory=CommonConfig)
    listen_address: str = "0.0.0.0:8080"
    max_upload_batch_size: int = 100
    max_upload_batch_write_delay_ms: int = 250
    #: Upload HPKE-open backend (ISSUE 14): "batched" groups concurrent
    #: uploads' expensive opens into one vectorized AES-GCM pass on a
    #: worker thread (bit-exact vs inline, per-report fallback on any
    #: batch-level error); "inline" keeps the legacy per-report open.
    upload_open_backend: str = "batched"
    upload_open_batch_size: int = 64
    upload_open_batch_delay_ms: int = 5
    #: Front-door admission control: past this many pending opens — or
    #: once the oldest pending open has waited upload_shed_delay_s —
    #: uploads shed with the DAP-retryable 503 + Retry-After (counted in
    #: janus_upload_shed_total) instead of drowning the event loop.
    upload_queue_max: int = 1024
    upload_shed_delay_s: float = 2.0
    #: Zero-copy ingest plane (ISSUE 18): write-behind report journal +
    #: direct upload->staging handoff; mode "synchronous" is the
    #: bit-for-bit legacy default.
    ingest: IngestConfig = field(default_factory=IngestConfig)
    batch_aggregation_shard_count: int = 8
    task_counter_shard_count: int = 8
    #: "tpu" routes whole-job prepare through one batched device launch.
    vdaf_backend: str = "tpu"
    #: Field-arithmetic layout for the device backends: "vpu" (scalar-lane
    #: CIOS chains + limb-planar Pallas kernels, the default) or "mxu"
    #: (limb-plane dot_general contractions so the FLP wire/gadget math
    #: runs on the matrix units).  Bit-exact either way — the A/B toggle
    #: for ops/field_jax.py's MXU contraction layer.
    field_backend: str = "vpu"
    #: Poplar1 AES-walk backend: "host" (cryptography/AES-NI, numpy
    #: soft-AES fallback — the legacy path) or "jax" (the jitted kernel in
    #: ops/aes_jax.py: table AES over u8 byte planes, the IDPF frontier
    #: and sketch vectors device-resident).  Bit-exact either way — the
    #: A/B toggle for the device-resident IDPF walk.
    poplar_backend: str = "host"
    #: Aggregation-job size for agg-param VDAFs (Poplar1), whose jobs are
    #: created by the collection request rather than the periodic creator.
    #: Small values cost nothing at prepare time with the executor on —
    #: the jobs' rows re-coalesce in the level-keyed poplar_init bucket.
    max_agg_param_job_size: int = 256
    #: Helper-side executor routing (default off): the helper's Prio3
    #: prep_init/combine — and Poplar1's poplar_init — submit through the
    #: process-wide device executor, sharing its continuous batching +
    #: circuit breaker with the drivers.
    device_executor: DeviceExecutorConfig = field(default_factory=DeviceExecutorConfig)
    garbage_collection_interval_s: Optional[float] = None
    #: Management REST API (aggregator_api.py): task CRUD + HPKE key
    #: management, bearer-auth, served on its OWN address (never the DAP
    #: port — provisioning must not share the front door's shed/auth
    #: story).  Empty disables; the canary plane provisions its probe
    #: tasks through this.
    task_api_listen_address: str = ""
    task_api_auth_tokens: List[str] = field(default_factory=list)
    #: Global-HPKE key rotation loop (reference: binaries/aggregator.rs:31-150
    #: runs the maintenance loops beside the server); None disables.
    key_rotator_interval_s: Optional[float] = None
    key_rotator_pending_duration_s: int = 86400
    key_rotator_active_duration_s: int = 7 * 86400
    key_rotator_expired_duration_s: int = 86400


@dataclass
class JobCreatorConfig:
    common: CommonConfig = field(default_factory=CommonConfig)
    aggregation_job_creation_interval_s: float = 60.0
    min_aggregation_job_size: int = 10
    max_aggregation_job_size: int = 256
    batch_aggregation_shard_count: int = 8
    #: Report-journal replay grace (ISSUE 18): journal rows younger than
    #: this are left for the upload replica's direct staged consumer —
    #: replaying them here is safe (delete-linearized) but wastes the
    #: zero-copy handoff.  0 replays everything immediately.
    journal_replay_min_age_s: float = 5.0


@dataclass
class JobDriverBinaryConfig:
    common: CommonConfig = field(default_factory=CommonConfig)
    job_driver: JobDriverConfig = field(default_factory=JobDriverConfig)
    batch_aggregation_shard_count: int = 8
    vdaf_backend: str = "tpu"
    #: Device field-arithmetic layout ("vpu" | "mxu") — see
    #: AggregatorConfig.field_backend.
    field_backend: str = "vpu"
    #: Poplar1 AES-walk backend ("host" | "jax") — see
    #: AggregatorConfig.poplar_backend.
    poplar_backend: str = "host"
    #: Continuous cross-job batching for device prepare (default off).
    device_executor: DeviceExecutorConfig = field(default_factory=DeviceExecutorConfig)
    #: While a shape's executable is still warming (background compile),
    #: wait up to this long on the compile future before serving the job
    #: on the CPU oracle; 0 = oracle immediately.
    warmup_wait_s: float = 0.0


def _merge_dataclass(cls, data: dict):
    """Build a (possibly nested) config dataclass from a YAML dict, applying
    defaults for absent keys and rejecting unknown ones."""
    import dataclasses

    if data is None:
        data = {}
    if not isinstance(data, dict):
        raise ConfigError(f"expected mapping for {cls.__name__}")
    fields = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(data) - set(fields)
    if unknown:
        raise ConfigError(f"unknown {cls.__name__} keys: {sorted(unknown)}")
    # `from __future__ import annotations` makes f.type a string; resolve
    # nested config classes by name.
    nested = {
        c.__name__: c
        for c in (
            CommonConfig,
            DbConfig,
            JobDriverConfig,
            DeviceExecutorConfig,
            AccumulatorStoreConfig,
            FaultInjectionConfig,
            FleetConfig,
            DatastoreHealthConfig,
            IngestConfig,
            CanaryConfig,
        )
    }
    kwargs = {}
    for name, f in fields.items():
        if name not in data:
            continue
        type_name = f.type if isinstance(f.type, str) else getattr(f.type, "__name__", "")
        if type_name in nested:
            kwargs[name] = _merge_dataclass(nested[type_name], data[name])
        else:
            kwargs[name] = data[name]
    return cls(**kwargs)


def load_config(cls, path: Optional[str] = None, text: Optional[str] = None):
    """Load a binary's config from YAML (path or literal text)."""
    if text is None:
        if path is None:
            return cls()
        with open(path) as f:
            text = f.read()
    return _merge_dataclass(cls, yaml.safe_load(text))


# -- secrets from the environment (reference: binary_utils.rs:207-238) ------


def datastore_keys_from_env() -> List[bytes]:
    """DATASTORE_KEYS: comma-separated base64url AES-128 keys; first one
    encrypts (reference: janus_cli create-datastore-key)."""
    raw = os.environ.get("DATASTORE_KEYS")
    if not raw:
        raise ConfigError("DATASTORE_KEYS environment variable is required")
    keys = []
    for part in raw.split(","):
        part = part.strip()
        pad = "=" * (-len(part) % 4)
        keys.append(base64.urlsafe_b64decode(part + pad))
    return keys


def parse_listen_address(addr: str):
    host, _, port = addr.rpartition(":")
    return host or "0.0.0.0", int(port)
