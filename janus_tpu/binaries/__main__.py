from .main import main

import sys

sys.exit(main())
