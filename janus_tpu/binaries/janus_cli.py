"""Ops CLI.

The analog of ``janus_cli`` plus the ``tools`` crate binaries (reference:
aggregator/src/binaries/janus_cli.rs:70-177, tools/src/bin/{dap_decode,
hpke_keygen}.rs, tools/src/bin/collect): task provisioning from YAML,
datastore/HPKE key generation, wire-message decoding, and a collector
front-end.
"""

from __future__ import annotations

import base64
import json
import secrets
import sys

import click


from ..messages.dap import _b64url as _b64u, _unb64url as _unb64u


@click.group()
def cli():
    """janus_tpu operations CLI."""


@cli.command("create-datastore-key")
def create_datastore_key():
    """Generate a datastore column-encryption key (reference:
    janus_cli.rs create-datastore-key)."""
    click.echo(_b64u(secrets.token_bytes(16)))


@cli.command("generate-hpke-key")
@click.option("--id", "config_id", type=int, default=1, help="HPKE config id")
def generate_hpke_key(config_id: int):
    """Generate an HPKE keypair (reference: tools/src/bin/hpke_keygen.rs:13)."""
    from ..core.hpke import HpkeKeypair

    kp = HpkeKeypair.generate(config_id)
    click.echo(
        json.dumps(
            {
                "config": _b64u(kp.config.get_encoded()),
                "private_key": _b64u(kp.private_key),
                "id": config_id,
            }
        )
    )


@cli.command("provision-tasks")
@click.argument("tasks_file", type=click.Path(exists=True))
@click.option("--config-file", type=click.Path(exists=True), default=None)
def provision_tasks(tasks_file: str, config_file):
    """Provision tasks from a YAML file into the datastore (reference:
    janus_cli.rs provision-tasks).

    Each task entry: task_id (b64url, optional — generated if absent),
    peer_aggregator_endpoint, query_type ({kind, max_batch_size?}), vdaf
    ({type, ...params}), role (Leader|Helper), vdaf_verify_key (b64url),
    min_batch_size, time_precision_s, auth tokens, collector_hpke_config,
    hpke_keys.
    """
    import yaml

    from ..core.auth_tokens import AuthenticationToken
    from ..core.hpke import HpkeKeypair
    from ..core.time import RealClock
    from ..datastore import (
        AggregatorTask,
        Crypter,
        Datastore,
        TaskQueryType,
    )
    from ..messages import Duration, HpkeConfig, Role, TaskId, Time
    from .config import AggregatorConfig, datastore_keys_from_env, load_config

    cfg = load_config(AggregatorConfig, config_file)
    ds = Datastore(
        cfg.common.database.path, Crypter(datastore_keys_from_env()), RealClock()
    )
    with open(tasks_file) as f:
        entries = yaml.safe_load(f)
    for entry in entries:
        qt = entry.get("query_type", {"kind": "TimeInterval"})
        btws = qt.get("batch_time_window_size")
        task = AggregatorTask(
            task_id=TaskId(_unb64u(entry["task_id"]))
            if "task_id" in entry
            else TaskId.random(),
            peer_aggregator_endpoint=entry["peer_aggregator_endpoint"],
            query_type=TaskQueryType(
                qt["kind"],
                qt.get("max_batch_size"),
                Duration(btws) if btws is not None else None,
            ),
            vdaf=entry["vdaf"],
            role=Role[entry["role"].upper()],
            vdaf_verify_key=_unb64u(entry["vdaf_verify_key"]),
            min_batch_size=entry["min_batch_size"],
            time_precision=Duration(entry["time_precision_s"]),
            task_expiration=Time(entry["task_expiration"])
            if entry.get("task_expiration")
            else None,
            report_expiry_age=Duration(entry["report_expiry_age_s"])
            if entry.get("report_expiry_age_s")
            else None,
            aggregator_auth_token=AuthenticationToken.new_bearer(
                entry["aggregator_auth_token"]
            )
            if entry.get("aggregator_auth_token")
            else None,
            aggregator_auth_token_hash=AuthenticationToken.new_bearer(
                entry["aggregator_auth_token_for_hash"]
            ).hash()
            if entry.get("aggregator_auth_token_for_hash")
            else None,
            collector_auth_token_hash=AuthenticationToken.new_bearer(
                entry["collector_auth_token_for_hash"]
            ).hash()
            if entry.get("collector_auth_token_for_hash")
            else None,
            collector_hpke_config=HpkeConfig.get_decoded(
                _unb64u(entry["collector_hpke_config"])
            )
            if entry.get("collector_hpke_config")
            else None,
            hpke_keys=[
                HpkeKeypair(
                    HpkeConfig.get_decoded(_unb64u(k["config"])),
                    _unb64u(k["private_key"]),
                )
                for k in entry.get("hpke_keys", [])
            ],
        )
        ds.run_tx("provision_task", lambda tx, t=task: tx.put_aggregator_task(t))
        click.echo(f"provisioned task {task.task_id}")


@cli.command("generate-global-hpke-key")
@click.option("--id", "config_id", type=int, required=True)
@click.option("--config-file", type=click.Path(exists=True), default=None)
def generate_global_hpke_key(config_id: int, config_file):
    """Generate + store a global HPKE key (reference: janus_cli.rs
    generate-global-hpke-key)."""
    from ..core.hpke import HpkeKeypair
    from ..core.time import RealClock
    from ..datastore import Crypter, Datastore
    from .config import AggregatorConfig, datastore_keys_from_env, load_config

    cfg = load_config(AggregatorConfig, config_file)
    ds = Datastore(
        cfg.common.database.path, Crypter(datastore_keys_from_env()), RealClock()
    )
    kp = HpkeKeypair.generate(config_id)
    ds.run_tx("put_global_key", lambda tx: tx.put_global_hpke_keypair(kp))
    click.echo(f"generated global HPKE key {config_id}")


@cli.command("set-global-hpke-key-state")
@click.option("--id", "config_id", type=int, required=True)
@click.option(
    "--state", type=click.Choice(["Pending", "Active", "Expired"]), required=True
)
@click.option("--config-file", type=click.Path(exists=True), default=None)
def set_global_hpke_key_state(config_id: int, state: str, config_file):
    """reference: janus_cli.rs set-global-hpke-key-state"""
    from ..core.time import RealClock
    from ..datastore import Crypter, Datastore, HpkeKeyState
    from .config import AggregatorConfig, datastore_keys_from_env, load_config

    cfg = load_config(AggregatorConfig, config_file)
    ds = Datastore(
        cfg.common.database.path, Crypter(datastore_keys_from_env()), RealClock()
    )
    ds.run_tx(
        "set_key_state",
        lambda tx: tx.set_global_hpke_keypair_state(config_id, HpkeKeyState(state)),
    )
    click.echo("ok")


@cli.command("add-taskprov-peer-aggregator")
@click.option("--endpoint", required=True)
@click.option("--role", type=click.Choice(["Leader", "Helper"]), required=True)
@click.option("--verify-key-init", required=True, help="b64url 32 bytes")
@click.option("--collector-hpke-config", required=True, help="b64url HpkeConfig")
@click.option("--aggregator-auth-token", default=None)
@click.option("--aggregator-auth-token-for-hash", default=None)
@click.option("--config-file", type=click.Path(exists=True), default=None)
def add_taskprov_peer_aggregator(
    endpoint,
    role,
    verify_key_init,
    collector_hpke_config,
    aggregator_auth_token,
    aggregator_auth_token_for_hash,
    config_file,
):
    """reference: janus_cli.rs add-taskprov-peer-aggregator"""
    from ..aggregator.taskprov import PeerAggregator
    from ..core.auth_tokens import AuthenticationToken
    from ..core.time import RealClock
    from ..datastore import Crypter, Datastore
    from ..messages import HpkeConfig, Role
    from .config import AggregatorConfig, datastore_keys_from_env, load_config

    cfg = load_config(AggregatorConfig, config_file)
    ds = Datastore(
        cfg.common.database.path, Crypter(datastore_keys_from_env()), RealClock()
    )
    peer = PeerAggregator(
        endpoint=endpoint,
        role=Role[role.upper()],
        verify_key_init=_unb64u(verify_key_init),
        collector_hpke_config=HpkeConfig.get_decoded(_unb64u(collector_hpke_config)),
        aggregator_auth_token=AuthenticationToken.new_bearer(aggregator_auth_token)
        if aggregator_auth_token
        else None,
        aggregator_auth_token_hash=AuthenticationToken.new_bearer(
            aggregator_auth_token_for_hash
        ).hash()
        if aggregator_auth_token_for_hash
        else None,
    )
    ds.run_tx("add_peer", lambda tx: tx.put_taskprov_peer_aggregator(peer))
    click.echo("ok")


@cli.command("quarantine-list")
@click.option("--task", default=None, help="hex task id filter")
@click.option(
    "--stage",
    default=None,
    help="stage filter (upload_open|prep_init|combine|journal|accumulator_journal)",
)
@click.option("--limit", type=int, default=256)
@click.option("--config-file", type=click.Path(exists=True), default=None)
def quarantine_list(task, stage, limit, config_file):
    """List quarantined poison/corrupt rows (ISSUE 19): what the bisection
    sieve and the journal checksum fence pulled out of the pipeline."""
    from ..core.time import RealClock
    from ..datastore import Crypter, Datastore
    from .config import AggregatorConfig, datastore_keys_from_env, load_config

    cfg = load_config(AggregatorConfig, config_file)
    ds = Datastore(
        cfg.common.database.path, Crypter(datastore_keys_from_env()), RealClock()
    )
    rows = ds.run_tx(
        "quarantine_list",
        lambda tx: tx.get_quarantined_reports(task=task, stage=stage, limit=limit),
    )
    for row in rows:
        click.echo(json.dumps(row))
    click.echo(f"{len(rows)} quarantined row(s)", err=True)


@cli.command("quarantine-purge")
@click.option("--task", default=None, help="hex task id filter")
@click.option("--stage", default=None, help="stage filter")
@click.option("--config-file", type=click.Path(exists=True), default=None)
@click.confirmation_option(
    prompt="Purge matching quarantined rows? The offender record is the only "
    "trace of what was dropped."
)
def quarantine_purge(task, stage, config_file):
    """Purge quarantined rows after investigation (ISSUE 19)."""
    from ..core.time import RealClock
    from ..datastore import Crypter, Datastore
    from .config import AggregatorConfig, datastore_keys_from_env, load_config

    cfg = load_config(AggregatorConfig, config_file)
    ds = Datastore(
        cfg.common.database.path, Crypter(datastore_keys_from_env()), RealClock()
    )
    purged = ds.run_tx(
        "quarantine_purge",
        lambda tx: tx.purge_quarantined_reports(task=task, stage=stage),
    )
    click.echo(f"purged {purged} quarantined row(s)")


def _fetch_statusz(replica: str, timeout_s: float) -> dict:
    """GET one replica's /statusz (stdlib only — the ops CLI must work on
    a box with nothing but the repo)."""
    import urllib.request

    url = replica.rstrip("/") + "/statusz"
    if not url.startswith(("http://", "https://")):
        url = "http://" + url
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode())


def _fmt_ms(seconds) -> str:
    return f"{seconds * 1e3:.1f}ms" if seconds is not None else "-"


@cli.command("canary-status")
@click.argument("replica", required=False, default=None)
@click.option(
    "--replicas",
    default=None,
    help="comma-separated health addresses (host:port) to query",
)
@click.option("--timeout", "timeout_s", type=float, default=5.0)
def canary_status(replica, replicas, timeout_s):
    """Fetch + pretty-print the /statusz canary section (ISSUE 20):
    verdict, per-stage p50/p99, last-good time per replica.  Exits
    non-zero when any replica's rolled-up verdict is "failing"."""
    import time as _time

    targets = []
    if replica:
        targets.append(replica)
    if replicas:
        targets.extend(r.strip() for r in replicas.split(",") if r.strip())
    if not targets:
        raise click.ClickException("give a replica address or --replicas")

    failing = False
    for target in targets:
        try:
            doc = _fetch_statusz(target, timeout_s)
        except Exception as e:
            click.echo(f"{target}: UNREACHABLE ({e})")
            failing = True
            continue
        canary = doc.get("canary") or {}
        if not canary.get("enabled"):
            click.echo(f"{target}: canary disabled")
            continue
        verdict = canary.get("verdict", "unknown")
        failing = failing or verdict == "failing"
        click.echo(f"{target}: verdict={verdict}")
        for name, fam in sorted((canary.get("families") or {}).items()):
            last_good = fam.get("last_good_unix")
            ago = (
                f"{max(0.0, _time.time() - last_good):.0f}s ago"
                if last_good
                else "never"
            )
            line = (
                f"  {name:<18} {fam.get('verdict', '?'):<9}"
                f" probes={fam.get('probes', 0)}"
                f" suppressed={fam.get('suppressed', 0)}"
                f" last_good={ago}"
            )
            if fam.get("failing_stage"):
                line += f" failing_stage={fam['failing_stage']}"
            if fam.get("last_outcome") and fam["last_outcome"] != "ok":
                line += f" last_outcome={fam['last_outcome']}"
            click.echo(line)
        for stage, pcts in sorted((canary.get("stage_latency_s") or {}).items()):
            if pcts.get("samples"):
                click.echo(
                    f"  stage {stage:<14} p50={_fmt_ms(pcts.get('p50'))}"
                    f" p99={_fmt_ms(pcts.get('p99'))}"
                    f" n={pcts['samples']}"
                )
    if failing:
        sys.exit(1)


@cli.command("dap-decode")
@click.argument("message_file", type=click.Path(exists=True))
@click.option(
    "--media-type",
    required=True,
    help="DAP media type, e.g. application/dap-report",
)
@click.option(
    "--query-type",
    type=click.Choice(["TimeInterval", "FixedSize"]),
    default="TimeInterval",
)
def dap_decode(message_file: str, media_type: str, query_type: str):
    """Decode a DAP wire message to a readable repr
    (reference: tools/src/bin/dap_decode.rs:15)."""
    from .. import messages as m

    by_media = {
        "application/dap-hpke-config": m.HpkeConfig,
        "application/dap-hpke-config-list": m.HpkeConfigList,
        "application/dap-report": m.Report,
        "application/dap-aggregation-job-init-req": m.AggregationJobInitializeReq,
        "application/dap-aggregation-job-continue-req": m.AggregationJobContinueReq,
        "application/dap-aggregation-job-resp": m.AggregationJobResp,
        "application/dap-collect-req": m.CollectionReq,
        "application/dap-collection": m.Collection,
        "application/dap-aggregate-share-req": m.AggregateShareReq,
        "application/dap-aggregate-share": m.AggregateShare,
    }
    cls = by_media.get(media_type)
    if cls is None:
        raise click.ClickException(f"unknown media type {media_type}")
    with open(message_file, "rb") as f:
        data = f.read()
    qt = m.TimeInterval if query_type == "TimeInterval" else m.FixedSize
    try:
        msg = cls.get_decoded(data, qt)
    except TypeError:
        msg = cls.get_decoded(data)
    click.echo(repr(msg))


@cli.command("collect")
@click.option("--task-id", required=True, help="b64url task id")
@click.option("--leader", required=True, help="leader endpoint URL")
@click.option("--vdaf", "vdaf_json", required=True, help="VDAF instance JSON")
@click.option("--auth-token", required=True, help="collector bearer token")
@click.option("--hpke-config", required=True, help="b64url collector HpkeConfig")
@click.option("--hpke-private-key", required=True, help="b64url private key")
@click.option("--batch-interval-start", type=int, default=None)
@click.option("--batch-interval-duration", type=int, default=None)
@click.option("--current-batch", is_flag=True, default=False)
def collect(
    task_id,
    leader,
    vdaf_json,
    auth_token,
    hpke_config,
    hpke_private_key,
    batch_interval_start,
    batch_interval_duration,
    current_batch,
):
    """Collector front-end (reference: tools collect CLI, 1,604 LoC)."""
    import asyncio

    from ..collector import Collector
    from ..core.auth_tokens import AuthenticationToken
    from ..core.hpke import HpkeKeypair
    from ..messages import (
        Duration,
        FixedSizeQuery,
        HpkeConfig,
        Interval,
        Query,
        TaskId,
        Time,
    )
    from ..vdaf.instances import vdaf_from_instance

    vdaf = vdaf_from_instance(json.loads(vdaf_json))
    collector = Collector(
        task_id=TaskId(_unb64u(task_id)),
        leader_endpoint=leader,
        vdaf=vdaf,
        auth_token=AuthenticationToken.new_bearer(auth_token),
        hpke_keypair=HpkeKeypair(
            HpkeConfig.get_decoded(_unb64u(hpke_config)), _unb64u(hpke_private_key)
        ),
    )
    if current_batch:
        query = Query.new_fixed_size(FixedSizeQuery.current_batch())
    else:
        if batch_interval_start is None or batch_interval_duration is None:
            raise click.ClickException(
                "either --current-batch or --batch-interval-start/duration required"
            )
        query = Query.new_time_interval(
            Interval(Time(batch_interval_start), Duration(batch_interval_duration))
        )
    result = asyncio.run(collector.collect(query))
    click.echo(
        json.dumps(
            {
                "report_count": result.report_count,
                "interval_start": result.interval.start.seconds,
                "interval_duration": result.interval.duration.seconds,
                "aggregate_result": result.aggregate_result,
            }
        )
    )


if __name__ == "__main__":
    cli()
