"""`collect` — command-line DAP collector front-end.

The analog of the reference's collect tool (reference:
tools/src/bin/collect.rs:295-720): given task parameters, VDAF parameters,
collector credentials, and a query, it creates a collection job against the
leader, polls it, HPKE-opens both aggregate shares, unshards, and prints the
aggregate.  Subcommands mirror the reference:

* (default / ``run``)  create a new collection job and poll to completion
* ``init``             create the job only; prints the collection job id
* ``poll``             poll an existing job once; exit 75 (EX_TEMPFAIL) if
                       it is not finished yet — the query options must match
                       the ones used at init so state can be reconstructed.
"""

from __future__ import annotations

import asyncio
import base64
import json
import sys

import click

EX_TEMPFAIL = 75


def _b64u_decode(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


def _build_vdaf(vdaf: str, length, bits, chunk_length):
    from ..vdaf.instances import vdaf_from_instance

    desc = {"type": {
        "count": "Prio3Count",
        "sum": "Prio3Sum",
        "sumvec": "Prio3SumVec",
        "histogram": "Prio3Histogram",
    }[vdaf]}
    if vdaf == "sum":
        if bits is None:
            raise click.UsageError("--bits is required for --vdaf=sum")
        desc["bits"] = bits
    elif vdaf == "sumvec":
        if length is None or bits is None:
            raise click.UsageError("--length and --bits are required for --vdaf=sumvec")
        desc.update(length=length, bits=bits, chunk_length=chunk_length or length)
    elif vdaf == "histogram":
        if length is None:
            raise click.UsageError("--length is required for --vdaf=histogram")
        desc.update(length=length, chunk_length=chunk_length or max(1, length // 2))
    return vdaf_from_instance(desc)


def _build_query(batch_interval_start, batch_interval_duration, batch_id, current_batch):
    from ..messages import BatchId, Duration, FixedSizeQuery, Interval, Query, Time

    given = [
        batch_interval_start is not None or batch_interval_duration is not None,
        batch_id is not None,
        current_batch,
    ]
    if sum(given) != 1:
        raise click.UsageError(
            "exactly one of (--batch-interval-start + --batch-interval-duration), "
            "--batch-id, or --current-batch must be given"
        )
    if batch_id is not None:
        return Query.new_fixed_size(FixedSizeQuery.by_batch_id(BatchId(_b64u_decode(batch_id))))
    if current_batch:
        return Query.new_fixed_size(FixedSizeQuery.current_batch())
    if batch_interval_start is None or batch_interval_duration is None:
        raise click.UsageError(
            "--batch-interval-start and --batch-interval-duration go together"
        )
    return Query.new_time_interval(
        Interval(Time(batch_interval_start), Duration(batch_interval_duration))
    )


def _collector(task_id, leader, auth, vdaf_obj, hpke_config, hpke_private_key):
    from ..collector import Collector
    from ..core.hpke import HpkeKeypair
    from ..messages import HpkeConfig, TaskId

    config = HpkeConfig.get_decoded(_b64u_decode(hpke_config))
    return Collector(
        task_id=TaskId(_b64u_decode(task_id)),
        leader_endpoint=leader,
        vdaf=vdaf_obj,
        auth_token=auth,
        hpke_keypair=HpkeKeypair(config, _b64u_decode(hpke_private_key)),
    )


def _print_result(result) -> None:
    payload = {
        "report_count": result.report_count,
        "aggregate_result": result.aggregate_result,
    }
    if result.interval is not None:
        payload["interval_start"] = result.interval.start.seconds
        payload["interval_duration"] = result.interval.duration.seconds
    pbs = getattr(result.partial_batch_selector, "batch_identifier", None)
    if pbs is not None:
        payload["batch_id"] = base64.urlsafe_b64encode(pbs.data).rstrip(b"=").decode()
    click.echo(json.dumps(payload))


_shared_options = [
    click.option("--task-id", required=True, help="DAP task id, unpadded base64url"),
    click.option("--leader", required=True, help="leader aggregator endpoint URL"),
    click.option(
        "--vdaf",
        type=click.Choice(["count", "sum", "sumvec", "histogram"]),
        required=True,
    ),
    click.option("--length", type=int, default=None, help="vector length / histogram buckets"),
    click.option("--bits", type=int, default=None, help="measurement bit width (sum/sumvec)"),
    click.option("--chunk-length", type=int, default=None),
    click.option("--dap-auth-token", default=None, help="DAP-Auth-Token header value"),
    click.option(
        "--authorization-bearer-token", default=None, help="Authorization: Bearer token"
    ),
    click.option("--batch-interval-start", type=int, default=None),
    click.option("--batch-interval-duration", type=int, default=None),
    click.option("--batch-id", default=None, help="fixed-size batch id, base64url"),
    click.option("--current-batch", is_flag=True, default=False),
    click.option("--hpke-config", required=True, help="HpkeConfig message, base64url"),
    click.option("--hpke-private-key", required=True, help="collector private key, base64url"),
]


def _with_shared(f):
    for opt in reversed(_shared_options):
        f = opt(f)
    return f


def _auth(dap_auth_token, authorization_bearer_token):
    from ..core.auth_tokens import AuthenticationToken

    if (dap_auth_token is None) == (authorization_bearer_token is None):
        raise click.UsageError(
            "exactly one of --dap-auth-token / --authorization-bearer-token required"
        )
    if dap_auth_token is not None:
        return AuthenticationToken.new_dap_auth(dap_auth_token)
    return AuthenticationToken.new_bearer(authorization_bearer_token)


@click.group(invoke_without_command=True)
@click.pass_context
@_with_shared
def collect(ctx, **kwargs):
    """Create a collection job and poll it to completion (default)."""
    ctx.ensure_object(dict)
    ctx.obj.update(kwargs)
    if ctx.invoked_subcommand is None:
        ctx.invoke(run)


def _setup(o):
    vdaf_obj = _build_vdaf(o["vdaf"], o["length"], o["bits"], o["chunk_length"])
    query = _build_query(
        o["batch_interval_start"],
        o["batch_interval_duration"],
        o["batch_id"],
        o["current_batch"],
    )
    auth = _auth(o["dap_auth_token"], o["authorization_bearer_token"])
    coll = _collector(
        o["task_id"], o["leader"], auth, vdaf_obj, o["hpke_config"], o["hpke_private_key"]
    )
    return coll, query


@collect.command()
@click.pass_context
def run(ctx):
    """Create a new collection job and poll it to completion."""
    coll, query = _setup(ctx.obj)
    result = asyncio.run(coll.collect(query))
    _print_result(result)


@collect.command()
@click.option("--collection-job-id", default=None, help="b64url 16 bytes; random if absent")
@click.pass_context
def init(ctx, collection_job_id):
    """Initialize a collection job; prints its id."""
    from ..messages import CollectionJobId

    coll, query = _setup(ctx.obj)
    job_id = (
        CollectionJobId(_b64u_decode(collection_job_id))
        if collection_job_id
        else CollectionJobId.random()
    )

    async def go():
        import aiohttp

        async with aiohttp.ClientSession() as session:
            await coll.create_job(query, job_id, session=session)

    asyncio.run(go())
    click.echo(base64.urlsafe_b64encode(job_id.data).rstrip(b"=").decode())


@collect.command()
@click.option("--collection-job-id", required=True, help="b64url 16 bytes")
@click.pass_context
def poll(ctx, collection_job_id):
    """Poll an existing collection job once; exit 75 while it runs."""
    from ..messages import CollectionJobId

    coll, query = _setup(ctx.obj)
    job_id = CollectionJobId(_b64u_decode(collection_job_id))

    async def go():
        import aiohttp

        async with aiohttp.ClientSession() as session:
            result, _retry = await coll.poll_once(query, job_id, session=session)
            return result

    result = asyncio.run(go())
    if result is None:
        sys.exit(EX_TEMPFAIL)
    _print_result(result)


if __name__ == "__main__":
    collect(obj={})
