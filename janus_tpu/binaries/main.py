"""Multi-call binary entry point.

The analog of the reference's single multi-call binary (reference:
aggregator/src/main.rs:93, binary_utils.rs:249 janus_main): one entry
dispatches by subcommand to the four long-running daemons and the ops CLI:

    python -m janus_tpu.binaries aggregator --config-file cfg.yaml
    python -m janus_tpu.binaries aggregation_job_creator ...
    python -m janus_tpu.binaries aggregation_job_driver ...
    python -m janus_tpu.binaries collection_job_driver ...
    python -m janus_tpu.binaries janus_cli <subcommand> ...

Bootstrap per binary: config load → logging → datastore (keys from env) →
SIGTERM-driven graceful stop → healthz server → main loop
(reference: binary_utils.rs:249-518).
"""

from __future__ import annotations

import asyncio
import logging
import signal
import sys
from typing import Optional

from ..core.time import RealClock
from ..datastore import Crypter, Datastore
from ..messages import Duration
from .config import (
    AggregatorConfig,
    CanaryBinaryConfig,
    ConfigError,
    JobCreatorConfig,
    JobDriverBinaryConfig,
    datastore_keys_from_env,
    load_config,
    parse_listen_address,
    redact_database_url,
)

logger = logging.getLogger("janus_tpu.binaries")


def _bootstrap(config_common):
    from ..core.trace import (
        TraceConfiguration,
        configure_chrome_trace,
        install_trace_subscriber,
        start_profiler_server,
    )

    install_trace_subscriber(TraceConfiguration(level=config_common.log_level))
    # Per-task cost-attribution cardinality cap (ISSUE 12): applied once
    # here, like the peer-health thresholds — the model is process-wide.
    from ..core.costs import configure_cost_attribution

    configure_cost_attribution(
        getattr(config_common, "cost_task_cardinality", 64)
    )
    # Datastore health tracker thresholds (ISSUE 17): process-wide like
    # the peer tracker — every binary's run_tx feeds the same verdict.
    db_cfg = getattr(config_common, "db_health", None)
    if db_cfg is not None:
        from ..core.db_health import tracker as db_tracker

        db_tracker().configure(
            failure_threshold=db_cfg.failure_threshold,
            suspect_dwell_s=db_cfg.suspect_dwell_s,
        )
    fault_cfg = getattr(config_common, "fault_injection", None)
    if fault_cfg is not None and fault_cfg.enabled:
        # Chaos mode: arm the deterministic fault registry.  Loud on
        # purpose — a production replica must never run armed silently.
        fault_cfg.install()
        logger.warning(
            "FAULT INJECTION ARMED (seed=%d, points=%s) — this replica "
            "will deliberately fail",
            fault_cfg.seed,
            sorted(fault_cfg.points),
        )
    if getattr(config_common, "distributed_coordinator", ""):
        # Gang-scheduled SPMD mode ONLY (see CommonConfig): join the
        # cluster BEFORE any backend touches jax.  initialize() blocks
        # until every process arrives — correct under a gang scheduler
        # that restarts the whole set together, wrong for independently
        # restarting replicas, which must leave this unset (their mesh is
        # local and the shared datastore is the cross-host scale model).
        nproc = config_common.distributed_num_processes
        pid = config_common.distributed_process_id
        if (nproc > 0) != (pid >= 0):
            raise ConfigError(
                "distributed_num_processes and distributed_process_id must "
                "be set together (or both left to auto-detection)"
            )
        import jax

        jax.distributed.initialize(
            coordinator_address=config_common.distributed_coordinator,
            num_processes=nproc or None,
            process_id=pid if pid >= 0 else None,
        )
        logger.info(
            "joined distributed cluster via %s (process %d of %d)",
            config_common.distributed_coordinator,
            jax.process_index(),
            jax.process_count(),
        )
    if getattr(config_common, "chrome_trace_path", ""):
        configure_chrome_trace(config_common.chrome_trace_path)
        logger.info("chrome trace -> %s", config_common.chrome_trace_path)
    if getattr(config_common, "otlp_endpoint", ""):
        # OTLP export (ISSUE 9): import-gated on the opentelemetry-sdk —
        # a config naming a collector must start cleanly on an SDK-less
        # host, with /statusz saying exactly why nothing is exported.
        from ..core.otlp import configure_otlp

        exporter = configure_otlp(config_common.otlp_endpoint)
        if exporter is not None and exporter.available:
            logger.info("otlp export -> %s", config_common.otlp_endpoint)
        else:
            logger.warning(
                "otlp export -> %s UNAVAILABLE (opentelemetry-sdk not "
                "installed); exporter is inert",
                config_common.otlp_endpoint,
            )
    if getattr(config_common, "slos", None):
        # SLO evaluation plane (ISSUE 9): declarative targets, evaluated
        # on the status-sampler tick.  Config typos fail startup loudly.
        from ..core.slo import configure_slos

        evaluator = configure_slos(config_common.slos)
        logger.info(
            "slo evaluator armed: %s",
            ", ".join(t.name for t in evaluator.targets),
        )
    if getattr(config_common, "profiler_port", 0):
        if start_profiler_server(config_common.profiler_port):
            logger.info("jax profiler server on :%d", config_common.profiler_port)
    if getattr(config_common, "compile_cache_dir", ""):
        # Fleet-wide persistent compile cache (ISSUE 8): a restarted
        # replica replays its XLA executables from the shared cache root
        # instead of re-paying every shape's compile.  enable_compile_cache
        # keeps the config/host-fingerprint scoping and the
        # no-cache-on-CPU guard (poisoned AOT loads) even for an explicit
        # root, so this is safe to set unconditionally in fleet config.
        from ..utils.jax_setup import enable_compile_cache, resolve_cache_dir

        if enable_compile_cache(config_common.compile_cache_dir):
            logger.info(
                "persistent compile cache -> %s",
                resolve_cache_dir(config_common.compile_cache_dir),
            )
        else:
            logger.info(
                "persistent compile cache disabled on this platform "
                "(CPU AOT loads are poisoned; cold compiles are cheaper)"
            )
    clock = RealClock()
    if fault_cfg is not None and fault_cfg.enabled:
        # clock-skew failure domain: armed replicas see a drifting clock
        # wherever the registry's clock.skew point fires (no-op otherwise)
        from ..core.faults import SkewedClock

        clock = SkewedClock(clock)
    crypter = Crypter(datastore_keys_from_env())
    logger.info("datastore: %s", redact_database_url(config_common.database.path))
    datastore = Datastore(
        config_common.database.path,
        crypter,
        clock,
        max_transaction_retries=config_common.max_transaction_retries,
    )
    return clock, datastore


def _stop_event_on_signals(loop) -> asyncio.Event:
    """SIGTERM/SIGINT → graceful stop (reference: binary_utils.rs:458)."""
    stop = asyncio.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    return stop


async def _serve_health(listen_address: str, datastore: Optional[Datastore] = None):
    """Health + zpages server: /healthz, /metrics, PUT /traceconfigz, and
    the GET /statusz introspection plane (reference: binary_utils.rs:398-456
    + the reference's zpages; core/statusz.py builds the snapshot)."""
    from aiohttp import web

    from ..core.metrics import GLOBAL_METRICS
    from ..core.statusz import statusz_snapshot
    from ..core.trace import reload_trace_filter

    async def healthz(_):
        return web.Response(text="ok")

    async def metrics(_):
        return web.Response(body=GLOBAL_METRICS.export(), content_type="text/plain")

    async def traceconfigz(request):
        level = (await request.text()).strip()
        reload_trace_filter(level)
        return web.Response(text=f"log level set to {level}\n")

    async def statusz(_):
        return web.json_response(await statusz_snapshot(datastore))

    app = web.Application()
    app.add_routes(
        [
            web.get("/healthz", healthz),
            web.get("/metrics", metrics),
            web.put("/traceconfigz", traceconfigz),
            web.get("/statusz", statusz),
        ]
    )
    runner = web.AppRunner(app)
    await runner.setup()
    host, port = parse_listen_address(listen_address)
    site = web.TCPSite(runner, host, port)
    await site.start()
    return runner


def _start_fleet_heartbeat(stop: asyncio.Event, datastore: Datastore, common):
    """Fleet heartbeat loop (core/fleet.py): refreshes this replica's
    member row on the configured cadence, republishing the peer-health
    tracker's current SUSPECT origins as the fleet-shared suspect set,
    and deregisters gracefully on shutdown so survivors re-route without
    waiting out the TTL.  Returns the task (or None when fleet is off)."""
    from ..core.fleet import fleet_router

    router = fleet_router()
    if router is None:
        return None
    interval = max(0.1, float(getattr(common.fleet, "heartbeat_interval_s", 2.0)))

    async def loop_():
        from ..core import peer_health

        consecutive_failures = 0
        while not stop.is_set():
            try:
                suspects = [
                    origin
                    for origin, s in peer_health.tracker().stats().items()
                    if s.get("state") == "suspect"
                ]
                # short per-beat deadline: a browned-out datastore must
                # not pin this beat through the full tx retry budget —
                # better to skip the beat and keep the loop's cadence
                await datastore.run_tx_async(
                    "fleet_heartbeat",
                    lambda tx: router.heartbeat(tx, suspects),
                    deadline_s=max(interval, 2.0),
                )
                consecutive_failures = 0
            except Exception:
                # A missed beat only ages our row (the TTL absorbs it) —
                # NEVER crash the binary over it.  Capped backoff: a
                # sustained brownout stretches the cadence instead of
                # hammering a struggling database with registration
                # writes; first failure logs the traceback, repeats stay
                # one line.
                consecutive_failures += 1
                if consecutive_failures == 1:
                    logger.exception("fleet heartbeat failed")
                else:
                    logger.warning(
                        "fleet heartbeat failed (%d consecutive; backing off)",
                        consecutive_failures,
                    )
            delay = min(interval * (2 ** min(consecutive_failures, 4)), 30.0)
            try:
                await asyncio.wait_for(stop.wait(), timeout=delay)
            except asyncio.TimeoutError:
                pass
        try:
            await datastore.run_tx_async("fleet_deregister", router.deregister)
        except Exception:
            logger.exception("fleet deregistration failed (TTL will expire us)")

    return asyncio.ensure_future(loop_())


def _start_status_sampler(stop: asyncio.Event, datastore: Datastore, common):
    """The small sampler loop every binary runs beside its main loop
    (ISSUE 5): publishes acquirable-backlog and journal-freshness gauges
    and retires idle executor buckets.  Returns the task (or None when
    disabled)."""
    interval = getattr(common, "status_sample_interval_s", 0)
    if not interval or interval <= 0:
        return None

    from ..core.otlp import export_tick, otlp_exporter
    from ..core.slo import evaluate_tick
    from ..core.statusz import retire_idle_executor_buckets, sample_status_metrics

    async def loop_():
        export_fut = None
        while not stop.is_set():
            # Self-evaluation rides the same tick (ISSUE 9) but NOT the
            # same failure domain: the evaluator reads only in-memory
            # registry snapshots, so it runs FIRST and in its own try —
            # a wedged datastore (the sampling below raising every tick)
            # is exactly when burn rates must keep moving.
            try:
                evaluate_tick()
            except Exception:
                logger.exception("slo evaluation tick failed")
            # OTLP export is fired WITHOUT awaiting: a slow/blackholed
            # collector (up to the exporter's timeout per POST) must not
            # stretch the sampling cadence.  At most one export is in
            # flight; a tick that finds the previous one still running
            # skips (export_once drains the whole queue each pass, so
            # nothing is lost).  Unconfigured (the default) or inert
            # (SDK absent — already logged at bootstrap, visible in
            # /statusz): no dispatch at all.
            exporter = otlp_exporter()
            if (
                exporter is not None
                and exporter.available
                and (export_fut is None or export_fut.done())
            ):
                export_fut = asyncio.get_running_loop().run_in_executor(
                    None, export_tick
                )
                export_fut.add_done_callback(
                    lambda f: f.exception()  # surfaced in otlp health; never raises past export_tick
                )
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, lambda: sample_status_metrics(datastore)
                )
                retire_idle_executor_buckets(
                    getattr(common, "executor_bucket_idle_s", 0)
                )
            except Exception:
                logger.exception("status sample failed")
            try:
                await asyncio.wait_for(stop.wait(), timeout=interval)
            except asyncio.TimeoutError:
                pass
        if export_fut is not None:
            await asyncio.gather(export_fut, return_exceptions=True)

    return asyncio.ensure_future(loop_())


def _start_accumulator_maintenance(stop: asyncio.Event, stepper_impl, cfg):
    """Dedicated accumulator maintenance loop beside the aggregation
    driver's main loop: drains due deferred buckets on cadence (an idle
    task's resident delta no longer waits for another job's commit) and
    rebalances resident occupancy.  Returns the task (None when the store
    or the cadence is disabled)."""
    acc = cfg.device_executor.accumulator
    interval = getattr(acc, "maintenance_interval_s", 0)
    if not acc.enabled or not interval or interval <= 0:
        return None

    async def loop_():
        while not stop.is_set():
            try:
                await stepper_impl.run_accumulator_maintenance()
            except Exception:
                logger.exception("accumulator maintenance pass failed")
            try:
                await asyncio.wait_for(stop.wait(), timeout=interval)
            except asyncio.TimeoutError:
                pass

    logger.info("accumulator maintenance loop every %.1fs", interval)
    return asyncio.ensure_future(loop_())


def _close_tracing() -> None:
    """Graceful-shutdown hook shared by every binary: flush/close the
    chrome tracer so a SIGTERM never truncates the trace mid-event
    (ISSUE 5 satellite; SIGKILL still loses at most the open spans)."""
    from ..core.trace import close_chrome_trace

    try:
        close_chrome_trace()
    except Exception:
        logger.exception("chrome-trace close failed during shutdown")


# ---------------------------------------------------------------------------


def run_aggregator(config_path: Optional[str]) -> None:
    """DAP HTTP server + optional GC loop
    (reference: binaries/aggregator.rs:31-150)."""
    cfg = load_config(AggregatorConfig, config_path)
    clock, datastore = _bootstrap(cfg.common)

    from aiohttp import web

    from ..aggregator import Aggregator, Config, GarbageCollector, aggregator_app

    agg = Aggregator(
        datastore,
        clock,
        Config(
            max_upload_batch_size=cfg.max_upload_batch_size,
            max_upload_batch_write_delay=cfg.max_upload_batch_write_delay_ms / 1000.0,
            upload_open_backend=cfg.upload_open_backend,
            upload_open_batch_size=cfg.upload_open_batch_size,
            upload_open_batch_delay=cfg.upload_open_batch_delay_ms / 1000.0,
            upload_queue_max=cfg.upload_queue_max,
            upload_shed_delay_s=cfg.upload_shed_delay_s,
            ingest_mode=cfg.ingest.mode,
            ingest_journal_batch_size=cfg.ingest.journal_batch_size,
            ingest_journal_write_delay=cfg.ingest.journal_write_delay_ms / 1000.0,
            ingest_journal_queue_max=cfg.ingest.journal_queue_max,
            ingest_stage_direct=cfg.ingest.stage_direct,
            ingest_stage_max_reports=cfg.ingest.stage_max_reports,
            batch_aggregation_shard_count=cfg.batch_aggregation_shard_count,
            task_counter_shard_count=cfg.task_counter_shard_count,
            vdaf_backend=cfg.vdaf_backend,
            field_backend=cfg.field_backend,
            poplar_backend=cfg.poplar_backend,
            max_agg_param_job_size=cfg.max_agg_param_job_size,
            device_executor=cfg.device_executor.to_executor_config()
            if cfg.device_executor.enabled
            else None,
        ),
    )

    async def main():
        loop = asyncio.get_running_loop()
        stop = _stop_event_on_signals(loop)
        health = await _serve_health(
            cfg.common.health_check_listen_address, datastore=datastore
        )
        app = aggregator_app(agg)
        runner = web.AppRunner(app)
        await runner.setup()
        host, port = parse_listen_address(cfg.listen_address)
        site = web.TCPSite(runner, host, port)
        await site.start()
        logger.info("aggregator serving on %s", cfg.listen_address)

        # Management REST API (ISSUE 20): task CRUD on its OWN listener,
        # never the DAP port — the canary plane provisions through this.
        task_api_runner = None
        if cfg.task_api_listen_address:
            from ..aggregator_api import aggregator_api_app

            task_api_runner = web.AppRunner(
                aggregator_api_app(datastore, cfg.task_api_auth_tokens)
            )
            await task_api_runner.setup()
            api_host, api_port = parse_listen_address(cfg.task_api_listen_address)
            await web.TCPSite(task_api_runner, api_host, api_port).start()
            logger.info("task API serving on %s", cfg.task_api_listen_address)

        async def periodic(name: str, fn, interval_s: float):
            """Run ``fn`` every interval until stop; failures log, not kill
            (the maintenance-loop shape of reference binaries/aggregator.rs)."""
            while not stop.is_set():
                try:
                    await fn()
                except Exception:
                    logger.exception("%s pass failed", name)
                try:
                    await asyncio.wait_for(stop.wait(), timeout=interval_s)
                except asyncio.TimeoutError:
                    pass

        tasks = []
        sampler = _start_status_sampler(stop, datastore, cfg.common)
        if sampler is not None:
            tasks.append(sampler)
        if agg.ingest is not None:
            # Zero-copy ingest plane (ISSUE 18).  Startup replay FIRST: a
            # previous journaled incarnation's ACKed-but-unmaterialized
            # rows become client_reports rows before traffic lands, so a
            # crash between ACK and flush loses nothing.
            from ..core.ingest import replay_report_journal

            replayed = await replay_report_journal(datastore)
            if replayed:
                logger.info(
                    "report-journal replay materialized %d report(s)", replayed
                )
            # The embedded staged consumer: packs direct-staged cohorts
            # into aggregation jobs without the creator's read-back
            # round-trip.  Sizing mirrors the standalone creator's knobs.
            from ..aggregator import AggregationJobCreator, CreatorConfig

            staged_creator = AggregationJobCreator(
                datastore,
                CreatorConfig(
                    min_aggregation_job_size=cfg.ingest.staged_min_job_size,
                    max_aggregation_job_size=cfg.ingest.staged_max_job_size,
                    batch_aggregation_shard_count=cfg.batch_aggregation_shard_count,
                ),
            )

            async def staged_pass():
                await staged_creator.run_staged_once(agg.ingest)

            tasks.append(
                asyncio.ensure_future(
                    periodic(
                        "staged consumer",
                        staged_pass,
                        max(0.01, cfg.ingest.staged_consume_interval_ms / 1000.0),
                    )
                )
            )

            async def materialize_pass():
                await agg.ingest.materialize_once(cfg.ingest.materialize_batch_size)

            tasks.append(
                asyncio.ensure_future(
                    periodic(
                        "ingest materializer",
                        materialize_pass,
                        max(0.01, cfg.ingest.materialize_interval_ms / 1000.0),
                    )
                )
            )
        if cfg.garbage_collection_interval_s:
            gc = GarbageCollector(datastore)
            tasks.append(
                asyncio.ensure_future(
                    periodic("GC", gc.run_once, cfg.garbage_collection_interval_s)
                )
            )
        if cfg.key_rotator_interval_s:
            from ..aggregator.key_rotator import HpkeKeyRotator, KeyRotatorConfig

            rotator = HpkeKeyRotator(
                datastore,
                KeyRotatorConfig(
                    pending_duration=Duration(cfg.key_rotator_pending_duration_s),
                    active_duration=Duration(cfg.key_rotator_active_duration_s),
                    expired_duration=Duration(cfg.key_rotator_expired_duration_s),
                ),
            )
            tasks.append(
                asyncio.ensure_future(
                    periodic("key rotator", rotator.run, cfg.key_rotator_interval_s)
                )
            )
        await stop.wait()
        for t in tasks:
            t.cancel()
        await agg.shutdown()
        if agg.ingest is not None:
            # flush queued journal writes, then fold the journal backlog
            # into client_reports; anything left is crash-replay's job
            await agg.ingest.drain()
        if cfg.device_executor.enabled:
            # This binary owns the process-wide executor: flush pending
            # mega-batches, then spill any resident accumulator state
            # before teardown (graceful path; crashes take discard+replay).
            from ..executor import peek_global_executor

            ex = peek_global_executor()
            if ex is not None:
                try:
                    await ex.drain()
                except Exception:
                    logger.exception("executor drain failed during shutdown")
                ex.shutdown(drain=True)
        if task_api_runner is not None:
            await task_api_runner.cleanup()
        await runner.cleanup()
        await health.cleanup()
        _close_tracing()

    asyncio.run(main())


def run_canary(config_path: Optional[str]) -> None:
    """The canary plane's prober (core/canary.py; ISSUE 20): continuous
    black-box end-to-end probes against a live fleet.  Deliberately
    datastore-free — the canary judges the fleet exactly the way a
    client + collector pair would, through the front doors only."""
    cfg = load_config(CanaryBinaryConfig, config_path)

    from ..core.trace import TraceConfiguration, install_trace_subscriber

    install_trace_subscriber(TraceConfiguration(level=cfg.common.log_level))
    if getattr(cfg.common, "slos", None):
        from ..core.slo import configure_slos

        evaluator = configure_slos(cfg.common.slos)
        logger.info(
            "slo evaluator armed: %s",
            ", ".join(t.name for t in evaluator.targets),
        )
    from ..core.canary import configure_canary

    plane = configure_canary(cfg.canary)

    async def main():
        import aiohttp

        from ..core.slo import evaluate_tick

        loop = asyncio.get_running_loop()
        stop = _stop_event_on_signals(loop)
        health = await _serve_health(cfg.common.health_check_listen_address)
        logger.info(
            "canary probing %s every %.1fs (families: %s)",
            cfg.canary.leader_endpoint,
            cfg.canary.probe_interval_s,
            ", ".join(cfg.canary.families),
        )
        session = aiohttp.ClientSession()
        try:
            while not stop.is_set():
                # provisioning retries inside the cycle: a fleet that is
                # still coming up just delays the first verdict
                try:
                    await plane.ensure_provisioned(session)
                    await plane.probe_once(session)
                except Exception:
                    logger.exception("canary probe cycle failed")
                try:
                    evaluate_tick()
                except Exception:
                    logger.exception("slo evaluation tick failed")
                try:
                    await asyncio.wait_for(
                        stop.wait(), timeout=max(0.1, cfg.canary.probe_interval_s)
                    )
                except asyncio.TimeoutError:
                    pass
        finally:
            await session.close()
        await health.cleanup()
        _close_tracing()

    asyncio.run(main())


def run_aggregation_job_creator(config_path: Optional[str]) -> None:
    """reference: binaries/aggregation_job_creator.rs"""
    cfg = load_config(JobCreatorConfig, config_path)
    clock, datastore = _bootstrap(cfg.common)

    from ..aggregator import AggregationJobCreator, CreatorConfig

    creator = AggregationJobCreator(
        datastore,
        CreatorConfig(
            min_aggregation_job_size=cfg.min_aggregation_job_size,
            max_aggregation_job_size=cfg.max_aggregation_job_size,
            batch_aggregation_shard_count=cfg.batch_aggregation_shard_count,
            journal_replay_min_age_s=cfg.journal_replay_min_age_s,
        ),
    )

    async def main():
        loop = asyncio.get_running_loop()
        stop = _stop_event_on_signals(loop)
        health = await _serve_health(
            cfg.common.health_check_listen_address, datastore=datastore
        )
        sampler = _start_status_sampler(stop, datastore, cfg.common)
        while not stop.is_set():
            try:
                n = await creator.run_once()
                if n:
                    logger.info("created %d aggregation jobs", n)
            except Exception:
                logger.exception("creation pass failed")
            try:
                await asyncio.wait_for(
                    stop.wait(), timeout=cfg.aggregation_job_creation_interval_s
                )
            except asyncio.TimeoutError:
                pass
        if sampler is not None:
            await asyncio.gather(sampler, return_exceptions=True)
        await health.cleanup()
        _close_tracing()

    asyncio.run(main())


def _run_job_driver_binary(config_path: Optional[str], kind: str) -> None:
    """Shared wiring for the two lease-driven drivers
    (reference: binaries/aggregation_job_driver.rs:12-66)."""
    cfg = load_config(JobDriverBinaryConfig, config_path)
    clock, datastore = _bootstrap(cfg.common)

    # Peer-health gating thresholds are applied ONCE here (the tracker
    # is process-wide; driver constructors deliberately don't touch it).
    from ..core import peer_health

    peer_health.tracker().configure(
        failure_threshold=cfg.job_driver.peer_failure_threshold,
        suspect_dwell_s=cfg.job_driver.peer_suspect_dwell_s,
    )

    # Fleet control plane (core/fleet.py): register this replica in the
    # per-role rendezvous domain BEFORE anything computes ownership — the
    # warmup walk below must already see this member, or it would warm
    # zero tasks (2-member view without self) on a cold fleet.
    if cfg.common.fleet.enabled:
        from ..core.fleet import configure_fleet, default_replica_id

        fc = cfg.common.fleet
        router = configure_fleet(
            fc.replica_id or default_replica_id(),
            kind,
            heartbeat_ttl_s=fc.heartbeat_ttl_s,
            takeover_grace_s=fc.takeover_grace_s,
            suspect_staleness_s=fc.suspect_staleness_s,
            mass_staleness_fraction=fc.mass_staleness_fraction,
        )
        datastore.run_tx("fleet_register", router.heartbeat)
        logger.info(
            "fleet member %s registered (role=%s, ttl=%.1fs)",
            router.replica_id,
            kind,
            fc.heartbeat_ttl_s,
        )

    import aiohttp

    from ..aggregator import (
        AggregationJobDriver,
        CollectionJobDriver,
        DriverConfig,
        JobDriver,
    )

    if kind == "aggregation":
        exec_cfg = (
            cfg.device_executor.to_executor_config()
            if cfg.device_executor.enabled
            else None
        )
        from ..core.retries import HttpRetryPolicy

        stepper_impl = AggregationJobDriver(
            datastore,
            aiohttp.ClientSession,
            DriverConfig(
                batch_aggregation_shard_count=cfg.batch_aggregation_shard_count,
                maximum_attempts_before_failure=cfg.job_driver.maximum_attempts_before_failure,
                max_step_attempts=cfg.job_driver.max_step_attempts,
                retry_initial_delay_s=cfg.job_driver.retry_initial_delay_s,
                retry_max_delay_s=cfg.job_driver.retry_max_delay_s,
                vdaf_backend=cfg.vdaf_backend,
                field_backend=cfg.field_backend,
                poplar_backend=cfg.poplar_backend,
                device_executor=exec_cfg,
                warmup_wait_s=cfg.warmup_wait_s,
                http_retry=HttpRetryPolicy(
                    attempt_timeout=cfg.job_driver.http_attempt_timeout_s
                ),
            ),
        )
        if exec_cfg is not None and exec_cfg.warmup_rows:
            # Registry-driven BACKGROUND warmup (ISSUE 8): walk the task
            # registry and resolve every task's backend — with canonical
            # shapes on, N tasks collapse to O(log N) distinct backends,
            # and each resolution queues its compile on the executor's
            # warmup thread, so startup (and the submit path) never blocks
            # behind XLA; submits for a still-warming shape drain through
            # the CPU oracle until the executable lands.
            import threading

            def _registry_warmup(driver=stepper_impl):
                from ..core.fleet import fleet_router

                def _owned_tasks(tx):
                    tasks = tx.get_aggregator_tasks()
                    r = fleet_router()
                    # cache affinity: only warm OWNED tasks' shapes, so
                    # each replica's compile_stats stays scoped to its
                    # share of the fleet (migrated-in tasks warm lazily
                    # through the submit path's oracle fallback)
                    return tasks if r is None else r.filter_owned(tx, tasks)

                try:
                    tasks = datastore.run_tx("warmup_tasks", _owned_tasks)
                except Exception:
                    logger.exception(
                        "warmup task-registry walk failed (serving cold)"
                    )
                    return
                resolved, shapes = 0, set()
                for task in tasks:
                    # per-task containment: one bad VDAF must not leave
                    # every other task serving cold at peak traffic
                    try:
                        vdaf = task.vdaf_instance()
                        shapes.add(driver._executor_shape(vdaf)[0])
                        driver._backend_for(task, vdaf)
                        resolved += 1
                    except Exception:
                        logger.exception(
                            "executor warmup failed for task %s (it serves cold)",
                            task.task_id,
                        )
                if tasks:
                    logger.info(
                        "device executor warmup resolved %d/%d task(s) "
                        "onto %d backend shape(s)",
                        resolved,
                        len(tasks),
                        len(shapes),
                    )

            threading.Thread(
                target=_registry_warmup, name="janus-warmup-registry", daemon=True
            ).start()

        async def acquirer(duration, limit):
            from ..aggregator.job_driver import acquisition_exclusions

            return await datastore.run_tx_async(
                "acquire_agg",
                # suspect-peer and fleet-routed tasks filter at the query
                # (task -> peer index, same tx) instead of
                # acquire-then-release churn
                lambda tx: tx.acquire_incomplete_aggregation_jobs(
                    duration,
                    limit,
                    exclude_task_ids=acquisition_exclusions(tx, "aggregation"),
                ),
            )

        async def reaper():
            return await datastore.run_tx_async(
                "reap_agg_leases",
                lambda tx: tx.reap_expired_aggregation_job_leases(),
            )

        stepper = stepper_impl.step_aggregation_job
        job_type = "aggregation"
    else:
        from ..aggregator.collection_job_driver import CollectionDriverConfig
        from ..core.retries import HttpRetryPolicy

        stepper_impl = CollectionJobDriver(
            datastore,
            aiohttp.ClientSession,
            CollectionDriverConfig(
                maximum_attempts_before_failure=cfg.job_driver.maximum_attempts_before_failure,
                max_step_attempts=cfg.job_driver.max_step_attempts,
                batch_aggregation_shard_count=cfg.batch_aggregation_shard_count,
                # the shared retry knobs configure the FAILURE backoff; the
                # readiness-poll curve keeps its own (reference) defaults
                step_retry_initial_delay=Duration(
                    max(1, int(cfg.job_driver.retry_initial_delay_s))
                ),
                step_retry_max_delay=Duration(int(cfg.job_driver.retry_max_delay_s)),
                http_retry=HttpRetryPolicy(
                    attempt_timeout=cfg.job_driver.http_attempt_timeout_s
                ),
            ),
        )

        async def acquirer(duration, limit):
            from ..aggregator.job_driver import acquisition_exclusions

            return await datastore.run_tx_async(
                "acquire_coll",
                lambda tx: tx.acquire_incomplete_collection_jobs(
                    duration,
                    limit,
                    exclude_task_ids=acquisition_exclusions(tx, "collection"),
                ),
            )

        async def reaper():
            return await datastore.run_tx_async(
                "reap_coll_leases",
                lambda tx: tx.reap_expired_collection_job_leases(),
            )

        stepper = stepper_impl.step_collection_job
        job_type = "collection"

    driver = JobDriver(
        clock,
        acquirer,
        stepper,
        job_discovery_interval=cfg.job_driver.job_discovery_interval_s,
        max_concurrent_job_workers=cfg.job_driver.max_concurrent_job_workers,
        worker_lease_duration=Duration(cfg.job_driver.worker_lease_duration_s),
        worker_lease_clock_skew_allowance=Duration(
            cfg.job_driver.worker_lease_clock_skew_allowance_s
        ),
        reaper=reaper if cfg.job_driver.lease_reap_interval_s > 0 else None,
        lease_reap_interval=cfg.job_driver.lease_reap_interval_s,
        job_type=job_type,
    )

    async def main():
        loop = asyncio.get_running_loop()
        stop = _stop_event_on_signals(loop)
        health = await _serve_health(
            cfg.common.health_check_listen_address, datastore=datastore
        )
        sampler = _start_status_sampler(stop, datastore, cfg.common)
        heartbeat = _start_fleet_heartbeat(stop, datastore, cfg.common)
        maintenance = (
            _start_accumulator_maintenance(stop, stepper_impl, cfg)
            if kind == "aggregation"
            else None
        )
        await driver.run(stop)
        # Graceful teardown (SIGTERM): in-flight steps have drained and
        # released their leases in-tx; now flush the executor's pending
        # mega-batches and spill committed-but-unspilled accumulator
        # deltas durably (the journal transaction), so ONLY a genuine
        # crash ever takes the discard-and-replay path.
        if maintenance is not None:
            await asyncio.gather(maintenance, return_exceptions=True)
        if kind == "aggregation":
            await stepper_impl.shutdown()
        else:
            await stepper_impl.close()
        if heartbeat is not None:
            await asyncio.gather(heartbeat, return_exceptions=True)
        if sampler is not None:
            await asyncio.gather(sampler, return_exceptions=True)
        await health.cleanup()
        _close_tracing()

    asyncio.run(main())


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(
            "usage: python -m janus_tpu.binaries "
            "{aggregator|aggregation_job_creator|aggregation_job_driver|"
            "collection_job_driver|canary|janus_cli} [--config-file F] ...",
            file=sys.stderr,
        )
        return 2
    binary = argv.pop(0)
    config_path = None
    if argv[:1] == ["--config-file"]:
        config_path = argv[1]
        argv = argv[2:]
    if binary == "aggregator":
        run_aggregator(config_path)
    elif binary == "aggregation_job_creator":
        run_aggregation_job_creator(config_path)
    elif binary == "aggregation_job_driver":
        _run_job_driver_binary(config_path, "aggregation")
    elif binary == "collection_job_driver":
        _run_job_driver_binary(config_path, "collection")
    elif binary == "canary":
        run_canary(config_path)
    elif binary == "janus_cli":
        from .janus_cli import cli

        cli.main(args=argv, standalone_mode=True)
    elif binary == "collect":
        from .collect import collect

        collect.main(args=argv, standalone_mode=True, obj={})
    elif binary.startswith("janus_interop_"):
        from ..interop import run_interop_binary

        port = 8080
        for i, arg in enumerate(argv):
            if arg == "--port":
                if i + 1 >= len(argv):
                    print("--port requires a value", file=sys.stderr)
                    return 2
                port = int(argv[i + 1])
        run_interop_binary(binary[len("janus_interop_") :], port)
    else:
        print(f"unknown binary {binary!r}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
