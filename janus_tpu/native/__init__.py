"""Native host kernel loader (ctypes).

Builds and loads ``native/turboshake.cpp`` — the C++ TurboSHAKE128 sponge
and VDAF XOF field expansion the CPU oracle uses for its hot loops.  The
build is one ``g++ -O3 -shared`` invocation, cached next to the source; if
the toolchain or the build is unavailable, callers fall back to the pure
Python sponge (bit-exact either way, asserted in tests/test_native.py).

Disable explicitly with JANUS_TPU_NATIVE=0.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import List, Optional

logger = logging.getLogger("janus_tpu.native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "turboshake.cpp")
_LIB = os.path.join(_REPO_ROOT, "native", "libjanusts.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # build to a temp path and rename: concurrent cold processes must never
    # CDLL a partially written library
    tmp = _LIB + f".tmp.{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _LIB)
        return True
    except Exception as e:
        logger.debug("native build failed: %s", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load() -> Optional[ctypes.CDLL]:
    """The loaded library, building it on first use; None if unavailable."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("JANUS_TPU_NATIVE", "1") == "0":
        return None
    if not os.path.exists(_SRC):
        return None
    if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError:
        return None
    lib.ts128_hash.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint8,
        ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.ts128_expand_vdaf.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.ts128_next_vec.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t,
    ]
    lib.ts128_next_vec.restype = ctypes.c_int
    _lib = lib
    return _lib


def _validate(seed: Optional[bytes], dst: bytes) -> None:
    """Mirror the Python XOF's input contract — the C ABI reads exactly 16
    seed bytes and truncates the dst length prefix to one byte."""
    if seed is not None and len(seed) != 16:
        raise ValueError("bad seed size")
    if len(dst) > 255:
        raise ValueError("dst too long")


def turboshake128(message: bytes, domain: int, length: int) -> Optional[bytes]:
    lib = load()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(length)
    lib.ts128_hash(message, len(message), domain, out, length)
    return out.raw


def xof_stream(seed: bytes, dst: bytes, binder: bytes, length: int) -> Optional[bytes]:
    """Full XofTurboShake128 stream of ``length`` bytes."""
    lib = load()
    if lib is None:
        return None
    _validate(seed, dst)
    out = ctypes.create_string_buffer(length)
    lib.ts128_expand_vdaf(seed, dst, len(dst), binder, len(binder), out, length)
    return out.raw


def next_vec(
    seed: bytes, dst: bytes, binder: bytes, field_encoded_size: int, length: int
) -> Optional[List[int]]:
    """Rejection-sampled field elements (Field64 or Field128)."""
    lib = load()
    if lib is None or field_encoded_size not in (8, 16):
        return None
    _validate(seed, dst)
    out = (ctypes.c_uint64 * (2 * length))()
    rc = lib.ts128_next_vec(
        seed, dst, len(dst), binder, len(binder),
        0 if field_encoded_size == 8 else 1, out, length,
    )
    if rc != 0:
        return None
    return [out[2 * i] | (out[2 * i + 1] << 64) for i in range(length)]
