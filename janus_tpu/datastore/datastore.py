"""SQLite-backed datastore with the reference's Postgres semantics.

The analog of ``Datastore``/``Transaction`` (reference:
aggregator_core/src/datastore.rs:108,249): all framework components
coordinate exclusively through this store; every protocol step commits a
state machine transition, so the database is the checkpoint.

Mapping of Postgres machinery onto SQLite:

- ``run_tx`` retry loop (reference datastore.rs:249-298): transactions run
  under ``BEGIN IMMEDIATE`` (writer) and retry on SQLITE_BUSY the way the
  reference retries serialization failures at RepeatableRead.
- ``FOR UPDATE SKIP LOCKED`` lease acquisition (reference datastore.rs:1916):
  SQLite has one writer at a time, so a single atomic
  ``UPDATE … WHERE id IN (SELECT …) RETURNING`` has the same effect — two
  concurrent acquirers can never lease the same job.
- Column crypto: AES-GCM via :class:`~janus_tpu.datastore.crypter.Crypter`
  with AAD = (table, row-ident, column) (reference datastore.rs:5622).

The SQL dialect is confined behind backend_sql.py: the default is this
module's documented SQLite mapping, and a ``postgres://`` database path
selects the shared-Postgres backend with real ``FOR UPDATE SKIP LOCKED``
lease scans and serialization-failure retries — the reference's deployment
shape — behind the same Transaction API.
"""

from __future__ import annotations

import asyncio
import json
import secrets
import sqlite3
import threading
import time as _time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, TypeVar

from ..core import faults
from ..core.quarantine import chain_crc
from ..core.auth_tokens import AuthenticationToken, AuthenticationTokenHash
from ..core.hpke import HpkeKeypair
from ..core.time import Clock
from ..messages import (
    AggregationJobId,
    AggregationJobStep,
    BatchId,
    CollectionJobId,
    Duration,
    Extension,
    FixedSize,
    HpkeCiphertext,
    HpkeConfig,
    Interval,
    PrepareError,
    PrepareResp,
    Query,
    ReportId,
    ReportIdChecksum,
    ReportMetadata,
    Role,
    TaskId,
    Time,
    TimeInterval,
)
from ..messages.codec import Decoder, Encoder
from .crypter import Crypter
from .models import (
    AccumulatorJournalEntry,
    AcquiredAggregationJob,
    AcquiredCollectionJob,
    AggregateShareJob,
    AggregationJob,
    AggregationJobState,
    BatchAggregation,
    BatchAggregationState,
    CollectionJob,
    CollectionJobState,
    FleetMember,
    GlobalHpkeKeypair,
    HpkeKeyState,
    Lease,
    LeaseToken,
    LeaderStoredReport,
    OutstandingBatch,
    ReportAggregation,
    ReportAggregationMetadata,
    ReportAggregationState,
    TaskUploadCounter,
)
from .schema import MIGRATIONS, SUPPORTED_SCHEMA_VERSIONS
from .task import AggregatorTask, TaskQueryType

T = TypeVar("T")

#: task-level query-type kind -> wire query-type class
QUERY_TYPES = {"TimeInterval": TimeInterval, "FixedSize": FixedSize}

#: (job_type, table, active-state) per leasable job table.  The
#: "acquirable" predicate these imply — ``state = <active> AND
#: lease_expiry <= now`` — MUST stay in lockstep with the
#: acquire_incomplete_*_jobs queries; Transaction.lease_summary() is the
#: single read-side source for those counts (/statusz + the
#: janus_acquirable_jobs sampler).
_JOB_LEASE_TABLES = (
    ("aggregation", "aggregation_jobs", "InProgress"),
    ("collection", "collection_jobs", "Start"),
)


class DatastoreError(Exception):
    pass


class DatastoreUnavailable(DatastoreError):
    """A transaction exhausted its retry budget on TRANSIENT failures
    (lock contention, serialization, injected faults) — the datastore is
    unreachable-or-overloaded right now, not wrong.  The HTTP layer maps
    this — and only this — DatastoreError shape to a DAP-retryable 503:
    permanent conditions (missing rows, schema mismatch) stay loud."""


class TxConflict(DatastoreError):
    """A uniqueness/state conflict the caller must handle (maps the
    reference's Error::MutationTargetAlreadyExists and friends)."""


class TaskNotFound(DatastoreError):
    pass


def _encode_extensions(extensions: Sequence[Extension]) -> bytes:
    w = Encoder()
    w.items_u16(extensions, lambda ww, e: e.encode(ww))
    return w.take()


def _decode_extensions(data: bytes) -> List[Extension]:
    r = Decoder(data)
    out = r.items_u16(Extension._decode)
    r.finish()
    return out


def _report_journal_crc(
    rid: bytes,
    ts: int,
    ext_b: Optional[bytes],
    public_share: Optional[bytes],
    enc_share: Optional[bytes],
    helper_b: Optional[bytes],
) -> int:
    """CRC32C witness over a report_journal row's payload columns (ISSUE
    19).  Computed at write time over the bytes as stored (the share
    ciphertext, not its plaintext) so verification never needs a decrypt."""
    return chain_crc(
        rid, int(ts).to_bytes(8, "big"), ext_b, public_share, enc_share, helper_b
    )


def _accumulator_journal_crc(
    batch_identifier: bytes, param: bytes, job_id: bytes, rids_b: bytes
) -> int:
    """CRC32C witness over an accumulator_journal row's payload columns."""
    return chain_crc(batch_identifier, param, job_id, rids_b)


def _metrics_tx(name: str, status: str) -> None:
    """reference: datastore.rs:186-224 per-tx status metrics."""
    from ..core.metrics import GLOBAL_METRICS

    if GLOBAL_METRICS.registry is not None:
        GLOBAL_METRICS.tx_total.labels(name=name, status=status).inc()


class Datastore:
    """Thread-safe handle; one backend connection per thread.

    ``path`` is an SQLite file path (hermetic default) or a
    ``postgres://`` DSN selecting the shared-Postgres backend
    (backend_sql.py; reference DbConfig url, config.rs:75).
    """

    def __init__(
        self,
        path: str,
        crypter: Crypter,
        clock: Clock,
        max_transaction_retries: int = 30,
        migrate_on_open: bool = True,
        _migrations_override: Optional[List[str]] = None,
    ):
        from .backend_sql import backend_for

        self.path = path
        self.backend = backend_for(path)
        self.crypter = crypter
        self.clock = clock
        self.max_transaction_retries = max_transaction_retries
        #: True (hermetic default): apply pending schema migrations on open.
        #: False: the production deploy shape — an operator migrates, the
        #: binary only checks SUPPORTED_SCHEMA_VERSIONS.
        self.migrate_on_open = migrate_on_open
        self._migrations_override = _migrations_override  # tests only
        self._local = threading.local()
        self._init_schema()

    # -- connections ----------------------------------------------------
    def _conn(self):
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = self.backend.connect()
            self._local.conn = conn
        return conn

    def _current_schema_version(self, conn) -> int:
        """Transaction-safe: probes the catalog first, so a missing table
        never errors (a failed SELECT would abort a Postgres transaction)."""
        exists = conn.execute(
            self.backend.table_exists_sql, ("schema_version",)
        ).fetchone()
        if exists is None:
            return 0
        row = conn.execute("SELECT version FROM schema_version").fetchone()
        return 0 if row is None else int(row[0])

    def _init_schema(self) -> None:
        conn = self._conn()
        current = self._current_schema_version(conn)
        migrations = self._migrations_override or MIGRATIONS
        target = len(migrations)
        if current > target:
            raise DatastoreError(
                f"database schema version {current} is newer than this build "
                f"supports ({target}); refusing to touch it"
            )
        if not self.migrate_on_open:
            # Production deploy shape: an operator applies migrations; the
            # binary only gates (reference: supported_schema_versions!,
            # datastore.rs:77-104).
            supported = (
                (target,) if self._migrations_override else SUPPORTED_SCHEMA_VERSIONS
            )
            if current not in supported:
                raise DatastoreError(
                    f"unsupported schema version {current} "
                    f"(supported: {supported})"
                )
            return
        for v in range(current, target):
            # One migration per transaction, DDL and version stamp TOGETHER:
            # a crash can never commit DDL without advancing the stamp, so
            # non-idempotent future migrations stay re-runnable.  (SQLite
            # runs DDL transactionally; Postgres supports transactional DDL
            # outright.)  The version is RE-READ under the write lock:
            # concurrent replica startups serialize here, and a replica
            # that lost the race skips the migration another already
            # applied instead of double-applying it.
            conn.execute(self.backend.begin_sql)
            try:
                if self._current_schema_version(conn) != v:
                    conn.rollback()
                    continue
                self.backend.init_schema(conn, migrations[v])
                if v == 0:
                    conn.execute(
                        "INSERT INTO schema_version (version) VALUES (?)", (1,)
                    )
                else:
                    conn.execute("UPDATE schema_version SET version = ?", (v + 1,))
                conn.commit()
            except BaseException:
                try:
                    conn.rollback()
                except Exception:
                    pass
                raise

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None

    def _evict_conn(self) -> None:
        """Drop this thread's cached connection (it may be dead — e.g. a
        network backend's server restarted).  The next _conn() reconnects."""
        conn = getattr(self._local, "conn", None)
        self._local.conn = None
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    # -- transactions ---------------------------------------------------
    def run_tx(
        self,
        name: str,
        fn: Callable[["Transaction"], T],
        deadline_s: Optional[float] = None,
    ) -> T:
        """Run ``fn`` in one transaction, retrying on lock contention /
        serialization failure (reference: datastore.rs:249 run_tx /
        :298 run_tx_once; retry classification is per-backend).

        Every transient (retryable) failure feeds the process-wide
        datastore health tracker (core/db_health.py) and sleeps a
        full-jitter exponential backoff; a commit resets it.  Permanent
        errors (schema, integrity) raise immediately and say nothing
        about datastore health.

        ``deadline_s`` bounds the retry loop's total wall time: a
        lease-holding caller (a job driver releasing mid-brownout) sets
        it so the release attempt always returns in-band instead of
        holding the lease through ``max_transaction_retries`` sleeps —
        exhausting the deadline raises ``DatastoreUnavailable`` exactly
        like exhausting the attempt budget."""
        from ..core.db_health import tracker as db_tracker

        deadline = (
            _time.monotonic() + deadline_s if deadline_s is not None else None
        )
        last_err: Optional[BaseException] = None
        for attempt in range(self.max_transaction_retries):
            conn = self._conn()
            try:
                # Failure-domain boundary: an injected begin fault is
                # indistinguishable from lock contention and retries the
                # same way (core/faults.py; off by default).
                faults.fire("datastore.tx.begin")
                conn.execute(self.backend.begin_sql)
            except Exception as e:
                if not self._is_retryable(e):
                    # Non-retryable BEGIN failure usually means the cached
                    # connection is dead (server restart on a network
                    # backend): reconnect before surfacing the error.
                    # Retryable failures (SQLite lock contention) keep the
                    # healthy connection — re-opening per retry would add
                    # connection churn to the contended hot path.
                    self._evict_conn()
                    raise
                last_err = e
                if not self._retry_backoff(e, attempt, deadline):
                    break
                continue
            tx = Transaction(self, conn)
            try:
                result = fn(tx)
                # Commit-boundary fault: rolls back and re-runs fn, exactly
                # like a serialization failure at COMMIT would.
                faults.fire("datastore.tx.commit")
                conn.commit()
                _metrics_tx(name, "committed")
                db_tracker().record_tx_success()
                return result
            except BaseException as e:
                try:
                    conn.rollback()
                except Exception:
                    # Never mask the original error with a rollback failure
                    # on a broken connection; reconnect next attempt.
                    self._evict_conn()
                if self._is_retryable(e):
                    last_err = e
                    if not self._retry_backoff(e, attempt, deadline):
                        break
                    continue
                raise
        _metrics_tx(name, "exhausted")
        raise DatastoreUnavailable(
            f"transaction {name!r} exhausted retries: {last_err}"
        )

    def _retry_backoff(
        self,
        err: BaseException,
        attempt: int,
        deadline: Optional[float],
    ) -> bool:
        """One transient-failure bookkeeping step for ``run_tx``: feed the
        health tracker, drop a disconnect-shaped connection (retrying a
        dead socket forever is not a retry), then sleep the jittered
        backoff.  Returns False when the sleep would cross ``deadline`` —
        the caller breaks to the exhausted raise instead of sleeping."""
        from ..core.db_health import backoff_s
        from ..core.db_health import tracker as db_tracker

        db_tracker().record_tx_failure()
        if self.backend.is_disconnect(err):
            self._evict_conn()
        delay = backoff_s(attempt)
        if deadline is not None and _time.monotonic() + delay >= deadline:
            return False
        _time.sleep(delay)
        return True

    def _is_retryable(self, e: BaseException) -> bool:
        """Backend retry classification, plus injected faults — which
        impersonate transient infrastructure failures by contract."""
        return isinstance(e, faults.FaultInjectedError) or self.backend.is_retryable(e)

    async def run_tx_async(
        self,
        name: str,
        fn: Callable[["Transaction"], T],
        deadline_s: Optional[float] = None,
    ) -> T:
        """Async wrapper: runs the (synchronous) transaction in a worker
        thread so the aiohttp event loop is never blocked on the database."""
        return await asyncio.get_running_loop().run_in_executor(
            None, lambda: self.run_tx(name, fn, deadline_s=deadline_s)
        )

    def now(self) -> Time:
        return self.clock.now()


class Transaction:
    """Typed query methods over one open transaction
    (reference: aggregator_core/src/datastore.rs Transaction)."""

    def __init__(self, ds: Datastore, conn: sqlite3.Connection):
        self.ds = ds
        self.conn = conn
        self.crypter = ds.crypter
        self.clock = ds.clock

    # ------------------------------------------------------------------
    # helpers

    def _task_pk(self, task_id: TaskId) -> int:
        row = self.conn.execute(
            "SELECT id FROM tasks WHERE task_id = ?", (task_id.data,)
        ).fetchone()
        if row is None:
            raise TaskNotFound(str(task_id))
        return row[0]

    def _now_s(self) -> int:
        return self.clock.now().seconds

    # ------------------------------------------------------------------
    # tasks (reference: datastore.rs put_aggregator_task / get_aggregator_task)

    def put_aggregator_task(self, task: AggregatorTask) -> None:
        enc_vk = self.crypter.encrypt(
            "tasks", task.task_id.data, "vdaf_verify_key", task.vdaf_verify_key
        )
        agg_token = agg_token_type = None
        if task.aggregator_auth_token is not None:
            agg_token_type = task.aggregator_auth_token.kind
            agg_token = self.crypter.encrypt(
                "tasks",
                task.task_id.data,
                "aggregator_auth_token",
                task.aggregator_auth_token.as_bytes(),
            )
        returning = self.ds.backend.supports_returning
        try:
            cur = self.conn.execute(
                """INSERT INTO tasks (task_id, aggregator_role,
                    peer_aggregator_endpoint, query_type, vdaf, task_expiration,
                    report_expiry_age, min_batch_size, time_precision,
                    tolerable_clock_skew, collector_hpke_config, vdaf_verify_key,
                    aggregator_auth_token_type, aggregator_auth_token,
                    aggregator_auth_token_hash, collector_auth_token_hash,
                    created_at)
                   VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)"""
                + (" RETURNING id" if returning else ""),
                (
                    task.task_id.data,
                    task.role.name.capitalize() if isinstance(task.role, Role) else str(task.role),
                    task.peer_aggregator_endpoint,
                    task.query_type.to_json(),
                    json.dumps(task.vdaf, sort_keys=True),
                    task.task_expiration.seconds if task.task_expiration else None,
                    task.report_expiry_age.seconds if task.report_expiry_age else None,
                    task.min_batch_size,
                    task.time_precision.seconds,
                    task.tolerable_clock_skew.seconds,
                    task.collector_hpke_config.get_encoded()
                    if task.collector_hpke_config
                    else None,
                    enc_vk,
                    agg_token_type,
                    agg_token,
                    json.dumps(task.aggregator_auth_token_hash.to_dict())
                    if task.aggregator_auth_token_hash
                    else None,
                    json.dumps(task.collector_auth_token_hash.to_dict())
                    if task.collector_auth_token_hash
                    else None,
                    self._now_s(),
                ),
            )
        except self.ds.backend.integrity_errors as e:
            raise TxConflict(f"task {task.task_id} already exists") from e
        # RETURNING id works on both dialects (cursor.lastrowid does not:
        # psycopg has no usable lastrowid for PG tables); pre-3.35 SQLite
        # lacks RETURNING but its lastrowid is reliable.
        pk = cur.fetchone()[0] if returning else cur.lastrowid
        for kp in task.hpke_keys:
            enc_sk = self.crypter.encrypt(
                "task_hpke_keys", task.task_id.data, "private_key", kp.private_key
            )
            self.conn.execute(
                """INSERT INTO task_hpke_keys (task_id, config_id, config, private_key)
                   VALUES (?,?,?,?)""",
                (pk, kp.config.id, kp.config.get_encoded(), enc_sk),
            )

    def _task_from_row(self, row: sqlite3.Row) -> AggregatorTask:
        (
            pk,
            task_id_b,
            role_s,
            peer,
            query_type_s,
            vdaf_s,
            expiration,
            expiry_age,
            min_batch,
            precision,
            skew,
            collector_cfg_b,
            enc_vk,
            tok_type,
            tok_enc,
            agg_hash_s,
            col_hash_s,
        ) = row
        task_id = TaskId(task_id_b)
        vk = self.crypter.decrypt("tasks", task_id_b, "vdaf_verify_key", enc_vk)
        token = None
        if tok_enc is not None:
            raw = self.crypter.decrypt("tasks", task_id_b, "aggregator_auth_token", tok_enc)
            token = AuthenticationToken(tok_type, raw.decode())
        keys = []
        for cfg_b, sk_enc in self.conn.execute(
            "SELECT config, private_key FROM task_hpke_keys WHERE task_id = ?"
            " ORDER BY config_id",
            (pk,),
        ):
            sk = self.crypter.decrypt("task_hpke_keys", task_id_b, "private_key", sk_enc)
            keys.append(HpkeKeypair(HpkeConfig.get_decoded(cfg_b), sk))
        return AggregatorTask(
            task_id=task_id,
            peer_aggregator_endpoint=peer,
            query_type=TaskQueryType.from_json(query_type_s),
            vdaf=json.loads(vdaf_s),
            role=Role[role_s.upper()],
            vdaf_verify_key=vk,
            min_batch_size=min_batch,
            time_precision=Duration(precision),
            task_expiration=Time(expiration) if expiration is not None else None,
            report_expiry_age=Duration(expiry_age) if expiry_age is not None else None,
            tolerable_clock_skew=Duration(skew),
            aggregator_auth_token=token,
            aggregator_auth_token_hash=AuthenticationTokenHash.from_dict(
                json.loads(agg_hash_s)
            )
            if agg_hash_s
            else None,
            collector_auth_token_hash=AuthenticationTokenHash.from_dict(
                json.loads(col_hash_s)
            )
            if col_hash_s
            else None,
            collector_hpke_config=HpkeConfig.get_decoded(collector_cfg_b)
            if collector_cfg_b
            else None,
            hpke_keys=keys,
        )

    _TASK_COLS = """id, task_id, aggregator_role, peer_aggregator_endpoint,
        query_type, vdaf, task_expiration, report_expiry_age, min_batch_size,
        time_precision, tolerable_clock_skew, collector_hpke_config,
        vdaf_verify_key, aggregator_auth_token_type, aggregator_auth_token,
        aggregator_auth_token_hash, collector_auth_token_hash"""

    def get_aggregator_task(self, task_id: TaskId) -> Optional[AggregatorTask]:
        row = self.conn.execute(
            f"SELECT {self._TASK_COLS} FROM tasks WHERE task_id = ?",
            (task_id.data,),
        ).fetchone()
        return self._task_from_row(row) if row else None

    def get_aggregator_tasks(self) -> List[AggregatorTask]:
        rows = self.conn.execute(
            f"SELECT {self._TASK_COLS} FROM tasks ORDER BY id"
        ).fetchall()
        return [self._task_from_row(r) for r in rows]

    def delete_task(self, task_id: TaskId) -> None:
        cur = self.conn.execute("DELETE FROM tasks WHERE task_id = ?", (task_id.data,))
        if cur.rowcount == 0:
            raise TaskNotFound(str(task_id))

    def update_task_expiration(self, task_id: TaskId, expiration: Optional[Time]) -> None:
        cur = self.conn.execute(
            "UPDATE tasks SET task_expiration = ? WHERE task_id = ?",
            (expiration.seconds if expiration else None, task_id.data),
        )
        if cur.rowcount == 0:
            raise TaskNotFound(str(task_id))

    def get_task_ids(self) -> List[TaskId]:
        return [
            TaskId(r[0])
            for r in self.conn.execute("SELECT task_id FROM tasks ORDER BY id")
        ]

    # ------------------------------------------------------------------
    # client reports (reference: datastore.rs:1254,1393,1590,1663)

    def put_client_report(self, report: LeaderStoredReport) -> None:
        pk = self._task_pk(report.task_id)
        row_ident = report.task_id.data + report.report_id.data
        enc_share = self.crypter.encrypt(
            "client_reports", row_ident, "leader_input_share", report.leader_input_share
        )
        try:
            self.conn.execute(
                """INSERT INTO client_reports (task_id, report_id, client_timestamp,
                    extensions, public_share, leader_input_share,
                    helper_encrypted_input_share, trace_id, created_at)
                   VALUES (?,?,?,?,?,?,?,?,?)""",
                (
                    pk,
                    report.report_id.data,
                    report.time.seconds,
                    _encode_extensions(report.leader_extensions),
                    report.public_share,
                    enc_share,
                    report.helper_encrypted_input_share.get_encoded(),
                    report.trace_id,
                    self._now_s(),
                ),
            )
        except self.ds.backend.integrity_errors as e:
            raise TxConflict(f"report {report.report_id} already exists") from e

    def get_client_report(
        self, task_id: TaskId, report_id: ReportId
    ) -> Optional[LeaderStoredReport]:
        pk = self._task_pk(task_id)
        row = self.conn.execute(
            """SELECT client_timestamp, extensions, public_share,
                      leader_input_share, helper_encrypted_input_share, trace_id
               FROM client_reports WHERE task_id = ? AND report_id = ?""",
            (pk, report_id.data),
        ).fetchone()
        if row is None:
            return None
        ts, ext_b, public_share, enc_share, helper_b, trace_id = row
        if enc_share is None:
            return None  # scrubbed
        row_ident = task_id.data + report_id.data
        share = self.crypter.decrypt(
            "client_reports", row_ident, "leader_input_share", enc_share
        )
        return LeaderStoredReport(
            task_id=task_id,
            metadata=ReportMetadata(report_id, Time(ts)),
            public_share=public_share,
            leader_extensions=_decode_extensions(ext_b) if ext_b else [],
            leader_input_share=share,
            helper_encrypted_input_share=HpkeCiphertext.get_decoded(helper_b),
            trace_id=trace_id,
        )

    def check_client_report_exists(self, task_id: TaskId, report_id: ReportId) -> bool:
        pk = self._task_pk(task_id)
        return (
            self.conn.execute(
                "SELECT 1 FROM client_reports WHERE task_id = ? AND report_id = ?",
                (pk, report_id.data),
            ).fetchone()
            is not None
        )

    def get_unaggregated_client_reports_for_task(
        self, task_id: TaskId, limit: int
    ) -> List[ReportMetadata]:
        """Atomically claim up to ``limit`` unaggregated reports (sets
        aggregation_started, reference datastore.rs:1254 + the partial
        index).  Claimed reports must be assigned to jobs or released via
        ``mark_reports_unaggregated``."""
        pk = self._task_pk(task_id)
        if self.ds.backend.supports_returning:
            rows = self.conn.execute(
                """UPDATE client_reports SET aggregation_started = 1
                   WHERE id IN (
                       SELECT id FROM client_reports
                       WHERE task_id = ? AND aggregation_started = 0
                       ORDER BY client_timestamp LIMIT ?)
                   RETURNING report_id, client_timestamp""",
                (pk, limit),
            ).fetchall()
        else:
            # select-then-mutate fallback (pre-3.35 SQLite): atomic under
            # BEGIN IMMEDIATE's single writer
            picked = self.conn.execute(
                """SELECT id, report_id, client_timestamp FROM client_reports
                   WHERE task_id = ? AND aggregation_started = 0
                   ORDER BY client_timestamp LIMIT ?""",
                (pk, limit),
            ).fetchall()
            self.conn.executemany(
                "UPDATE client_reports SET aggregation_started = 1 WHERE id = ?",
                [(r[0],) for r in picked],
            )
            rows = [(r[1], r[2]) for r in picked]
        return [ReportMetadata(ReportId(r[0]), Time(r[1])) for r in rows]

    def mark_reports_unaggregated(
        self, task_id: TaskId, report_ids: Sequence[ReportId]
    ) -> None:
        """reference: datastore.rs:1393 mark_report_unaggregated"""
        pk = self._task_pk(task_id)
        self.conn.executemany(
            "UPDATE client_reports SET aggregation_started = 0"
            " WHERE task_id = ? AND report_id = ?",
            [(pk, rid.data) for rid in report_ids],
        )

    def scrub_client_report(self, task_id: TaskId, report_id: ReportId) -> None:
        """Null out share payloads once packed into an aggregation job
        (reference: datastore.rs:1663)."""
        pk = self._task_pk(task_id)
        self.conn.execute(
            """UPDATE client_reports SET extensions = NULL, public_share = NULL,
               leader_input_share = NULL, helper_encrypted_input_share = NULL,
               aggregation_started = 1
               WHERE task_id = ? AND report_id = ?""",
            (pk, report_id.data),
        )

    def get_client_reports_for_interval(
        self, task_id: TaskId, interval: Interval, limit: int
    ) -> List[LeaderStoredReport]:
        """Full (unscrubbed) reports in an interval — the collection-driven
        creation path for aggregation-parameter VDAFs, whose reports are
        re-aggregated at every level and therefore never scrubbed.  One
        query; per-row work is only the column decrypt."""
        pk = self._task_pk(task_id)
        rows = self.conn.execute(
            """SELECT report_id, client_timestamp, extensions, public_share,
                      leader_input_share, helper_encrypted_input_share, trace_id
               FROM client_reports
               WHERE task_id = ? AND client_timestamp >= ? AND client_timestamp < ?
                 AND leader_input_share IS NOT NULL
               ORDER BY client_timestamp LIMIT ?""",
            (pk, interval.start.seconds, interval.end().seconds, limit),
        ).fetchall()
        out = []
        for rid, ts, ext_b, public_share, enc_share, helper_b, trace_id in rows:
            share = self.crypter.decrypt(
                "client_reports", task_id.data + rid, "leader_input_share", enc_share
            )
            out.append(
                LeaderStoredReport(
                    task_id=task_id,
                    metadata=ReportMetadata(ReportId(rid), Time(ts)),
                    public_share=public_share,
                    leader_extensions=_decode_extensions(ext_b) if ext_b else [],
                    leader_input_share=share,
                    helper_encrypted_input_share=HpkeCiphertext.get_decoded(helper_b),
                    trace_id=trace_id,
                )
            )
        return out

    def get_aggregation_params_by_report_for_interval(
        self, task_id: TaskId, interval: Interval
    ) -> Dict[bytes, List[bytes]]:
        """report_id -> distinct aggregation params, for every report in the
        interval, in one query (the batch form of
        get_aggregation_params_for_report)."""
        pk = self._task_pk(task_id)
        rows = self.conn.execute(
            """SELECT DISTINCT ra.report_id, aj.aggregation_param
               FROM report_aggregations ra
               JOIN aggregation_jobs aj ON ra.aggregation_job_id = aj.id
               WHERE ra.task_id = ? AND ra.client_timestamp >= ?
                 AND ra.client_timestamp < ?""",
            (pk, interval.start.seconds, interval.end().seconds),
        ).fetchall()
        out: Dict[bytes, List[bytes]] = {}
        for rid, param in rows:
            out.setdefault(rid, []).append(param)
        return out

    def count_client_reports_for_interval(
        self, task_id: TaskId, interval: Interval
    ) -> int:
        pk = self._task_pk(task_id)
        return self.conn.execute(
            """SELECT COUNT(*) FROM client_reports
               WHERE task_id = ? AND client_timestamp >= ? AND client_timestamp < ?""",
            (pk, interval.start.seconds, interval.end().seconds),
        ).fetchone()[0]

    def get_aggregated_report_trace_ids(
        self,
        task_id: TaskId,
        interval: Optional[Interval] = None,
        batch_id: Optional[BatchId] = None,
        limit: int = 512,
    ) -> List[str]:
        """Distinct upload trace ids of reports AGGREGATED into a batch
        (ISSUE 9): the collection driver links them into its
        collection-finish span so the merged timeline runs client ingress
        -> collection.  Membership is by report_aggregations join — not a
        bare client_reports time scan — so unaggregated leftovers and
        (for fixed-size tasks, via ``batch_id``) reports packed into
        OTHER batches in the same time range never leak into another
        collection's merged trace.  Scrubbing nulls the share columns but
        keeps trace_id, so linked ids survive packing; GC-deleted rows
        simply drop out."""
        pk = self._task_pk(task_id)
        sql = """SELECT DISTINCT cr.trace_id
                 FROM report_aggregations ra
                 JOIN aggregation_jobs aj ON ra.aggregation_job_id = aj.id
                 JOIN client_reports cr
                   ON cr.task_id = ra.task_id AND cr.report_id = ra.report_id
                 WHERE ra.task_id = ? AND cr.trace_id IS NOT NULL"""
        params: list = [pk]
        if batch_id is not None:
            sql += " AND aj.batch_id = ?"
            params.append(batch_id.data)
        if interval is not None:
            sql += " AND ra.client_timestamp >= ? AND ra.client_timestamp < ?"
            params += [interval.start.seconds, interval.end().seconds]
        sql += " ORDER BY cr.trace_id LIMIT ?"
        params.append(limit)
        return [r[0] for r in self.conn.execute(sql, params).fetchall()]

    def count_unaggregated_client_reports_for_interval(
        self, task_id: TaskId, interval: Interval
    ) -> int:
        """Collection readiness gate input (reference:
        collection_job_driver.rs:124-262)."""
        pk = self._task_pk(task_id)
        return self.conn.execute(
            """SELECT COUNT(*) FROM client_reports
               WHERE task_id = ? AND aggregation_started = 0
                 AND client_timestamp >= ? AND client_timestamp < ?""",
            (pk, interval.start.seconds, interval.end().seconds),
        ).fetchone()[0]

    def delete_expired_client_reports(self, task_id: TaskId, expiry: Time, limit: int) -> int:
        """reference: datastore.rs:4691

        Reports with an OUTSTANDING report-journal row are skipped — the
        same guard shape as ``delete_expired_aggregation_artifacts``'s
        accumulator-journal clause.  A journal row outliving its
        materialized client_reports row would RESURRECT the report on
        replay after GC deleted it (or double-pack it if a staged
        consumer raced the delete); the replay/materializer consumes the
        row first and the next GC pass collects the report."""
        pk = self._task_pk(task_id)
        cur = self.conn.execute(
            """DELETE FROM client_reports WHERE id IN (
                 SELECT cr.id FROM client_reports cr
                 WHERE cr.task_id = ? AND cr.client_timestamp < ?
                   AND NOT EXISTS (
                     SELECT 1 FROM report_journal rj
                     WHERE rj.task_id = cr.task_id
                       AND rj.report_id = cr.report_id)
                 LIMIT ?)""",
            (pk, expiry.seconds, limit),
        )
        return cur.rowcount

    # ------------------------------------------------------------------
    # aggregation jobs (reference: datastore.rs:1916-2188)

    def put_aggregation_job(self, job: AggregationJob) -> None:
        pk = self._task_pk(job.task_id)
        now = self._now_s()
        try:
            self.conn.execute(
                """INSERT INTO aggregation_jobs (task_id, aggregation_job_id,
                    aggregation_param, batch_id, client_timestamp_interval_start,
                    client_timestamp_interval_duration, state, step,
                    last_request_hash, trace_id, created_at, updated_at)
                   VALUES (?,?,?,?,?,?,?,?,?,?,?,?)""",
                (
                    pk,
                    job.aggregation_job_id.data,
                    job.aggregation_parameter,
                    job.partial_batch_identifier.data
                    if job.partial_batch_identifier
                    else None,
                    job.client_timestamp_interval.start.seconds,
                    job.client_timestamp_interval.duration.seconds,
                    job.state.value,
                    int(job.step),
                    job.last_request_hash,
                    job.trace_id,
                    now,
                    now,
                ),
            )
        except self.ds.backend.integrity_errors as e:
            raise TxConflict(f"aggregation job {job.aggregation_job_id} exists") from e

    def get_aggregation_job(
        self, task_id: TaskId, aggregation_job_id: AggregationJobId
    ) -> Optional[AggregationJob]:
        pk = self._task_pk(task_id)
        row = self.conn.execute(
            """SELECT aggregation_param, batch_id, client_timestamp_interval_start,
                      client_timestamp_interval_duration, state, step,
                      last_request_hash, trace_id
               FROM aggregation_jobs WHERE task_id = ? AND aggregation_job_id = ?""",
            (pk, aggregation_job_id.data),
        ).fetchone()
        if row is None:
            return None
        param, batch_id, istart, idur, state, step, req_hash, trace_id = row
        return AggregationJob(
            task_id=task_id,
            aggregation_job_id=aggregation_job_id,
            aggregation_parameter=param,
            partial_batch_identifier=BatchId(batch_id) if batch_id else None,
            client_timestamp_interval=Interval(Time(istart), Duration(idur)),
            state=AggregationJobState(state),
            step=AggregationJobStep(step),
            last_request_hash=req_hash,
            trace_id=trace_id,
        )

    def update_aggregation_job(self, job: AggregationJob) -> None:
        pk = self._task_pk(job.task_id)
        cur = self.conn.execute(
            """UPDATE aggregation_jobs SET state = ?, step = ?,
                 last_request_hash = ?, updated_at = ?
               WHERE task_id = ? AND aggregation_job_id = ?""",
            (
                job.state.value,
                int(job.step),
                job.last_request_hash,
                self._now_s(),
                pk,
                job.aggregation_job_id.data,
            ),
        )
        if cur.rowcount == 0:
            raise DatastoreError(f"no aggregation job {job.aggregation_job_id}")

    def get_aggregation_jobs_for_task(self, task_id: TaskId) -> List[AggregationJob]:
        pk = self._task_pk(task_id)
        rows = self.conn.execute(
            """SELECT aggregation_job_id, aggregation_param, batch_id,
                      client_timestamp_interval_start,
                      client_timestamp_interval_duration, state, step,
                      last_request_hash, trace_id
               FROM aggregation_jobs WHERE task_id = ? ORDER BY id""",
            (pk,),
        ).fetchall()
        return [
            AggregationJob(
                task_id=task_id,
                aggregation_job_id=AggregationJobId(job_id),
                aggregation_parameter=param,
                partial_batch_identifier=BatchId(batch_id) if batch_id else None,
                client_timestamp_interval=Interval(Time(istart), Duration(idur)),
                state=AggregationJobState(state),
                step=AggregationJobStep(step),
                last_request_hash=req_hash,
                trace_id=trace_id,
            )
            for (
                job_id,
                param,
                batch_id,
                istart,
                idur,
                state,
                step,
                req_hash,
                trace_id,
            ) in rows
        ]

    def get_task_peer_index(self) -> List[Tuple[bytes, str]]:
        """(task_id bytes, peer aggregator endpoint) for every task — the
        task -> peer index behind peer-health-aware job acquisition
        (job_driver.suspect_task_ids): tasks of a suspect peer are
        filtered at the acquire query instead of acquired-then-released."""
        return [
            (r[0], r[1])
            for r in self.conn.execute(
                "SELECT task_id, peer_aggregator_endpoint FROM tasks"
            ).fetchall()
        ]

    def _task_exclusion_clause(self, exclude_task_ids):
        """(SQL fragment, params) excluding jobs of the named tasks from an
        acquisition pick.  Empty/None excludes nothing."""
        ids = list(exclude_task_ids or ())
        if not ids:
            return "", []
        marks = ",".join("?" * len(ids))
        return (
            f" AND task_id NOT IN (SELECT id FROM tasks WHERE task_id IN ({marks}))",
            ids,
        )

    def acquire_incomplete_aggregation_jobs(
        self,
        lease_duration: Duration,
        limit: int,
        exclude_task_ids: Optional[Sequence[bytes]] = None,
    ) -> List[Lease]:
        """Lease InProgress jobs whose lease expired — the reference's
        ``FOR UPDATE … SKIP LOCKED`` loop (datastore.rs:1916-1985), expressed
        as one atomic UPDATE under SQLite's single-writer transaction.
        ``exclude_task_ids`` filters suspect-peer tasks AT THE QUERY
        (peer-health-aware acquisition): their jobs stay acquirable by
        replicas that still reach the peer, without this replica paying an
        acquire-then-release tx round trip per job per poll."""
        now = self._now_s()
        expiry = now + lease_duration.seconds
        token = secrets.token_bytes(16)
        excl_sql, excl_params = self._task_exclusion_clause(exclude_task_ids)
        if self.ds.backend.supports_returning:
            rows = self.conn.execute(
                f"""UPDATE aggregation_jobs
                   SET lease_expiry = ?, lease_token = ?, lease_attempts = lease_attempts + 1,
                       updated_at = ?
                   WHERE id IN (
                       SELECT id FROM aggregation_jobs
                       WHERE state = 'InProgress' AND lease_expiry <= ?{excl_sql}
                       ORDER BY id LIMIT ? /*skip-locked*/)
                   RETURNING task_id, aggregation_job_id, lease_attempts,
                             trace_id, created_at""",
                (expiry, token, now, now, *excl_params, limit),
            ).fetchall()
        else:
            picked = self.conn.execute(
                f"""SELECT id, task_id, aggregation_job_id, lease_attempts,
                          trace_id, created_at
                   FROM aggregation_jobs
                   WHERE state = 'InProgress' AND lease_expiry <= ?{excl_sql}
                   ORDER BY id LIMIT ?""",
                (now, *excl_params, limit),
            ).fetchall()
            self.conn.executemany(
                """UPDATE aggregation_jobs SET lease_expiry = ?, lease_token = ?,
                     lease_attempts = lease_attempts + 1, updated_at = ?
                   WHERE id = ?""",
                [(expiry, token, now, r[0]) for r in picked],
            )
            rows = [(r[1], r[2], r[3] + 1, r[4], r[5]) for r in picked]
        leases = []
        for task_pk, job_id, attempts, trace_id, created_at in rows:
            trow = self.conn.execute(
                "SELECT task_id, query_type, vdaf FROM tasks WHERE id = ?", (task_pk,)
            ).fetchone()
            leases.append(
                Lease(
                    leased=AcquiredAggregationJob(
                        task_id=TaskId(trow[0]),
                        aggregation_job_id=AggregationJobId(job_id),
                        query_type=TaskQueryType.from_json(trow[1]).kind,
                        vdaf=json.loads(trow[2]),
                        trace_id=trace_id,
                        age_seconds=float(max(0, now - (created_at or now))),
                    ),
                    lease_expiry=Time(expiry),
                    lease_token=LeaseToken(token),
                    lease_attempts=attempts,
                )
            )
        return leases

    def release_aggregation_job(
        self, lease: Lease, reacquire_delay: Optional[Duration] = None
    ) -> None:
        """reference: datastore.rs:1991 (release_aggregation_job); the token
        check fences stale lease holders."""
        job = lease.leased
        pk = self._task_pk(job.task_id)
        new_expiry = (
            self._now_s() + reacquire_delay.seconds if reacquire_delay is not None else 0
        )
        cur = self.conn.execute(
            """UPDATE aggregation_jobs SET lease_expiry = ?, lease_token = NULL
               WHERE task_id = ? AND aggregation_job_id = ? AND lease_token = ?""",
            (new_expiry, pk, job.aggregation_job_id.data, lease.lease_token.data),
        )
        if cur.rowcount == 0:
            raise TxConflict("lease no longer held")

    # ------------------------------------------------------------------
    # report aggregations (reference: datastore.rs:2190-2519)

    def put_report_aggregation(self, ra: ReportAggregation) -> None:
        pk = self._task_pk(ra.task_id)
        jrow = self.conn.execute(
            "SELECT id FROM aggregation_jobs WHERE task_id = ? AND aggregation_job_id = ?",
            (pk, ra.aggregation_job_id.data),
        ).fetchone()
        if jrow is None:
            raise DatastoreError(f"no aggregation job {ra.aggregation_job_id}")
        cols = self._ra_payload_cols(ra)
        try:
            self.conn.execute(
                """INSERT INTO report_aggregations (task_id, aggregation_job_id, ord,
                    report_id, client_timestamp, last_prep_resp, state, public_share,
                    leader_extensions, leader_input_share, helper_encrypted_input_share,
                    leader_prep_transition, helper_prep_state, error_code)
                   VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?)""",
                (
                    pk,
                    jrow[0],
                    ra.ord,
                    ra.report_id.data,
                    ra.time.seconds,
                    ra.last_prep_resp.get_encoded() if ra.last_prep_resp else None,
                    ra.state.value,
                    *cols,
                ),
            )
        except self.ds.backend.integrity_errors as e:
            raise TxConflict(f"report aggregation ord {ra.ord} already exists") from e

    def _ra_payload_cols(self, ra: ReportAggregation) -> Tuple:
        row_ident = ra.task_id.data + ra.aggregation_job_id.data + ra.report_id.data
        enc_input = (
            self.crypter.encrypt(
                "report_aggregations", row_ident, "leader_input_share",
                ra.leader_input_share,
            )
            if ra.leader_input_share is not None
            else None
        )
        enc_transition = (
            self.crypter.encrypt(
                "report_aggregations", row_ident, "leader_prep_transition",
                ra.leader_prep_transition,
            )
            if ra.leader_prep_transition is not None
            else None
        )
        enc_helper_state = (
            self.crypter.encrypt(
                "report_aggregations", row_ident, "helper_prep_state",
                ra.helper_prep_state,
            )
            if ra.helper_prep_state is not None
            else None
        )
        return (
            ra.public_share,
            _encode_extensions(ra.leader_extensions) if ra.leader_extensions else None,
            enc_input,
            ra.helper_encrypted_input_share.get_encoded()
            if ra.helper_encrypted_input_share
            else None,
            enc_transition,
            enc_helper_state,
            int(ra.error) if ra.error is not None else None,
        )

    def update_report_aggregation(self, ra: ReportAggregation) -> None:
        pk = self._task_pk(ra.task_id)
        cols = self._ra_payload_cols(ra)
        cur = self.conn.execute(
            """UPDATE report_aggregations SET last_prep_resp = ?, state = ?,
                 public_share = ?, leader_extensions = ?, leader_input_share = ?,
                 helper_encrypted_input_share = ?, leader_prep_transition = ?,
                 helper_prep_state = ?, error_code = ?
               WHERE task_id = ? AND report_id = ? AND aggregation_job_id =
                 (SELECT id FROM aggregation_jobs
                  WHERE task_id = ? AND aggregation_job_id = ?)""",
            (
                ra.last_prep_resp.get_encoded() if ra.last_prep_resp else None,
                ra.state.value,
                *cols,
                pk,
                ra.report_id.data,
                pk,
                ra.aggregation_job_id.data,
            ),
        )
        if cur.rowcount == 0:
            raise DatastoreError(f"no report aggregation for {ra.report_id}")

    def get_report_aggregations_for_aggregation_job(
        self, task_id: TaskId, aggregation_job_id: AggregationJobId
    ) -> List[ReportAggregation]:
        pk = self._task_pk(task_id)
        rows = self.conn.execute(
            """SELECT ra.ord, ra.report_id, ra.client_timestamp, ra.last_prep_resp,
                      ra.state, ra.public_share, ra.leader_extensions,
                      ra.leader_input_share, ra.helper_encrypted_input_share,
                      ra.leader_prep_transition, ra.helper_prep_state, ra.error_code
               FROM report_aggregations ra
               JOIN aggregation_jobs aj ON ra.aggregation_job_id = aj.id
               WHERE aj.task_id = ? AND aj.aggregation_job_id = ?
               ORDER BY ra.ord""",
            (pk, aggregation_job_id.data),
        ).fetchall()
        out = []
        for (
            ord_,
            rid,
            ts,
            prep_resp_b,
            state,
            public_share,
            ext_b,
            enc_input,
            helper_b,
            enc_trans,
            enc_hstate,
            err,
        ) in rows:
            row_ident = task_id.data + aggregation_job_id.data + rid
            out.append(
                ReportAggregation(
                    task_id=task_id,
                    aggregation_job_id=aggregation_job_id,
                    report_id=ReportId(rid),
                    time=Time(ts),
                    ord=ord_,
                    state=ReportAggregationState(state),
                    last_prep_resp=PrepareResp.get_decoded(prep_resp_b)
                    if prep_resp_b
                    else None,
                    public_share=public_share,
                    leader_extensions=_decode_extensions(ext_b) if ext_b else [],
                    leader_input_share=self.crypter.decrypt(
                        "report_aggregations", row_ident, "leader_input_share", enc_input
                    )
                    if enc_input
                    else None,
                    helper_encrypted_input_share=HpkeCiphertext.get_decoded(helper_b)
                    if helper_b
                    else None,
                    leader_prep_transition=self.crypter.decrypt(
                        "report_aggregations", row_ident, "leader_prep_transition", enc_trans
                    )
                    if enc_trans
                    else None,
                    helper_prep_state=self.crypter.decrypt(
                        "report_aggregations", row_ident, "helper_prep_state", enc_hstate
                    )
                    if enc_hstate
                    else None,
                    error=PrepareError(err) if err is not None else None,
                )
            )
        return out

    def put_report_aggregation_metadata(self, meta: ReportAggregationMetadata) -> None:
        """Creator path: StartLeader rows without payloads (the report data is
        scrubbed from client_reports only after packing; reference
        aggregation_job_creator.rs:718-731 stores metadata-only rows)."""
        pk = self._task_pk(meta.task_id)
        jrow = self.conn.execute(
            "SELECT id FROM aggregation_jobs WHERE task_id = ? AND aggregation_job_id = ?",
            (pk, meta.aggregation_job_id.data),
        ).fetchone()
        if jrow is None:
            raise DatastoreError(f"no aggregation job {meta.aggregation_job_id}")
        try:
            self.conn.execute(
                """INSERT INTO report_aggregations (task_id, aggregation_job_id, ord,
                    report_id, client_timestamp, state)
                   VALUES (?,?,?,?,?,?)""",
                (
                    pk,
                    jrow[0],
                    meta.ord,
                    meta.report_id.data,
                    meta.time.seconds,
                    ReportAggregationState.START_LEADER.value,
                ),
            )
        except self.ds.backend.integrity_errors as e:
            raise TxConflict(f"report aggregation ord {meta.ord} already exists") from e

    def get_aggregation_params_for_report(
        self,
        task_id: TaskId,
        report_id: ReportId,
        exclude_aggregation_job_id: Optional[AggregationJobId] = None,
    ) -> List[bytes]:
        """Distinct aggregation parameters of jobs this report is already in
        (the VDAF decides which of them CONFLICT with a new one)."""
        pk = self._task_pk(task_id)
        sql = """SELECT DISTINCT aj.aggregation_param FROM report_aggregations ra
                 JOIN aggregation_jobs aj ON ra.aggregation_job_id = aj.id
                 WHERE ra.task_id = ? AND ra.report_id = ?"""
        args: List[Any] = [pk, report_id.data]
        if exclude_aggregation_job_id is not None:
            sql += " AND aj.aggregation_job_id != ?"
            args.append(exclude_aggregation_job_id.data)
        return [r[0] for r in self.conn.execute(sql, args)]

    def check_report_aggregation_exists(
        self,
        task_id: TaskId,
        report_id: ReportId,
        aggregation_parameter: bytes = b"",
        exclude_aggregation_job_id: Optional[AggregationJobId] = None,
    ) -> bool:
        """Exact-parameter replay check, expressed over
        get_aggregation_params_for_report so the two can't diverge.  Role
        logic uses the VDAF's conflict key on the params list instead
        (reference: aggregator.rs:1765 dup-report-ID check)."""
        return aggregation_parameter in self.get_aggregation_params_for_report(
            task_id, report_id, exclude_aggregation_job_id
        )

    # ------------------------------------------------------------------
    # batch aggregations (reference: datastore.rs:3626-4008)

    def put_batch_aggregation(self, ba: BatchAggregation) -> None:
        pk = self._task_pk(ba.task_id)
        try:
            self.conn.execute(
                """INSERT INTO batch_aggregations (task_id, batch_identifier,
                    aggregation_param, ord, state, aggregate_share, report_count,
                    checksum, client_timestamp_interval_start,
                    client_timestamp_interval_duration, aggregation_jobs_created,
                    aggregation_jobs_terminated, created_at)
                   VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?)""",
                (
                    pk,
                    ba.batch_identifier,
                    ba.aggregation_parameter,
                    ba.ord,
                    ba.state.value,
                    ba.aggregate_share,
                    ba.report_count,
                    ba.checksum.data,
                    ba.client_timestamp_interval.start.seconds,
                    ba.client_timestamp_interval.duration.seconds,
                    ba.aggregation_jobs_created,
                    ba.aggregation_jobs_terminated,
                    self._now_s(),
                ),
            )
        except self.ds.backend.integrity_errors as e:
            raise TxConflict("batch aggregation shard already exists") from e

    def update_batch_aggregation(self, ba: BatchAggregation) -> None:
        pk = self._task_pk(ba.task_id)
        cur = self.conn.execute(
            """UPDATE batch_aggregations SET state = ?, aggregate_share = ?,
                 report_count = ?, checksum = ?,
                 client_timestamp_interval_start = ?,
                 client_timestamp_interval_duration = ?,
                 aggregation_jobs_created = ?, aggregation_jobs_terminated = ?
               WHERE task_id = ? AND batch_identifier = ? AND aggregation_param = ?
                 AND ord = ?""",
            (
                ba.state.value,
                ba.aggregate_share,
                ba.report_count,
                ba.checksum.data,
                ba.client_timestamp_interval.start.seconds,
                ba.client_timestamp_interval.duration.seconds,
                ba.aggregation_jobs_created,
                ba.aggregation_jobs_terminated,
                pk,
                ba.batch_identifier,
                ba.aggregation_parameter,
                ba.ord,
            ),
        )
        if cur.rowcount == 0:
            raise DatastoreError("no batch aggregation shard to update")

    def get_batch_aggregation(
        self,
        task_id: TaskId,
        batch_identifier: bytes,
        aggregation_parameter: bytes,
        ord: int,
    ) -> Optional[BatchAggregation]:
        rows = self._get_batch_aggregations(
            task_id, batch_identifier, aggregation_parameter, ord
        )
        return rows[0] if rows else None

    def get_batch_aggregations_for_batch(
        self, task_id: TaskId, batch_identifier: bytes, aggregation_parameter: bytes
    ) -> List[BatchAggregation]:
        return self._get_batch_aggregations(task_id, batch_identifier, aggregation_parameter)

    def _get_batch_aggregations(
        self,
        task_id: TaskId,
        batch_identifier: bytes,
        aggregation_parameter: bytes,
        ord: Optional[int] = None,
    ) -> List[BatchAggregation]:
        pk = self._task_pk(task_id)
        sql = """SELECT ord, state, aggregate_share, report_count, checksum,
                        client_timestamp_interval_start,
                        client_timestamp_interval_duration,
                        aggregation_jobs_created, aggregation_jobs_terminated
                 FROM batch_aggregations
                 WHERE task_id = ? AND batch_identifier = ? AND aggregation_param = ?"""
        args: List[Any] = [pk, batch_identifier, aggregation_parameter]
        if ord is not None:
            sql += " AND ord = ?"
            args.append(ord)
        sql += " ORDER BY ord"
        out = []
        for row in self.conn.execute(sql, args):
            (
                ord_,
                state,
                share,
                count,
                checksum,
                istart,
                idur,
                created,
                terminated,
            ) = row
            out.append(
                BatchAggregation(
                    task_id=task_id,
                    batch_identifier=batch_identifier,
                    aggregation_parameter=aggregation_parameter,
                    ord=ord_,
                    state=BatchAggregationState(state),
                    aggregate_share=share,
                    report_count=count,
                    checksum=ReportIdChecksum(checksum),
                    client_timestamp_interval=Interval(Time(istart), Duration(idur)),
                    aggregation_jobs_created=created,
                    aggregation_jobs_terminated=terminated,
                )
            )
        return out

    def get_batch_aggregations_overlapping_interval(
        self, task_id: TaskId, interval: Interval
    ) -> List[Tuple[bytes, bytes]]:
        """(batch_identifier, aggregation_param) pairs whose client timestamp
        interval overlaps — used for TimeInterval collection validation."""
        pk = self._task_pk(task_id)
        rows = self.conn.execute(
            """SELECT DISTINCT batch_identifier, aggregation_param
               FROM batch_aggregations
               WHERE task_id = ?
                 AND client_timestamp_interval_start < ?
                 AND client_timestamp_interval_start
                     + client_timestamp_interval_duration > ?""",
            (pk, interval.end().seconds, interval.start.seconds),
        ).fetchall()
        return [(r[0], r[1]) for r in rows]

    # ------------------------------------------------------------------
    # collection jobs (reference: datastore.rs:3222-3397)

    def put_collection_job(self, job: CollectionJob) -> None:
        pk = self._task_pk(job.task_id)
        row_ident = job.task_id.data + job.collection_job_id.data
        enc_share = (
            self.crypter.encrypt(
                "collection_jobs", row_ident, "leader_aggregate_share",
                job.leader_aggregate_share,
            )
            if job.leader_aggregate_share is not None
            else None
        )
        now = self._now_s()
        try:
            self.conn.execute(
                """INSERT INTO collection_jobs (task_id, collection_job_id, query,
                    aggregation_param, batch_identifier, state, report_count,
                    client_timestamp_interval_start, client_timestamp_interval_duration,
                    leader_aggregate_share, helper_aggregate_share, trace_id,
                    created_at, updated_at)
                   VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?)""",
                (
                    pk,
                    job.collection_job_id.data,
                    job.query.get_encoded(),
                    job.aggregation_parameter,
                    job.batch_identifier,
                    job.state.value,
                    job.report_count,
                    job.client_timestamp_interval.start.seconds
                    if job.client_timestamp_interval
                    else None,
                    job.client_timestamp_interval.duration.seconds
                    if job.client_timestamp_interval
                    else None,
                    enc_share,
                    job.helper_aggregate_share.get_encoded()
                    if job.helper_aggregate_share
                    else None,
                    job.trace_id,
                    now,
                    now,
                ),
            )
        except self.ds.backend.integrity_errors as e:
            raise TxConflict(f"collection job {job.collection_job_id} exists") from e

    def get_collection_job(
        self, task_id: TaskId, collection_job_id: CollectionJobId, query_kind: str
    ) -> Optional[CollectionJob]:
        pk = self._task_pk(task_id)
        row = self.conn.execute(
            """SELECT query, aggregation_param, batch_identifier, state,
                      report_count, client_timestamp_interval_start,
                      client_timestamp_interval_duration, leader_aggregate_share,
                      helper_aggregate_share, trace_id
               FROM collection_jobs WHERE task_id = ? AND collection_job_id = ?""",
            (pk, collection_job_id.data),
        ).fetchone()
        if row is None:
            return None
        return self._collection_job_from_row(task_id, collection_job_id, query_kind, row)

    def _collection_job_from_row(
        self, task_id, collection_job_id, query_kind: str, row
    ) -> CollectionJob:
        (
            query_b,
            param,
            batch_ident,
            state,
            count,
            istart,
            idur,
            enc_share,
            helper_b,
            trace_id,
        ) = row
        row_ident = task_id.data + collection_job_id.data
        return CollectionJob(
            task_id=task_id,
            collection_job_id=collection_job_id,
            query=Query.get_decoded(query_b, QUERY_TYPES[query_kind]),
            aggregation_parameter=param,
            batch_identifier=batch_ident,
            state=CollectionJobState(state),
            report_count=count,
            client_timestamp_interval=Interval(Time(istart), Duration(idur))
            if istart is not None
            else None,
            leader_aggregate_share=self.crypter.decrypt(
                "collection_jobs", row_ident, "leader_aggregate_share", enc_share
            )
            if enc_share
            else None,
            helper_aggregate_share=HpkeCiphertext.get_decoded(helper_b)
            if helper_b
            else None,
            trace_id=trace_id,
        )

    def update_collection_job(self, job: CollectionJob) -> None:
        pk = self._task_pk(job.task_id)
        row_ident = job.task_id.data + job.collection_job_id.data
        enc_share = (
            self.crypter.encrypt(
                "collection_jobs", row_ident, "leader_aggregate_share",
                job.leader_aggregate_share,
            )
            if job.leader_aggregate_share is not None
            else None
        )
        cur = self.conn.execute(
            """UPDATE collection_jobs SET state = ?, report_count = ?,
                 client_timestamp_interval_start = ?,
                 client_timestamp_interval_duration = ?,
                 leader_aggregate_share = ?, helper_aggregate_share = ?,
                 updated_at = ?
               WHERE task_id = ? AND collection_job_id = ?""",
            (
                job.state.value,
                job.report_count,
                job.client_timestamp_interval.start.seconds
                if job.client_timestamp_interval
                else None,
                job.client_timestamp_interval.duration.seconds
                if job.client_timestamp_interval
                else None,
                enc_share,
                job.helper_aggregate_share.get_encoded()
                if job.helper_aggregate_share
                else None,
                self._now_s(),
                pk,
                job.collection_job_id.data,
            ),
        )
        if cur.rowcount == 0:
            raise DatastoreError(f"no collection job {job.collection_job_id}")

    def get_collection_jobs_by_batch_identifier(
        self, task_id: TaskId, batch_identifier: bytes, query_kind: str
    ) -> List[CollectionJob]:
        pk = self._task_pk(task_id)
        rows = self.conn.execute(
            """SELECT collection_job_id, query, aggregation_param, batch_identifier,
                      state, report_count, client_timestamp_interval_start,
                      client_timestamp_interval_duration, leader_aggregate_share,
                      helper_aggregate_share, trace_id
               FROM collection_jobs WHERE task_id = ? AND batch_identifier = ?""",
            (pk, batch_identifier),
        ).fetchall()
        return [
            self._collection_job_from_row(task_id, CollectionJobId(r[0]), query_kind, r[1:])
            for r in rows
        ]

    def increment_collection_job_step_attempts(
        self, task_id: TaskId, collection_job_id: CollectionJobId
    ) -> int:
        pk = self._task_pk(task_id)
        if self.ds.backend.supports_returning:
            row = self.conn.execute(
                """UPDATE collection_jobs SET step_attempts = step_attempts + 1
                   WHERE task_id = ? AND collection_job_id = ?
                   RETURNING step_attempts""",
                (pk, collection_job_id.data),
            ).fetchone()
        else:
            cur = self.conn.execute(
                """UPDATE collection_jobs SET step_attempts = step_attempts + 1
                   WHERE task_id = ? AND collection_job_id = ?""",
                (pk, collection_job_id.data),
            )
            row = (
                self.conn.execute(
                    "SELECT step_attempts FROM collection_jobs"
                    " WHERE task_id = ? AND collection_job_id = ?",
                    (pk, collection_job_id.data),
                ).fetchone()
                if cur.rowcount
                else None
            )
        if row is None:
            raise DatastoreError(f"no collection job {collection_job_id}")
        return row[0]

    def acquire_incomplete_collection_jobs(
        self,
        lease_duration: Duration,
        limit: int,
        exclude_task_ids: Optional[Sequence[bytes]] = None,
    ) -> List[Lease]:
        """reference: datastore.rs:3295.  ``exclude_task_ids``: the same
        suspect-peer acquisition filter as the aggregation form."""
        now = self._now_s()
        expiry = now + lease_duration.seconds
        token = secrets.token_bytes(16)
        excl_sql, excl_params = self._task_exclusion_clause(exclude_task_ids)
        if self.ds.backend.supports_returning:
            rows = self.conn.execute(
                f"""UPDATE collection_jobs
                   SET lease_expiry = ?, lease_token = ?, lease_attempts = lease_attempts + 1,
                       updated_at = ?
                   WHERE id IN (
                       SELECT id FROM collection_jobs
                       WHERE state = 'Start' AND lease_expiry <= ?{excl_sql}
                       ORDER BY id LIMIT ? /*skip-locked*/)
                   RETURNING task_id, collection_job_id, lease_attempts, step_attempts,
                             trace_id, created_at""",
                (expiry, token, now, now, *excl_params, limit),
            ).fetchall()
        else:
            picked = self.conn.execute(
                f"""SELECT id, task_id, collection_job_id, lease_attempts, step_attempts,
                          trace_id, created_at
                   FROM collection_jobs
                   WHERE state = 'Start' AND lease_expiry <= ?{excl_sql}
                   ORDER BY id LIMIT ?""",
                (now, *excl_params, limit),
            ).fetchall()
            self.conn.executemany(
                """UPDATE collection_jobs SET lease_expiry = ?, lease_token = ?,
                     lease_attempts = lease_attempts + 1, updated_at = ?
                   WHERE id = ?""",
                [(expiry, token, now, r[0]) for r in picked],
            )
            rows = [(r[1], r[2], r[3] + 1, r[4], r[5], r[6]) for r in picked]
        leases = []
        for task_pk, job_id, attempts, step_attempts, trace_id, created_at in rows:
            trow = self.conn.execute(
                "SELECT task_id, query_type, vdaf FROM tasks WHERE id = ?", (task_pk,)
            ).fetchone()
            leases.append(
                Lease(
                    leased=AcquiredCollectionJob(
                        task_id=TaskId(trow[0]),
                        collection_job_id=CollectionJobId(job_id),
                        query_type=TaskQueryType.from_json(trow[1]).kind,
                        vdaf=json.loads(trow[2]),
                        step_attempts=step_attempts,
                        trace_id=trace_id,
                        age_seconds=float(max(0, now - (created_at or now))),
                    ),
                    lease_expiry=Time(expiry),
                    lease_token=LeaseToken(token),
                    lease_attempts=attempts,
                )
            )
        return leases

    def release_collection_job(
        self, lease: Lease, reacquire_delay: Optional[Duration] = None
    ) -> None:
        """reference: datastore.rs:3397"""
        job = lease.leased
        pk = self._task_pk(job.task_id)
        new_expiry = (
            self._now_s() + reacquire_delay.seconds if reacquire_delay is not None else 0
        )
        cur = self.conn.execute(
            """UPDATE collection_jobs SET lease_expiry = ?, lease_token = NULL
               WHERE task_id = ? AND collection_job_id = ? AND lease_token = ?""",
            (new_expiry, pk, job.collection_job_id.data, lease.lease_token.data),
        )
        if cur.rowcount == 0:
            raise TxConflict("lease no longer held")

    # ------------------------------------------------------------------
    # aggregate share jobs (reference: datastore.rs:4086-4328)

    def put_aggregate_share_job(self, job: AggregateShareJob) -> None:
        pk = self._task_pk(job.task_id)
        row_ident = job.task_id.data + job.batch_identifier
        enc = self.crypter.encrypt(
            "aggregate_share_jobs", row_ident, "helper_aggregate_share",
            job.helper_aggregate_share,
        )
        try:
            self.conn.execute(
                """INSERT INTO aggregate_share_jobs (task_id, batch_identifier,
                    aggregation_param, helper_aggregate_share, report_count,
                    checksum, created_at)
                   VALUES (?,?,?,?,?,?,?)""",
                (
                    pk,
                    job.batch_identifier,
                    job.aggregation_parameter,
                    enc,
                    job.report_count,
                    job.checksum.data,
                    self._now_s(),
                ),
            )
        except self.ds.backend.integrity_errors as e:
            raise TxConflict("aggregate share job already exists") from e

    def get_aggregate_share_job(
        self, task_id: TaskId, batch_identifier: bytes, aggregation_parameter: bytes
    ) -> Optional[AggregateShareJob]:
        pk = self._task_pk(task_id)
        row = self.conn.execute(
            """SELECT helper_aggregate_share, report_count, checksum
               FROM aggregate_share_jobs
               WHERE task_id = ? AND batch_identifier = ? AND aggregation_param = ?""",
            (pk, batch_identifier, aggregation_parameter),
        ).fetchone()
        if row is None:
            return None
        row_ident = task_id.data + batch_identifier
        return AggregateShareJob(
            task_id=task_id,
            batch_identifier=batch_identifier,
            aggregation_parameter=aggregation_parameter,
            helper_aggregate_share=self.crypter.decrypt(
                "aggregate_share_jobs", row_ident, "helper_aggregate_share", row[0]
            ),
            report_count=row[1],
            checksum=ReportIdChecksum(row[2]),
        )

    def count_aggregate_share_jobs_for_batch(
        self, task_id: TaskId, batch_identifier: bytes
    ) -> int:
        pk = self._task_pk(task_id)
        return self.conn.execute(
            "SELECT COUNT(*) FROM aggregate_share_jobs"
            " WHERE task_id = ? AND batch_identifier = ?",
            (pk, batch_identifier),
        ).fetchone()[0]

    # ------------------------------------------------------------------
    # outstanding batches (reference: datastore.rs:4394-4646)

    def put_outstanding_batch(
        self, task_id: TaskId, batch_id: BatchId, time_bucket_start: Optional[Time]
    ) -> None:
        pk = self._task_pk(task_id)
        try:
            self.conn.execute(
                """INSERT INTO outstanding_batches (task_id, batch_id,
                    time_bucket_start, created_at) VALUES (?,?,?,?)""",
                (
                    pk,
                    batch_id.data,
                    time_bucket_start.seconds if time_bucket_start else None,
                    self._now_s(),
                ),
            )
        except self.ds.backend.integrity_errors as e:
            raise TxConflict("outstanding batch already exists") from e

    def get_unfilled_outstanding_batches(
        self, task_id: TaskId, time_bucket_start: Optional[Time]
    ) -> List[OutstandingBatch]:
        pk = self._task_pk(task_id)
        if time_bucket_start is None:
            rows = self.conn.execute(
                """SELECT batch_id, time_bucket_start FROM outstanding_batches
                   WHERE task_id = ? AND filled = 0 AND time_bucket_start IS NULL""",
                (pk,),
            ).fetchall()
        else:
            rows = self.conn.execute(
                """SELECT batch_id, time_bucket_start FROM outstanding_batches
                   WHERE task_id = ? AND filled = 0 AND time_bucket_start = ?""",
                (pk, time_bucket_start.seconds),
            ).fetchall()
        out = []
        for batch_id_b, bucket in rows:
            size_min, size_max = self._outstanding_batch_size(pk, batch_id_b)
            out.append(
                OutstandingBatch(
                    task_id=task_id,
                    batch_id=BatchId(batch_id_b),
                    time_bucket_start=Time(bucket) if bucket is not None else None,
                    size_min=size_min,
                    size_max=size_max,
                )
            )
        return out

    def _outstanding_batch_size(self, task_pk: int, batch_id: bytes) -> Tuple[int, int]:
        """Possible report-count range for a batch: min counts Finished report
        aggregations, max also counts in-flight ones
        (reference: datastore.rs read_batch_size)."""
        row = self.conn.execute(
            """SELECT
                 SUM(CASE WHEN ra.state = 'Finished' THEN 1 ELSE 0 END),
                 SUM(CASE WHEN ra.state != 'Failed' THEN 1 ELSE 0 END)
               FROM report_aggregations ra
               JOIN aggregation_jobs aj ON ra.aggregation_job_id = aj.id
               WHERE aj.task_id = ? AND aj.batch_id = ?""",
            (task_pk, batch_id),
        ).fetchone()
        return (row[0] or 0, row[1] or 0)

    def mark_outstanding_batch_filled(self, task_id: TaskId, batch_id: BatchId) -> None:
        pk = self._task_pk(task_id)
        self.conn.execute(
            "UPDATE outstanding_batches SET filled = 1 WHERE task_id = ? AND batch_id = ?",
            (pk, batch_id.data),
        )

    def acquire_filled_outstanding_batch(
        self, task_id: TaskId, min_size: int
    ) -> Optional[BatchId]:
        """Pick (and remove) one outstanding batch with at least ``min_size``
        finished reports — serves FixedSizeQuery::CurrentBatch
        (reference: datastore.rs acquire_outstanding_batch_with_report_count)."""
        pk = self._task_pk(task_id)
        for (batch_id_b,) in self.conn.execute(
            "SELECT batch_id FROM outstanding_batches WHERE task_id = ? ORDER BY created_at",
            (pk,),
        ).fetchall():
            size_min, _ = self._outstanding_batch_size(pk, batch_id_b)
            if size_min >= min_size:
                self.conn.execute(
                    "DELETE FROM outstanding_batches WHERE task_id = ? AND batch_id = ?",
                    (pk, batch_id_b),
                )
                return BatchId(batch_id_b)
        return None

    def delete_outstanding_batch(self, task_id: TaskId, batch_id: BatchId) -> None:
        pk = self._task_pk(task_id)
        self.conn.execute(
            "DELETE FROM outstanding_batches WHERE task_id = ? AND batch_id = ?",
            (pk, batch_id.data),
        )

    # ------------------------------------------------------------------
    # GC (reference: datastore.rs:4733,4793)

    def delete_expired_aggregation_artifacts(
        self, task_id: TaskId, expiry: Time, limit: int
    ) -> int:
        """Delete aggregation jobs (and their report aggregations, via
        cascade) whose entire client-timestamp interval is before expiry.
        Jobs with an OUTSTANDING accumulator-journal row are skipped:
        their FINISHED rows' retained payloads are the only material the
        journal replay can re-derive the missing shares from — deleting
        them would either wedge the batch's readiness gate (row kept) or
        silently corrupt its aggregate (row dropped with the count
        already committed).  The replay consumes the row, and the next
        GC pass collects the job."""
        pk = self._task_pk(task_id)
        cur = self.conn.execute(
            """DELETE FROM aggregation_jobs WHERE id IN (
                 SELECT j.id FROM aggregation_jobs j
                 WHERE j.task_id = ?
                   AND j.client_timestamp_interval_start
                       + j.client_timestamp_interval_duration < ?
                   AND j.state != 'InProgress'
                   AND NOT EXISTS (
                     SELECT 1 FROM accumulator_journal aj
                     WHERE aj.task_id = j.task_id
                       AND aj.aggregation_job_id = j.aggregation_job_id)
                 LIMIT ?)""",
            (pk, expiry.seconds, limit),
        )
        return cur.rowcount

    def count_accumulator_journal_entries(self, task_id: TaskId) -> int:
        """Task-wide outstanding-row count (one indexed COUNT — the
        collection driver's cheap pre-replay probe)."""
        pk = self._task_pk(task_id)
        return self.conn.execute(
            "SELECT COUNT(*) FROM accumulator_journal WHERE task_id = ?", (pk,)
        ).fetchone()[0]

    def delete_expired_collection_artifacts(
        self, task_id: TaskId, expiry: Time, limit: int
    ) -> int:
        pk = self._task_pk(task_id)
        n = self.conn.execute(
            """DELETE FROM collection_jobs WHERE id IN (
                 SELECT id FROM collection_jobs
                 WHERE task_id = ? AND state IN ('Finished','Abandoned','Deleted')
                   AND client_timestamp_interval_start IS NOT NULL
                   AND client_timestamp_interval_start
                       + client_timestamp_interval_duration < ?
                 LIMIT ?)""",
            (pk, expiry.seconds, limit),
        ).rowcount
        n += self.conn.execute(
            """DELETE FROM batch_aggregations WHERE id IN (
                 SELECT id FROM batch_aggregations
                 WHERE task_id = ? AND state != 'Aggregating'
                   AND client_timestamp_interval_start
                       + client_timestamp_interval_duration < ?
                 LIMIT ?)""",
            (pk, expiry.seconds, limit),
        ).rowcount
        n += self.conn.execute(
            """DELETE FROM aggregate_share_jobs WHERE id IN (
                 SELECT id FROM aggregate_share_jobs
                 WHERE task_id = ? AND created_at < ? LIMIT ?)""",
            (pk, expiry.seconds, limit),
        ).rowcount
        return n

    # ------------------------------------------------------------------
    # global HPKE keys (reference: datastore.rs:4857-4983)

    def put_global_hpke_keypair(self, keypair: HpkeKeypair) -> None:
        enc = self.crypter.encrypt(
            "global_hpke_keys",
            bytes([keypair.config.id]),
            "private_key",
            keypair.private_key,
        )
        try:
            self.conn.execute(
                """INSERT INTO global_hpke_keys (config_id, config, private_key,
                    state, updated_at) VALUES (?,?,?,?,?)""",
                (
                    keypair.config.id,
                    keypair.config.get_encoded(),
                    enc,
                    HpkeKeyState.PENDING.value,
                    self._now_s(),
                ),
            )
        except self.ds.backend.integrity_errors as e:
            raise TxConflict("global HPKE key id already exists") from e

    def get_global_hpke_keypairs(self) -> List[GlobalHpkeKeypair]:
        out = []
        for config_id, cfg_b, enc, state, updated in self.conn.execute(
            "SELECT config_id, config, private_key, state, updated_at"
            " FROM global_hpke_keys ORDER BY config_id"
        ):
            sk = self.crypter.decrypt(
                "global_hpke_keys", bytes([config_id]), "private_key", enc
            )
            out.append(
                GlobalHpkeKeypair(
                    config=HpkeConfig.get_decoded(cfg_b),
                    private_key=sk,
                    state=HpkeKeyState(state),
                    updated_at=Time(updated),
                )
            )
        return out

    def set_global_hpke_keypair_state(self, config_id: int, state: HpkeKeyState) -> None:
        cur = self.conn.execute(
            "UPDATE global_hpke_keys SET state = ?, updated_at = ? WHERE config_id = ?",
            (state.value, self._now_s(), config_id),
        )
        if cur.rowcount == 0:
            raise DatastoreError(f"no global HPKE key {config_id}")

    def delete_global_hpke_keypair(self, config_id: int) -> None:
        cur = self.conn.execute(
            "DELETE FROM global_hpke_keys WHERE config_id = ?", (config_id,)
        )
        if cur.rowcount == 0:
            raise DatastoreError(f"no global HPKE key {config_id}")

    # ------------------------------------------------------------------
    # taskprov peer aggregators (reference: datastore.rs:4983-5326)

    def put_taskprov_peer_aggregator(self, peer) -> None:
        from ..aggregator.taskprov import PeerAggregator  # noqa: F401 (type)

        row_ident = peer.endpoint.encode() + bytes([peer.role.value])
        enc_init = self.crypter.encrypt(
            "taskprov_peer_aggregators", row_ident, "verify_key_init",
            peer.verify_key_init,
        )
        tok_type = tok_enc = None
        if peer.aggregator_auth_token is not None:
            tok_type = peer.aggregator_auth_token.kind
            tok_enc = self.crypter.encrypt(
                "taskprov_peer_aggregators", row_ident, "aggregator_auth_token",
                peer.aggregator_auth_token.as_bytes(),
            )
        try:
            self.conn.execute(
                """INSERT INTO taskprov_peer_aggregators (endpoint, role,
                    verify_key_init, collector_hpke_config, report_expiry_age,
                    tolerable_clock_skew, aggregator_auth_token_type,
                    aggregator_auth_token, aggregator_auth_token_hash,
                    collector_auth_token_hash)
                   VALUES (?,?,?,?,?,?,?,?,?,?)""",
                (
                    peer.endpoint,
                    peer.role.name.capitalize(),
                    enc_init,
                    peer.collector_hpke_config.get_encoded(),
                    peer.report_expiry_age.seconds if peer.report_expiry_age else None,
                    peer.tolerable_clock_skew.seconds,
                    tok_type,
                    tok_enc,
                    json.dumps(peer.aggregator_auth_token_hash.to_dict())
                    if peer.aggregator_auth_token_hash
                    else None,
                    json.dumps(peer.collector_auth_token_hash.to_dict())
                    if peer.collector_auth_token_hash
                    else None,
                ),
            )
        except self.ds.backend.integrity_errors as e:
            raise TxConflict("taskprov peer already exists") from e

    def _peer_from_row(self, row):
        from ..aggregator.taskprov import PeerAggregator

        (
            endpoint,
            role_s,
            enc_init,
            cfg_b,
            expiry_age,
            skew,
            tok_type,
            tok_enc,
            agg_hash_s,
            col_hash_s,
        ) = row
        role = Role[role_s.upper()]
        row_ident = endpoint.encode() + bytes([role.value])
        token = None
        if tok_enc is not None:
            raw = self.crypter.decrypt(
                "taskprov_peer_aggregators", row_ident, "aggregator_auth_token", tok_enc
            )
            token = AuthenticationToken(tok_type, raw.decode())
        return PeerAggregator(
            endpoint=endpoint,
            role=role,
            verify_key_init=self.crypter.decrypt(
                "taskprov_peer_aggregators", row_ident, "verify_key_init", enc_init
            ),
            collector_hpke_config=HpkeConfig.get_decoded(cfg_b),
            report_expiry_age=Duration(expiry_age) if expiry_age is not None else None,
            tolerable_clock_skew=Duration(skew),
            aggregator_auth_token=token,
            aggregator_auth_token_hash=AuthenticationTokenHash.from_dict(
                json.loads(agg_hash_s)
            )
            if agg_hash_s
            else None,
            collector_auth_token_hash=AuthenticationTokenHash.from_dict(
                json.loads(col_hash_s)
            )
            if col_hash_s
            else None,
        )

    _PEER_COLS = """endpoint, role, verify_key_init, collector_hpke_config,
        report_expiry_age, tolerable_clock_skew, aggregator_auth_token_type,
        aggregator_auth_token, aggregator_auth_token_hash,
        collector_auth_token_hash"""

    def get_taskprov_peer_aggregator(self, endpoint: str, role: Role):
        row = self.conn.execute(
            f"SELECT {self._PEER_COLS} FROM taskprov_peer_aggregators"
            " WHERE endpoint = ? AND role = ?",
            (endpoint, role.name.capitalize()),
        ).fetchone()
        return self._peer_from_row(row) if row else None

    def get_taskprov_peer_aggregators(self):
        rows = self.conn.execute(
            f"SELECT {self._PEER_COLS} FROM taskprov_peer_aggregators ORDER BY id"
        ).fetchall()
        return [self._peer_from_row(r) for r in rows]

    def delete_taskprov_peer_aggregator(self, endpoint: str, role: Role) -> None:
        cur = self.conn.execute(
            "DELETE FROM taskprov_peer_aggregators WHERE endpoint = ? AND role = ?",
            (endpoint, role.name.capitalize()),
        )
        if cur.rowcount == 0:
            raise DatastoreError("no such taskprov peer")

    # ------------------------------------------------------------------
    # lease reaping (crash recovery: a killed replica's leases expire and
    # are re-acquirable anyway, but reaping makes the redelivery PROMPT
    # and — more importantly — observable: each reaped row is a lease that
    # expired without release, i.e. a holder that died or wedged)

    def reap_expired_aggregation_job_leases(self) -> int:
        """Clear the lease token of every InProgress aggregation job whose
        lease expired without being released (the holder never came back).
        Returns the number of reaped leases.  ``lease_attempts`` is left
        untouched — it was incremented at acquire time, so the
        delivery-count budgets survive the holder's death."""
        cur = self.conn.execute(
            """UPDATE aggregation_jobs SET lease_token = NULL, lease_expiry = 0
               WHERE state = 'InProgress' AND lease_token IS NOT NULL
                 AND lease_expiry <= ?""",
            (self._now_s(),),
        )
        return cur.rowcount

    def reap_expired_collection_job_leases(self) -> int:
        cur = self.conn.execute(
            """UPDATE collection_jobs SET lease_token = NULL, lease_expiry = 0
               WHERE state = 'Start' AND lease_token IS NOT NULL
                 AND lease_expiry <= ?""",
            (self._now_s(),),
        )
        return cur.rowcount

    # ------------------------------------------------------------------
    # fleet introspection (ISSUE 5: the binaries' status sampler and the
    # /statusz endpoint — cheap indexed COUNTs, no payload reads)

    def accumulator_journal_stats(self) -> Tuple[int, Optional[int]]:
        """(outstanding rows, oldest created_at) across every task — the
        freshness sampler's journal-age input."""
        count, oldest = self.conn.execute(
            "SELECT COUNT(*), MIN(created_at) FROM accumulator_journal"
        ).fetchone()
        return int(count or 0), (int(oldest) if oldest is not None else None)

    def lease_summary(self) -> Dict[str, Dict[str, int]]:
        """Per-job-type lease occupancy: held (live lease), expired_held
        (lease token outstanding past expiry — a dead/wedged holder the
        reaper has not cleared yet), acquirable.  The single source for
        the acquirable-backlog counts (/statusz AND the
        janus_acquirable_jobs sampler)."""
        now = self._now_s()
        out: Dict[str, Dict[str, int]] = {}
        for job_type, table, state in _JOB_LEASE_TABLES:
            held, expired, acquirable, active = self.conn.execute(
                f"""SELECT
                      SUM(CASE WHEN lease_token IS NOT NULL AND lease_expiry > ?
                          THEN 1 ELSE 0 END),
                      SUM(CASE WHEN lease_token IS NOT NULL AND lease_expiry <= ?
                          THEN 1 ELSE 0 END),
                      SUM(CASE WHEN lease_expiry <= ? THEN 1 ELSE 0 END),
                      COUNT(*)
                    FROM {table} WHERE state = ?""",
                (now, now, now, state),
            ).fetchone()
            out[job_type] = {
                "active": int(active or 0),
                "held": int(held or 0),
                "expired_held": int(expired or 0),
                "acquirable": int(acquirable or 0),
            }
        return out

    # ------------------------------------------------------------------
    # fleet control plane membership (core/fleet.py; schema.py
    # _FLEET_MEMBERS_SCHEMA).  One row per registered driver replica;
    # the heartbeat write doubles as the suspect-set advertisement.

    def upsert_fleet_member(
        self,
        replica_id: str,
        role: str,
        suspect_peers: Sequence[str] = (),
    ) -> None:
        """Register ``replica_id`` or refresh its heartbeat to tx-now.

        ``started_at`` is preserved across refreshes (it is only set on
        first insert); ``suspect_peers``/``suspect_updated_at`` are
        rewritten on every heartbeat so a healed peer un-publishes by
        simply advertising an empty set."""
        now = self._now_s()
        encoded = json.dumps(sorted(set(suspect_peers)))
        cur = self.conn.execute(
            "UPDATE fleet_members SET role = ?, heartbeat = ?,"
            " suspect_peers = ?, suspect_updated_at = ?"
            " WHERE replica_id = ?",
            (role, now, encoded, now, replica_id),
        )
        if cur.rowcount == 0:
            try:
                self.conn.execute(
                    "INSERT INTO fleet_members (replica_id, role, heartbeat,"
                    " started_at, suspect_peers, suspect_updated_at)"
                    " VALUES (?, ?, ?, ?, ?, ?)",
                    (replica_id, role, now, now, encoded, now),
                )
            except self.ds.backend.integrity_errors as e:
                # Two handles racing the same replica_id's first heartbeat;
                # the retry loop's next attempt takes the UPDATE path.
                raise TxConflict(f"fleet member insert race: {e}") from e

    def get_fleet_members(self, role: Optional[str] = None) -> List[FleetMember]:
        """Every registered member (optionally one role), stale included —
        liveness is the *caller's* TTL judgment, so routers and /statusz
        can both see dead rows (the latter reports them as such)."""
        if role is None:
            rows = self.conn.execute(
                "SELECT replica_id, role, heartbeat, started_at,"
                " suspect_peers, suspect_updated_at FROM fleet_members"
                " ORDER BY replica_id"
            ).fetchall()
        else:
            rows = self.conn.execute(
                "SELECT replica_id, role, heartbeat, started_at,"
                " suspect_peers, suspect_updated_at FROM fleet_members"
                " WHERE role = ? ORDER BY replica_id",
                (role,),
            ).fetchall()
        out = []
        for rid, mrole, hb, started, suspects, sus_at in rows:
            try:
                peers = tuple(json.loads(suspects)) if suspects else ()
            except ValueError:
                peers = ()
            out.append(
                FleetMember(
                    replica_id=rid,
                    role=mrole,
                    heartbeat=Time(int(hb)),
                    started_at=Time(int(started)),
                    suspect_peers=peers,
                    suspect_updated_at=(
                        Time(int(sus_at)) if sus_at is not None else None
                    ),
                )
            )
        return out

    def delete_fleet_member(self, replica_id: str) -> bool:
        """Graceful deregistration (clean shutdown): the member drops out
        of the rendezvous domain immediately instead of after the TTL."""
        cur = self.conn.execute(
            "DELETE FROM fleet_members WHERE replica_id = ?", (replica_id,)
        )
        return cur.rowcount > 0

    def prune_fleet_members(self, older_than: Duration) -> int:
        """Delete rows whose heartbeat is older than ``older_than`` — dead
        replicas that never deregistered.  Routers treat stale rows as
        non-live regardless, so pruning is pure hygiene and any live
        replica may do it opportunistically."""
        cutoff = self._now_s() - older_than.seconds
        cur = self.conn.execute(
            "DELETE FROM fleet_members WHERE heartbeat < ?", (cutoff,)
        )
        return cur.rowcount

    # ------------------------------------------------------------------
    # accumulator journal (deferred device-resident drains; see
    # executor/accumulator.py and schema.py _ACCUMULATOR_JOURNAL_SCHEMA)

    def put_accumulator_journal_entry(
        self,
        task_id: TaskId,
        batch_identifier: bytes,
        aggregation_parameter: bytes,
        aggregation_job_id: AggregationJobId,
        report_ids: Sequence[bytes],
    ) -> None:
        """Record one job's un-drained resident delta.  Must run in the
        SAME transaction as the writer commit that records these reports
        Finished — the journal row and the FINISHED states are one fact."""
        pk = self._task_pk(task_id)
        rids_b = b"".join(report_ids)
        row_crc = _accumulator_journal_crc(
            batch_identifier, aggregation_parameter, aggregation_job_id.data, rids_b
        )
        # corruption fault point AFTER the CRC: stored bytes may lie, the
        # checksum witnesses what SHOULD have been stored
        rids_b = faults.corrupt_bytes(
            "journal.corrupt", rids_b, target="accumulator_journal"
        )
        try:
            self.conn.execute(
                """INSERT INTO accumulator_journal (task_id, batch_identifier,
                    aggregation_param, aggregation_job_id, report_ids, created_at,
                    row_crc)
                   VALUES (?,?,?,?,?,?,?)""",
                (
                    pk,
                    batch_identifier,
                    aggregation_parameter,
                    aggregation_job_id.data,
                    rids_b,
                    self._now_s(),
                    row_crc,
                ),
            )
        except self.ds.backend.integrity_errors as e:
            raise TxConflict(
                f"accumulator journal entry for job {aggregation_job_id} exists"
            ) from e

    def get_accumulator_journal_entries(
        self, task_id: TaskId, batch_identifier: Optional[bytes] = None
    ) -> List[AccumulatorJournalEntry]:
        pk = self._task_pk(task_id)
        sql = """SELECT id, batch_identifier, aggregation_param, aggregation_job_id,
                        report_ids, created_at, row_crc
                 FROM accumulator_journal WHERE task_id = ?"""
        args: List[Any] = [pk]
        if batch_identifier is not None:
            sql += " AND batch_identifier = ?"
            args.append(batch_identifier)
        sql += " ORDER BY id"
        out = []
        for rowid, ident, param, job_id, rids_b, created, row_crc in self.conn.execute(
            sql, args
        ):
            # NULL row_crc = pre-migration row, accepted unverified
            if row_crc is not None and row_crc != _accumulator_journal_crc(
                ident, param, job_id, rids_b or b""
            ):
                self._quarantine_corrupt_journal_row(
                    "accumulator_journal",
                    "DELETE FROM accumulator_journal WHERE id = ?",
                    rowid,
                    task_hex=task_id.data.hex(),
                    payload=rids_b,
                )
                continue
            out.append(
                AccumulatorJournalEntry(
                    task_id=task_id,
                    batch_identifier=ident,
                    aggregation_parameter=param,
                    aggregation_job_id=AggregationJobId(job_id),
                    report_ids=tuple(
                        rids_b[i : i + 16] for i in range(0, len(rids_b), 16)
                    ),
                    created_at=Time(created),
                )
            )
        return out

    def _quarantine_corrupt_journal_row(
        self,
        stage: str,
        delete_sql: str,
        rowid: int,
        task_hex: Optional[str],
        payload: Optional[bytes],
        report_id: Optional[bytes] = None,
    ) -> None:
        """Pull a checksum-failed durable row out of its journal: record it
        in quarantined_reports and DELETE it in the same transaction (a
        corrupt row left in place would wedge collection readiness gates
        and re-fail every materialize pass forever).  Counting happens via
        the process recorder; a tx retry may double-count the metric but
        the SQL effects re-apply atomically."""
        from ..core import quarantine

        self.put_quarantined_report(
            task=task_hex,
            report_id=report_id,
            stage=stage,
            error_class="ChecksumMismatch",
            payload_digest=quarantine.payload_digest(payload or b""),
        )
        self.conn.execute(delete_sql, (rowid,))
        quarantine.note_corrupt_row(stage)

    def count_accumulator_journal_entries_for_batch(
        self,
        task_id: TaskId,
        batch_identifier: bytes,
        aggregation_parameter: Optional[bytes] = None,
    ) -> int:
        """Collection readiness input: >0 means counted reports whose
        shares are not yet merged into batch_aggregations.  Filter by
        aggregation parameter when gating ONE parameter's collection —
        another parameter's outstanding rows do not affect its
        accumulators (and the replay only consumes matching rows)."""
        pk = self._task_pk(task_id)
        sql = (
            "SELECT COUNT(*) FROM accumulator_journal"
            " WHERE task_id = ? AND batch_identifier = ?"
        )
        args: List[Any] = [pk, batch_identifier]
        if aggregation_parameter is not None:
            sql += " AND aggregation_param = ?"
            args.append(aggregation_parameter)
        return self.conn.execute(sql, args).fetchone()[0]

    def delete_accumulator_journal_entry(
        self,
        task_id: TaskId,
        batch_identifier: bytes,
        aggregation_parameter: bytes,
        aggregation_job_id: AggregationJobId,
    ) -> bool:
        """Consume one journal row; returns False when it was already
        consumed (a drain and a crash-recovery replay raced — the loser
        MUST NOT merge its vector, or the delta double-counts)."""
        pk = self._task_pk(task_id)
        cur = self.conn.execute(
            """DELETE FROM accumulator_journal
               WHERE task_id = ? AND batch_identifier = ?
                 AND aggregation_param = ? AND aggregation_job_id = ?""",
            (pk, batch_identifier, aggregation_parameter, aggregation_job_id.data),
        )
        return cur.rowcount > 0

    # ------------------------------------------------------------------
    # report journal (write-behind ingest, core/ingest.py; schema.py
    # _REPORT_JOURNAL_SCHEMA).  One row per ACKed-but-unmaterialized
    # report; the journal-flush transaction that inserts it is the
    # client's durability ACK and the ONLY place report_success is
    # counted — materialization/consumption never touches counters.

    def put_report_journal_row(self, report: LeaderStoredReport) -> None:
        """Park one ACKed report's full payload until the background
        materializer (or crash replay, or a surviving replica's creator)
        consumes it.  The share ciphertext is bound to the client_reports
        AAD — deliberately, so materialization is a verbatim column copy
        with no decrypt/re-encrypt hop."""
        pk = self._task_pk(report.task_id)
        row_ident = report.task_id.data + report.report_id.data
        enc_share = self.crypter.encrypt(
            "client_reports", row_ident, "leader_input_share", report.leader_input_share
        )
        ext_b = _encode_extensions(report.leader_extensions)
        helper_b = report.helper_encrypted_input_share.get_encoded()
        row_crc = _report_journal_crc(
            report.report_id.data,
            report.time.seconds,
            ext_b,
            report.public_share,
            enc_share,
            helper_b,
        )
        # corruption fault point AFTER the CRC: a fired corrupt-mode spec
        # stores mangled ciphertext under the honest checksum — exactly
        # what a torn write / bit rot looks like to the verify pass
        enc_share = faults.corrupt_bytes(
            "journal.corrupt", enc_share, target="report_journal"
        )
        try:
            self.conn.execute(
                """INSERT INTO report_journal (task_id, report_id, client_timestamp,
                    extensions, public_share, leader_input_share,
                    helper_encrypted_input_share, trace_id, created_at, row_crc)
                   VALUES (?,?,?,?,?,?,?,?,?,?)""",
                (
                    pk,
                    report.report_id.data,
                    report.time.seconds,
                    ext_b,
                    report.public_share,
                    enc_share,
                    helper_b,
                    report.trace_id,
                    self._now_s(),
                    row_crc,
                ),
            )
        except self.ds.backend.integrity_errors as e:
            raise TxConflict(
                f"journal row for report {report.report_id} already exists"
            ) from e

    def delete_report_journal_row(self, task_id: TaskId, report_id: ReportId) -> bool:
        """Consume one journal row; returns False when it was already
        consumed (the materializer and a staged-cohort consumer raced —
        the loser MUST NOT write anything for this report, or it lands in
        client_reports / an aggregation job twice)."""
        pk = self._task_pk(task_id)
        cur = self.conn.execute(
            "DELETE FROM report_journal WHERE task_id = ? AND report_id = ?",
            (pk, report_id.data),
        )
        return cur.rowcount > 0

    def get_report_journal_reports(
        self, task_id: TaskId, limit: int = 512
    ) -> List[LeaderStoredReport]:
        """Full (decrypted) journaled reports for one task, oldest first —
        introspection and the per-task replay fallback; the bulk path is
        ``materialize_report_journal_rows``, which never decrypts."""
        pk = self._task_pk(task_id)
        rows = self.conn.execute(
            """SELECT id, report_id, client_timestamp, extensions, public_share,
                      leader_input_share, helper_encrypted_input_share, trace_id,
                      row_crc
               FROM report_journal WHERE task_id = ? ORDER BY id LIMIT ?""",
            (pk, limit),
        ).fetchall()
        out = []
        for rowid, rid, ts, ext_b, public_share, enc_share, helper_b, trace_id, crc in rows:
            # checksum fence BEFORE the decrypt: a torn/bit-flipped
            # ciphertext would fail its AEAD tag and crash the replay —
            # quarantine + skip instead (NULL crc = pre-migration row)
            if crc is not None and crc != _report_journal_crc(
                rid, ts, ext_b, public_share, enc_share, helper_b
            ):
                self._quarantine_corrupt_journal_row(
                    "journal",
                    "DELETE FROM report_journal WHERE id = ?",
                    rowid,
                    task_hex=task_id.data.hex(),
                    payload=enc_share,
                    report_id=rid,
                )
                continue
            share = self.crypter.decrypt(
                "client_reports", task_id.data + rid, "leader_input_share", enc_share
            )
            out.append(
                LeaderStoredReport(
                    task_id=task_id,
                    metadata=ReportMetadata(ReportId(rid), Time(ts)),
                    public_share=public_share,
                    leader_extensions=_decode_extensions(ext_b) if ext_b else [],
                    leader_input_share=share,
                    helper_encrypted_input_share=HpkeCiphertext.get_decoded(helper_b),
                    trace_id=trace_id,
                )
            )
        return out

    def count_report_journal_rows(self, task_id: Optional[TaskId] = None) -> int:
        if task_id is None:
            return self.conn.execute(
                "SELECT COUNT(*) FROM report_journal"
            ).fetchone()[0]
        pk = self._task_pk(task_id)
        return self.conn.execute(
            "SELECT COUNT(*) FROM report_journal WHERE task_id = ?", (pk,)
        ).fetchone()[0]

    def materialize_report_journal_rows(
        self, limit: int, min_age_s: float = 0.0
    ) -> Tuple[int, int]:
        """Move up to ``limit`` journal rows (oldest first, across every
        task) into client_reports and consume them; returns (consumed,
        materialized).  A row whose report already exists in
        client_reports (a duplicate that raced in through the synchronous
        path) is consumed without inserting — counters were settled at
        journal-flush time either way.  Pure SQL column copies: the share
        ciphertext moves between tables without ever being decrypted.

        ``min_age_s`` restricts the pass to rows at least that old — the
        creator's periodic pre-pass uses it as a grace window so it does
        not steal rows out from under the upload replica's direct
        staged-cohort consumer (stealing is SAFE — the row delete
        linearizes the race — but it downgrades a zero-copy packing to a
        read-back for no reason).

        Every candidate row's CRC32C is verified first (ISSUE 19): a
        checksum-failed row is quarantined + consumed WITHOUT materializing
        — corruption costs one counted report, never a crashed binary or a
        materializer that re-fails the same fold forever.  Corrupt rows
        count as consumed in the returned tuple."""
        candidates = self.conn.execute(
            """SELECT rj.id, rj.report_id, rj.client_timestamp, rj.extensions,
                      rj.public_share, rj.leader_input_share,
                      rj.helper_encrypted_input_share, rj.row_crc, t.task_id
               FROM report_journal rj JOIN tasks t ON t.id = rj.task_id
               WHERE rj.created_at <= ? ORDER BY rj.id LIMIT ?""",
            (self._now_s() - min_age_s, limit),
        ).fetchall()
        ids = []
        for rowid, rid, ts, ext_b, public, enc, helper_b, crc, task_blob in candidates:
            if crc is not None and crc != _report_journal_crc(
                rid, ts, ext_b, public, enc, helper_b
            ):
                self._quarantine_corrupt_journal_row(
                    "journal",
                    "DELETE FROM report_journal WHERE id = ?",
                    rowid,
                    task_hex=bytes(task_blob).hex(),
                    payload=enc,
                    report_id=rid,
                )
                continue
            ids.append(rowid)
        if not ids:
            return len(candidates), 0
        ph = ",".join("?" * len(ids))
        cur = self.conn.execute(
            f"""INSERT INTO client_reports (task_id, report_id, client_timestamp,
                    extensions, public_share, leader_input_share,
                    helper_encrypted_input_share, trace_id, created_at)
                SELECT rj.task_id, rj.report_id, rj.client_timestamp,
                       rj.extensions, rj.public_share, rj.leader_input_share,
                       rj.helper_encrypted_input_share, rj.trace_id, rj.created_at
                FROM report_journal rj
                WHERE rj.id IN ({ph}) AND NOT EXISTS (
                    SELECT 1 FROM client_reports cr
                    WHERE cr.task_id = rj.task_id
                      AND cr.report_id = rj.report_id)""",
            ids,
        )
        materialized = cur.rowcount
        self.conn.execute(f"DELETE FROM report_journal WHERE id IN ({ph})", ids)
        return len(candidates), materialized

    def report_journal_stats(self) -> Tuple[int, Optional[int]]:
        """(outstanding rows, oldest created_at) across every task — the
        /statusz ingest section + journal-depth sampler input."""
        count, oldest = self.conn.execute(
            "SELECT COUNT(*), MIN(created_at) FROM report_journal"
        ).fetchone()
        return int(count or 0), (int(oldest) if oldest is not None else None)

    def put_scrubbed_client_report(
        self,
        task_id: TaskId,
        report_id: ReportId,
        client_timestamp: Time,
        trace_id: Optional[str],
    ) -> bool:
        """Tombstone insert for the direct-staged consumption path
        (core/ingest.py): the report goes straight from the upload batch
        into an aggregation job, so its client_reports row is born
        already scrubbed (NULL payloads, aggregation_started) — exactly
        what put + scrub would have left, minus the round-trip; trace_id
        is kept so collection-time trace linking still sees the upload.
        Returns False when a row already exists (a synchronous-mode
        duplicate raced us in): the caller must NOT pack the report —
        the existing row's owner already has it."""
        pk = self._task_pk(task_id)
        cur = self.conn.execute(
            """INSERT INTO client_reports (task_id, report_id, client_timestamp,
                aggregation_started, trace_id, created_at)
               VALUES (?,?,?,1,?,?)
               ON CONFLICT(task_id, report_id) DO NOTHING""",
            (pk, report_id.data, client_timestamp.seconds, trace_id, self._now_s()),
        )
        return cur.rowcount > 0

    # ------------------------------------------------------------------
    # quarantined reports (blast-radius isolation, core/quarantine.py;
    # schema.py _QUARANTINE_SCHEMA).  The durable offender ledger: rows
    # pulled out of a vectorized cohort by bisection, or durable journal
    # rows that failed their CRC.  Writes are idempotent (dedupe index +
    # DO NOTHING) so replays and client retries of the same poison report
    # record once.

    def put_quarantined_report(
        self,
        task: Optional[str],
        report_id: Optional[bytes],
        stage: str,
        error_class: str,
        payload_digest: Optional[str] = None,
    ) -> bool:
        cur = self.conn.execute(
            """INSERT INTO quarantined_reports
                   (task, report_id, stage, error_class, payload_digest,
                    created_at)
               VALUES (?,?,?,?,?,?)
               ON CONFLICT DO NOTHING""",
            (task, report_id, stage, error_class, payload_digest, self._now_s()),
        )
        return cur.rowcount > 0

    def get_quarantined_reports(
        self,
        task: Optional[str] = None,
        stage: Optional[str] = None,
        limit: int = 256,
    ) -> List[Dict[str, Any]]:
        sql = (
            "SELECT task, report_id, stage, error_class, payload_digest,"
            " created_at FROM quarantined_reports"
        )
        conds, args = [], []
        if task is not None:
            conds.append("task = ?")
            args.append(task)
        if stage is not None:
            conds.append("stage = ?")
            args.append(stage)
        if conds:
            sql += " WHERE " + " AND ".join(conds)
        sql += " ORDER BY id LIMIT ?"
        args.append(limit)
        return [
            {
                "task": t,
                "report_id": bytes(rid).hex() if rid is not None else None,
                "stage": s,
                "error_class": ec,
                "payload_digest": dig,
                "created_at": int(created),
            }
            for t, rid, s, ec, dig, created in self.conn.execute(sql, args)
        ]

    def count_quarantined_reports(self, stage: Optional[str] = None) -> int:
        if stage is None:
            return self.conn.execute(
                "SELECT COUNT(*) FROM quarantined_reports"
            ).fetchone()[0]
        return self.conn.execute(
            "SELECT COUNT(*) FROM quarantined_reports WHERE stage = ?", (stage,)
        ).fetchone()[0]

    def purge_quarantined_reports(
        self, task: Optional[str] = None, stage: Optional[str] = None
    ) -> int:
        sql = "DELETE FROM quarantined_reports"
        conds, args = [], []
        if task is not None:
            conds.append("task = ?")
            args.append(task)
        if stage is not None:
            conds.append("stage = ?")
            args.append(stage)
        if conds:
            sql += " WHERE " + " AND ".join(conds)
        cur = self.conn.execute(sql, args)
        return cur.rowcount

    # ------------------------------------------------------------------
    # upload counters (reference: datastore.rs:5326-5429)

    def increment_task_upload_counter(
        self, task_id: TaskId, ord: int, counter: TaskUploadCounter
    ) -> None:
        pk = self._task_pk(task_id)
        self.conn.execute(
            """INSERT INTO task_upload_counters (task_id, ord) VALUES (?, ?)
               ON CONFLICT(task_id, ord) DO NOTHING""",
            (pk, ord),
        )
        sets = ", ".join(f"{c} = {c} + ?" for c in TaskUploadCounter.COLUMNS)
        self.conn.execute(
            f"UPDATE task_upload_counters SET {sets} WHERE task_id = ? AND ord = ?",
            tuple(getattr(counter, c) for c in TaskUploadCounter.COLUMNS) + (pk, ord),
        )

    def get_task_upload_counter(self, task_id: TaskId) -> TaskUploadCounter:
        pk = self._task_pk(task_id)
        sums = ", ".join(f"COALESCE(SUM({c}), 0)" for c in TaskUploadCounter.COLUMNS)
        row = self.conn.execute(
            f"SELECT {sums} FROM task_upload_counters WHERE task_id = ?", (pk,)
        ).fetchone()
        return TaskUploadCounter(
            task_id, **dict(zip(TaskUploadCounter.COLUMNS, row))
        )
