"""Ephemeral datastore harness for tests.

The analog of ``EphemeralDatastore``/``EphemeralDatabase`` (reference:
aggregator_core/src/datastore/test_util.rs:33-120): a throwaway database per
test with a fresh crypter key and a MockClock, so every time-driven path is
deterministic.
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional

from ..core.time import Clock, MockClock
from .crypter import Crypter, generate_key
from .datastore import Datastore


class EphemeralDatastore:
    def __init__(self, clock: Optional[Clock] = None):
        fd, self.path = tempfile.mkstemp(suffix=".sqlite3", prefix="janus-tpu-test-")
        os.close(fd)
        os.unlink(self.path)  # let SQLite create it fresh
        self.clock = clock if clock is not None else MockClock()
        #: raw crypter key, kept so cross-process tests (chaos soaks
        #: spawning replica binaries against this store) can export it
        #: as DATASTORE_KEYS
        self.key = generate_key()
        self.crypter = Crypter([self.key])
        self.datastore = Datastore(self.path, self.crypter, self.clock)

    def __enter__(self) -> Datastore:
        return self.datastore

    def __exit__(self, *exc) -> None:
        self.cleanup()

    def cleanup(self) -> None:
        self.datastore.close()
        for suffix in ("", "-wal", "-shm"):
            try:
                os.unlink(self.path + suffix)
            except FileNotFoundError:
                pass
