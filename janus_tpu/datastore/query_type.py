"""Query-type strategy traits.

The analog of ``AccumulableQueryType`` / ``CollectableQueryType``
(reference: aggregator_core/src/query_type.rs:20,178): per-query-type policy
for mapping a report to its batch, validating collection identifiers, and
enumerating the batches a collection covers.  Batch identifiers are handled
in their encoded form (``bytes``) at the datastore boundary.
"""

from __future__ import annotations

from typing import List, Optional

from ..core.time import (
    interval_contains_interval,
    time_to_batch_interval,
)
from ..messages import BatchId, Duration, Interval, Query, Time
from .task import AggregatorTask


def encode_interval_identifier(interval: Interval) -> bytes:
    return interval.get_encoded()


def decode_interval_identifier(data: bytes) -> Interval:
    return Interval.get_decoded(data)


class TimeIntervalStrategy:
    """reference: query_type.rs impl for TimeInterval"""

    kind = "TimeInterval"

    @staticmethod
    def to_batch_identifier(task: AggregatorTask, client_timestamp: Time) -> bytes:
        """A report belongs to the batch interval containing its timestamp
        (reference: query_type.rs:20 AccumulableQueryType)."""
        return time_to_batch_interval(client_timestamp, task.time_precision).get_encoded()

    @staticmethod
    def validate_query(task: AggregatorTask, query: Query) -> Optional[str]:
        """Returns an error string, or None if acceptable
        (reference: aggregator.rs validate_batch_interval)."""
        interval: Interval = query.query_body
        tp = task.time_precision.seconds
        if interval.start.seconds % tp != 0 or interval.duration.seconds % tp != 0:
            return "batch interval must be aligned to the time precision"
        if interval.duration.seconds < tp:
            return "batch interval must be at least the time precision"
        return None

    @staticmethod
    def collection_identifier(task: AggregatorTask, query: Query) -> bytes:
        return query.query_body.get_encoded()

    @staticmethod
    def batch_identifiers_for_collection_identifier(
        task: AggregatorTask, collection_identifier: bytes
    ) -> List[bytes]:
        """Every time-precision-aligned batch interval inside the collection
        interval (reference: query_type.rs CollectableQueryType)."""
        interval = decode_interval_identifier(collection_identifier)
        tp = task.time_precision.seconds
        out = []
        start = interval.start.seconds
        while start < interval.end().seconds:
            out.append(Interval(Time(start), Duration(tp)).get_encoded())
            start += tp
        return out

    @staticmethod
    def contains(collection_identifier: bytes, batch_identifier: bytes) -> bool:
        return interval_contains_interval(
            decode_interval_identifier(collection_identifier),
            decode_interval_identifier(batch_identifier),
        )


class FixedSizeStrategy:
    """reference: query_type.rs impl for FixedSize"""

    kind = "FixedSize"

    @staticmethod
    def to_batch_identifier(task: AggregatorTask, batch_id: BatchId) -> bytes:
        return batch_id.get_encoded()

    @staticmethod
    def validate_query(task: AggregatorTask, query: Query) -> Optional[str]:
        return None

    @staticmethod
    def batch_identifiers_for_collection_identifier(
        task: AggregatorTask, collection_identifier: bytes
    ) -> List[bytes]:
        return [collection_identifier]

    @staticmethod
    def contains(collection_identifier: bytes, batch_identifier: bytes) -> bool:
        return collection_identifier == batch_identifier


STRATEGIES = {
    "TimeInterval": TimeIntervalStrategy,
    "FixedSize": FixedSizeStrategy,
}


def strategy_for(task: AggregatorTask):
    return STRATEGIES[task.query_type.kind]
