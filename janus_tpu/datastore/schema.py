"""Datastore SQL schema + migrations.

The analog of the reference's migrations (reference:
db/00000000000001_initial_schema.up.sql and siblings).  SQLite dialect:
BLOBs for ids and ciphertexts, INTEGER seconds for times/durations, TEXT for
JSON-serialized enums/configs.  Structure (tables, uniqueness, indexes incl.
the partial index on unaggregated reports and lease-expiry indexes) mirrors
the reference schema; GiST interval indexes become ordinary (start, end)
b-trees.

``MIGRATIONS[k]`` is the DDL taking a version-k store to version k+1; a
fresh database applies all of them in order, an existing one only the tail
past its stamped version (Datastore._init_schema).  The binary-side
compatibility gate is ``SUPPORTED_SCHEMA_VERSIONS``, the analog of the
reference's ``supported_schema_versions!``
(aggregator_core/src/datastore.rs:77-104): with migrate_on_open disabled
(the production deploy shape, where an operator runs migrations), the
datastore refuses to operate on any version not in this set.
"""

_INITIAL_SCHEMA = """
CREATE TABLE IF NOT EXISTS schema_version (
    version INTEGER NOT NULL
);

-- reference: initial_schema.up.sql `tasks`
CREATE TABLE IF NOT EXISTS tasks (
    id INTEGER PRIMARY KEY,
    task_id BLOB NOT NULL UNIQUE,
    aggregator_role TEXT NOT NULL,              -- 'Leader' | 'Helper'
    peer_aggregator_endpoint TEXT NOT NULL,
    query_type TEXT NOT NULL,                   -- TaskQueryType JSON
    vdaf TEXT NOT NULL,                         -- VdafInstance JSON
    task_expiration INTEGER,
    report_expiry_age INTEGER,
    min_batch_size INTEGER NOT NULL,
    time_precision INTEGER NOT NULL,
    tolerable_clock_skew INTEGER NOT NULL,
    collector_hpke_config BLOB,
    vdaf_verify_key BLOB NOT NULL,              -- encrypted
    aggregator_auth_token_type TEXT,
    aggregator_auth_token BLOB,                 -- encrypted (leader only)
    aggregator_auth_token_hash TEXT,            -- JSON (helper only)
    collector_auth_token_hash TEXT,             -- JSON (leader only)
    created_at INTEGER NOT NULL
);

-- reference: initial_schema.up.sql `task_hpke_keys`
CREATE TABLE IF NOT EXISTS task_hpke_keys (
    id INTEGER PRIMARY KEY,
    task_id INTEGER NOT NULL REFERENCES tasks(id) ON DELETE CASCADE,
    config_id INTEGER NOT NULL,
    config BLOB NOT NULL,
    private_key BLOB NOT NULL,                  -- encrypted
    UNIQUE(task_id, config_id)
);

-- reference: initial_schema.up.sql `client_reports` (:204 partial index)
CREATE TABLE IF NOT EXISTS client_reports (
    id INTEGER PRIMARY KEY,
    task_id INTEGER NOT NULL REFERENCES tasks(id) ON DELETE CASCADE,
    report_id BLOB NOT NULL,
    client_timestamp INTEGER NOT NULL,
    extensions BLOB,
    public_share BLOB,
    leader_input_share BLOB,                    -- encrypted
    helper_encrypted_input_share BLOB,
    aggregation_started INTEGER NOT NULL DEFAULT 0,
    created_at INTEGER NOT NULL,
    UNIQUE(task_id, report_id)
);
CREATE INDEX IF NOT EXISTS client_reports_task_unaggregated
    ON client_reports(task_id, client_timestamp) WHERE aggregation_started = 0;

-- reference: initial_schema.up.sql `aggregation_jobs` (lease index :239)
CREATE TABLE IF NOT EXISTS aggregation_jobs (
    id INTEGER PRIMARY KEY,
    task_id INTEGER NOT NULL REFERENCES tasks(id) ON DELETE CASCADE,
    aggregation_job_id BLOB NOT NULL,
    aggregation_param BLOB NOT NULL,
    batch_id BLOB,                              -- fixed-size tasks only
    client_timestamp_interval_start INTEGER NOT NULL,
    client_timestamp_interval_duration INTEGER NOT NULL,
    state TEXT NOT NULL,                        -- AggregationJobState
    step INTEGER NOT NULL DEFAULT 0,
    last_request_hash BLOB,
    lease_expiry INTEGER NOT NULL DEFAULT 0,
    lease_token BLOB,
    lease_attempts INTEGER NOT NULL DEFAULT 0,
    created_at INTEGER NOT NULL,
    updated_at INTEGER NOT NULL,
    UNIQUE(task_id, aggregation_job_id)
);
CREATE INDEX IF NOT EXISTS aggregation_jobs_state_lease
    ON aggregation_jobs(state, lease_expiry) WHERE state = 'InProgress';

-- reference: initial_schema.up.sql `report_aggregations`
CREATE TABLE IF NOT EXISTS report_aggregations (
    id INTEGER PRIMARY KEY,
    task_id INTEGER NOT NULL REFERENCES tasks(id) ON DELETE CASCADE,
    aggregation_job_id INTEGER NOT NULL
        REFERENCES aggregation_jobs(id) ON DELETE CASCADE,
    ord INTEGER NOT NULL,
    report_id BLOB NOT NULL,
    client_timestamp INTEGER NOT NULL,
    last_prep_resp BLOB,
    state TEXT NOT NULL,                        -- ReportAggregationState
    -- state-specific payloads (reference: models.rs:898-1105):
    public_share BLOB,                          -- StartLeader
    leader_extensions BLOB,                     -- StartLeader
    leader_input_share BLOB,                    -- StartLeader, encrypted
    helper_encrypted_input_share BLOB,          -- StartLeader
    leader_prep_transition BLOB,                -- WaitingLeader, encrypted
    helper_prep_state BLOB,                     -- WaitingHelper, encrypted
    error_code INTEGER,                         -- Failed
    UNIQUE(aggregation_job_id, ord)
);
CREATE INDEX IF NOT EXISTS report_aggregations_by_report
    ON report_aggregations(task_id, report_id);

-- reference: initial_schema.up.sql `batch_aggregations` (sharded accumulators)
CREATE TABLE IF NOT EXISTS batch_aggregations (
    id INTEGER PRIMARY KEY,
    task_id INTEGER NOT NULL REFERENCES tasks(id) ON DELETE CASCADE,
    batch_identifier BLOB NOT NULL,             -- encoded Interval or BatchId
    aggregation_param BLOB NOT NULL,
    ord INTEGER NOT NULL,                       -- shard index
    state TEXT NOT NULL,                        -- Aggregating|Collected|Scrubbed
    aggregate_share BLOB,
    report_count INTEGER NOT NULL DEFAULT 0,
    checksum BLOB,
    client_timestamp_interval_start INTEGER NOT NULL,
    client_timestamp_interval_duration INTEGER NOT NULL,
    aggregation_jobs_created INTEGER NOT NULL DEFAULT 0,
    aggregation_jobs_terminated INTEGER NOT NULL DEFAULT 0,
    created_at INTEGER NOT NULL,
    UNIQUE(task_id, batch_identifier, aggregation_param, ord)
);
CREATE INDEX IF NOT EXISTS batch_aggregations_by_interval
    ON batch_aggregations(task_id, client_timestamp_interval_start);

-- reference: initial_schema.up.sql `collection_jobs` (GiST :363 -> b-tree)
CREATE TABLE IF NOT EXISTS collection_jobs (
    id INTEGER PRIMARY KEY,
    task_id INTEGER NOT NULL REFERENCES tasks(id) ON DELETE CASCADE,
    collection_job_id BLOB NOT NULL,
    query BLOB NOT NULL,
    aggregation_param BLOB NOT NULL,
    batch_identifier BLOB NOT NULL,
    state TEXT NOT NULL,                        -- Start|Finished|Abandoned|Deleted
    report_count INTEGER,
    client_timestamp_interval_start INTEGER,
    client_timestamp_interval_duration INTEGER,
    leader_aggregate_share BLOB,                -- encrypted
    helper_aggregate_share BLOB,
    lease_expiry INTEGER NOT NULL DEFAULT 0,
    lease_token BLOB,
    lease_attempts INTEGER NOT NULL DEFAULT 0,
    step_attempts INTEGER NOT NULL DEFAULT 0,
    created_at INTEGER NOT NULL,
    updated_at INTEGER NOT NULL,
    UNIQUE(task_id, collection_job_id)
);
CREATE INDEX IF NOT EXISTS collection_jobs_state_lease
    ON collection_jobs(state, lease_expiry) WHERE state = 'Start';
CREATE INDEX IF NOT EXISTS collection_jobs_by_batch
    ON collection_jobs(task_id, batch_identifier);

-- reference: initial_schema.up.sql `aggregate_share_jobs` (helper cache)
CREATE TABLE IF NOT EXISTS aggregate_share_jobs (
    id INTEGER PRIMARY KEY,
    task_id INTEGER NOT NULL REFERENCES tasks(id) ON DELETE CASCADE,
    batch_identifier BLOB NOT NULL,
    aggregation_param BLOB NOT NULL,
    helper_aggregate_share BLOB NOT NULL,       -- encrypted
    report_count INTEGER NOT NULL,
    checksum BLOB NOT NULL,
    created_at INTEGER NOT NULL,
    UNIQUE(task_id, batch_identifier, aggregation_param)
);

-- reference: initial_schema.up.sql `outstanding_batches`
CREATE TABLE IF NOT EXISTS outstanding_batches (
    id INTEGER PRIMARY KEY,
    task_id INTEGER NOT NULL REFERENCES tasks(id) ON DELETE CASCADE,
    batch_id BLOB NOT NULL,
    time_bucket_start INTEGER,
    filled INTEGER NOT NULL DEFAULT 0,
    created_at INTEGER NOT NULL,
    UNIQUE(task_id, batch_id)
);
CREATE INDEX IF NOT EXISTS outstanding_batches_open
    ON outstanding_batches(task_id, time_bucket_start) WHERE filled = 0;

-- reference: initial_schema.up.sql `global_hpke_keys`
CREATE TABLE IF NOT EXISTS global_hpke_keys (
    config_id INTEGER PRIMARY KEY,
    config BLOB NOT NULL,
    private_key BLOB NOT NULL,                  -- encrypted
    state TEXT NOT NULL,                        -- Pending|Active|Expired
    updated_at INTEGER NOT NULL
);

-- reference: taskprov_* tables
CREATE TABLE IF NOT EXISTS taskprov_peer_aggregators (
    id INTEGER PRIMARY KEY,
    endpoint TEXT NOT NULL,
    role TEXT NOT NULL,
    verify_key_init BLOB NOT NULL,              -- encrypted
    collector_hpke_config BLOB NOT NULL,
    report_expiry_age INTEGER,
    tolerable_clock_skew INTEGER NOT NULL,
    aggregator_auth_token_type TEXT,
    aggregator_auth_token BLOB,                 -- encrypted
    aggregator_auth_token_hash TEXT,            -- JSON
    collector_auth_token_hash TEXT,             -- JSON
    UNIQUE(endpoint, role)
);

-- reference: task_upload_counters (:5326), sharded
CREATE TABLE IF NOT EXISTS task_upload_counters (
    id INTEGER PRIMARY KEY,
    task_id INTEGER NOT NULL REFERENCES tasks(id) ON DELETE CASCADE,
    ord INTEGER NOT NULL,
    interval_collected INTEGER NOT NULL DEFAULT 0,
    report_decode_failure INTEGER NOT NULL DEFAULT 0,
    report_decrypt_failure INTEGER NOT NULL DEFAULT 0,
    report_expired INTEGER NOT NULL DEFAULT 0,
    report_outdated_key INTEGER NOT NULL DEFAULT 0,
    report_success INTEGER NOT NULL DEFAULT 0,
    report_too_early INTEGER NOT NULL DEFAULT 0,
    task_expired INTEGER NOT NULL DEFAULT 0,
    UNIQUE(task_id, ord)
);
"""

_ACCUMULATOR_JOURNAL_SCHEMA = """
-- Device-resident accumulator journal (executor/accumulator.py): one row
-- per (aggregation job, batch) whose FINISHED reports' out shares are
-- still resident in some replica's device accumulator (deferred drains).
-- Written in the SAME transaction as the AggregationJobWriter commit that
-- records the reports Finished; deleted by the drain transaction that
-- merges the resident delta into batch_aggregations, or by the
-- collection-time oracle replay that re-derives the shares from the
-- retained report_aggregations payloads after the owning process died.
-- An outstanding row therefore means exactly: "these reports are counted
-- but their shares are not yet in batch_aggregations".
CREATE TABLE IF NOT EXISTS accumulator_journal (
    id INTEGER PRIMARY KEY,
    task_id INTEGER NOT NULL REFERENCES tasks(id) ON DELETE CASCADE,
    batch_identifier BLOB NOT NULL,
    aggregation_param BLOB NOT NULL,
    aggregation_job_id BLOB NOT NULL,
    report_ids BLOB NOT NULL,                   -- concatenated 16-byte ids
    created_at INTEGER NOT NULL,
    UNIQUE(task_id, batch_identifier, aggregation_param, aggregation_job_id)
);
CREATE INDEX IF NOT EXISTS accumulator_journal_by_batch
    ON accumulator_journal(task_id, batch_identifier);
"""

_TRACE_CONTEXT_SCHEMA = """
-- Cross-process trace correlation (core/trace.py, ISSUE 5): a W3C-style
-- 32-hex trace id minted at job creation (leader) or inherited from the
-- peer's traceparent header (helper), carried on every lease acquisition
-- so any replica stepping the job binds the same id into its logs and
-- chrome-trace spans.  TEXT, nullable: rows from older schema versions
-- simply have no trace.
ALTER TABLE aggregation_jobs ADD COLUMN trace_id TEXT;
ALTER TABLE collection_jobs ADD COLUMN trace_id TEXT;
"""

_UPLOAD_TRACE_SCHEMA = """
-- Upload-minted trace ids (core/trace.py, ISSUE 9): the client-ingress
-- hop of cross-process correlation.  handle_upload adopts a strict-hex
-- client ``traceparent`` (or mints a fresh 32-hex id when the header is
-- absent/malformed) and the report writer persists it here, so the
-- aggregation-job creator can link each job's span back to the upload
-- traces of the reports it packs — one merged timeline from client
-- ingress through prepare to collection.  TEXT, nullable: rows from
-- older schema versions simply have no upload trace.
ALTER TABLE client_reports ADD COLUMN trace_id TEXT;
"""

_FLEET_MEMBERS_SCHEMA = """
-- Fleet control plane membership (core/fleet.py, ISSUE 16): one row per
-- registered driver replica, heartbeat-refreshed on the replica's
-- heartbeat cadence.  The LIVE member set (heartbeat within the TTL) is
-- the rendezvous-hash domain for task -> replica routing; a member whose
-- heartbeat goes stale simply drops out of the set, which re-routes its
-- tasks to the survivors (migration) with no coordination beyond this
-- table.  ``role`` scopes membership per job type (aggregation vs
-- collection drivers are separate rendezvous domains — a collection
-- replica must never absorb ownership of aggregation acquisition).
-- ``suspect_peers`` is the fleet-shared suspect set: a JSON array of
-- peer origins this replica's in-memory tracker currently holds SUSPECT,
-- republished (or emptied, on heal) with every heartbeat so replicas
-- that never talked to a partitioned peer skip its tasks too;
-- ``suspect_updated_at`` bounds its staleness on the consumer side.
CREATE TABLE IF NOT EXISTS fleet_members (
    replica_id TEXT PRIMARY KEY,
    role TEXT NOT NULL,
    heartbeat INTEGER NOT NULL,
    started_at INTEGER NOT NULL,
    suspect_peers TEXT,
    suspect_updated_at INTEGER
);
CREATE INDEX IF NOT EXISTS fleet_members_by_role
    ON fleet_members(role, heartbeat);
"""

_REPORT_JOURNAL_SCHEMA = """
-- Write-behind report journal (core/ingest.py, ISSUE 18): one row per
-- report that has been ACKed to its client but whose authoritative
-- client_reports row is not yet materialized.  The journaled ingest mode
-- commits THIS row on the upload critical path (the durability ACK) and
-- defers the client_reports insert to a bounded background materializer
-- (write-behind for the aggregation-visibility path, never for the ACK).
-- An outstanding row therefore means exactly: "this report was accepted
-- and counted, but client_reports does not know it yet" — crash replay
-- (and the surviving replicas' creators, for the migration handoff)
-- materialize or consume it, and the report_success counter was already
-- incremented by the journal-flush transaction, so neither path touches
-- counters.  Columns mirror client_reports verbatim; leader_input_share
-- is encrypted under the SAME ("client_reports", task||report,
-- "leader_input_share") AAD so materialization is a ciphertext column
-- copy — no decrypt/re-encrypt round-trip on the background path.
CREATE TABLE IF NOT EXISTS report_journal (
    id INTEGER PRIMARY KEY,
    task_id INTEGER NOT NULL REFERENCES tasks(id) ON DELETE CASCADE,
    report_id BLOB NOT NULL,
    client_timestamp INTEGER NOT NULL,
    extensions BLOB,
    public_share BLOB,
    leader_input_share BLOB,                    -- encrypted (client_reports AAD)
    helper_encrypted_input_share BLOB,
    trace_id TEXT,
    created_at INTEGER NOT NULL,
    UNIQUE(task_id, report_id)
);
CREATE INDEX IF NOT EXISTS report_journal_by_task
    ON report_journal(task_id, client_timestamp);
"""

_QUARANTINE_SCHEMA = """
-- Blast-radius isolation (core/quarantine.py, ISSUE 19).
--
-- quarantined_reports: the durable offender ledger.  A row means a report
-- (or durable journal row) was pulled out of a vectorized cohort — poison
-- isolated by batch bisection, or a CRC32C checksum failure at journal
-- materialize/replay — so the healthy remainder could proceed.  `task` is
-- the hex task id (TEXT: executor stages may only know an opaque task
-- label); `report_id` is NULL for offenders with no per-report identity
-- (combine rows, torn journal rows whose id column itself is suspect).
-- The UNIQUE index + ON CONFLICT DO NOTHING writes make recording
-- idempotent across replays and client retries of the same poison report.
CREATE TABLE IF NOT EXISTS quarantined_reports (
    id INTEGER PRIMARY KEY,
    task TEXT,
    report_id BLOB,
    stage TEXT NOT NULL,
    error_class TEXT NOT NULL,
    payload_digest TEXT,
    created_at INTEGER NOT NULL
);
CREATE UNIQUE INDEX IF NOT EXISTS quarantined_reports_dedupe
    ON quarantined_reports(task, report_id, stage);
CREATE INDEX IF NOT EXISTS quarantined_reports_by_stage
    ON quarantined_reports(stage, created_at);

-- row_crc: CRC32C over a length-prefixed concatenation of the row's
-- payload columns, computed at write time and verified at materialize /
-- replay / readback.  NULL marks a pre-migration row (accepted
-- unverified — the checksum cannot be retrofitted without the plaintext).
ALTER TABLE report_journal ADD COLUMN row_crc INTEGER;
ALTER TABLE accumulator_journal ADD COLUMN row_crc INTEGER;
"""

#: MIGRATIONS[k]: DDL taking schema version k -> k+1.  Append-only — never
#: edit an entry that has shipped (existing stores have already applied it).
MIGRATIONS = [
    _INITIAL_SCHEMA,
    _ACCUMULATOR_JOURNAL_SCHEMA,
    _TRACE_CONTEXT_SCHEMA,
    _UPLOAD_TRACE_SCHEMA,
    _FLEET_MEMBERS_SCHEMA,
    _REPORT_JOURNAL_SCHEMA,
    _QUARANTINE_SCHEMA,
]

SCHEMA_VERSION = len(MIGRATIONS)

#: Versions this build can operate against without migrating.
SUPPORTED_SCHEMA_VERSIONS = (SCHEMA_VERSION,)

#: Back-compat alias (full schema for a fresh store at version 1).
SCHEMA = _INITIAL_SCHEMA
