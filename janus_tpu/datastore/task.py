"""Aggregator task model.

The analog of the reference's ``AggregatorTask`` + ``AggregatorTaskParameters``
(reference: aggregator_core/src/task.rs:211,520) and the task-level query-type
config (task.rs:36).  A task is the unit of configuration shared (out of band)
between the two aggregators: VDAF instance, verify key, HPKE keys, auth
tokens, batch/time parameters.
"""

from __future__ import annotations

import json
import secrets
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional

from ..core.auth_tokens import AuthenticationToken, AuthenticationTokenHash
from ..core.hpke import HpkeKeypair
from ..messages import Duration, HpkeConfig, Role, TaskId, Time
from ..vdaf.instances import vdaf_from_instance


@dataclass(frozen=True)
class TaskQueryType:
    """Task-level query type (reference: aggregator_core/src/task.rs:36).

    ``kind`` is "TimeInterval" or "FixedSize"; FixedSize carries an optional
    ``max_batch_size`` and optional ``batch_time_window_size`` (seconds).
    """

    kind: str
    max_batch_size: Optional[int] = None
    batch_time_window_size: Optional[Duration] = None

    def __post_init__(self):
        if self.kind not in ("TimeInterval", "FixedSize"):
            raise ValueError(f"unknown query type {self.kind!r}")
        if self.kind == "TimeInterval" and self.max_batch_size is not None:
            raise ValueError("TimeInterval takes no max_batch_size")

    def to_json(self) -> str:
        d: Dict[str, Any] = {"kind": self.kind}
        if self.max_batch_size is not None:
            d["max_batch_size"] = self.max_batch_size
        if self.batch_time_window_size is not None:
            d["batch_time_window_size"] = self.batch_time_window_size.seconds
        return json.dumps(d, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "TaskQueryType":
        d = json.loads(s)
        btws = d.get("batch_time_window_size")
        return cls(
            kind=d["kind"],
            max_batch_size=d.get("max_batch_size"),
            batch_time_window_size=Duration(btws) if btws is not None else None,
        )

    @classmethod
    def time_interval(cls) -> "TaskQueryType":
        return cls("TimeInterval")

    @classmethod
    def fixed_size(
        cls,
        max_batch_size: Optional[int] = None,
        batch_time_window_size: Optional[Duration] = None,
    ) -> "TaskQueryType":
        return cls("FixedSize", max_batch_size, batch_time_window_size)


@dataclass(frozen=True)
class AggregatorTask:
    """One aggregator's view of a DAP task
    (reference: aggregator_core/src/task.rs:211).
    """

    task_id: TaskId
    peer_aggregator_endpoint: str
    query_type: TaskQueryType
    vdaf: Dict[str, Any]  # serialized VdafInstance description
    role: Role
    # Secret hygiene: never in logs (reference: aggregator_core/src/lib.rs:28).
    vdaf_verify_key: bytes = field(repr=False)
    min_batch_size: int
    time_precision: Duration
    task_expiration: Optional[Time] = None
    report_expiry_age: Optional[Duration] = None
    tolerable_clock_skew: Duration = Duration(60)
    # Leader: token used to authenticate to the helper.  Helper: hash used to
    # check the leader's token (reference task.rs:520 role-specific params).
    aggregator_auth_token: Optional[AuthenticationToken] = None
    aggregator_auth_token_hash: Optional[AuthenticationTokenHash] = None
    # Leader only: hash of the collector's token.
    collector_auth_token_hash: Optional[AuthenticationTokenHash] = None
    collector_hpke_config: Optional[HpkeConfig] = None
    hpke_keys: List[HpkeKeypair] = field(default_factory=list)

    def __post_init__(self):
        if not self.role.is_aggregator():
            raise ValueError("task role must be Leader or Helper")
        if self.min_batch_size < 1:
            raise ValueError("min_batch_size must be positive")
        if self.time_precision.seconds <= 0:
            raise ValueError("time_precision must be positive")
        expected = vdaf_verify_key_length(self.vdaf)
        if len(self.vdaf_verify_key) != expected:
            raise ValueError(
                f"verify key must be {expected} bytes for {self.vdaf.get('type')}"
            )

    # -- VDAF -----------------------------------------------------------
    def vdaf_instance(self, backend: Optional[str] = None):
        return vdaf_from_instance(self.vdaf, backend=backend)

    # -- HPKE -----------------------------------------------------------
    def hpke_keypair_for(self, config_id: int) -> Optional[HpkeKeypair]:
        for kp in self.hpke_keys:
            if kp.config.id == config_id:
                return kp
        return None

    def current_hpke_keypair(self) -> HpkeKeypair:
        if not self.hpke_keys:
            raise ValueError("task has no HPKE keys")
        return max(self.hpke_keys, key=lambda kp: kp.config.id)

    def with_hpke_keys(self, keys: List[HpkeKeypair]) -> "AggregatorTask":
        return replace(self, hpke_keys=list(keys))


def vdaf_verify_key_length(vdaf: Dict[str, Any]) -> int:
    """Verify-key size for a serialized VDAF instance
    (reference: core/src/vdaf.rs:16,24 via task.rs VerifyKey<SEED_SIZE>)."""
    if vdaf.get("type") == "Prio3SumVecField64MultiproofHmacSha256Aes128":
        return 32
    return 16


def generate_vdaf_verify_key(vdaf: Dict[str, Any]) -> bytes:
    return secrets.token_bytes(vdaf_verify_key_length(vdaf))


def validate_vdaf_instance(vdaf: Dict[str, Any]) -> None:
    """Raise ValueError if the instance description is unknown/invalid."""
    vdaf_from_instance(vdaf)
