"""Datastore models and state machines.

The analog of the reference's ``aggregator_core/src/datastore/models.rs``:
every protocol step persists one of these state machines, which is what makes
the database the checkpoint — any process can die at any point and another
resumes from the stored state (SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import enum
import secrets
from dataclasses import dataclass, field, replace
from typing import Any, List, Optional, Tuple

from ..messages import (
    AggregationJobId,
    AggregationJobStep,
    BatchId,
    CollectionJobId,
    Duration,
    Extension,
    HpkeCiphertext,
    HpkeConfig,
    Interval,
    PrepareError,
    PrepareResp,
    Query,
    ReportId,
    ReportIdChecksum,
    ReportMetadata,
    TaskId,
    Time,
)


# --------------------------------------------------------------------------
# Client reports


@dataclass(frozen=True)
class LeaderStoredReport:
    """A decrypted, validated report stored by the leader
    (reference: models.rs:103)."""

    task_id: TaskId
    metadata: ReportMetadata
    public_share: bytes  # encoded VDAF public share
    leader_extensions: List[Extension]
    leader_input_share: bytes  # encoded plaintext leader input share
    helper_encrypted_input_share: HpkeCiphertext
    #: 32-hex upload trace id (core/trace.py, ISSUE 9): adopted from the
    #: client's strict-hex ``traceparent`` or minted at upload; persisted
    #: so aggregation-job creation can link jobs back to client ingress.
    trace_id: Optional[str] = None

    @property
    def report_id(self) -> ReportId:
        return self.metadata.report_id

    @property
    def time(self) -> Time:
        return self.metadata.time


# --------------------------------------------------------------------------
# Aggregation jobs


class AggregationJobState(str, enum.Enum):
    """reference: models.rs:513"""

    IN_PROGRESS = "InProgress"
    FINISHED = "Finished"
    ABANDONED = "Abandoned"
    DELETED = "Deleted"


@dataclass(frozen=True)
class AggregationJob:
    """reference: models.rs:359"""

    task_id: TaskId
    aggregation_job_id: AggregationJobId
    aggregation_parameter: bytes
    # Fixed-size tasks: the batch this job contributes to; TimeInterval: None
    # (the partial batch identifier is ()).
    partial_batch_identifier: Optional[BatchId]
    client_timestamp_interval: Interval
    state: AggregationJobState
    step: AggregationJobStep
    last_request_hash: Optional[bytes] = None
    #: 32-hex cross-process trace id (core/trace.py): minted at creation on
    #: the leader, inherited from the peer's traceparent on the helper.
    trace_id: Optional[str] = None

    def with_state(self, state: AggregationJobState) -> "AggregationJob":
        return replace(self, state=state)

    def with_step(self, step: AggregationJobStep) -> "AggregationJob":
        return replace(self, step=step)

    def with_last_request_hash(self, h: bytes) -> "AggregationJob":
        return replace(self, last_request_hash=h)


# --------------------------------------------------------------------------
# Leases


@dataclass(frozen=True)
class LeaseToken:
    """Random token fencing lease ownership (reference: models.rs:526)."""

    data: bytes

    @classmethod
    def random(cls) -> "LeaseToken":
        return cls(secrets.token_bytes(16))


@dataclass(frozen=True)
class Lease:
    """An acquired lease on a job (reference: models.rs:~600)."""

    leased: Any  # AcquiredAggregationJob | AcquiredCollectionJob
    lease_expiry: Time
    lease_token: LeaseToken
    lease_attempts: int


@dataclass(frozen=True)
class AcquiredAggregationJob:
    """reference: models.rs:635"""

    task_id: TaskId
    aggregation_job_id: AggregationJobId
    query_type: str
    vdaf: dict
    #: persisted trace id, bound by the stepping driver (core/trace.py)
    trace_id: Optional[str] = None
    #: created_at -> acquire, for janus_job_age_at_acquire_seconds
    age_seconds: float = 0.0


@dataclass(frozen=True)
class AcquiredCollectionJob:
    """reference: models.rs:681"""

    task_id: TaskId
    collection_job_id: CollectionJobId
    query_type: str
    vdaf: dict
    step_attempts: int
    trace_id: Optional[str] = None
    age_seconds: float = 0.0


# --------------------------------------------------------------------------
# Report aggregations


class ReportAggregationState(str, enum.Enum):
    """reference: models.rs:898"""

    START_LEADER = "StartLeader"
    WAITING_LEADER = "WaitingLeader"
    WAITING_HELPER = "WaitingHelper"
    FINISHED = "Finished"
    FAILED = "Failed"


@dataclass(frozen=True)
class ReportAggregation:
    """Per-report progress through one aggregation job
    (reference: models.rs:769).  State-specific payloads:

    - StartLeader: the full unaggregated report data (public share,
      extensions, leader input share, helper encrypted share).
    - WaitingLeader: the serialized ping-pong transition to evaluate when the
      helper's response arrives.
    - WaitingHelper: the helper's serialized prepare state.
    - Failed: the PrepareError.
    """

    task_id: TaskId
    aggregation_job_id: AggregationJobId
    report_id: ReportId
    time: Time
    ord: int
    state: ReportAggregationState
    last_prep_resp: Optional[PrepareResp] = None
    # StartLeader payload:
    public_share: Optional[bytes] = None
    leader_extensions: List[Extension] = field(default_factory=list)
    leader_input_share: Optional[bytes] = None
    helper_encrypted_input_share: Optional[HpkeCiphertext] = None
    # WaitingLeader payload:
    leader_prep_transition: Optional[bytes] = None
    # WaitingHelper payload:
    helper_prep_state: Optional[bytes] = None
    # Failed payload:
    error: Optional[PrepareError] = None

    def with_state(self, state: ReportAggregationState, **payload) -> "ReportAggregation":
        cleared = dict(
            public_share=None,
            leader_extensions=[],
            leader_input_share=None,
            helper_encrypted_input_share=None,
            leader_prep_transition=None,
            helper_prep_state=None,
            error=None,
        )
        cleared.update(payload)
        return replace(self, state=state, **cleared)

    def failed(self, error: PrepareError) -> "ReportAggregation":
        return self.with_state(ReportAggregationState.FAILED, error=error)

    def with_last_prep_resp(self, resp: Optional[PrepareResp]) -> "ReportAggregation":
        return replace(self, last_prep_resp=resp)


@dataclass(frozen=True)
class ReportAggregationMetadata:
    """Creation-time view without VDAF payloads (reference: models.rs:1116) —
    used by the aggregation job creator, which never touches share data."""

    task_id: TaskId
    aggregation_job_id: AggregationJobId
    report_id: ReportId
    time: Time
    ord: int


# --------------------------------------------------------------------------
# Batch aggregations (sharded accumulators)


class BatchAggregationState(str, enum.Enum):
    """reference: models.rs:1421"""

    AGGREGATING = "Aggregating"
    COLLECTED = "Collected"
    SCRUBBED = "Scrubbed"


@dataclass(frozen=True)
class BatchAggregation:
    """One shard of a batch's accumulated aggregate share
    (reference: models.rs:1195).  ``batch_identifier`` is the encoded
    Interval (TimeInterval) or BatchId (FixedSize)."""

    task_id: TaskId
    batch_identifier: bytes
    aggregation_parameter: bytes
    ord: int
    state: BatchAggregationState
    aggregate_share: Optional[bytes]  # encoded field vector, None if empty
    report_count: int
    checksum: ReportIdChecksum
    client_timestamp_interval: Interval
    aggregation_jobs_created: int
    aggregation_jobs_terminated: int

    def scrubbed(self) -> "BatchAggregation":
        return replace(
            self,
            state=BatchAggregationState.SCRUBBED,
            aggregate_share=None,
            report_count=0,
            checksum=ReportIdChecksum.zero(),
        )


# --------------------------------------------------------------------------
# Collection jobs


class CollectionJobState(str, enum.Enum):
    """reference: models.rs:1778"""

    START = "Start"
    FINISHED = "Finished"
    ABANDONED = "Abandoned"
    DELETED = "Deleted"


@dataclass(frozen=True)
class CollectionJob:
    """reference: models.rs:1651"""

    task_id: TaskId
    collection_job_id: CollectionJobId
    query: Query
    aggregation_parameter: bytes
    batch_identifier: bytes  # encoded Interval or BatchId
    state: CollectionJobState
    report_count: Optional[int] = None
    client_timestamp_interval: Optional[Interval] = None
    leader_aggregate_share: Optional[bytes] = None  # encoded field vector
    helper_aggregate_share: Optional[HpkeCiphertext] = None
    #: 32-hex cross-process trace id minted at collection-job creation
    trace_id: Optional[str] = None

    def finished(
        self,
        report_count: int,
        client_timestamp_interval: Interval,
        leader_aggregate_share: bytes,
        helper_aggregate_share: HpkeCiphertext,
    ) -> "CollectionJob":
        return replace(
            self,
            state=CollectionJobState.FINISHED,
            report_count=report_count,
            client_timestamp_interval=client_timestamp_interval,
            leader_aggregate_share=leader_aggregate_share,
            helper_aggregate_share=helper_aggregate_share,
        )

    def with_state(self, state: CollectionJobState) -> "CollectionJob":
        return replace(self, state=state)


# --------------------------------------------------------------------------
# Aggregate share jobs (helper-side collection cache)


@dataclass(frozen=True)
class AggregateShareJob:
    """reference: models.rs:1883"""

    task_id: TaskId
    batch_identifier: bytes
    aggregation_parameter: bytes
    helper_aggregate_share: bytes  # encoded field vector (plaintext)
    report_count: int
    checksum: ReportIdChecksum


# --------------------------------------------------------------------------
# Outstanding batches (fixed-size filling)


@dataclass(frozen=True)
class OutstandingBatch:
    """reference: models.rs:2008"""

    task_id: TaskId
    batch_id: BatchId
    time_bucket_start: Optional[Time]
    # inclusive range of possible report counts given current aggregations
    size_min: int = 0
    size_max: int = 0


# --------------------------------------------------------------------------
# Global HPKE keys


class HpkeKeyState(str, enum.Enum):
    """reference: models.rs:2186"""

    PENDING = "Pending"
    ACTIVE = "Active"
    EXPIRED = "Expired"


@dataclass(frozen=True)
class GlobalHpkeKeypair:
    config: HpkeConfig
    private_key: bytes
    state: HpkeKeyState
    updated_at: Time


# --------------------------------------------------------------------------
# Upload counters


@dataclass(frozen=True)
class TaskUploadCounter:
    """Sharded per-task upload outcome counters (reference: models.rs:2234)."""

    task_id: TaskId
    interval_collected: int = 0
    report_decode_failure: int = 0
    report_decrypt_failure: int = 0
    report_expired: int = 0
    report_outdated_key: int = 0
    report_success: int = 0
    report_too_early: int = 0
    task_expired: int = 0

    COLUMNS = (
        "interval_collected",
        "report_decode_failure",
        "report_decrypt_failure",
        "report_expired",
        "report_outdated_key",
        "report_success",
        "report_too_early",
        "task_expired",
    )


# --------------------------------------------------------------------------
# Accumulator journal (deferred device-resident drains)


@dataclass(frozen=True)
class AccumulatorJournalEntry:
    """One aggregation job's contribution to a device-resident accumulator
    bucket that has not been drained into ``batch_aggregations`` yet.

    Persisted in the same transaction that records the reports Finished
    (aggregation_job_writer.py), so after a process death the surviving
    replicas can enumerate exactly which counted reports still lack their
    share merge and re-derive them on the bit-exact CPU oracle from the
    retained ``report_aggregations`` payloads (collection_job_driver.py
    replay path)."""

    task_id: TaskId
    batch_identifier: bytes
    aggregation_parameter: bytes
    aggregation_job_id: AggregationJobId
    report_ids: Tuple[bytes, ...]
    created_at: Time


# --------------------------------------------------------------------------
# Fleet control plane membership (core/fleet.py)


@dataclass(frozen=True)
class FleetMember:
    """One registered driver replica's membership row (fleet_members).

    A member is *live* iff ``now - heartbeat <= heartbeat_ttl``; the live
    set of a role is the rendezvous-hash domain routing task_id -> replica
    for that job type.  ``suspect_peers`` is the fleet-shared suspect set:
    the peer origins this replica's in-memory health tracker currently
    holds SUSPECT, JSON-encoded, refreshed (or emptied on heal) with every
    heartbeat; ``suspect_updated_at`` bounds how stale a consumer will
    honor that advertisement."""

    replica_id: str
    role: str
    heartbeat: Time
    started_at: Time
    suspect_peers: Tuple[str, ...] = ()
    suspect_updated_at: Optional[Time] = None

    def heartbeat_age(self, now: Time) -> int:
        return max(0, now.seconds - self.heartbeat.seconds)
