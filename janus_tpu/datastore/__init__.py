"""Persistence layer: the database IS the checkpoint.

The analog of the reference's ``aggregator_core`` crate: Datastore/Transaction
with lease-based work distribution, column crypto, task model, datastore
models/state machines, and query-type strategies (reference:
aggregator_core/src/{datastore.rs,task.rs,query_type.rs}, db/).
"""

from .crypter import Crypter, CrypterError, generate_key
from .datastore import (
    Datastore,
    DatastoreError,
    TaskNotFound,
    Transaction,
    TxConflict,
)
from .models import (
    AccumulatorJournalEntry,
    AcquiredAggregationJob,
    AcquiredCollectionJob,
    AggregateShareJob,
    AggregationJob,
    AggregationJobState,
    BatchAggregation,
    BatchAggregationState,
    CollectionJob,
    CollectionJobState,
    FleetMember,
    GlobalHpkeKeypair,
    HpkeKeyState,
    LeaderStoredReport,
    Lease,
    LeaseToken,
    OutstandingBatch,
    ReportAggregation,
    ReportAggregationMetadata,
    ReportAggregationState,
    TaskUploadCounter,
)
from .query_type import (
    FixedSizeStrategy,
    TimeIntervalStrategy,
    decode_interval_identifier,
    encode_interval_identifier,
    strategy_for,
)
from .task import (
    AggregatorTask,
    TaskQueryType,
    generate_vdaf_verify_key,
    validate_vdaf_instance,
    vdaf_verify_key_length,
)

__all__ = [n for n in dir() if not n.startswith("_")]
