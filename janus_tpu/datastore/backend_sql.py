"""Pluggable SQL backends for the datastore: SQLite and PostgreSQL.

The Transaction API (datastore.py) is written once against the DB-API-ish
surface ``conn.execute(sql, params) -> cursor``; this module supplies the
dialect underneath:

- :class:`SqliteBackend` — the hermetic default (one file, WAL, busy-retry).
  N replicas on one HOST share the file (proven by
  tests/test_multi_replica.py); cross-host scale-out needs Postgres.
- :class:`PostgresBackend` — the reference's deployment shape
  (aggregator_core/src/datastore.rs:108: every component coordinates through
  one shared Postgres): psycopg under a statement-translation adapter, with
  real ``FOR UPDATE SKIP LOCKED`` lease acquisition and retry on
  serialization failures (SQLSTATE 40001/40P01), matching the reference's
  run_tx retry loop (datastore.rs:249-298).  Requires the ``psycopg2`` or
  ``psycopg`` package at runtime; everything else (statement translation,
  schema translation, retry classification) is importable and unit-tested
  without a server.

Statement translation is mechanical: ``?`` placeholders become ``%s``, and
the ``/*skip-locked*/`` marker — placed inside the lease-acquisition
subselects — expands to ``FOR UPDATE SKIP LOCKED`` so concurrent Postgres
replicas never serialize on lease scans.  The blind placeholder rewrite is
safe only while no Transaction SQL puts ``?`` or ``%`` inside a quoted
string literal (state-name literals like ``'InProgress'`` are fine); keep
new SQL within that rule.
"""

from __future__ import annotations

import re
import time as _time
from typing import Any, Optional

__all__ = [
    "SqliteBackend",
    "PostgresBackend",
    "backend_for",
    "translate_sql_to_postgres",
    "translate_schema_to_postgres",
    "split_sql_statements",
]

SKIP_LOCKED_MARKER = "/*skip-locked*/"


class _NeverRaised(Exception):
    """Placeholder exception type when no Postgres driver is importable."""


def translate_sql_to_postgres(sql: str) -> str:
    """SQLite-dialect statement -> Postgres dialect.

    Only mechanical rewrites are needed: the Transaction SQL uses ``?``
    placeholders, no string literals, and marks lease subselects with
    ``/*skip-locked*/``.
    """
    out = sql.replace("?", "%s")
    out = out.replace(SKIP_LOCKED_MARKER, " FOR UPDATE SKIP LOCKED")
    return out


def split_sql_statements(script: str):
    """Split a DDL script into statements on TOP-LEVEL semicolons.

    Semicolons inside single-quoted strings, dollar-quoted bodies
    (``$$...$$`` / ``$tag$...$tag$``), line comments, and block comments do
    NOT split — the naive ``script.split(";")`` breaks on the first
    trigger or inlined function body (VERDICT r4 weak #3).
    """
    stmts = []
    buf = []
    i, n = 0, len(script)
    while i < n:
        c = script[i]
        nxt = script[i + 1] if i + 1 < n else ""
        if c == "'":  # string literal ('' escapes)
            j = i + 1
            while j < n:
                if script[j] == "'":
                    if j + 1 < n and script[j + 1] == "'":
                        j += 2
                        continue
                    break
                j += 1
            buf.append(script[i : j + 1])
            i = j + 1
        elif c == "-" and nxt == "-":  # line comment
            j = script.find("\n", i)
            j = n if j == -1 else j
            buf.append(script[i:j])
            i = j
        elif c == "/" and nxt == "*":  # block comment
            j = script.find("*/", i + 2)
            j = n - 2 if j == -1 else j
            buf.append(script[i : j + 2])
            i = j + 2
        elif c == "$":  # dollar-quoted body
            m = re.match(r"\$[A-Za-z_]*\$", script[i:])
            if m:
                tag = m.group(0)
                j = script.find(tag, i + len(tag))
                j = n - len(tag) if j == -1 else j
                buf.append(script[i : j + len(tag)])
                i = j + len(tag)
            else:
                buf.append(c)
                i += 1
        elif c == ";":
            stmt = "".join(buf).strip()
            if stmt:
                stmts.append(stmt)
            buf = []
            i += 1
        else:
            buf.append(c)
            i += 1
    tail = "".join(buf).strip()
    if tail:
        stmts.append(tail)
    return stmts


def translate_schema_to_postgres(schema: str) -> str:
    """The SQLite schema (schema.py) translated to Postgres DDL.

    Mirrors the reference's initial migration
    (db/00000000000001_initial_schema.up.sql) type for type: synthetic row
    ids become BIGSERIAL, BLOB columns BYTEA, and INTEGER columns BIGINT
    (times/durations are integral seconds in both dialects).
    """
    lines = []
    for line in schema.splitlines():
        if line.strip().startswith("PRAGMA"):
            continue
        line = re.sub(r"\bINTEGER PRIMARY KEY\b", "BIGSERIAL PRIMARY KEY", line)
        line = re.sub(r"\bBLOB\b", "BYTEA", line)
        line = re.sub(r"\bINTEGER\b", "BIGINT", line)
        lines.append(line)
    return "\n".join(lines)


class SqliteBackend:
    """File-backed SQLite with the semantics documented in datastore.py."""

    dialect = "sqlite"
    begin_sql = "BEGIN IMMEDIATE"
    #: catalog probe usable INSIDE a transaction without erroring (a failed
    #: SELECT would abort a Postgres transaction; see Datastore._init_schema)
    table_exists_sql = "SELECT 1 FROM sqlite_master WHERE type='table' AND name = ?"

    #: Per-connection lock wait before SQLITE_BUSY surfaces, in ms.  Set
    #: BOTH ways on every connection — the ``timeout=`` connect kwarg and
    #: the ``busy_timeout`` PRAGMA — because the kwarg only covers the
    #: Python wrapper's own waits while the PRAGMA covers statements run
    #: through the C library directly; a contended writer that exhausts
    #: it surfaces "database is locked", which ``is_retryable`` classifies
    #: transient so run_tx retries instead of failing the loser.
    BUSY_TIMEOUT_MS = 10_000

    def __init__(self, path: str):
        import sqlite3

        self.path = path
        #: RETURNING needs SQLite >= 3.35; older libs (Debian bullseye
        #: ships 3.34) take the select-then-mutate fallback paths in
        #: datastore.py — equivalent under BEGIN IMMEDIATE's single
        #: writer, just two statements instead of one.
        self.supports_returning = sqlite3.sqlite_version_info >= (3, 35)

    def connect(self):
        import sqlite3

        conn = sqlite3.connect(
            self.path, timeout=self.BUSY_TIMEOUT_MS / 1000.0, isolation_level=None
        )
        conn.execute("PRAGMA journal_mode = WAL")
        conn.execute("PRAGMA synchronous = NORMAL")
        conn.execute("PRAGMA foreign_keys = ON")
        conn.execute(f"PRAGMA busy_timeout = {self.BUSY_TIMEOUT_MS}")
        return conn

    # No statement translation: Transaction SQL is written in the SQLite
    # dialect, and the /*skip-locked*/ marker is comment-shaped on purpose.

    @property
    def integrity_errors(self):
        import sqlite3

        return (sqlite3.IntegrityError,)

    def is_retryable(self, exc: BaseException) -> bool:
        """SQLITE_BUSY / "database is locked" are transient weather (a
        contended writer, a checkpoint in flight) — retry; everything
        else (schema errors, integrity violations) stays loud."""
        import sqlite3

        return isinstance(exc, sqlite3.OperationalError) and (
            "locked" in str(exc) or "busy" in str(exc)
        )

    def is_disconnect(self, exc: BaseException) -> bool:
        """SQLite is in-process: there is no connection to drop.  Lock
        contention retries on the SAME connection (reconnecting per retry
        would add churn to the contended hot path)."""
        return False

    def init_schema(self, conn, schema: str) -> None:
        """Apply DDL WITHOUT committing: the caller stamps schema_version in
        the same transaction so a crash can never commit DDL unstamped
        (Datastore._init_schema)."""
        for stmt in split_sql_statements(schema):
            conn.execute(stmt)


class _PgConnAdapter:
    """psycopg connection behind the sqlite3-like execute() surface."""

    def __init__(self, conn, backend: "PostgresBackend"):
        self._conn = conn
        self._backend = backend

    def execute(self, sql: str, params: tuple = ()):
        cur = self._conn.cursor()
        cur.execute(self._backend.translate(sql), params)
        return cur

    def executemany(self, sql: str, seq_of_params) -> None:
        cur = self._conn.cursor()
        cur.executemany(self._backend.translate(sql), seq_of_params)

    # The connection runs in driver-autocommit with explicit BEGIN/COMMIT
    # statements (run_tx owns transaction boundaries); statement-level
    # commit/rollback works identically on psycopg v2 and v3.
    def commit(self) -> None:
        self._conn.cursor().execute("COMMIT")

    def rollback(self) -> None:
        self._conn.cursor().execute("ROLLBACK")

    def close(self) -> None:
        self._conn.close()


class PostgresBackend:
    """Shared-Postgres backend (reference deployment shape)."""

    dialect = "postgres"
    # psycopg opens the transaction implicitly on the first statement; the
    # BEGIN here just pins the isolation level per-transaction the way the
    # reference uses REPEATABLE READ (datastore.rs:298).
    begin_sql = "BEGIN ISOLATION LEVEL REPEATABLE READ"
    table_exists_sql = (
        "SELECT 1 FROM pg_tables WHERE schemaname = 'public' AND tablename = ?"
    )
    #: Postgres has supported RETURNING since 8.2.
    supports_returning = True

    def __init__(self, dsn: str):
        self.dsn = dsn
        self._translated: dict = {}

    def _driver(self):
        try:
            import psycopg  # psycopg3

            return psycopg
        except ImportError:
            pass
        try:
            import psycopg2

            return psycopg2
        except ImportError:
            raise ImportError(
                "PostgresBackend requires the psycopg (v3) or psycopg2 package; "
                "install one, or use an SQLite database path instead"
            )

    def connect(self):
        driver = self._driver()
        conn = driver.connect(self.dsn)
        conn.autocommit = True  # run_tx manages transactions explicitly
        return _PgConnAdapter(conn, self)

    def translate(self, sql: str) -> str:
        out = self._translated.get(sql)
        if out is None:
            out = translate_sql_to_postgres(sql)
            self._translated[sql] = out
        return out

    @property
    def integrity_errors(self):
        out = []
        try:
            import psycopg

            out.append(psycopg.errors.IntegrityError)
        except ImportError:
            pass
        try:
            import psycopg2

            out.append(psycopg2.IntegrityError)
        except ImportError:
            pass
        return tuple(out) or (_NeverRaised,)

    def _disconnect_errors(self) -> tuple:
        """Driver exception classes that mean the CONNECTION (not the
        statement) failed: psycopg's OperationalError covers connection
        refused/reset, server shutdown, and failover blips; InterfaceError
        covers using a connection the driver already knows is dead."""
        out = []
        try:
            import psycopg

            out.extend([psycopg.OperationalError, psycopg.InterfaceError])
        except ImportError:
            pass
        try:
            import psycopg2

            out.extend([psycopg2.OperationalError, psycopg2.InterfaceError])
        except ImportError:
            pass
        return tuple(out) or (_NeverRaised,)

    def is_retryable(self, exc: BaseException) -> bool:
        # SQLSTATE 40001 serialization_failure / 40P01 deadlock_detected,
        # exactly the classes the reference retries (datastore.rs:273-289)
        # — plus disconnect-shaped OperationalErrors (server restart,
        # failover, reset): transient weather, not bugs.  Integrity and
        # ProgrammingError (schema) never land here — distinct classes
        # under the driver's hierarchy — so they stay loud.
        sqlstate = getattr(exc, "sqlstate", None) or getattr(exc, "pgcode", None)
        if sqlstate in ("40001", "40P01"):
            return True
        return self.is_disconnect(exc)

    def is_disconnect(self, exc: BaseException) -> bool:
        """run_tx evicts this thread's cached connection before retrying a
        disconnect-shaped failure — retrying a dead socket on the same
        connection would fail all ``max_transaction_retries`` attempts.
        Shapes: an OperationalError/InterfaceError with no SQLSTATE (the
        driver lost the socket before the server could answer) or with a
        connection-exception / operator-intervention class code."""
        sqlstate = getattr(exc, "sqlstate", None) or getattr(exc, "pgcode", None)
        return isinstance(exc, self._disconnect_errors()) and sqlstate in (
            None,
            "57P01",  # admin_shutdown (failover)
            "57P02",  # crash_shutdown
            "57P03",  # cannot_connect_now (server starting up)
            "08000",  # connection_exception
            "08003",  # connection_does_not_exist
            "08006",  # connection_failure
        )

    def init_schema(self, conn, schema: str) -> None:
        """Apply DDL WITHOUT committing (see SqliteBackend.init_schema)."""
        pg_schema = translate_schema_to_postgres(schema)
        for stmt in split_sql_statements(pg_schema):
            conn.execute(stmt)


def backend_for(path_or_url: str):
    """Dispatch on the configured database location.

    ``postgres://`` / ``postgresql://`` DSNs select the Postgres backend;
    anything else is an SQLite file path (the reference's DbConfig url is a
    Postgres DSN, config.rs:75; SQLite is this framework's hermetic mode).
    """
    if path_or_url.startswith(("postgres://", "postgresql://")):
        return PostgresBackend(path_or_url)
    return SqliteBackend(path_or_url)
