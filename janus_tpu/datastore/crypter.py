"""AES-128-GCM encryption of sensitive datastore columns.

The analog of the reference's ``Crypter`` (reference:
aggregator_core/src/datastore.rs:5622-5720): values are sealed with
AAD = (table, row-identifier, column) so ciphertexts cannot be swapped
between rows/columns; multiple keys support rotation — the first key
encrypts, every key is tried on decrypt.

The AEAD comes from the utils/gcm.py seam (ISSUE 14 de-shim):
`cryptography`'s AESGCM whenever it is importable AND functional
(known-answer probed — AES-NI in production), the KAT-anchored soft
fallback otherwise, so the datastore — and every suite that needs one —
runs on cryptography-less dev hosts too.
"""

from __future__ import annotations

import os
import secrets
from typing import List, Sequence

from ..utils.gcm import INVALID_TAG_EXCEPTIONS, aesgcm

#: Kept for callers that used to gate on the wheel: the AEAD seam always
#: works now (soft fallback), so this is about which BACKEND serves.
from ..utils.gcm import HAVE_FUNCTIONAL_CRYPTOGRAPHY as HAVE_CRYPTOGRAPHY  # noqa: F401

KEY_LEN = 16
NONCE_LEN = 12


class CrypterError(Exception):
    pass


def generate_key() -> bytes:
    return secrets.token_bytes(KEY_LEN)


class Crypter:
    def __init__(self, keys: Sequence[bytes]):
        if not keys:
            raise CrypterError("Crypter requires at least one key")
        for k in keys:
            if len(k) != KEY_LEN:
                raise CrypterError(f"datastore keys must be {KEY_LEN} bytes")
        self._aeads: List[object] = [aesgcm(k) for k in keys]

    @staticmethod
    def _aad(table: str, row: bytes, column: str) -> bytes:
        return table.encode() + b"/" + row + b"/" + column.encode()

    def encrypt(self, table: str, row: bytes, column: str, value: bytes) -> bytes:
        nonce = os.urandom(NONCE_LEN)
        ct = self._aeads[0].encrypt(nonce, value, self._aad(table, row, column))
        return nonce + ct

    def decrypt(self, table: str, row: bytes, column: str, value: bytes) -> bytes:
        if len(value) < NONCE_LEN:
            raise CrypterError("ciphertext too short")
        nonce, ct = value[:NONCE_LEN], value[NONCE_LEN:]
        aad = self._aad(table, row, column)
        for aead in self._aeads:
            try:
                return aead.decrypt(nonce, ct, aad)
            except INVALID_TAG_EXCEPTIONS:
                continue
        raise CrypterError(f"unable to decrypt {table}.{column}")
