"""DAP Collector SDK.

The analog of the reference's ``collector`` crate (reference:
collector/src/lib.rs:381-760): PUT a CollectionReq, poll the collection job
with Retry-After-aware backoff, HPKE-open both aggregate shares, and unshard
to the aggregate result.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Optional

from .core.auth_tokens import AuthenticationToken
from .core.hpke import HpkeApplicationInfo, HpkeKeypair, Label, open_
from .messages import (
    AggregateShareAad,
    BatchId,
    BatchSelector,
    Collection,
    CollectionJobId,
    CollectionReq,
    FixedSize,
    Query,
    TaskId,
    TimeInterval,
)


class CollectorError(Exception):
    pass


@dataclass
class CollectionResult:
    """Decrypted, unsharded collection (reference: collector/src/lib.rs
    Collection)."""

    partial_batch_selector: object
    report_count: int
    interval: object
    aggregate_result: object


@dataclass
class Collector:
    """reference: collector/src/lib.rs:381 Collector"""

    task_id: TaskId
    leader_endpoint: str
    vdaf: object
    auth_token: AuthenticationToken
    hpke_keypair: HpkeKeypair  # collector's own keypair
    poll_interval: float = 1.0
    max_poll_time: float = 120.0

    def _query_class(self, query: Query):
        return query.query_type

    async def collect(
        self,
        query: Query,
        aggregation_parameter: bytes = b"",
        *,
        session=None,
    ) -> CollectionResult:
        """PUT + poll until complete (reference: collector/src/lib.rs:439
        collect, :639 poll_until_complete)."""
        import aiohttp

        own_session = session is None
        if own_session:
            session = aiohttp.ClientSession()
        try:
            collection_job_id = CollectionJobId.random()
            await self.create_job(query, collection_job_id, aggregation_parameter, session=session)

            # poll (reference: :522 poll_once w/ Retry-After)
            deadline = asyncio.get_running_loop().time() + self.max_poll_time
            while True:
                out, retry_after = await self.poll_once(
                    query, collection_job_id, aggregation_parameter, session=session
                )
                if out is not None:
                    return out
                if asyncio.get_running_loop().time() > deadline:
                    raise CollectorError("collection timed out")
                await asyncio.sleep(
                    min(retry_after or self.poll_interval, self.poll_interval)
                )
        finally:
            if own_session:
                await session.close()

    def _job_url(self, collection_job_id: CollectionJobId) -> str:
        return (
            self.leader_endpoint.rstrip("/")
            + f"/tasks/{self.task_id}/collection_jobs/{collection_job_id}"
        )

    async def create_job(
        self,
        query: Query,
        collection_job_id: CollectionJobId,
        aggregation_parameter: bytes = b"",
        *,
        session,
    ) -> None:
        """PUT the collection job (reference: collector/src/lib.rs:439)."""
        name, value = self.auth_token.request_authentication()
        headers = {name: value, "Content-Type": CollectionReq.MEDIA_TYPE}
        req = CollectionReq(query, aggregation_parameter)
        url = self._job_url(collection_job_id)
        async with session.put(url, data=req.get_encoded(), headers=headers) as resp:
            if resp.status not in (200, 201):
                raise CollectorError(
                    f"collection create failed: {resp.status} {await resp.text()}"
                )

    async def poll_once(
        self,
        query: Query,
        collection_job_id: CollectionJobId,
        aggregation_parameter: bytes = b"",
        *,
        session,
    ) -> tuple:
        """One POST poll -> (result | None, server Retry-After seconds | None)
        (reference: collector/src/lib.rs:522 poll_once)."""
        name, value = self.auth_token.request_authentication()
        url = self._job_url(collection_job_id)
        async with session.post(url, headers={name: value}) as resp:
            if resp.status == 200:
                body = await resp.read()
                return (
                    self._decrypt(
                        Collection.get_decoded(body, self._query_class(query)),
                        query,
                        aggregation_parameter,
                    ),
                    None,
                )
            if resp.status != 202:
                raise CollectorError(
                    f"collection poll failed: {resp.status} {await resp.text()}"
                )
            retry_after = resp.headers.get("Retry-After")
            try:
                return None, float(retry_after) if retry_after is not None else None
            except ValueError:
                return None, None

    def _decrypt(
        self, collection: Collection, query: Query, aggregation_parameter: bytes
    ) -> CollectionResult:
        """HPKE-open both shares and unshard
        (reference: collector/src/lib.rs:560-636)."""
        if query.query_type is TimeInterval:
            batch_selector = BatchSelector.new_time_interval(query.query_body)
        else:
            batch_selector = BatchSelector.new_fixed_size(
                collection.partial_batch_selector.batch_identifier
            )
        aad = AggregateShareAad(
            self.task_id, aggregation_parameter, batch_selector
        ).get_encoded()
        from .messages import Role

        agg_param = self.vdaf.decode_agg_param(aggregation_parameter)
        field = self.vdaf.field_for_agg_param(agg_param)
        shares = []
        for role, ct in (
            (Role.LEADER, collection.leader_encrypted_agg_share),
            (Role.HELPER, collection.helper_encrypted_agg_share),
        ):
            info = HpkeApplicationInfo.new(Label.AGGREGATE_SHARE, role, Role.COLLECTOR)
            plaintext = open_(self.hpke_keypair, info, ct, aad)
            shares.append(field.decode_vec(plaintext))
        result = self.vdaf.unshard_with_param(
            agg_param, shares, collection.report_count
        )
        return CollectionResult(
            partial_batch_selector=collection.partial_batch_selector,
            report_count=collection.report_count,
            interval=collection.interval,
            aggregate_result=result,
        )
