"""FLP gadgets: arithmetic sub-circuits with bounded degree.

draft-irtf-cfrg-vdaf-08 §7.3.2 (Mul, PolyEval/Range2) and §7.3.3 (ParallelSum,
the wide-vector gadget behind SumVec/Histogram — the reference's analog of
"chunked" wide-vector parallelism, SURVEY.md §2.3 P7).
"""

from __future__ import annotations

from typing import List, Sequence

from ..fields import poly_add, poly_eval, poly_mul


class Gadget:
    ARITY: int
    DEGREE: int

    def eval(self, field: type, inp: Sequence[int]) -> int:
        raise NotImplementedError

    def eval_poly(self, field: type, wire_polys: Sequence[Sequence[int]]) -> List[int]:
        """Evaluate the gadget over polynomial-valued wires."""
        raise NotImplementedError


class Mul(Gadget):
    ARITY = 2
    DEGREE = 2

    def eval(self, field, inp):
        return field.mul(inp[0], inp[1])

    def eval_poly(self, field, wire_polys):
        return poly_mul(field, wire_polys[0], wire_polys[1])


class PolyEval(Gadget):
    """Evaluate a fixed univariate polynomial p at the (single) input wire."""

    ARITY = 1

    def __init__(self, poly: Sequence[int]):
        if len(poly) < 2:
            raise ValueError("polynomial must have degree >= 1")
        self.poly = list(poly)  # may hold negative ints; normalized per field on use
        self.DEGREE = len(poly) - 1
        self._norm_cache = {}

    def _norm(self, field) -> List[int]:
        coeffs = self._norm_cache.get(field)
        if coeffs is None:
            coeffs = [c % field.MODULUS for c in self.poly]
            self._norm_cache[field] = coeffs
        return coeffs

    def eval(self, field, inp):
        return poly_eval(field, self._norm(field), inp[0])

    def eval_poly(self, field, wire_polys):
        # Horner over polynomials: p(w(x)).
        coeffs = self._norm(field)
        w = list(wire_polys[0])
        out: List[int] = [coeffs[-1]]
        for c in reversed(coeffs[:-1]):
            out = poly_mul(field, out, w)
            out = poly_add(field, out, [c])
        return out


def Range2() -> PolyEval:
    """p(x) = x^2 - x, the bit-check gadget (§7.3.2)."""
    return PolyEval([0, -1, 1])


class ParallelSum(Gadget):
    """Sum of `count` applications of an inner gadget over disjoint wire chunks."""

    def __init__(self, inner: Gadget, count: int):
        self.inner = inner
        self.count = count
        self.ARITY = inner.ARITY * count
        self.DEGREE = inner.DEGREE

    def eval(self, field, inp):
        a = self.inner.ARITY
        acc = 0
        for i in range(self.count):
            acc = field.add(acc, self.inner.eval(field, inp[i * a : (i + 1) * a]))
        return acc

    def eval_poly(self, field, wire_polys):
        a = self.inner.ARITY
        out: List[int] = []
        for i in range(self.count):
            out = poly_add(field, out, self.inner.eval_poly(field, wire_polys[i * a : (i + 1) * a]))
        return out
