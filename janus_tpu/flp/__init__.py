"""FLP (fully linear proof) system for Prio3 — draft-irtf-cfrg-vdaf-08 §7.3.

The reference consumes this from the external ``prio`` crate (SURVEY.md §2.2
"prio crate surface"); here it is re-implemented natively: an exact CPU oracle
in this package, and batched TPU kernels in ``janus_tpu.ops`` that must agree
bit-for-bit.
"""

from .gadgets import Mul, ParallelSum, PolyEval, Range2
from .circuits import Count, FixedPointBoundedL2VecSum, Histogram, Sum, SumVec
from .generic import FlpError, FlpGeneric

__all__ = [
    "Mul",
    "ParallelSum",
    "PolyEval",
    "Range2",
    "Count",
    "FixedPointBoundedL2VecSum",
    "Histogram",
    "Sum",
    "SumVec",
    "FlpError",
    "FlpGeneric",
]
