"""Generic FLP construction from a validity circuit — draft-irtf-cfrg-vdaf-08 §7.3.

prove:  the prover evaluates the circuit on the measurement, recording every
        gadget's input wires; each gadget's wires are interpolated (seeded with
        one prove-rand element at the point alpha^0) into wire polynomials, and
        the gadget applied to those polynomials yields the gadget polynomial
        shipped in the proof.
query:  each verifier evaluates the circuit on its *share*, answering gadget
        calls from the proof's gadget polynomial (evaluated at alpha^k for call
        k), then spot-checks the gadget polynomial at a random point t.
decide: on the combined verifier message, check the circuit output is zero and
        that each gadget's claimed output matches a direct gadget evaluation.

The prepare-side pieces (query/decide) are what the TPU backend batches across
reports (SURVEY.md §2.3 P1); this module is their bit-exact oracle.
"""

from __future__ import annotations

from typing import List, Sequence

from ..fields import next_power_of_2, poly_eval, poly_interp
from .circuits import Valid
from .gadgets import Gadget


class FlpError(Exception):
    pass


class _ProveGadget:
    def __init__(self, field: type, wire_seeds: Sequence[int], inner: Gadget, calls: int):
        self.inner = inner
        self.calls = calls
        self.P = next_power_of_2(1 + calls)
        self.wire = [[0] * self.P for _ in range(inner.ARITY)]
        for j, s in enumerate(wire_seeds):
            self.wire[j][0] = s
        self.k = 0

    def eval(self, field, inp):
        self.k += 1
        if self.k > self.calls:
            raise FlpError("gadget called more times than declared")
        for j in range(self.inner.ARITY):
            self.wire[j][self.k] = inp[j]
        return self.inner.eval(field, inp)


class _QueryGadget:
    def __init__(
        self,
        field: type,
        wire_seeds: Sequence[int],
        gadget_poly: Sequence[int],
        inner: Gadget,
        calls: int,
    ):
        self.inner = inner
        self.calls = calls
        self.P = next_power_of_2(1 + calls)
        self.alpha = field.root(self.P)
        self.gadget_poly = list(gadget_poly)
        self.wire = [[0] * self.P for _ in range(inner.ARITY)]
        for j, s in enumerate(wire_seeds):
            self.wire[j][0] = s
        self.k = 0

    def eval(self, field, inp):
        self.k += 1
        if self.k > self.calls:
            raise FlpError("gadget called more times than declared")
        for j in range(self.inner.ARITY):
            self.wire[j][self.k] = inp[j]
        return poly_eval(field, self.gadget_poly, pow(self.alpha, self.k, field.MODULUS))


class FlpGeneric:
    def __init__(self, valid: Valid):
        self.valid = valid
        self.field = valid.field
        gadgets = valid.new_gadgets()
        self.MEAS_LEN = valid.MEAS_LEN
        self.OUTPUT_LEN = valid.OUTPUT_LEN
        self.JOINT_RAND_LEN = valid.JOINT_RAND_LEN
        self.PROVE_RAND_LEN = sum(g.ARITY for g in gadgets)
        self.QUERY_RAND_LEN = len(gadgets)
        self.PROOF_LEN = 0
        self.VERIFIER_LEN = 1
        for g, calls in zip(gadgets, valid.GADGET_CALLS):
            p = next_power_of_2(1 + calls)
            self.PROOF_LEN += g.ARITY + g.DEGREE * (p - 1) + 1
            self.VERIFIER_LEN += g.ARITY + 1

    # ------------------------------------------------------------------
    def prove(self, meas: Sequence[int], prove_rand: Sequence[int], joint_rand: Sequence[int]) -> List[int]:
        if len(prove_rand) != self.PROVE_RAND_LEN:
            raise FlpError("bad prove_rand length")
        field = self.field
        gadgets = []
        idx = 0
        for g, calls in zip(self.valid.new_gadgets(), self.valid.GADGET_CALLS):
            seeds = prove_rand[idx : idx + g.ARITY]
            idx += g.ARITY
            gadgets.append(_ProveGadget(field, seeds, g, calls))
        self.valid.eval(list(meas), list(joint_rand), 1, gadgets)
        proof: List[int] = []
        for pg in gadgets:
            if pg.k != pg.calls:
                raise FlpError("circuit under-used a gadget")
            wire_polys = [poly_interp(field, w) for w in pg.wire]
            gadget_poly = pg.inner.eval_poly(field, wire_polys)
            want = pg.inner.DEGREE * (pg.P - 1) + 1
            gadget_poly = list(gadget_poly[:want]) + [0] * (want - len(gadget_poly))
            proof.extend(w[0] for w in pg.wire)
            proof.extend(gadget_poly)
        assert len(proof) == self.PROOF_LEN
        return proof

    # ------------------------------------------------------------------
    def query(
        self,
        meas_share: Sequence[int],
        proof_share: Sequence[int],
        query_rand: Sequence[int],
        joint_rand: Sequence[int],
        num_shares: int,
    ) -> List[int]:
        if len(proof_share) != self.PROOF_LEN:
            raise FlpError("bad proof length")
        if len(query_rand) != self.QUERY_RAND_LEN:
            raise FlpError("bad query_rand length")
        field = self.field
        gadgets = []
        idx = 0
        for g, calls in zip(self.valid.new_gadgets(), self.valid.GADGET_CALLS):
            p = next_power_of_2(1 + calls)
            seg_len = g.ARITY + g.DEGREE * (p - 1) + 1
            seg = proof_share[idx : idx + seg_len]
            idx += seg_len
            gadgets.append(_QueryGadget(field, seg[: g.ARITY], seg[g.ARITY :], g, calls))
        v = self.valid.eval(list(meas_share), list(joint_rand), num_shares, gadgets)
        verifier: List[int] = [v]
        for i, qg in enumerate(gadgets):
            t = query_rand[i]
            if pow(t, qg.P, field.MODULUS) == 1:
                # Negligible probability for honestly derived query rand.
                raise FlpError("query randomness is a root of unity")
            for w in qg.wire:
                verifier.append(poly_eval(field, poly_interp(field, w), t))
            verifier.append(poly_eval(field, qg.gadget_poly, t))
        assert len(verifier) == self.VERIFIER_LEN
        return verifier

    # ------------------------------------------------------------------
    def decide(self, verifier: Sequence[int]) -> bool:
        if len(verifier) != self.VERIFIER_LEN:
            raise FlpError("bad verifier length")
        field = self.field
        if verifier[0] != 0:
            return False
        idx = 1
        for g, _calls in zip(self.valid.new_gadgets(), self.valid.GADGET_CALLS):
            x = verifier[idx : idx + g.ARITY]
            idx += g.ARITY
            y = verifier[idx]
            idx += 1
            if g.eval(field, x) != y:
                return False
        return True

    # Convenience passthroughs -----------------------------------------
    def encode(self, measurement):
        return self.valid.encode(measurement)

    def truncate(self, meas):
        return self.valid.truncate(meas)

    def decode(self, output, num_measurements):
        return self.valid.decode(output, num_measurements)
