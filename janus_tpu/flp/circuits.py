"""Validity circuits for the Prio3 family — draft-irtf-cfrg-vdaf-08 §7.4.

These define the VDAFs the reference registers in its ``VdafInstance`` enum
(reference: core/src/vdaf.rs:65-108): Prio3Count, Prio3Sum{bits},
Prio3SumVec{bits,length,chunk_length}, Prio3Histogram{length,chunk_length}, and
the Field64 multiproof SumVec variant (core/src/vdaf.rs:178-195) which reuses
the SumVec circuit over Field64.

A circuit evaluates to zero iff the measurement is valid.  ``eval`` receives
the number of additive shares so that additive *constants* in the circuit can
be scaled by 1/num_shares (each aggregator evaluates on its share; the shares
of the circuit output then sum to the true output).
"""

from __future__ import annotations

from typing import List, Sequence

from ..fields import Field64, Field128
from .gadgets import Gadget, Mul, ParallelSum, Range2


class Valid:
    """Base class: a validity circuit plus measurement encode/truncate/decode."""

    field: type
    MEAS_LEN: int
    OUTPUT_LEN: int
    JOINT_RAND_LEN: int
    GADGET_CALLS: List[int]

    def new_gadgets(self) -> List[Gadget]:
        """Fresh plain gadget evaluators (prove/query wrap these)."""
        raise NotImplementedError

    def eval(self, meas, joint_rand, num_shares, gadgets) -> int:
        raise NotImplementedError

    def encode(self, measurement) -> List[int]:
        raise NotImplementedError

    def truncate(self, meas: Sequence[int]) -> List[int]:
        raise NotImplementedError

    def decode(self, output: Sequence[int], num_measurements: int):
        raise NotImplementedError

    def check_valid(self, meas, joint_rand):
        if len(meas) != self.MEAS_LEN:
            raise ValueError("measurement length mismatch")
        if len(joint_rand) != self.JOINT_RAND_LEN:
            raise ValueError("joint randomness length mismatch")


class Count(Valid):
    """C(x) = x*x - x; one boolean measurement.  Field64, no joint rand."""

    field = Field64
    MEAS_LEN = 1
    OUTPUT_LEN = 1
    JOINT_RAND_LEN = 0
    GADGET_CALLS = [1]

    def new_gadgets(self):
        return [Mul()]

    def eval(self, meas, joint_rand, num_shares, gadgets):
        self.check_valid(meas, joint_rand)
        squared = gadgets[0].eval(self.field, [meas[0], meas[0]])
        return self.field.sub(squared, meas[0])

    def encode(self, measurement):
        if measurement not in (0, 1):
            raise ValueError("Count measurement must be 0 or 1")
        return [int(measurement)]

    def truncate(self, meas):
        return list(meas)

    def decode(self, output, num_measurements):
        return output[0]


class Sum(Valid):
    """Integer in [0, 2^bits); bit-decomposed, each bit range-checked."""

    field = Field128

    def __init__(self, bits: int):
        if not 0 < bits < self.field.MODULUS.bit_length():
            raise ValueError("bits out of range")
        self.bits = bits
        self.MEAS_LEN = bits
        self.OUTPUT_LEN = 1
        self.JOINT_RAND_LEN = 1
        self.GADGET_CALLS = [bits]

    def new_gadgets(self):
        return [Range2()]

    def eval(self, meas, joint_rand, num_shares, gadgets):
        self.check_valid(meas, joint_rand)
        f = self.field
        out = 0
        r = joint_rand[0]
        for b in meas:
            out = f.add(out, f.mul(r, gadgets[0].eval(f, [b])))
            r = f.mul(r, joint_rand[0])
        return out

    def encode(self, measurement):
        if not 0 <= measurement < (1 << self.bits):
            raise ValueError("measurement out of range")
        return [(measurement >> l) & 1 for l in range(self.bits)]

    def truncate(self, meas):
        f = self.field
        acc = 0
        for l, b in enumerate(meas):
            acc = f.add(acc, f.mul(pow(2, l, f.MODULUS), b))
        return [acc]

    def decode(self, output, num_measurements):
        return output[0]


class SumVec(Valid):
    """Vector of `length` integers each in [0, 2^bits); ParallelSum bit checks.

    Field is parametric: Field128 for standard Prio3SumVec, Field64 for the
    multiproof variant (reference: core/src/vdaf.rs:178-195).
    """

    def __init__(self, length: int, bits: int, chunk_length: int, field: type = Field128):
        if length <= 0 or bits <= 0 or chunk_length <= 0:
            raise ValueError("invalid SumVec parameters")
        self.field = field
        self.length = length
        self.bits = bits
        self.chunk_length = chunk_length
        self.MEAS_LEN = length * bits
        self.OUTPUT_LEN = length
        self.GADGET_CALLS = [(self.MEAS_LEN + chunk_length - 1) // chunk_length]
        self.JOINT_RAND_LEN = self.GADGET_CALLS[0]

    def new_gadgets(self):
        return [ParallelSum(Mul(), self.chunk_length)]

    def eval(self, meas, joint_rand, num_shares, gadgets):
        self.check_valid(meas, joint_rand)
        f = self.field
        out = 0
        shares_inv = f.inv(num_shares)
        for i in range(self.GADGET_CALLS[0]):
            r = joint_rand[i]
            r_power = r
            inputs = []
            for j in range(self.chunk_length):
                index = i * self.chunk_length + j
                meas_elem = meas[index] if index < len(meas) else 0
                inputs.append(f.mul(meas_elem, r_power))
                inputs.append(f.sub(meas_elem, shares_inv))
                r_power = f.mul(r_power, r)
            out = f.add(out, gadgets[0].eval(f, inputs))
        return out

    def encode(self, measurement):
        if len(measurement) != self.length:
            raise ValueError("measurement length mismatch")
        meas = []
        for v in measurement:
            if not 0 <= v < (1 << self.bits):
                raise ValueError("vector element out of range")
            meas.extend((v >> l) & 1 for l in range(self.bits))
        return meas

    def truncate(self, meas):
        f = self.field
        out = []
        for l in range(self.length):
            acc = 0
            for b in range(self.bits):
                acc = f.add(acc, f.mul(pow(2, b, f.MODULUS), meas[l * self.bits + b]))
            out.append(acc)
        return out

    def decode(self, output, num_measurements):
        return list(output)


class Histogram(Valid):
    """One-hot vector of `length` buckets; range check + sum-to-one check."""

    field = Field128

    def __init__(self, length: int, chunk_length: int, field: type = Field128):
        if length <= 0 or chunk_length <= 0:
            raise ValueError("invalid Histogram parameters")
        self.field = field
        self.length = length
        self.chunk_length = chunk_length
        self.MEAS_LEN = length
        self.OUTPUT_LEN = length
        self.GADGET_CALLS = [(length + chunk_length - 1) // chunk_length]
        self.JOINT_RAND_LEN = 2

    def new_gadgets(self):
        return [ParallelSum(Mul(), self.chunk_length)]

    def eval(self, meas, joint_rand, num_shares, gadgets):
        self.check_valid(meas, joint_rand)
        f = self.field
        shares_inv = f.inv(num_shares)
        # Range check: every bucket is 0 or 1.
        range_check = 0
        r = joint_rand[0]
        r_power = r
        for i in range(self.GADGET_CALLS[0]):
            inputs = []
            for j in range(self.chunk_length):
                index = i * self.chunk_length + j
                meas_elem = meas[index] if index < len(meas) else 0
                inputs.append(f.mul(meas_elem, r_power))
                inputs.append(f.sub(meas_elem, shares_inv))
                r_power = f.mul(r_power, r)
            range_check = f.add(range_check, gadgets[0].eval(f, inputs))
        # Sum check: buckets sum to exactly one.
        sum_check = f.neg(shares_inv)
        for b in meas:
            sum_check = f.add(sum_check, b)
        out = f.add(
            f.mul(joint_rand[1], range_check),
            f.mul(f.mul(joint_rand[1], joint_rand[1]), sum_check),
        )
        return out

    def encode(self, measurement):
        if not 0 <= measurement < self.length:
            raise ValueError("bucket index out of range")
        return [1 if i == measurement else 0 for i in range(self.length)]

    def truncate(self, meas):
        return list(meas)

    def decode(self, output, num_measurements):
        return list(output)
