"""Validity circuits for the Prio3 family — draft-irtf-cfrg-vdaf-08 §7.4.

These define the VDAFs the reference registers in its ``VdafInstance`` enum
(reference: core/src/vdaf.rs:65-108): Prio3Count, Prio3Sum{bits},
Prio3SumVec{bits,length,chunk_length}, Prio3Histogram{length,chunk_length}, and
the Field64 multiproof SumVec variant (core/src/vdaf.rs:178-195) which reuses
the SumVec circuit over Field64.

A circuit evaluates to zero iff the measurement is valid.  ``eval`` receives
the number of additive shares so that additive *constants* in the circuit can
be scaled by 1/num_shares (each aggregator evaluates on its share; the shares
of the circuit output then sum to the true output).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..fields import Field64, Field128
from .gadgets import Gadget, Mul, ParallelSum, Range2


class Valid:
    """Base class: a validity circuit plus measurement encode/truncate/decode."""

    field: type
    MEAS_LEN: int
    OUTPUT_LEN: int
    JOINT_RAND_LEN: int
    GADGET_CALLS: List[int]

    def new_gadgets(self) -> List[Gadget]:
        """Fresh plain gadget evaluators (prove/query wrap these)."""
        raise NotImplementedError

    def eval(self, meas, joint_rand, num_shares, gadgets) -> int:
        raise NotImplementedError

    def encode(self, measurement) -> List[int]:
        raise NotImplementedError

    def truncate(self, meas: Sequence[int]) -> List[int]:
        raise NotImplementedError

    def decode(self, output: Sequence[int], num_measurements: int):
        raise NotImplementedError

    def check_valid(self, meas, joint_rand):
        if len(meas) != self.MEAS_LEN:
            raise ValueError("measurement length mismatch")
        if len(joint_rand) != self.JOINT_RAND_LEN:
            raise ValueError("joint randomness length mismatch")


class Count(Valid):
    """C(x) = x*x - x; one boolean measurement.  Field64, no joint rand."""

    field = Field64
    MEAS_LEN = 1
    OUTPUT_LEN = 1
    JOINT_RAND_LEN = 0
    GADGET_CALLS = [1]

    def new_gadgets(self):
        return [Mul()]

    def eval(self, meas, joint_rand, num_shares, gadgets):
        self.check_valid(meas, joint_rand)
        squared = gadgets[0].eval(self.field, [meas[0], meas[0]])
        return self.field.sub(squared, meas[0])

    def encode(self, measurement):
        if measurement not in (0, 1):
            raise ValueError("Count measurement must be 0 or 1")
        return [int(measurement)]

    def truncate(self, meas):
        return list(meas)

    def decode(self, output, num_measurements):
        return output[0]


class Sum(Valid):
    """Integer in [0, 2^bits); bit-decomposed, each bit range-checked."""

    field = Field128

    def __init__(self, bits: int):
        if not 0 < bits < self.field.MODULUS.bit_length():
            raise ValueError("bits out of range")
        self.bits = bits
        self.MEAS_LEN = bits
        self.OUTPUT_LEN = 1
        self.JOINT_RAND_LEN = 1
        self.GADGET_CALLS = [bits]

    def new_gadgets(self):
        return [Range2()]

    def eval(self, meas, joint_rand, num_shares, gadgets):
        self.check_valid(meas, joint_rand)
        f = self.field
        out = 0
        r = joint_rand[0]
        for b in meas:
            out = f.add(out, f.mul(r, gadgets[0].eval(f, [b])))
            r = f.mul(r, joint_rand[0])
        return out

    def encode(self, measurement):
        if not 0 <= measurement < (1 << self.bits):
            raise ValueError("measurement out of range")
        return [(measurement >> l) & 1 for l in range(self.bits)]

    def truncate(self, meas):
        f = self.field
        acc = 0
        for l, b in enumerate(meas):
            acc = f.add(acc, f.mul(pow(2, l, f.MODULUS), b))
        return [acc]

    def decode(self, output, num_measurements):
        return output[0]


class SumVec(Valid):
    """Vector of `length` integers each in [0, 2^bits); ParallelSum bit checks.

    Field is parametric: Field128 for standard Prio3SumVec, Field64 for the
    multiproof variant (reference: core/src/vdaf.rs:178-195).
    """

    def __init__(self, length: int, bits: int, chunk_length: int, field: type = Field128):
        if length <= 0 or bits <= 0 or chunk_length <= 0:
            raise ValueError("invalid SumVec parameters")
        self.field = field
        self.length = length
        self.bits = bits
        self.chunk_length = chunk_length
        self.MEAS_LEN = length * bits
        self.OUTPUT_LEN = length
        self.GADGET_CALLS = [(self.MEAS_LEN + chunk_length - 1) // chunk_length]
        self.JOINT_RAND_LEN = self.GADGET_CALLS[0]

    def new_gadgets(self):
        return [ParallelSum(Mul(), self.chunk_length)]

    def eval(self, meas, joint_rand, num_shares, gadgets):
        self.check_valid(meas, joint_rand)
        f = self.field
        out = 0
        shares_inv = f.inv(num_shares)
        for i in range(self.GADGET_CALLS[0]):
            r = joint_rand[i]
            r_power = r
            inputs = []
            for j in range(self.chunk_length):
                index = i * self.chunk_length + j
                meas_elem = meas[index] if index < len(meas) else 0
                inputs.append(f.mul(meas_elem, r_power))
                inputs.append(f.sub(meas_elem, shares_inv))
                r_power = f.mul(r_power, r)
            out = f.add(out, gadgets[0].eval(f, inputs))
        return out

    def encode(self, measurement):
        if len(measurement) != self.length:
            raise ValueError("measurement length mismatch")
        meas = []
        for v in measurement:
            if not 0 <= v < (1 << self.bits):
                raise ValueError("vector element out of range")
            meas.extend((v >> l) & 1 for l in range(self.bits))
        return meas

    def truncate(self, meas):
        f = self.field
        out = []
        for l in range(self.length):
            acc = 0
            for b in range(self.bits):
                acc = f.add(acc, f.mul(pow(2, b, f.MODULUS), meas[l * self.bits + b]))
            out.append(acc)
        return out

    def decode(self, output, num_measurements):
        return list(output)


class Histogram(Valid):
    """One-hot vector of `length` buckets; range check + sum-to-one check."""

    field = Field128

    def __init__(self, length: int, chunk_length: int, field: type = Field128):
        if length <= 0 or chunk_length <= 0:
            raise ValueError("invalid Histogram parameters")
        self.field = field
        self.length = length
        self.chunk_length = chunk_length
        self.MEAS_LEN = length
        self.OUTPUT_LEN = length
        self.GADGET_CALLS = [(length + chunk_length - 1) // chunk_length]
        self.JOINT_RAND_LEN = 2

    def new_gadgets(self):
        return [ParallelSum(Mul(), self.chunk_length)]

    def eval(self, meas, joint_rand, num_shares, gadgets):
        self.check_valid(meas, joint_rand)
        f = self.field
        shares_inv = f.inv(num_shares)
        # Range check: every bucket is 0 or 1.
        range_check = 0
        r = joint_rand[0]
        r_power = r
        for i in range(self.GADGET_CALLS[0]):
            inputs = []
            for j in range(self.chunk_length):
                index = i * self.chunk_length + j
                meas_elem = meas[index] if index < len(meas) else 0
                inputs.append(f.mul(meas_elem, r_power))
                inputs.append(f.sub(meas_elem, shares_inv))
                r_power = f.mul(r_power, r)
            range_check = f.add(range_check, gadgets[0].eval(f, inputs))
        # Sum check: buckets sum to exactly one.
        sum_check = f.neg(shares_inv)
        for b in meas:
            sum_check = f.add(sum_check, b)
        out = f.add(
            f.mul(joint_rand[1], range_check),
            f.mul(f.mul(joint_rand[1], joint_rand[1]), sum_check),
        )
        return out

    def encode(self, measurement):
        if not 0 <= measurement < self.length:
            raise ValueError("bucket index out of range")
        return [1 if i == measurement else 0 for i in range(self.length)]

    def truncate(self, meas):
        return list(meas)

    def decode(self, output, num_measurements):
        return list(output)


class FixedPointBoundedL2VecSum(Valid):
    """Fixed-point vector sum with an L2-norm bound (federated-learning
    gradient aggregation).

    The analog of the reference's ``fpvec_bounded_l2`` VDAF family
    (reference: core/src/vdaf.rs:91 Prio3FixedPointBoundedL2VecSum; the
    circuit lives in the external prio crate, flp/types/fixedpoint_l2.rs).
    Each measurement is a vector of ``entries`` fixed-point values in
    [-1, 1) with ``bits_per_entry`` bits (1 sign + n-1 fraction), encoded
    via the unsigned offset representation X = x*2^(n-1) + 2^(n-1).  The
    client additionally claims the squared L2 norm of the ORIGINAL vector
    as a (2n-2)-bit decomposition, which bounds it below 1.

    Validity checks, combined into one output by Schwartz-Zippel random
    linear combination (the Histogram pattern above):
    1. every entry bit and norm bit is 0/1 (chunked ParallelSum(Mul) with
       per-chunk joint-rand weights, the SumVec pattern);
    2. the claimed norm equals the recomputed norm
       sum_i (X_i - 2^(n-1))^2 = sum_i X_i^2 - 2^n sum_i X_i + d*2^(2n-2),
       where the squares come from a second ParallelSum(Mul) gadget over
       entry pairs (X_i, X_i) and the rest is affine in the shares.
    """

    def __init__(
        self,
        bits_per_entry: int,
        entries: int,
        chunk_length: Optional[int] = None,
        field: type = Field128,
    ):
        if bits_per_entry < 2 or entries <= 0:
            raise ValueError("invalid FixedPointBoundedL2VecSum parameters")
        n = bits_per_entry
        self.field = field
        self.bits_per_entry = n
        self.entries = entries
        self.bits_for_norm = 2 * (n - 1)
        self.MEAS_LEN = entries * n + self.bits_for_norm
        self.OUTPUT_LEN = entries
        self.chunk_length = chunk_length or max(1, int(self.MEAS_LEN**0.5))
        bit_calls = (self.MEAS_LEN + self.chunk_length - 1) // self.chunk_length
        sq_calls = (entries + self.chunk_length - 1) // self.chunk_length
        self.GADGET_CALLS = [bit_calls, sq_calls]
        # one weight per bit chunk + one combiner for the norm equality
        self.JOINT_RAND_LEN = bit_calls + 1

    def new_gadgets(self):
        return [
            ParallelSum(Mul(), self.chunk_length),
            ParallelSum(Mul(), self.chunk_length),
        ]

    def _entry(self, f, meas, i):
        n = self.bits_per_entry
        acc = 0
        for b in range(n):
            acc = f.add(acc, f.mul(pow(2, b, f.MODULUS), meas[i * n + b]))
        return acc

    def eval(self, meas, joint_rand, num_shares, gadgets):
        self.check_valid(meas, joint_rand)
        f = self.field
        n = self.bits_per_entry
        d = self.entries
        shares_inv = f.inv(num_shares)
        bit_calls, sq_calls = self.GADGET_CALLS

        # 1. bit range checks over ALL MEAS_LEN positions (SumVec pattern).
        bit_check = 0
        for i in range(bit_calls):
            r = joint_rand[i]
            r_power = r
            inputs = []
            for j in range(self.chunk_length):
                index = i * self.chunk_length + j
                meas_elem = meas[index] if index < len(meas) else 0
                inputs.append(f.mul(meas_elem, r_power))
                inputs.append(f.sub(meas_elem, shares_inv))
                r_power = f.mul(r_power, r)
            bit_check = f.add(bit_check, gadgets[0].eval(f, inputs))

        # 2. norm equality.
        entries_f = [self._entry(f, meas, i) for i in range(d)]
        sumsq = 0
        for i in range(sq_calls):
            inputs = []
            for j in range(self.chunk_length):
                index = i * self.chunk_length + j
                x = entries_f[index] if index < d else 0
                inputs.append(x)
                inputs.append(x)
            sumsq = f.add(sumsq, gadgets[1].eval(f, inputs))
        sum_x = 0
        for x in entries_f:
            sum_x = f.add(sum_x, x)
        offset_sq = f.mul(
            shares_inv, f.mul(d % f.MODULUS, pow(2, 2 * n - 2, f.MODULUS))
        )
        computed = f.add(
            f.sub(sumsq, f.mul(pow(2, n, f.MODULUS), sum_x)), offset_sq
        )
        claimed = 0
        for b in range(self.bits_for_norm):
            claimed = f.add(
                claimed,
                f.mul(pow(2, b, f.MODULUS), meas[d * n + b]),
            )
        norm_check = f.sub(computed, claimed)

        rn = joint_rand[bit_calls]
        return f.add(f.mul(rn, bit_check), f.mul(f.mul(rn, rn), norm_check))

    def encode(self, measurement):
        """measurement: sequence of floats in [-1, 1)."""
        n = self.bits_per_entry
        if len(measurement) != self.entries:
            raise ValueError("measurement length mismatch")
        xs = []
        for v in measurement:
            if not -1.0 <= float(v) < 1.0:
                raise ValueError("fixed-point value out of [-1, 1)")
            # Clamp the rounded magnitude to the largest representable
            # value: floats in [1 - 2^-(n-1), 1) would otherwise round up
            # to the unrepresentable 2^(n-1) (the reference takes
            # fixed-point-typed inputs, where this cannot arise).
            scaled = min(int(round(float(v) * (1 << (n - 1)))), (1 << (n - 1)) - 1)
            xs.append(scaled + (1 << (n - 1)))
        norm = sum((x - (1 << (n - 1))) ** 2 for x in xs)
        if norm >= 1 << self.bits_for_norm:
            raise ValueError("L2 norm out of bounds")
        meas = []
        for x in xs:
            meas.extend((x >> b) & 1 for b in range(n))
        meas.extend((norm >> b) & 1 for b in range(self.bits_for_norm))
        return meas

    def truncate(self, meas):
        f = self.field
        return [self._entry(f, meas, i) for i in range(self.entries)]

    def decode(self, output, num_measurements):
        n = self.bits_per_entry
        offset = num_measurements << (n - 1)
        return [
            (int(o) - offset) / float(1 << (n - 1)) for o in output
        ]
