"""DAP Client SDK.

The analog of the reference's ``client`` crate (reference:
client/src/lib.rs:270-470): fetch + validate the aggregators' HPKE configs,
shard a measurement through the VDAF, HPKE-seal one input share to each
aggregator, and PUT the Report to the leader.

``prepare_report`` is pure (no I/O) so tests and batch producers can build
wire-exact reports without a network; ``Client.upload`` drives the HTTP flow
with aiohttp.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import Optional, Tuple

from .core.hpke import HpkeApplicationInfo, Label, is_hpke_config_supported, seal
from .core.time import time_to_batch_interval_start
from .messages import (
    Duration,
    HpkeConfig,
    HpkeConfigList,
    InputShareAad,
    PlaintextInputShare,
    Report,
    ReportId,
    ReportMetadata,
    Role,
    TaskId,
    Time,
)


class ClientError(Exception):
    pass


def prepare_report(
    vdaf,
    task_id: TaskId,
    leader_hpke_config: HpkeConfig,
    helper_hpke_config: HpkeConfig,
    time_precision: Duration,
    measurement,
    *,
    time: Optional[Time] = None,
    now: Optional[Time] = None,
) -> Report:
    """Shard + seal one measurement into a wire Report
    (reference: client/src/lib.rs:390 upload's report construction)."""
    for config in (leader_hpke_config, helper_hpke_config):
        if not is_hpke_config_supported(config):
            raise ClientError(f"unsupported HPKE config {config.id}")
    if time is None:
        import time as _time

        time = now if now is not None else Time(int(_time.time()))
    # Report timestamps are rounded down to the task's time precision so the
    # exact upload time is not leaked (reference: client/src/lib.rs).
    t = time_to_batch_interval_start(time, time_precision)

    report_id = ReportId.random()
    rand = secrets.token_bytes(vdaf.RAND_SIZE)
    public_share, input_shares = vdaf.shard(measurement, report_id.data, rand)
    public_share_bytes = vdaf.encode_public_share(public_share)
    metadata = ReportMetadata(report_id, t)
    aad = InputShareAad(task_id, metadata, public_share_bytes).get_encoded()

    encrypted = []
    for role, config, share in (
        (Role.LEADER, leader_hpke_config, input_shares[0]),
        (Role.HELPER, helper_hpke_config, input_shares[1]),
    ):
        plaintext = PlaintextInputShare([], share.encode(vdaf)).get_encoded()
        info = HpkeApplicationInfo.new(Label.INPUT_SHARE, Role.CLIENT, role)
        encrypted.append(seal(config, info, plaintext, aad))

    return Report(metadata, public_share_bytes, encrypted[0], encrypted[1])


@dataclass
class Client:
    """HTTP client front-end (reference: client/src/lib.rs:270 Client)."""

    task_id: TaskId
    leader_endpoint: str
    helper_endpoint: str
    vdaf: object
    time_precision: Duration
    leader_hpke_config: Optional[HpkeConfig] = None
    helper_hpke_config: Optional[HpkeConfig] = None

    async def _fetch_hpke_config(self, session, endpoint: str) -> HpkeConfig:
        url = endpoint.rstrip("/") + "/hpke_config?task_id=" + str(self.task_id)
        async with session.get(url) as resp:
            if resp.status != 200:
                raise ClientError(f"hpke_config fetch failed: {resp.status}")
            body = await resp.read()
        configs = HpkeConfigList.get_decoded(body).hpke_configs
        for config in configs:
            if is_hpke_config_supported(config):
                return config
        raise ClientError("no supported HPKE config advertised")

    async def refresh_hpke_configs(self, session) -> None:
        self.leader_hpke_config = await self._fetch_hpke_config(
            session, self.leader_endpoint
        )
        self.helper_hpke_config = await self._fetch_hpke_config(
            session, self.helper_endpoint
        )

    async def upload(self, measurement, *, time: Optional[Time] = None) -> None:
        """Shard, seal, and PUT the report to the leader
        (reference: client/src/lib.rs:390 upload)."""
        import aiohttp

        async with aiohttp.ClientSession() as session:
            if self.leader_hpke_config is None or self.helper_hpke_config is None:
                await self.refresh_hpke_configs(session)
            report = prepare_report(
                self.vdaf,
                self.task_id,
                self.leader_hpke_config,
                self.helper_hpke_config,
                self.time_precision,
                measurement,
                time=time,
            )
            url = (
                self.leader_endpoint.rstrip("/")
                + f"/tasks/{self.task_id}/reports"
            )
            async with session.put(
                url,
                data=report.get_encoded(),
                headers={"Content-Type": Report.MEDIA_TYPE},
            ) as resp:
                if resp.status not in (200, 201):
                    detail = await resp.text()
                    raise ClientError(f"upload failed: {resp.status} {detail}")
