"""Management REST API.

The analog of the reference's ``aggregator_api`` crate (reference:
aggregator_api/src/lib.rs:71, routes.rs:32-420): task CRUD, per-task upload
metrics, global HPKE config management, and taskprov peer management, under
the versioned content type and bearer-token auth.

Routes (all JSON, content type ``application/vnd.janus.aggregator+json;
version=0.1``):

    GET    /                          — API root/version probe
    GET    /task_ids
    POST   /tasks
    GET    /tasks/:task_id
    DELETE /tasks/:task_id
    PATCH  /tasks/:task_id            — mutable fields (task_expiration)
    GET    /tasks/:task_id/metrics/uploads
    GET    /hpke_configs              — global HPKE keys
    PUT    /hpke_configs              — generate a new key
    PATCH  /hpke_configs/:config_id   — set state
    DELETE /hpke_configs/:config_id
    GET    /taskprov/peer_aggregators — configured taskprov peers
    POST   /taskprov/peer_aggregators — add a peer (insert-only)
    DELETE /taskprov/peer_aggregators — remove a peer (endpoint+role body)
"""

from __future__ import annotations

import json
import logging
import secrets
from typing import Optional

from aiohttp import web

from .core.auth_tokens import AuthenticationToken
from .core.hpke import HpkeKeypair
from .datastore import (
    AggregatorTask,
    Datastore,
    DatastoreError,
    HpkeKeyState,
    TaskNotFound,
    TaskQueryType,
    TxConflict,
    generate_vdaf_verify_key,
    validate_vdaf_instance,
)
from .messages import Duration, HpkeConfig, Role, TaskId, Time

CONTENT_TYPE = "application/vnd.janus.aggregator+json;version=0.1"


from .messages.dap import _b64url as _b64u, _unb64url as _unb64u


def _task_to_json(task: AggregatorTask) -> dict:
    return {
        "task_id": _b64u(task.task_id.data),
        "peer_aggregator_endpoint": task.peer_aggregator_endpoint,
        "query_type": json.loads(task.query_type.to_json()),
        "vdaf": task.vdaf,
        "role": task.role.name.capitalize(),
        "vdaf_verify_key": _b64u(task.vdaf_verify_key),
        "task_expiration": task.task_expiration.seconds
        if task.task_expiration
        else None,
        "report_expiry_age": task.report_expiry_age.seconds
        if task.report_expiry_age
        else None,
        "min_batch_size": task.min_batch_size,
        "time_precision": task.time_precision.seconds,
        "tolerable_clock_skew": task.tolerable_clock_skew.seconds,
        "collector_hpke_config": _b64u(task.collector_hpke_config.get_encoded())
        if task.collector_hpke_config
        else None,
        "aggregator_auth_token": task.aggregator_auth_token.token
        if task.aggregator_auth_token
        else None,
        "hpke_configs": [_b64u(kp.config.get_encoded()) for kp in task.hpke_keys],
    }


def aggregator_api_app(datastore: Datastore, auth_tokens: list) -> web.Application:
    """Build the management API (reference: aggregator_api/src/lib.rs:71
    aggregator_api_handler).  ``auth_tokens``: accepted bearer tokens."""
    hashes = [AuthenticationToken.new_bearer(t).hash() for t in auth_tokens]

    @web.middleware
    async def auth_middleware(request: web.Request, handler):
        auth = request.headers.get("Authorization", "")
        ok = False
        if auth.startswith("Bearer "):
            try:
                presented = AuthenticationToken.new_bearer(auth[len("Bearer ") :])
                ok = any(h.validate(presented) for h in hashes)
            except ValueError:
                ok = False
        if not ok:
            return web.json_response({"error": "unauthorized"}, status=401)
        try:
            return await handler(request)
        except TaskNotFound:
            return web.json_response({"error": "task not found"}, status=404)
        except (KeyError, ValueError) as e:
            return web.json_response({"error": str(e)}, status=400)

    def ok_json(payload, status=200):
        return web.Response(
            status=status, content_type="application/json", text=json.dumps(payload),
            headers={"X-Content-Type-Version": CONTENT_TYPE},
        )

    async def get_root(_request):
        return ok_json({"version": "0.1"})

    async def get_task_ids(_request):
        ids = await datastore.run_tx_async("api_task_ids", lambda tx: tx.get_task_ids())
        return ok_json({"task_ids": [_b64u(t.data) for t in ids]})

    async def post_task(request: web.Request):
        body = await request.json()
        validate_vdaf_instance(body["vdaf"])
        if not body.get("collector_hpke_config"):
            # without it, collection responses can never be sealed
            raise ValueError("collector_hpke_config is required")
        qt = body.get("query_type", {"kind": "TimeInterval"})
        btws = qt.get("batch_time_window_size")
        role = Role[body["role"].upper()]
        vk = (
            _unb64u(body["vdaf_verify_key"])
            if body.get("vdaf_verify_key")
            else generate_vdaf_verify_key(body["vdaf"])
        )
        agg_token = None
        agg_token_hash = None
        if role == Role.LEADER:
            agg_token = AuthenticationToken.new_bearer(
                body.get("aggregator_auth_token") or secrets.token_urlsafe(32)
            )
        else:
            if not body.get("aggregator_auth_token"):
                raise ValueError("helper task requires aggregator_auth_token")
            agg_token_hash = AuthenticationToken.new_bearer(
                body["aggregator_auth_token"]
            ).hash()
        task = AggregatorTask(
            task_id=TaskId(_unb64u(body["task_id"]))
            if body.get("task_id")
            else TaskId.random(),
            peer_aggregator_endpoint=body["peer_aggregator_endpoint"],
            query_type=TaskQueryType(
                qt["kind"],
                qt.get("max_batch_size"),
                Duration(btws) if btws is not None else None,
            ),
            vdaf=body["vdaf"],
            role=role,
            vdaf_verify_key=vk,
            min_batch_size=body["min_batch_size"],
            time_precision=Duration(body["time_precision"]),
            task_expiration=Time(body["task_expiration"])
            if body.get("task_expiration")
            else None,
            report_expiry_age=Duration(body["report_expiry_age"])
            if body.get("report_expiry_age")
            else None,
            aggregator_auth_token=agg_token,
            aggregator_auth_token_hash=agg_token_hash,
            collector_auth_token_hash=AuthenticationToken.new_bearer(
                body["collector_auth_token"]
            ).hash()
            if body.get("collector_auth_token")
            else None,
            collector_hpke_config=HpkeConfig.get_decoded(
                _unb64u(body["collector_hpke_config"])
            )
            if body.get("collector_hpke_config")
            else None,
            hpke_keys=[HpkeKeypair.generate(1)],
        )
        await datastore.run_tx_async(
            "api_post_task", lambda tx: tx.put_aggregator_task(task)
        )
        payload = _task_to_json(task)
        # Provisioning-time device-path check: surface (in the response AND
        # the log) when this VDAF will run on the CPU oracle regardless of a
        # device backend configuration (VERDICT r3 weak #3).  Every task
        # also gets an explicit `device_path` routing label — notably
        # Poplar1, which used to read as a bare "supported" while riding a
        # per-job path outside the executor (ISSUE 10: no silent tier
        # split, in either direction).
        try:
            from .vdaf.backend import device_path_label, device_supported

            vdaf_instance = task.vdaf_instance()
            payload["device_path"] = device_path_label(vdaf_instance)
            ok, reason = device_supported(vdaf_instance)
            if not ok:
                warning = (
                    f"VDAF runs on the CPU oracle, not the device path: {reason}"
                )
                payload["warnings"] = [warning]
                logging.getLogger("janus_tpu.aggregator_api").warning(
                    "task %s: %s", task.task_id, warning
                )
        except Exception:
            # The check must never block provisioning — but a broken check
            # must not be silent either (that would recreate the exact
            # silent tier-split this warning exists to prevent).
            logging.getLogger("janus_tpu.aggregator_api").warning(
                "task %s: device-path capability check failed", task.task_id,
                exc_info=True,
            )
        return ok_json(payload, status=201)

    async def get_task(request: web.Request):
        task_id = TaskId(_unb64u(request.match_info["task_id"]))
        task = await datastore.run_tx_async(
            "api_get_task", lambda tx: tx.get_aggregator_task(task_id)
        )
        if task is None:
            return web.json_response({"error": "task not found"}, status=404)
        return ok_json(_task_to_json(task))

    async def delete_task(request: web.Request):
        task_id = TaskId(_unb64u(request.match_info["task_id"]))
        await datastore.run_tx_async(
            "api_delete_task", lambda tx: tx.delete_task(task_id)
        )
        return web.Response(status=204)

    async def patch_task(request: web.Request):
        task_id = TaskId(_unb64u(request.match_info["task_id"]))
        body = await request.json()
        existing = await datastore.run_tx_async(
            "api_get_task", lambda tx: tx.get_aggregator_task(task_id)
        )
        if existing is None:
            return web.json_response({"error": "task not found"}, status=404)
        if "task_expiration" in body:
            exp = body["task_expiration"]
            await datastore.run_tx_async(
                "api_patch_task",
                lambda tx: tx.update_task_expiration(
                    task_id, Time(exp) if exp is not None else None
                ),
            )
        task = await datastore.run_tx_async(
            "api_get_task", lambda tx: tx.get_aggregator_task(task_id)
        )
        return ok_json(_task_to_json(task))

    async def get_upload_metrics(request: web.Request):
        task_id = TaskId(_unb64u(request.match_info["task_id"]))
        counter = await datastore.run_tx_async(
            "api_metrics", lambda tx: tx.get_task_upload_counter(task_id)
        )
        return ok_json(
            {c: getattr(counter, c) for c in counter.COLUMNS}
        )

    async def get_hpke_configs(_request):
        keypairs = await datastore.run_tx_async(
            "api_hpke", lambda tx: tx.get_global_hpke_keypairs()
        )
        return ok_json(
            [
                {
                    "config": _b64u(kp.config.get_encoded()),
                    "id": kp.config.id,
                    "state": kp.state.value,
                }
                for kp in keypairs
            ]
        )

    async def put_hpke_config(request: web.Request):
        body = await request.json() if request.can_read_body else {}
        config_id = body.get("id")
        if config_id is not None and (
            not isinstance(config_id, int) or not 0 <= config_id <= 255
        ):
            raise ValueError("id must be an integer in [0, 255]")

        # pick-and-insert in ONE transaction so concurrent PUTs cannot race
        def tx_fn(tx):
            used = {kp.config.id for kp in tx.get_global_hpke_keypairs()}
            cid = config_id
            if cid is None:
                free = [i for i in range(256) if i not in used]
                if not free:
                    raise ValueError("all 256 HPKE config ids are in use")
                cid = free[0]
            elif cid in used:
                raise TxConflict(f"HPKE config id {cid} already exists")
            kp = HpkeKeypair.generate(cid)
            tx.put_global_hpke_keypair(kp)
            return kp, cid

        try:
            kp, cid = await datastore.run_tx_async("api_hpke_put", tx_fn)
        except TxConflict as e:
            return web.json_response({"error": str(e)}, status=409)
        return ok_json(
            {"config": _b64u(kp.config.get_encoded()), "id": cid}, status=201
        )

    async def patch_hpke_config(request: web.Request):
        config_id = int(request.match_info["config_id"])
        body = await request.json()
        state = HpkeKeyState(body["state"])
        await datastore.run_tx_async(
            "api_hpke_patch",
            lambda tx: tx.set_global_hpke_keypair_state(config_id, state),
        )
        return web.Response(status=200)

    async def delete_hpke_config(request: web.Request):
        config_id = int(request.match_info["config_id"])
        await datastore.run_tx_async(
            "api_hpke_delete", lambda tx: tx.delete_global_hpke_keypair(config_id)
        )
        return web.Response(status=204)

    # -- taskprov peer aggregators (reference: routes.rs:401-467) --------
    def _peer_to_json(peer) -> dict:
        # Secrets (verify_key_init, auth tokens) never leave the API —
        # matching the reference's PeerAggregator resource shape.
        return {
            "endpoint": peer.endpoint,
            "role": peer.role.name.capitalize(),
            "collector_hpke_config": _b64u(peer.collector_hpke_config.get_encoded()),
            "report_expiry_age": peer.report_expiry_age.seconds
            if peer.report_expiry_age
            else None,
            "tolerable_clock_skew": peer.tolerable_clock_skew.seconds,
        }

    async def get_taskprov_peers(_request):
        peers = await datastore.run_tx_async(
            "api_get_taskprov_peers", lambda tx: tx.get_taskprov_peer_aggregators()
        )
        return ok_json([_peer_to_json(p) for p in peers])

    async def post_taskprov_peer(request: web.Request):
        from .aggregator.taskprov import PeerAggregator

        try:
            body = await request.json()
            role = Role[body["peer_role"].upper()]
            if role not in (Role.LEADER, Role.HELPER):
                # Matching the reference routes: a peer AGGREGATOR is one of
                # the two aggregator roles; anything else would store an
                # unusable peer and silently drop its auth token.
                raise ValueError("peer_role must be Leader or Helper")
            vk_init = _unb64u(body["verify_key_init"])
            peer = _build_peer(PeerAggregator, body, role, vk_init)
        except (KeyError, ValueError) as e:
            return web.json_response({"error": str(e)}, status=400)
        try:
            await datastore.run_tx_async(
                "api_post_taskprov_peer", lambda tx: tx.put_taskprov_peer_aggregator(peer)
            )
        except TxConflict as e:
            # insert-only, as in the reference (routes.rs:416-421): delete
            # then re-create to change an existing peer.
            return web.json_response({"error": str(e)}, status=409)
        return ok_json(_peer_to_json(peer), status=201)

    def _build_peer(PeerAggregator, body, role, vk_init):
        return PeerAggregator(
            endpoint=body["endpoint"],
            role=role,
            verify_key_init=vk_init,
            collector_hpke_config=HpkeConfig.get_decoded(
                _unb64u(body["collector_hpke_config"])
            ),
            report_expiry_age=Duration(body["report_expiry_age"])
            if body.get("report_expiry_age")
            else None,
            tolerable_clock_skew=Duration(body.get("tolerable_clock_skew", 60)),
            # If WE are the leader for this peer we hold the token; as the
            # helper we hold its hash (reference: taskprov.rs:97).
            aggregator_auth_token=AuthenticationToken.new_bearer(
                body["aggregator_auth_token"]
            )
            if role == Role.HELPER and body.get("aggregator_auth_token")
            else None,
            aggregator_auth_token_hash=AuthenticationToken.new_bearer(
                body["aggregator_auth_token"]
            ).hash()
            if role == Role.LEADER and body.get("aggregator_auth_token")
            else None,
            collector_auth_token_hash=AuthenticationToken.new_bearer(
                body["collector_auth_token"]
            ).hash()
            if body.get("collector_auth_token")
            else None,
        )

    async def delete_taskprov_peer(request: web.Request):
        try:
            body = await request.json()
            role = Role[body["peer_role"].upper()]
            endpoint = body["endpoint"]
        except (KeyError, ValueError) as e:
            return web.json_response({"error": str(e)}, status=400)

        def tx_fn(tx):
            tx.delete_taskprov_peer_aggregator(endpoint, role)

        try:
            await datastore.run_tx_async("api_delete_taskprov_peer", tx_fn)
        except DatastoreError:
            return web.Response(status=404)
        return web.Response(status=204)

    app = web.Application(middlewares=[auth_middleware])
    app.add_routes(
        [
            web.get("/", get_root),
            web.get("/task_ids", get_task_ids),
            web.post("/tasks", post_task),
            web.get("/tasks/{task_id}", get_task),
            web.delete("/tasks/{task_id}", delete_task),
            web.patch("/tasks/{task_id}", patch_task),
            web.get("/tasks/{task_id}/metrics/uploads", get_upload_metrics),
            web.get("/hpke_configs", get_hpke_configs),
            web.put("/hpke_configs", put_hpke_config),
            web.patch("/hpke_configs/{config_id}", patch_hpke_config),
            web.delete("/hpke_configs/{config_id}", delete_hpke_config),
            web.get("/taskprov/peer_aggregators", get_taskprov_peers),
            web.post("/taskprov/peer_aggregators", post_taskprov_peer),
            web.delete("/taskprov/peer_aggregators", delete_taskprov_peer),
        ]
    )
    return app
