"""Finite fields used by Prio3, bit-exact CPU oracle.

Mirrors the field parameters of the ``prio`` crate (libprio-rs v0.16.2) consumed
by the reference (reference: core/src/vdaf.rs:65-108 names the VDAFs; the fields
themselves are defined by draft-irtf-cfrg-vdaf-08 §6.1):

* ``Field64``  — p = 2^32 * 4294967295 + 1 = 2^64 - 2^32 + 1   ("Goldilocks")
* ``Field128`` — p = 2^66 * 4611686018427387897 + 1

Elements are represented as plain Python ints in ``[0, p)``; vectors as lists of
ints.  This module is the correctness oracle for the TPU kernels in
``janus_tpu.ops`` — every device kernel must agree with it bit-for-bit.

Wire encoding is little-endian fixed-width per element (draft-irtf-cfrg-vdaf-08
§6.1: Field.encode_vec / decode_vec), matching the TLS-syntax opaque encoding the
DAP messages embed (reference: messages/src/lib.rs:11-17 uses prio::codec).
"""

from __future__ import annotations

from typing import List, Sequence


def next_power_of_2(n: int) -> int:
    if n <= 0:
        raise ValueError("n must be positive")
    return 1 << (n - 1).bit_length()


class Field:
    """A prime field with high 2-adicity. Subclasses set the parameters."""

    MODULUS: int
    ENCODED_SIZE: int  # bytes per element, little-endian
    NUM_ROOTS: int  # 2-adicity: 2^NUM_ROOTS divides p-1
    GEN_BASE: int = 7  # multiplicative generator base (as in the VDAF spec tables)

    # --- scalar ops -------------------------------------------------------
    @classmethod
    def add(cls, a: int, b: int) -> int:
        return (a + b) % cls.MODULUS

    @classmethod
    def sub(cls, a: int, b: int) -> int:
        return (a - b) % cls.MODULUS

    @classmethod
    def mul(cls, a: int, b: int) -> int:
        return (a * b) % cls.MODULUS

    @classmethod
    def neg(cls, a: int) -> int:
        return (-a) % cls.MODULUS

    @classmethod
    def inv(cls, a: int) -> int:
        if a % cls.MODULUS == 0:
            raise ZeroDivisionError("field inverse of zero")
        return pow(a, cls.MODULUS - 2, cls.MODULUS)

    # --- vector ops -------------------------------------------------------
    @classmethod
    def vec_add(cls, a: Sequence[int], b: Sequence[int]) -> List[int]:
        if len(a) != len(b):
            raise ValueError("vector length mismatch")
        p = cls.MODULUS
        return [(x + y) % p for x, y in zip(a, b)]

    @classmethod
    def vec_sub(cls, a: Sequence[int], b: Sequence[int]) -> List[int]:
        if len(a) != len(b):
            raise ValueError("vector length mismatch")
        p = cls.MODULUS
        return [(x - y) % p for x, y in zip(a, b)]

    # --- roots of unity ---------------------------------------------------
    @classmethod
    def gen(cls) -> int:
        """Generator of the subgroup of order 2^NUM_ROOTS (= GEN_ORDER)."""
        return cls._GEN

    @classmethod
    def gen_order(cls) -> int:
        return 1 << cls.NUM_ROOTS

    @classmethod
    def root(cls, order: int) -> int:
        """Principal root of unity of the given power-of-two order."""
        if order & (order - 1):
            raise ValueError("order must be a power of two")
        if order > cls.gen_order():
            raise ValueError("order exceeds field 2-adicity")
        return pow(cls._GEN, cls.gen_order() // order, cls.MODULUS)

    # --- codec ------------------------------------------------------------
    @classmethod
    def encode_elem(cls, x: int) -> bytes:
        return int(x).to_bytes(cls.ENCODED_SIZE, "little")

    @classmethod
    def decode_elem(cls, data: bytes) -> int:
        if len(data) != cls.ENCODED_SIZE:
            raise ValueError("wrong length for field element")
        x = int.from_bytes(data, "little")
        if x >= cls.MODULUS:
            raise ValueError("field element out of range")
        return x

    @classmethod
    def encode_vec(cls, vec: Sequence[int]) -> bytes:
        return b"".join(cls.encode_elem(x) for x in vec)

    @classmethod
    def decode_vec(cls, data: bytes) -> List[int]:
        n = cls.ENCODED_SIZE
        if len(data) % n:
            raise ValueError("encoded vector length not a multiple of element size")
        out = []
        for i in range(0, len(data), n):
            out.append(cls.decode_elem(data[i : i + n]))
        return out


class Field64(Field):
    MODULUS = 2**32 * 4294967295 + 1  # = 2^64 - 2^32 + 1
    ENCODED_SIZE = 8
    NUM_ROOTS = 32


class Field128(Field):
    MODULUS = 2**66 * 4611686018427387897 + 1  # = 2^128 - 7*2^66 + 1
    ENCODED_SIZE = 16
    NUM_ROOTS = 66


class Field255(Field):
    """GF(2^255 - 19): the Poplar1 leaf field (VDAF spec field table).

    No NTT support (NUM_ROOTS unset): Poplar1 does no polynomial work, only
    additive sharing and sketch algebra.
    """

    MODULUS = 2**255 - 19
    ENCODED_SIZE = 32


def _init_field(cls: type) -> None:
    p = cls.MODULUS
    # explicit raises: these import-time invariants must hold even under -O
    if (p - 1) % (1 << cls.NUM_ROOTS) != 0:
        raise AssertionError(f"{cls.__name__}: 2-adicity does not divide p-1")
    g = pow(cls.GEN_BASE, (p - 1) >> cls.NUM_ROOTS, p)
    # g must have order exactly 2^NUM_ROOTS.
    if pow(g, 1 << cls.NUM_ROOTS, p) != 1 or pow(g, 1 << (cls.NUM_ROOTS - 1), p) == 1:
        raise AssertionError(f"{cls.__name__}: generator order check failed")
    cls._GEN = g


_init_field(Field64)
_init_field(Field128)


# ---------------------------------------------------------------------------
# Polynomial helpers over a field (coefficient vectors, low-order first).
# Used by the FLP proof system (janus_tpu.flp.generic).
# ---------------------------------------------------------------------------

def poly_eval(field: type, coeffs: Sequence[int], x: int) -> int:
    """Horner evaluation of the polynomial at x."""
    p = field.MODULUS
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % p
    return acc


def poly_mul(field: type, a: Sequence[int], b: Sequence[int]) -> List[int]:
    """Naive convolution; fine for the small polynomials in FLP proofs."""
    p = field.MODULUS
    if not a or not b:
        return []
    out = [0] * (len(a) + len(b) - 1)
    for i, x in enumerate(a):
        if x == 0:
            continue
        for j, y in enumerate(b):
            out[i + j] = (out[i + j] + x * y) % p
    return out


def poly_add(field: type, a: Sequence[int], b: Sequence[int]) -> List[int]:
    p = field.MODULUS
    n = max(len(a), len(b))
    out = [0] * n
    for i, x in enumerate(a):
        out[i] = x
    for i, y in enumerate(b):
        out[i] = (out[i] + y) % p
    return out


def ntt(field: type, values: Sequence[int], inverse: bool = False) -> List[int]:
    """Radix-2 NTT of power-of-two size n over the field.

    Forward maps coefficients c to evaluations at w^k (w = principal n-th root,
    k in NTT order 0..n-1); inverse maps evaluations back to coefficients.
    """
    n = len(values)
    if n & (n - 1):
        raise ValueError("NTT size must be a power of two")
    p = field.MODULUS
    a = list(values)
    if n == 1:
        return a
    w = field.root(n)
    if inverse:
        w = pow(w, p - 2, p)
    # bit-reversal permutation
    j = 0
    for i in range(1, n):
        bit = n >> 1
        while j & bit:
            j ^= bit
            bit >>= 1
        j |= bit
        if i < j:
            a[i], a[j] = a[j], a[i]
    length = 2
    while length <= n:
        wl = pow(w, n // length, p)
        half = length // 2
        for start in range(0, n, length):
            wn = 1
            for k in range(start, start + half):
                u = a[k]
                v = a[k + half] * wn % p
                a[k] = (u + v) % p
                a[k + half] = (u - v) % p
                wn = wn * wl % p
        length <<= 1
    if inverse:
        n_inv = pow(n, p - 2, p)
        a = [x * n_inv % p for x in a]
    return a


def poly_interp(field: type, values: Sequence[int]) -> List[int]:
    """Interpolate the polynomial with value values[k] at w^k (w of order n).

    n = len(values) must be a power of two.  Returns n coefficients.
    """
    return ntt(field, values, inverse=True)
