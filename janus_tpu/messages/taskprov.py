"""Taskprov extension messages (draft-wang-ppm-dap-taskprov), byte-compatible
with the reference (reference: messages/src/taskprov.rs)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import ClassVar, Optional

from .codec import CodecError, Decoder, Encoder, Message
from .dap import Duration, Time, Url


@dataclass(frozen=True)
class DpMechanism(Message):
    """reference: messages/src/taskprov.rs:514"""

    RESERVED: ClassVar[int] = 0
    NONE: ClassVar[int] = 1

    codepoint: int
    payload: bytes = b""

    @classmethod
    def none(cls) -> "DpMechanism":
        return cls(cls.NONE)

    def encode(self, w: Encoder) -> None:
        w.u8(self.codepoint)
        w.write(self.payload)

    @classmethod
    def _decode(cls, r: Decoder) -> "DpMechanism":
        codepoint = r.u8()
        if codepoint in (cls.RESERVED, cls.NONE):
            return cls(codepoint)
        # Unrecognized mechanisms swallow the remaining payload.
        return cls(codepoint, r.read(r.remaining()))


@dataclass(frozen=True)
class DpConfig(Message):
    """reference: messages/src/taskprov.rs:479"""

    dp_mechanism: DpMechanism

    def encode(self, w: Encoder) -> None:
        self.dp_mechanism.encode(w)

    @classmethod
    def _decode(cls, r: Decoder) -> "DpConfig":
        return cls(DpMechanism._decode(r))


@dataclass(frozen=True)
class VdafType(Message):
    """Tagged VDAF descriptor; codes match the reference and the VDAF spec
    (reference: messages/src/taskprov.rs:321-433)."""

    PRIO3COUNT: ClassVar[int] = 0x00000000
    PRIO3SUM: ClassVar[int] = 0x00000001
    PRIO3SUMVEC: ClassVar[int] = 0x00000002
    PRIO3HISTOGRAM: ClassVar[int] = 0x00000003
    PRIO3SUMVECFIELD64MULTIPROOFHMACSHA256AES128: ClassVar[int] = 0xFFFF1003
    POPLAR1: ClassVar[int] = 0x00001000

    code: int
    bits: Optional[int] = None
    length: Optional[int] = None
    chunk_length: Optional[int] = None
    proofs: Optional[int] = None

    def encode(self, w: Encoder) -> None:
        w.u32(self.code)
        if self.code == self.PRIO3COUNT:
            pass
        elif self.code == self.PRIO3SUM:
            w.u8(self.bits)
        elif self.code == self.PRIO3SUMVEC:
            w.u32(self.length)
            w.u8(self.bits)
            w.u32(self.chunk_length)
        elif self.code == self.PRIO3SUMVECFIELD64MULTIPROOFHMACSHA256AES128:
            w.u32(self.length)
            w.u8(self.bits)
            w.u32(self.chunk_length)
            w.u8(self.proofs)
        elif self.code == self.PRIO3HISTOGRAM:
            w.u32(self.length)
            w.u32(self.chunk_length)
        elif self.code == self.POPLAR1:
            w.u16(self.bits)
        else:
            raise CodecError(f"unknown VdafType code {self.code:#x}")

    @classmethod
    def _decode(cls, r: Decoder) -> "VdafType":
        code = r.u32()
        if code == cls.PRIO3COUNT:
            return cls(code)
        if code == cls.PRIO3SUM:
            return cls(code, bits=r.u8())
        if code == cls.PRIO3SUMVEC:
            return cls(code, length=r.u32(), bits=r.u8(), chunk_length=r.u32())
        if code == cls.PRIO3SUMVECFIELD64MULTIPROOFHMACSHA256AES128:
            return cls(
                code, length=r.u32(), bits=r.u8(), chunk_length=r.u32(), proofs=r.u8()
            )
        if code == cls.PRIO3HISTOGRAM:
            return cls(code, length=r.u32(), chunk_length=r.u32())
        if code == cls.POPLAR1:
            return cls(code, bits=r.u16())
        raise CodecError(f"unknown VdafType code {code:#x}")

    def to_instance(self) -> dict:
        """Serialized VdafInstance description (janus_tpu.vdaf.instances)."""
        if self.code == self.PRIO3COUNT:
            return {"type": "Prio3Count"}
        if self.code == self.PRIO3SUM:
            return {"type": "Prio3Sum", "bits": self.bits}
        if self.code == self.PRIO3SUMVEC:
            return {
                "type": "Prio3SumVec",
                "length": self.length,
                "bits": self.bits,
                "chunk_length": self.chunk_length,
            }
        if self.code == self.PRIO3SUMVECFIELD64MULTIPROOFHMACSHA256AES128:
            return {
                "type": "Prio3SumVecField64MultiproofHmacSha256Aes128",
                "length": self.length,
                "bits": self.bits,
                "chunk_length": self.chunk_length,
                "proofs": self.proofs,
            }
        if self.code == self.PRIO3HISTOGRAM:
            return {
                "type": "Prio3Histogram",
                "length": self.length,
                "chunk_length": self.chunk_length,
            }
        if self.code == self.POPLAR1:
            return {"type": "Poplar1", "bits": self.bits}
        raise CodecError(f"unknown VdafType code {self.code:#x}")


@dataclass(frozen=True)
class VdafConfig(Message):
    """reference: messages/src/taskprov.rs:272"""

    dp_config: DpConfig
    vdaf_type: VdafType

    def encode(self, w: Encoder) -> None:
        w.opaque_u16(self.dp_config.get_encoded())
        self.vdaf_type.encode(w)

    @classmethod
    def _decode(cls, r: Decoder) -> "VdafConfig":
        dp_config = DpConfig.get_decoded(r.opaque_u16())
        return cls(dp_config, VdafType._decode(r))


@dataclass(frozen=True)
class TaskprovQuery(Message):
    """reference: messages/src/taskprov.rs:219"""

    RESERVED: ClassVar[int] = 0
    TIME_INTERVAL: ClassVar[int] = 1
    FIXED_SIZE: ClassVar[int] = 2

    variant: int
    max_batch_size: Optional[int] = None

    @classmethod
    def time_interval(cls) -> "TaskprovQuery":
        return cls(cls.TIME_INTERVAL)

    @classmethod
    def fixed_size(cls, max_batch_size: int) -> "TaskprovQuery":
        return cls(cls.FIXED_SIZE, max_batch_size)

    def encode(self, w: Encoder) -> None:
        w.u8(self.variant)
        if self.variant == self.FIXED_SIZE:
            w.u32(self.max_batch_size)

    @classmethod
    def _decode(cls, r: Decoder) -> "TaskprovQuery":
        variant = r.u8()
        if variant == cls.FIXED_SIZE:
            return cls(variant, r.u32())
        if variant in (cls.RESERVED, cls.TIME_INTERVAL):
            return cls(variant)
        raise CodecError(f"unexpected taskprov query type {variant}")


@dataclass(frozen=True)
class QueryConfig(Message):
    """reference: messages/src/taskprov.rs:133"""

    time_precision: Duration
    max_batch_query_count: int
    min_batch_size: int
    query: TaskprovQuery

    def encode(self, w: Encoder) -> None:
        self.time_precision.encode(w)
        w.u16(self.max_batch_query_count)
        w.u32(self.min_batch_size)
        self.query.encode(w)

    @classmethod
    def _decode(cls, r: Decoder) -> "QueryConfig":
        return cls(Duration._decode(r), r.u16(), r.u32(), TaskprovQuery._decode(r))


@dataclass(frozen=True)
class TaskConfig(Message):
    """reference: messages/src/taskprov.rs:17"""

    task_info: bytes
    leader_aggregator_endpoint: Url
    helper_aggregator_endpoint: Url
    query_config: QueryConfig
    task_expiration: Time
    vdaf_config: VdafConfig

    def encode(self, w: Encoder) -> None:
        w.u8(len(self.task_info))
        w.write(self.task_info)
        self.leader_aggregator_endpoint.encode(w)
        self.helper_aggregator_endpoint.encode(w)
        w.opaque_u16(self.query_config.get_encoded())
        self.task_expiration.encode(w)
        w.opaque_u16(self.vdaf_config.get_encoded())

    @classmethod
    def _decode(cls, r: Decoder) -> "TaskConfig":
        task_info = r.read(r.u8())
        leader = Url._decode(r)
        helper = Url._decode(r)
        query_config = QueryConfig.get_decoded(r.opaque_u16())
        task_expiration = Time._decode(r)
        vdaf_config = VdafConfig.get_decoded(r.opaque_u16())
        return cls(task_info, leader, helper, query_config, task_expiration, vdaf_config)
