"""DAP wire messages (draft-ietf-ppm-dap-09), byte-compatible with the
reference's ``janus_messages`` crate (reference: messages/src/lib.rs).

Every type carries its reference location in the docstring so parity can be
checked; encodings are anchored to the reference's own test hex in
tests/test_messages.py.  Fixed-size IDs are raw bytes; varying payloads are
u16/u32 length-prefixed per TLS syntax.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from enum import IntEnum
from typing import ClassVar, List, Optional, Type, Union

from ..vdaf.pingpong import PingPongMessage
from .codec import CodecError, Decoder, Encoder, Message


def _b64url(data: bytes) -> str:
    import base64

    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64url(s: str) -> bytes:
    import base64

    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


class _FixedId(Message):
    """Fixed-length opaque identifier (TaskId, ReportId, BatchId, ...)."""

    LEN: ClassVar[int]

    def __init__(self, data: bytes):
        if len(data) != self.LEN:
            raise ValueError(f"{type(self).__name__} must be {self.LEN} bytes")
        self._data = bytes(data)

    @classmethod
    def random(cls):
        return cls(os.urandom(cls.LEN))

    @property
    def data(self) -> bytes:
        return self._data

    def encode(self, w: Encoder) -> None:
        w.fixed(self._data, self.LEN)

    @classmethod
    def _decode(cls, r: Decoder):
        return cls(r.read(cls.LEN))

    @classmethod
    def from_str(cls, s: str):
        return cls(_unb64url(s))

    def __str__(self) -> str:
        return _b64url(self._data)

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self})"

    def __eq__(self, other) -> bool:
        return type(self) is type(other) and self._data == other._data

    def __hash__(self) -> int:
        return hash((type(self).__name__, self._data))

    def __lt__(self, other) -> bool:
        return self._data < other._data


class TaskId(_FixedId):
    """reference: messages/src/lib.rs:640"""

    LEN = 32


class BatchId(_FixedId):
    """reference: messages/src/lib.rs:286"""

    LEN = 32


class ReportId(_FixedId):
    """reference: messages/src/lib.rs:366"""

    LEN = 16


class ReportIdChecksum(_FixedId):
    """XOR-of-SHA256 checksum; reference: messages/src/lib.rs:446"""

    LEN = 32

    @classmethod
    def zero(cls) -> "ReportIdChecksum":
        return cls(bytes(cls.LEN))


class AggregationJobId(_FixedId):
    """reference: messages/src/lib.rs:2266"""

    LEN = 16


class CollectionJobId(_FixedId):
    """reference: messages/src/lib.rs:1674"""

    LEN = 16


class Duration(Message):
    """Seconds; u64 BE. reference: messages/src/lib.rs:132"""

    def __init__(self, seconds: int):
        self.seconds = int(seconds)

    ZERO: ClassVar["Duration"]

    @classmethod
    def from_seconds(cls, s: int) -> "Duration":
        return cls(s)

    def encode(self, w: Encoder) -> None:
        w.u64(self.seconds)

    @classmethod
    def _decode(cls, r: Decoder) -> "Duration":
        return cls(r.u64())

    def __eq__(self, o) -> bool:
        return isinstance(o, Duration) and self.seconds == o.seconds

    def __hash__(self):
        return hash(("Duration", self.seconds))

    def __repr__(self):
        return f"Duration({self.seconds})"


Duration.ZERO = Duration(0)


class Time(Message):
    """Seconds since epoch; u64 BE. reference: messages/src/lib.rs:172"""

    def __init__(self, seconds: int):
        self.seconds = int(seconds)

    def encode(self, w: Encoder) -> None:
        w.u64(self.seconds)

    @classmethod
    def _decode(cls, r: Decoder) -> "Time":
        return cls(r.u64())

    def __eq__(self, o) -> bool:
        return isinstance(o, Time) and self.seconds == o.seconds

    def __lt__(self, o) -> bool:
        return self.seconds < o.seconds

    def __le__(self, o) -> bool:
        return self.seconds <= o.seconds

    def __hash__(self):
        return hash(("Time", self.seconds))

    def __repr__(self):
        return f"Time({self.seconds})"


@dataclass(frozen=True)
class Interval(Message):
    """Half-open [start, start+duration). reference: messages/src/lib.rs:223"""

    start: Time
    duration: Duration

    def __post_init__(self):
        if self.start.seconds + self.duration.seconds >= 1 << 64:
            raise ValueError("interval end overflows Time")

    EMPTY: ClassVar["Interval"]

    def end(self) -> Time:
        return Time(self.start.seconds + self.duration.seconds)

    def contains(self, t: Time) -> bool:
        return self.start.seconds <= t.seconds < self.end().seconds

    def encode(self, w: Encoder) -> None:
        self.start.encode(w)
        self.duration.encode(w)

    @classmethod
    def _decode(cls, r: Decoder) -> "Interval":
        return cls(Time._decode(r), Duration._decode(r))


Interval.EMPTY = Interval(Time(0), Duration.ZERO)


class Url(Message):
    """u16-length-prefixed ASCII URL. reference: messages/src/lib.rs:56"""

    MAX_LEN = 2**16 - 1

    def __init__(self, url: Union[str, bytes]):
        raw = url.encode("ascii") if isinstance(url, str) else bytes(url)
        if not raw or len(raw) > self.MAX_LEN:
            raise ValueError("bad URL length")
        raw.decode("ascii")  # must be ASCII
        self.raw = raw

    def __str__(self) -> str:
        return self.raw.decode("ascii")

    def encode(self, w: Encoder) -> None:
        w.opaque_u16(self.raw)

    @classmethod
    def _decode(cls, r: Decoder) -> "Url":
        try:
            return cls(r.opaque_u16())
        except (ValueError, UnicodeDecodeError) as e:
            raise CodecError(f"bad URL: {e}")

    def __eq__(self, o):
        return isinstance(o, Url) and self.raw == o.raw

    def __hash__(self):
        return hash(("Url", self.raw))

    def __repr__(self):
        return f"Url({self})"


class Role(IntEnum):
    """reference: messages/src/lib.rs:516"""

    COLLECTOR = 0
    CLIENT = 1
    LEADER = 2
    HELPER = 3

    def is_aggregator(self) -> bool:
        return self in (Role.LEADER, Role.HELPER)

    def index(self) -> Optional[int]:
        return {Role.LEADER: 0, Role.HELPER: 1}.get(self)

    def encode(self, w: Encoder) -> None:
        w.u8(self.value)

    @classmethod
    def _decode(cls, r: Decoder) -> "Role":
        try:
            return cls(r.u8())
        except ValueError as e:
            raise CodecError(str(e))


# HPKE config ids are plain u8 ints on the wire (reference newtype:
# messages/src/lib.rs:596); the alias keeps the reference name importable.
HpkeConfigId = int


class HpkeKemId(IntEnum):
    """RFC 9180 KEM ids; reference: messages/src/lib.rs:770"""

    RESERVED = 0x0000
    P256_HKDF_SHA256 = 0x0010
    P384_HKDF_SHA384 = 0x0011
    P521_HKDF_SHA512 = 0x0012
    X25519_HKDF_SHA256 = 0x0020

    def encode(self, w: Encoder) -> None:
        w.u16(self.value)

    @classmethod
    def _decode(cls, r: Decoder) -> "HpkeKemId":
        val = r.u16()
        try:
            return cls(val)
        except ValueError:
            raise CodecError(f"unknown HPKE KEM id {val:#06x}")


class HpkeKdfId(IntEnum):
    """reference: messages/src/lib.rs:809"""

    RESERVED = 0x0000
    HKDF_SHA256 = 0x0001
    HKDF_SHA384 = 0x0002
    HKDF_SHA512 = 0x0003

    def encode(self, w: Encoder) -> None:
        w.u16(self.value)

    @classmethod
    def _decode(cls, r: Decoder) -> "HpkeKdfId":
        val = r.u16()
        try:
            return cls(val)
        except ValueError:
            raise CodecError(f"unknown HPKE KDF id {val:#06x}")


class HpkeAeadId(IntEnum):
    """reference: messages/src/lib.rs:844"""

    RESERVED = 0x0000
    AES_128_GCM = 0x0001
    AES_256_GCM = 0x0002
    CHACHA20_POLY1305 = 0x0003

    def encode(self, w: Encoder) -> None:
        w.u16(self.value)

    @classmethod
    def _decode(cls, r: Decoder) -> "HpkeAeadId":
        val = r.u16()
        try:
            return cls(val)
        except ValueError:
            raise CodecError(f"unknown HPKE AEAD id {val:#06x}")


class ExtensionType(IntEnum):
    """reference: messages/src/lib.rs:928"""

    TBD = 0
    TASKPROV = 0xFF00


@dataclass(frozen=True)
class Extension(Message):
    """reference: messages/src/lib.rs:875"""

    extension_type: ExtensionType
    extension_data: bytes = b""

    def encode(self, w: Encoder) -> None:
        w.u16(self.extension_type.value)
        w.opaque_u16(self.extension_data)

    @classmethod
    def _decode(cls, r: Decoder) -> "Extension":
        try:
            ext_type = ExtensionType(r.u16())
        except ValueError as e:
            raise CodecError(str(e))
        return cls(ext_type, r.opaque_u16())


@dataclass(frozen=True)
class HpkeCiphertext(Message):
    """reference: messages/src/lib.rs:955"""

    config_id: int
    encapsulated_key: bytes
    payload: bytes

    def encode(self, w: Encoder) -> None:
        w.u8(self.config_id)
        w.opaque_u16(self.encapsulated_key)
        w.opaque_u32(self.payload)

    @classmethod
    def _decode(cls, r: Decoder) -> "HpkeCiphertext":
        return cls(r.u8(), r.opaque_u16(), r.opaque_u32())


@dataclass(frozen=True)
class HpkePublicKey(Message):
    """reference: messages/src/lib.rs:1031"""

    raw: bytes

    def encode(self, w: Encoder) -> None:
        w.opaque_u16(self.raw)

    @classmethod
    def _decode(cls, r: Decoder) -> "HpkePublicKey":
        return cls(r.opaque_u16())


@dataclass(frozen=True)
class HpkeConfig(Message):
    """reference: messages/src/lib.rs:1127"""

    MEDIA_TYPE: ClassVar[str] = "application/dap-hpke-config"

    id: int
    kem_id: HpkeKemId
    kdf_id: HpkeKdfId
    aead_id: HpkeAeadId
    public_key: HpkePublicKey

    def encode(self, w: Encoder) -> None:
        w.u8(self.id)
        self.kem_id.encode(w)
        self.kdf_id.encode(w)
        self.aead_id.encode(w)
        self.public_key.encode(w)

    @classmethod
    def _decode(cls, r: Decoder) -> "HpkeConfig":
        return cls(
            r.u8(),
            HpkeKemId._decode(r),
            HpkeKdfId._decode(r),
            HpkeAeadId._decode(r),
            HpkePublicKey._decode(r),
        )


@dataclass(frozen=True)
class HpkeConfigList(Message):
    """reference: messages/src/lib.rs:1219"""

    MEDIA_TYPE: ClassVar[str] = "application/dap-hpke-config-list"

    hpke_configs: tuple

    def __init__(self, hpke_configs):
        object.__setattr__(self, "hpke_configs", tuple(hpke_configs))

    def encode(self, w: Encoder) -> None:
        w.items_u16(self.hpke_configs, lambda ww, c: c.encode(ww))

    @classmethod
    def _decode(cls, r: Decoder) -> "HpkeConfigList":
        return cls(r.items_u16(HpkeConfig._decode))


@dataclass(frozen=True)
class ReportMetadata(Message):
    """reference: messages/src/lib.rs:1257"""

    report_id: ReportId
    time: Time

    def encode(self, w: Encoder) -> None:
        self.report_id.encode(w)
        self.time.encode(w)

    @classmethod
    def _decode(cls, r: Decoder) -> "ReportMetadata":
        return cls(ReportId._decode(r), Time._decode(r))


@dataclass(frozen=True)
class PlaintextInputShare(Message):
    """reference: messages/src/lib.rs:1301"""

    extensions: tuple
    payload: bytes

    def __init__(self, extensions, payload: bytes):
        object.__setattr__(self, "extensions", tuple(extensions))
        object.__setattr__(self, "payload", bytes(payload))

    def encode(self, w: Encoder) -> None:
        w.items_u16(self.extensions, lambda ww, e: e.encode(ww))
        w.opaque_u32(self.payload)

    @classmethod
    def _decode(cls, r: Decoder) -> "PlaintextInputShare":
        return cls(r.items_u16(Extension._decode), r.opaque_u32())


@dataclass(frozen=True)
class Report(Message):
    """reference: messages/src/lib.rs:1357"""

    MEDIA_TYPE: ClassVar[str] = "application/dap-report"

    metadata: ReportMetadata
    public_share: bytes
    leader_encrypted_input_share: HpkeCiphertext
    helper_encrypted_input_share: HpkeCiphertext

    def encode(self, w: Encoder) -> None:
        self.metadata.encode(w)
        w.opaque_u32(self.public_share)
        self.leader_encrypted_input_share.encode(w)
        self.helper_encrypted_input_share.encode(w)

    @classmethod
    def _decode(cls, r: Decoder) -> "Report":
        return cls(
            ReportMetadata._decode(r),
            r.opaque_u32(),
            HpkeCiphertext._decode(r),
            HpkeCiphertext._decode(r),
        )


@dataclass(frozen=True)
class InputShareAad(Message):
    """AAD for input-share encryption; reference: messages/src/lib.rs:1825"""

    task_id: TaskId
    metadata: ReportMetadata
    public_share: bytes

    def encode(self, w: Encoder) -> None:
        self.task_id.encode(w)
        self.metadata.encode(w)
        w.opaque_u32(self.public_share)

    @classmethod
    def _decode(cls, r: Decoder) -> "InputShareAad":
        return cls(TaskId._decode(r), ReportMetadata._decode(r), r.opaque_u32())


# ---------------------------------------------------------------------------
# Query types (reference: messages/src/query_type.rs)
# ---------------------------------------------------------------------------


class QueryCode(IntEnum):
    """reference: messages/src/query_type.rs:110"""

    RESERVED = 0
    TIME_INTERVAL = 1
    FIXED_SIZE = 2


class TimeInterval:
    """reference: messages/src/query_type.rs:66"""

    CODE = QueryCode.TIME_INTERVAL
    NAME = "TimeInterval"

    # BatchIdentifier = Interval; PartialBatchIdentifier = (); QueryBody = Interval
    @staticmethod
    def encode_batch_identifier(w: Encoder, ident: Interval) -> None:
        ident.encode(w)

    @staticmethod
    def decode_batch_identifier(r: Decoder) -> Interval:
        return Interval._decode(r)

    @staticmethod
    def encode_partial_batch_identifier(w: Encoder, ident) -> None:
        if ident is not None:
            raise CodecError("time-interval partial batch identifier is empty")

    @staticmethod
    def decode_partial_batch_identifier(r: Decoder):
        return None

    @staticmethod
    def encode_query_body(w: Encoder, body: Interval) -> None:
        body.encode(w)

    @staticmethod
    def decode_query_body(r: Decoder) -> Interval:
        return Interval._decode(r)

    @staticmethod
    def partial_batch_identifier(batch_identifier):
        return None


@dataclass(frozen=True)
class FixedSizeQuery(Message):
    """reference: messages/src/lib.rs:1440"""

    BY_BATCH_ID: ClassVar[int] = 0
    CURRENT_BATCH: ClassVar[int] = 1

    variant: int
    batch_id: Optional[BatchId] = None

    @classmethod
    def by_batch_id(cls, batch_id: BatchId) -> "FixedSizeQuery":
        return cls(cls.BY_BATCH_ID, batch_id)

    @classmethod
    def current_batch(cls) -> "FixedSizeQuery":
        return cls(cls.CURRENT_BATCH)

    def encode(self, w: Encoder) -> None:
        w.u8(self.variant)
        if self.variant == self.BY_BATCH_ID:
            self.batch_id.encode(w)

    @classmethod
    def _decode(cls, r: Decoder) -> "FixedSizeQuery":
        variant = r.u8()
        if variant == cls.BY_BATCH_ID:
            return cls(variant, BatchId._decode(r))
        if variant == cls.CURRENT_BATCH:
            return cls(variant)
        raise CodecError(f"unexpected FixedSizeQueryType value {variant}")


class FixedSize:
    """reference: messages/src/query_type.rs:89"""

    CODE = QueryCode.FIXED_SIZE
    NAME = "FixedSize"

    @staticmethod
    def encode_batch_identifier(w: Encoder, ident: BatchId) -> None:
        ident.encode(w)

    @staticmethod
    def decode_batch_identifier(r: Decoder) -> BatchId:
        return BatchId._decode(r)

    @staticmethod
    def encode_partial_batch_identifier(w: Encoder, ident: BatchId) -> None:
        ident.encode(w)

    @staticmethod
    def decode_partial_batch_identifier(r: Decoder) -> BatchId:
        return BatchId._decode(r)

    @staticmethod
    def encode_query_body(w: Encoder, body: FixedSizeQuery) -> None:
        body.encode(w)

    @staticmethod
    def decode_query_body(r: Decoder) -> FixedSizeQuery:
        return FixedSizeQuery._decode(r)

    @staticmethod
    def partial_batch_identifier(batch_identifier: BatchId) -> BatchId:
        return batch_identifier


QUERY_TYPES = {TimeInterval.CODE: TimeInterval, FixedSize.CODE: FixedSize}


def _expect_code(r: Decoder, query_type) -> None:
    code = r.u8()
    if code != query_type.CODE.value:
        raise CodecError(f"unexpected query type code {code}")


@dataclass(frozen=True)
class Query(Message):
    """reference: messages/src/lib.rs:1483"""

    query_type: type
    query_body: object

    @classmethod
    def new_time_interval(cls, batch_interval: Interval) -> "Query":
        return cls(TimeInterval, batch_interval)

    @classmethod
    def new_fixed_size(cls, fixed_size_query: FixedSizeQuery) -> "Query":
        return cls(FixedSize, fixed_size_query)

    def encode(self, w: Encoder) -> None:
        w.u8(self.query_type.CODE.value)
        self.query_type.encode_query_body(w, self.query_body)

    @classmethod
    def _decode(cls, r: Decoder, query_type=TimeInterval) -> "Query":
        _expect_code(r, query_type)
        return cls(query_type, query_type.decode_query_body(r))


@dataclass(frozen=True)
class PartialBatchSelector(Message):
    """reference: messages/src/lib.rs:1610"""

    query_type: type
    batch_identifier: object = None

    @classmethod
    def new_time_interval(cls) -> "PartialBatchSelector":
        return cls(TimeInterval, None)

    @classmethod
    def new_fixed_size(cls, batch_id: BatchId) -> "PartialBatchSelector":
        return cls(FixedSize, batch_id)

    def encode(self, w: Encoder) -> None:
        w.u8(self.query_type.CODE.value)
        self.query_type.encode_partial_batch_identifier(w, self.batch_identifier)

    @classmethod
    def _decode(cls, r: Decoder, query_type=TimeInterval) -> "PartialBatchSelector":
        _expect_code(r, query_type)
        return cls(query_type, query_type.decode_partial_batch_identifier(r))


@dataclass(frozen=True)
class BatchSelector(Message):
    """reference: messages/src/lib.rs:2558"""

    query_type: type
    batch_identifier: object

    @classmethod
    def new_time_interval(cls, batch_interval: Interval) -> "BatchSelector":
        return cls(TimeInterval, batch_interval)

    @classmethod
    def new_fixed_size(cls, batch_id: BatchId) -> "BatchSelector":
        return cls(FixedSize, batch_id)

    def encode(self, w: Encoder) -> None:
        w.u8(self.query_type.CODE.value)
        self.query_type.encode_batch_identifier(w, self.batch_identifier)

    @classmethod
    def _decode(cls, r: Decoder, query_type=TimeInterval) -> "BatchSelector":
        _expect_code(r, query_type)
        return cls(query_type, query_type.decode_batch_identifier(r))


# ---------------------------------------------------------------------------
# Collection flow
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class CollectionReq(Message):
    """reference: messages/src/lib.rs:1555"""

    MEDIA_TYPE: ClassVar[str] = "application/dap-collect-req"

    query: Query
    aggregation_parameter: bytes = b""

    def encode(self, w: Encoder) -> None:
        self.query.encode(w)
        w.opaque_u32(self.aggregation_parameter)

    @classmethod
    def _decode(cls, r: Decoder, query_type=TimeInterval) -> "CollectionReq":
        return cls(Query._decode(r, query_type), r.opaque_u32())


@dataclass(frozen=True)
class Collection(Message):
    """reference: messages/src/lib.rs:1730"""

    MEDIA_TYPE: ClassVar[str] = "application/dap-collection"

    partial_batch_selector: PartialBatchSelector
    report_count: int
    interval: Interval
    leader_encrypted_agg_share: HpkeCiphertext
    helper_encrypted_agg_share: HpkeCiphertext

    def encode(self, w: Encoder) -> None:
        self.partial_batch_selector.encode(w)
        w.u64(self.report_count)
        self.interval.encode(w)
        self.leader_encrypted_agg_share.encode(w)
        self.helper_encrypted_agg_share.encode(w)

    @classmethod
    def _decode(cls, r: Decoder, query_type=TimeInterval) -> "Collection":
        return cls(
            PartialBatchSelector._decode(r, query_type),
            r.u64(),
            Interval._decode(r),
            HpkeCiphertext._decode(r),
            HpkeCiphertext._decode(r),
        )


@dataclass(frozen=True)
class AggregateShareAad(Message):
    """reference: messages/src/lib.rs:1891"""

    task_id: TaskId
    aggregation_parameter: bytes
    batch_selector: BatchSelector

    def encode(self, w: Encoder) -> None:
        self.task_id.encode(w)
        w.opaque_u32(self.aggregation_parameter)
        self.batch_selector.encode(w)

    @classmethod
    def _decode(cls, r: Decoder, query_type=TimeInterval) -> "AggregateShareAad":
        return cls(
            TaskId._decode(r), r.opaque_u32(), BatchSelector._decode(r, query_type)
        )


# ---------------------------------------------------------------------------
# Aggregation flow
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ReportShare(Message):
    """reference: messages/src/lib.rs:1961"""

    metadata: ReportMetadata
    public_share: bytes
    encrypted_input_share: HpkeCiphertext

    def encode(self, w: Encoder) -> None:
        self.metadata.encode(w)
        w.opaque_u32(self.public_share)
        self.encrypted_input_share.encode(w)

    @classmethod
    def _decode(cls, r: Decoder) -> "ReportShare":
        return cls(ReportMetadata._decode(r), r.opaque_u32(), HpkeCiphertext._decode(r))


@dataclass(frozen=True)
class PrepareInit(Message):
    """reference: messages/src/lib.rs:2032"""

    report_share: ReportShare
    message: PingPongMessage

    def encode(self, w: Encoder) -> None:
        self.report_share.encode(w)
        w.opaque_u32(self.message.encode())

    @classmethod
    def _decode(cls, r: Decoder) -> "PrepareInit":
        report_share = ReportShare._decode(r)
        return cls(report_share, PingPongMessage.decode(r.opaque_u32()))


class PrepareError(IntEnum):
    """reference: messages/src/lib.rs:2185"""

    BATCH_COLLECTED = 0
    REPORT_REPLAYED = 1
    REPORT_DROPPED = 2
    HPKE_UNKNOWN_CONFIG_ID = 3
    HPKE_DECRYPT_ERROR = 4
    VDAF_PREP_ERROR = 5
    BATCH_SATURATED = 6
    TASK_EXPIRED = 7
    INVALID_MESSAGE = 8
    REPORT_TOO_EARLY = 9


@dataclass(frozen=True)
class PrepareStepResult(Message):
    """Tagged union Continue{message} | Finished | Reject(error).
    reference: messages/src/lib.rs:2130"""

    CONTINUE: ClassVar[int] = 0
    FINISHED: ClassVar[int] = 1
    REJECT: ClassVar[int] = 2

    variant: int
    message: Optional[PingPongMessage] = None
    error: Optional[PrepareError] = None

    @classmethod
    def new_continue(cls, message: PingPongMessage) -> "PrepareStepResult":
        return cls(cls.CONTINUE, message=message)

    @classmethod
    def finished(cls) -> "PrepareStepResult":
        return cls(cls.FINISHED)

    @classmethod
    def reject(cls, error: PrepareError) -> "PrepareStepResult":
        return cls(cls.REJECT, error=error)

    def encode(self, w: Encoder) -> None:
        w.u8(self.variant)
        if self.variant == self.CONTINUE:
            w.opaque_u32(self.message.encode())
        elif self.variant == self.REJECT:
            w.u8(self.error.value)

    @classmethod
    def _decode(cls, r: Decoder) -> "PrepareStepResult":
        variant = r.u8()
        if variant == cls.CONTINUE:
            return cls(variant, message=PingPongMessage.decode(r.opaque_u32()))
        if variant == cls.FINISHED:
            return cls(variant)
        if variant == cls.REJECT:
            try:
                return cls(variant, error=PrepareError(r.u8()))
            except ValueError as e:
                raise CodecError(str(e))
        raise CodecError(f"unexpected PrepareStepResult value {variant}")


@dataclass(frozen=True)
class PrepareResp(Message):
    """reference: messages/src/lib.rs:2084"""

    report_id: ReportId
    result: PrepareStepResult

    def encode(self, w: Encoder) -> None:
        self.report_id.encode(w)
        self.result.encode(w)

    @classmethod
    def _decode(cls, r: Decoder) -> "PrepareResp":
        return cls(ReportId._decode(r), PrepareStepResult._decode(r))


@dataclass(frozen=True)
class PrepareContinue(Message):
    """reference: messages/src/lib.rs:2220"""

    report_id: ReportId
    message: PingPongMessage

    def encode(self, w: Encoder) -> None:
        self.report_id.encode(w)
        w.opaque_u32(self.message.encode())

    @classmethod
    def _decode(cls, r: Decoder) -> "PrepareContinue":
        return cls(ReportId._decode(r), PingPongMessage.decode(r.opaque_u32()))


@dataclass(frozen=True)
class AggregationJobInitializeReq(Message):
    """reference: messages/src/lib.rs:2329"""

    MEDIA_TYPE: ClassVar[str] = "application/dap-aggregation-job-init-req"

    aggregation_parameter: bytes
    partial_batch_selector: PartialBatchSelector
    prepare_inits: tuple

    def __init__(self, aggregation_parameter, partial_batch_selector, prepare_inits):
        object.__setattr__(self, "aggregation_parameter", bytes(aggregation_parameter))
        object.__setattr__(self, "partial_batch_selector", partial_batch_selector)
        object.__setattr__(self, "prepare_inits", tuple(prepare_inits))

    def encode(self, w: Encoder) -> None:
        w.opaque_u32(self.aggregation_parameter)
        self.partial_batch_selector.encode(w)
        w.items_u32(self.prepare_inits, lambda ww, p: p.encode(ww))

    @classmethod
    def _decode(cls, r: Decoder, query_type=TimeInterval) -> "AggregationJobInitializeReq":
        return cls(
            r.opaque_u32(),
            PartialBatchSelector._decode(r, query_type),
            r.items_u32(PrepareInit._decode),
        )


class AggregationJobStep(int):
    """u16 step counter; reference: messages/src/lib.rs:2404"""

    def increment(self) -> "AggregationJobStep":
        return AggregationJobStep(self + 1)

    def encode(self, w: Encoder) -> None:
        w.u16(int(self))

    @classmethod
    def _decode(cls, r: Decoder) -> "AggregationJobStep":
        return cls(r.u16())


@dataclass(frozen=True)
class AggregationJobContinueReq(Message):
    """reference: messages/src/lib.rs:2461"""

    MEDIA_TYPE: ClassVar[str] = "application/dap-aggregation-job-continue-req"

    step: AggregationJobStep
    prepare_continues: tuple

    def __init__(self, step, prepare_continues):
        object.__setattr__(self, "step", AggregationJobStep(step))
        object.__setattr__(self, "prepare_continues", tuple(prepare_continues))

    def encode(self, w: Encoder) -> None:
        self.step.encode(w)
        w.items_u32(self.prepare_continues, lambda ww, p: p.encode(ww))

    @classmethod
    def _decode(cls, r: Decoder) -> "AggregationJobContinueReq":
        return cls(AggregationJobStep._decode(r), r.items_u32(PrepareContinue._decode))


@dataclass(frozen=True)
class AggregationJobResp(Message):
    """reference: messages/src/lib.rs:2516"""

    MEDIA_TYPE: ClassVar[str] = "application/dap-aggregation-job-resp"

    prepare_resps: tuple

    def __init__(self, prepare_resps):
        object.__setattr__(self, "prepare_resps", tuple(prepare_resps))

    def encode(self, w: Encoder) -> None:
        w.items_u32(self.prepare_resps, lambda ww, p: p.encode(ww))

    @classmethod
    def _decode(cls, r: Decoder) -> "AggregationJobResp":
        return cls(r.items_u32(PrepareResp._decode))


@dataclass(frozen=True)
class AggregateShareReq(Message):
    """reference: messages/src/lib.rs:2630"""

    MEDIA_TYPE: ClassVar[str] = "application/dap-aggregate-share-req"

    batch_selector: BatchSelector
    aggregation_parameter: bytes
    report_count: int
    checksum: ReportIdChecksum

    def encode(self, w: Encoder) -> None:
        self.batch_selector.encode(w)
        w.opaque_u32(self.aggregation_parameter)
        w.u64(self.report_count)
        self.checksum.encode(w)

    @classmethod
    def _decode(cls, r: Decoder, query_type=TimeInterval) -> "AggregateShareReq":
        return cls(
            BatchSelector._decode(r, query_type),
            r.opaque_u32(),
            r.u64(),
            ReportIdChecksum._decode(r),
        )


@dataclass(frozen=True)
class AggregateShare(Message):
    """reference: messages/src/lib.rs:2716"""

    MEDIA_TYPE: ClassVar[str] = "application/dap-aggregate-share"

    encrypted_aggregate_share: HpkeCiphertext

    def encode(self, w: Encoder) -> None:
        self.encrypted_aggregate_share.encode(w)

    @classmethod
    def _decode(cls, r: Decoder) -> "AggregateShare":
        return cls(HpkeCiphertext._decode(r))
