"""TLS-syntax codec primitives (network byte order, length-prefixed opaques).

The analog of ``prio::codec``'s Encode/Decode traits consumed by the reference
wire types (reference: messages/src/lib.rs:11-17).  Messages implement
``encode(w)`` / ``decode(cls, r)`` against these primitives; `get_encoded` /
`get_decoded` mirror the Rust helper methods and enforce full consumption.
"""

from __future__ import annotations

from typing import Callable, List, TypeVar

T = TypeVar("T")


class CodecError(Exception):
    pass


class Encoder:
    def __init__(self) -> None:
        self._parts: List[bytes] = []

    def write(self, data: bytes) -> None:
        self._parts.append(data)

    def u8(self, v: int) -> None:
        self.write(v.to_bytes(1, "big"))

    def u16(self, v: int) -> None:
        self.write(v.to_bytes(2, "big"))

    def u32(self, v: int) -> None:
        self.write(v.to_bytes(4, "big"))

    def u64(self, v: int) -> None:
        self.write(v.to_bytes(8, "big"))

    def fixed(self, data: bytes, size: int) -> None:
        if len(data) != size:
            raise CodecError(f"fixed field expected {size} bytes, got {len(data)}")
        self.write(data)

    def opaque_u16(self, data: bytes) -> None:
        if len(data) >= 1 << 16:
            raise CodecError("opaque too long for u16 prefix")
        self.u16(len(data))
        self.write(data)

    def opaque_u32(self, data: bytes) -> None:
        if len(data) >= 1 << 32:
            raise CodecError("opaque too long for u32 prefix")
        self.u32(len(data))
        self.write(data)

    def items_u16(self, items, encode_item: Callable) -> None:
        """Encode a u16-length-prefixed vector (length in bytes, not count)."""
        body = Encoder()
        for item in items:
            encode_item(body, item)
        self.opaque_u16(body.take())

    def items_u32(self, items, encode_item: Callable) -> None:
        body = Encoder()
        for item in items:
            encode_item(body, item)
        self.opaque_u32(body.take())

    def take(self) -> bytes:
        return b"".join(self._parts)


class Decoder:
    def __init__(self, data: bytes) -> None:
        self._data = data
        self._pos = 0

    def remaining(self) -> int:
        return len(self._data) - self._pos

    def read(self, n: int) -> bytes:
        if self.remaining() < n:
            raise CodecError("unexpected end of buffer")
        out = self._data[self._pos : self._pos + n]
        self._pos += n
        return out

    def u8(self) -> int:
        return self.read(1)[0]

    def u16(self) -> int:
        return int.from_bytes(self.read(2), "big")

    def u32(self) -> int:
        return int.from_bytes(self.read(4), "big")

    def u64(self) -> int:
        return int.from_bytes(self.read(8), "big")

    def opaque_u16(self) -> bytes:
        return self.read(self.u16())

    def opaque_u32(self) -> bytes:
        return self.read(self.u32())

    def items_u16(self, decode_item: Callable[["Decoder"], T]) -> List[T]:
        sub = Decoder(self.opaque_u16())
        out: List[T] = []
        while sub.remaining():
            out.append(decode_item(sub))
        return out

    def items_u32(self, decode_item: Callable[["Decoder"], T]) -> List[T]:
        sub = Decoder(self.opaque_u32())
        out: List[T] = []
        while sub.remaining():
            out.append(decode_item(sub))
        return out

    def finish(self) -> None:
        if self.remaining():
            raise CodecError(f"{self.remaining()} trailing bytes")


class Message:
    """Base for wire messages: subclasses define encode(w) and _decode(r)."""

    def encode(self, w: Encoder) -> None:
        raise NotImplementedError

    @classmethod
    def _decode(cls, r: Decoder):
        raise NotImplementedError

    def get_encoded(self) -> bytes:
        w = Encoder()
        self.encode(w)
        return w.take()

    @classmethod
    def get_decoded(cls, data: bytes, *args, **kwargs):
        r = Decoder(data)
        out = cls._decode(r, *args, **kwargs)
        r.finish()
        return out
