"""DAP problem types (RFC 7807 problem-details URNs).

reference: messages/src/problem_type.rs:7 and the HTTP error mapping in
aggregator/src/aggregator/problem_details.rs.
"""

from __future__ import annotations

from enum import Enum


class DapProblemType(Enum):
    INVALID_MESSAGE = ("invalidMessage", "The message type for a response was incorrect or the payload was malformed.")
    UNRECOGNIZED_TASK = ("unrecognizedTask", "An endpoint received a message with an unknown task ID.")
    STEP_MISMATCH = ("stepMismatch", "The leader and helper are not on the same step of VDAF preparation.")
    MISSING_TASK_ID = ("missingTaskID", "HPKE configuration was requested without specifying a task ID.")
    UNRECOGNIZED_AGGREGATION_JOB = ("unrecognizedAggregationJob", "An endpoint received a message with an unknown aggregation job ID.")
    OUTDATED_CONFIG = ("outdatedConfig", "The message was generated using an outdated configuration.")
    REPORT_REJECTED = ("reportRejected", "Report could not be processed.")
    REPORT_TOO_EARLY = ("reportTooEarly", "Report could not be processed because it arrived too early.")
    BATCH_INVALID = ("batchInvalid", "The batch implied by the query is invalid.")
    INVALID_BATCH_SIZE = ("invalidBatchSize", "The number of reports included in the batch is invalid.")
    BATCH_QUERIED_TOO_MANY_TIMES = ("batchQueriedTooManyTimes", "The batch described by the query has been queried too many times.")
    BATCH_MISMATCH = ("batchMismatch", "Leader and helper disagree on reports aggregated in a batch.")
    UNAUTHORIZED_REQUEST = ("unauthorizedRequest", "The request's authorization is not valid.")
    BATCH_OVERLAP = ("batchOverlap", "The queried batch overlaps with a previously queried batch.")
    INVALID_TASK = ("invalidTask", "Aggregator has opted out of the indicated task.")

    @property
    def type_uri(self) -> str:
        return f"urn:ietf:params:ppm:dap:error:{self.value[0]}"

    @property
    def description(self) -> str:
        return self.value[1]

    @classmethod
    def from_uri(cls, uri: str) -> "DapProblemType":
        for v in cls:
            if v.type_uri == uri:
                return v
        raise ValueError(f"unknown DAP problem type {uri}")


def problem_document(problem_type: DapProblemType, task_id=None, detail=None) -> dict:
    """RFC 7807 JSON body the DAP HTTP layer returns on errors
    (reference: aggregator/src/aggregator/problem_details.rs)."""
    doc = {
        "type": problem_type.type_uri,
        "title": problem_type.description,
    }
    if detail is not None:
        doc["detail"] = detail
    if task_id is not None:
        doc["taskid"] = str(task_id)
    return doc
