"""Batched Prio3 prepare on device — the north-star hot loop.

This composes the leaf kernels (``field_jax`` limb arithmetic, ``keccak_jax``
batched TurboSHAKE, ``xof_jax`` rejection sampling) into the full per-report
prepare pipeline, vmapped over an aggregation job:

    seeds/nonces → XOF expand (meas + proof shares, query/joint rands)
                 → FLP query (gadget wires, Lagrange eval, gadget poly)
                 → verifier shares + out shares,
    then ``prep_shares_to_prep``: combine verifiers, decide, joint-rand seed.

The reference runs the scalar equivalent per report on a rayon pool
(reference: aggregator/src/aggregator/aggregation_job_driver.rs:397-428 leader,
aggregator/src/aggregator.rs:2101 helper).  Here one XLA launch handles the
whole batch; every output is byte-identical to the CPU oracle
(janus_tpu.vdaf.prio3) — asserted in tests/test_prepare.py.

Montgomery domain convention: the BULK tensors (meas, proofs, wires, gadget
outputs, verifiers, out shares) stay CANONICAL end to end; only the handful
of per-report scalars that multiply them — joint-rand r, query point t, the
precomputed alpha powers / barycentric weights — are held in Montgomery
form.  ``mont_mul(x_canonical, y_montgomery) = x*y canonical`` makes every
product land back in canonical form for free, which eliminates the
full-width to_mont/from_mont passes over meas (MEAS_LEN muls), proofs
(PROOF_LEN), and the verifier (VERIFIER_LEN) that an all-Montgomery circuit
needs — ~26% of the field multiplies in the histogram1024 pipeline.  The
gadget check in prep_shares_to_prep compares g*R^-1 against y*R^-1 (R is
invertible, so equality is unchanged).  All arithmetic is exact integer
math mod p, so there is no reassociation hazard.

Wire-polynomial evaluation avoids a device NTT: the verifier needs each wire
polynomial only *evaluated at t*, and the wire values live on the P-th roots
of unity, so barycentric Lagrange applies:

    poly(t) = (t^P - 1)/P * sum_k  val_k * w^k / (t - w^k)

with one batched Montgomery inversion over the k axis (field_jax.batch_inv_mont).
Values at unused points are zero, so only calls+1 terms are needed.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..fields import next_power_of_2
from ..flp.circuits import (
    Count,
    FixedPointBoundedL2VecSum,
    Histogram,
    Sum,
    SumVec,
)
from ..vdaf.prio3 import (
    USAGE_JOINT_RAND_PART,
    USAGE_JOINT_RAND_SEED,
    USAGE_JOINT_RANDOMNESS,
    USAGE_MEAS_SHARE,
    USAGE_PROOF_SHARE,
    USAGE_QUERY_RANDOMNESS,
    Prio3,
)
from ..xof import XofTurboShake128
from .field_jax import JField, _scan_fence
from .keccak_jax import bytes_to_words, words_to_bytes, xof_turboshake128_batch
from .xof_jax import xof_next_vec_batch

_U32 = jnp.uint32


def limbs_to_bytes(limbs: jnp.ndarray) -> jnp.ndarray:
    """Canonical (..., L, n) u32 limbs -> (..., L*4n) u8 little-endian wire bytes."""
    flat = limbs.reshape(limbs.shape[:-2] + (limbs.shape[-2] * limbs.shape[-1],))
    return words_to_bytes(flat)


def bytes_to_limbs(jf: JField, data: jnp.ndarray, num_elems: int) -> jnp.ndarray:
    """(..., num_elems*4n) u8 wire bytes -> (..., num_elems, n) u32 limbs."""
    words = bytes_to_words(data)
    return words.reshape(words.shape[:-1] + (num_elems, jf.n))


class _GadgetPlan:
    """Static shape of ONE gadget inside a device circuit: its call count,
    wire arity/degree, interpolation modulus P = next_pow2(1 + calls), and
    gadget-polynomial length.  The proof and verifier wire formats are the
    concatenation of per-gadget segments in declaration order — exactly
    the scalar ``flp/generic.py`` layout."""

    __slots__ = ("calls", "arity", "degree", "P", "glen")

    def __init__(self, calls: int, arity: int, degree: int):
        self.calls = calls
        self.arity = arity
        self.degree = degree
        self.P = next_power_of_2(1 + calls)
        self.glen = degree * (self.P - 1) + 1


class _DeviceCircuit:
    """Device twin of one FLP validity circuit.

    Circuits hold a PER-GADGET plan list (``self.plans``); the original
    single-gadget families are the trivial 1-plan case and keep their
    gadget-0 attribute aliases (``calls``/``arity``/``P``/``glen``) so the
    planar Pallas paths — which only serve single-gadget circuits — read
    them unchanged.  Multi-gadget circuits (FixedPointBoundedL2VecSum)
    override the ``*_g`` per-gadget hooks.

    ``mxu=True`` routes the K-axis field contractions (wire Lagrange
    evaluation, weighted truncates, joint-rand verifier folds) through the
    limb-plane dot_general layer (JField.mat_mul_mont/dot_mont) instead of
    mont_mul/sum trees — identical canonical limbs, MXU-shaped compute.
    """

    def __init__(self, valid, mxu: bool = False):
        self.valid = valid
        self.mxu = mxu
        self.plans = [
            _GadgetPlan(calls, g.ARITY, g.DEGREE)
            for g, calls in zip(valid.new_gadgets(), valid.GADGET_CALLS)
        ]
        p0 = self.plans[0]
        self.calls = p0.calls
        self.arity = p0.arity
        self.degree = p0.degree
        self.P = p0.P
        self.glen = p0.glen

    # subclasses: inputs(), v(), truncate(), gadget_eval_scaled().
    # Convention: meas/gk/wires canonical; jr_m Montgomery; consts as noted.

    def calls_from_meas_len(self, meas_len):
        """Per-row LIVE gadget-call count for a (possibly canonical-padded)
        measurement length — the mask boundary for the barycentric
        coefficients and the gadget-output fold (vdaf/canonical.py).
        Chunked circuits: ceil(meas_len / chunk)."""
        chunk = getattr(self.valid, "chunk_length", 1)
        return (meas_len + (chunk - 1)) // chunk

    # -- per-gadget hooks (multi-gadget circuits override) ---------------
    def calls_live_list(self, meas_len):
        """Per-GADGET live-call counts for a per-row measurement length
        (canonical masking, vdaf/canonical.py) — one entry per plan."""
        return [self.calls_from_meas_len(meas_len)]

    def wire_evals_g(self, gi, jf, meas_m, jr_m, lag, seeds, consts, ml=None):
        """Wire evaluations for gadget ``gi``; the single-gadget default
        delegates to the circuit's ``wire_evals``.  ``ml`` (B,) i32 is the
        per-row true measurement length under canonical padding (None on
        exact-shape graphs) — only length-dependent gadget inputs (the
        fixed-point entry recomposition) consume it."""
        assert gi == 0
        return self.wire_evals(jf, meas_m, jr_m, lag, seeds, consts)

    def gadget_eval_scaled_g(self, gi, jf, x):
        """Direct gadget evaluation (scaled by R^-1) for gadget ``gi`` on
        its combined wire evaluations — the decide-side check."""
        return self.gadget_eval_scaled(jf, x)

    def v_multi(self, jf, gks, meas_m, jr_m, consts, ml=None):
        """Circuit output from the per-gadget output lists (``gks`` has
        one (B, calls_g, n) tensor per plan).  Single-gadget default
        delegates to ``v``."""
        return self.v(jf, gks[0], meas_m, jr_m, consts)

    def wire_evals(self, jf, meas_m, jr_m, lag, seeds, consts):
        """Wire-polynomial evaluations at t: (B, arity, n) canonical.

        lag (B, K, n) Montgomery barycentric coefficients, seeds (B, arity, n)
        canonical.  Default path materializes the gadget-input tensor; the
        chunked circuits override with a fused form (the input tensor is
        (B, calls, arity, n) — ~165 MB/launch for histogram1024 at B=4096 —
        and this device is HBM-bandwidth-bound, so never writing it is the
        win)."""
        inp = self.inputs(jf, meas_m, jr_m, consts)  # (B, calls, arity, n)
        wires = jnp.concatenate([seeds[:, None], inp], axis=1)  # (B, K, arity, n)
        if self.mxu:
            return jf.dot_mont(wires, lag)
        return jf.sum(jf.mont_mul(wires, lag[:, :, None, :]), axis=1)


class _DCount(_DeviceCircuit):
    def inputs(self, jf, meas_m, jr_m, consts):
        # Single call: [meas0, meas0].
        m0 = meas_m[:, 0:1]  # (B, 1, n)
        return jnp.stack([m0, m0], axis=2)  # (B, 1, 2, n)

    def v(self, jf, gk, meas_m, jr_m, consts):
        return jf.sub(gk[:, 0], meas_m[:, 0])

    def truncate(self, jf, meas_m, consts, ml=None):
        return meas_m

    def gadget_eval_scaled(self, jf, x):
        """Gadget output scaled by R^-1, from canonical wire inputs."""
        return jf.mont_mul(x[:, 0], x[:, 1])


class _DSum(_DeviceCircuit):
    def inputs(self, jf, meas_m, jr_m, consts):
        return meas_m[:, :, None, :]  # (B, bits, 1, n)

    def v(self, jf, gk, meas_m, jr_m, consts):
        r = jr_m[:, 0]  # (B, n) Montgomery
        r_b = jnp.broadcast_to(r[:, None, :], gk.shape)
        r_pows = jf.cumprod_mont(r_b, axis=1)  # r^(k+1)*R at call k
        if self.mxu:
            # joint-rand verifier fold as a (1 x calls) x (calls x 1) dot
            return jnp.squeeze(jf.dot_mont(gk[:, :, None, :], r_pows), axis=1)
        return jf.sum(jf.mont_mul(r_pows, gk), axis=1)  # canonical

    def truncate(self, jf, meas_m, consts, ml=None):
        w = consts["pow2_m"]  # (bits, n) Montgomery constants 2^b*R
        if self.mxu:
            # bit-weight contraction against the shared constant vector
            return jf.dot_mont(meas_m[:, :, None, :], w)
        return jf.sum(jf.mont_mul(meas_m, w[None]), axis=1)[:, None, :]

    def gadget_eval_scaled(self, jf, x):
        x0 = x[:, 0]
        # (x^2 - x)*R^-1 from canonical x: x*x*R^-1 - x*1*R^-1.
        return jf.sub(jf.mont_mul(x0, x0), jf.from_mont(x0))


class _DChunked(_DeviceCircuit):
    """Shared machinery for the ParallelSum(Mul, chunk) circuits."""

    def __init__(self, valid, mxu: bool = False):
        super().__init__(valid, mxu)
        self.chunk = valid.chunk_length
        self.pad_len = self.calls * self.chunk - valid.MEAS_LEN

    def _pad(self, jf, meas_m):
        if self.pad_len == 0:
            return meas_m
        B = meas_m.shape[0]
        zeros = jnp.zeros((B, self.pad_len, jf.n), dtype=_U32)
        return jnp.concatenate([meas_m, zeros], axis=1)

    def _interleave(self, a, b):
        # wire order per call: [a_0, b_0, a_1, b_1, ...]
        B, calls, chunk, n = a.shape
        return jnp.stack([a, b], axis=3).reshape(B, calls, 2 * chunk, n)

    def gadget_eval_scaled(self, jf, x):
        B, arity, n = x.shape
        pairs = x.reshape(B, arity // 2, 2, n)
        prod = jf.mont_mul(pairs[:, :, 0], pairs[:, :, 1])  # (a*b)*R^-1
        return jf.sum(prod, axis=1)

    def _odds_and_seed(self, jf, m, lagk, lag0, seeds, consts):
        """Shared pieces of the fused wire evaluation.

        odds[u] = sum_k lag_{k+1}*(m[k,u] - 1/shares)
                = sum_k mont_mul(m[k,u], lag_{k+1}) - mont_mul(1/shares, sum_k lag_{k+1})
        (exact: mont_mul distributes over mod-p addition; canonical limbs are
        unique, so the rearranged form is byte-identical to the oracle's).
        """
        if self.mxu:
            s2 = jf.dot_mont(m, lagk)  # (B, chunk, n) via one dot_general
        else:
            s2 = jf.sum(jf.mont_mul(m, lagk[:, :, None, :]), axis=1)  # (B, chunk, n)
        lag_sum = jf.sum(lagk, axis=1)  # (B, n) Montgomery
        c = jnp.broadcast_to(consts["shares_inv_c"], lag_sum.shape)
        ccorr = jf.mont_mul(c, lag_sum)  # (B, n) canonical
        odds = jf.sub(s2, ccorr[:, None, :])
        se = jf.mont_mul(seeds, lag0[:, None, :])  # (B, arity, n)
        return odds, se

    def _zip_wires(self, jf, evens, odds, se):
        B = evens.shape[0]
        pair = jnp.stack([evens, odds], axis=2).reshape(B, 2 * self.chunk, jf.n)
        return jf.add(se, pair)


class _DSumVec(_DChunked):
    def inputs(self, jf, meas_m, jr_m, consts):
        B = meas_m.shape[0]
        m = self._pad(jf, meas_m).reshape(B, self.calls, self.chunk, jf.n)
        # r_power resets per call: jr[i]^(j+1)
        jr_b = jnp.broadcast_to(jr_m[:, :, None, :], m.shape)
        r_pows = jf.cumprod_mont(jr_b, axis=2)
        a = jf.mont_mul(m, r_pows)
        b = jf.sub(m, jnp.broadcast_to(consts["shares_inv_c"], m.shape))
        return self._interleave(a, b)

    def wire_evals(self, jf, meas_m, jr_m, lag, seeds, consts):
        """Fused: evens[u] = sum_k lag_{k+1} * m[k,u] * jr_k^(u+1).

        jr differs per call, so lag folds into the per-(k,u) Montgomery
        power table; no (B, calls, arity, n) tensor is ever written.  (The
        evens coefficient varies over BOTH contraction axes, so unlike the
        histogram it is not a matmul — under mxu only the odds/seed halves
        ride the dot layer, via _odds_and_seed.)"""
        B = meas_m.shape[0]
        m = self._pad(jf, meas_m).reshape(B, self.calls, self.chunk, jf.n)
        lag0, lagk = lag[:, 0], lag[:, 1:]
        jr_b = jnp.broadcast_to(jr_m[:, :, None, :], m.shape)
        r_pows = jf.cumprod_mont(jr_b, axis=2)  # jr_k^(u+1) * R
        rl = jf.mont_mul(r_pows, jnp.broadcast_to(lagk[:, :, None, :], m.shape))
        evens = jf.sum(jf.mont_mul(m, rl), axis=1)  # (B, chunk, n)
        odds, se = self._odds_and_seed(jf, m, lagk, lag0, seeds, consts)
        return self._zip_wires(jf, evens, odds, se)

    def v(self, jf, gk, meas_m, jr_m, consts):
        return jf.sum(gk, axis=1)

    def truncate(self, jf, meas_m, consts, ml=None):
        if self.valid.bits == 1:
            # sum over a single bit weighted 2^0 is the identity; skip the
            # MEAS_LEN-wide multiply (len=100k circuits pay for it).
            return meas_m
        B = meas_m.shape[0]
        w = consts["pow2_m"]  # (bits, n)
        m = meas_m.reshape(B, self.valid.length, self.valid.bits, jf.n)
        if self.mxu:
            return jf.dot_mont(jnp.swapaxes(m, 1, 2), w)  # (B, length, n)
        return jf.sum(jf.mont_mul(m, w[None, None]), axis=2)


class _DHistogram(_DChunked):
    def inputs(self, jf, meas_m, jr_m, consts):
        B = meas_m.shape[0]
        m = self._pad(jf, meas_m).reshape(B, self.calls, self.chunk, jf.n)
        # r_power is global: r^(index+1) over the padded, flattened axis.
        r = jr_m[:, 0]  # (B, n)
        r_flat = jnp.broadcast_to(r[:, None, :], (B, self.calls * self.chunk, jf.n))
        r_pows = jf.cumprod_mont(r_flat, axis=1).reshape(m.shape)
        a = jf.mont_mul(m, r_pows)
        b = jf.sub(m, jnp.broadcast_to(consts["shares_inv_c"], m.shape))
        return self._interleave(a, b)

    def wire_evals(self, jf, meas_m, jr_m, lag, seeds, consts):
        """Fused with the global r-power pulled apart as an outer product.

        r^(k*chunk + u + 1) = r^(k*chunk) * r^(u+1), so
        evens[u] = mont_mul( sum_k mont_mul(m[k,u], kl[k]),  r_ch[u] )
        with kl[k] = mont_mul(r_call[k], lag_{k+1}) a TINY (B, calls, n)
        table — the k-contraction happens before the chunk-wide multiply,
        reading meas once and writing only (B, chunk, n).  Every
        rearrangement is an exact mod-p identity, so the canonical output
        limbs are byte-identical to the unfused form.  The coefficient
        tensors come from planar_coeffs — the SAME code that feeds the
        limb-planar Pallas kernel, so the two paths cannot drift.
        """
        B = meas_m.shape[0]
        m = self._pad(jf, meas_m).reshape(B, self.calls, self.chunk, jf.n)
        kl, lagk, lag0, ccorr, r_ch = self.planar_coeffs(jf, jr_m, lag, consts)
        if self.mxu:
            # Both k-contractions share the measurement operand, so the kl
            # and lagk coefficient columns stack into ONE (B, calls, 2, n)
            # rhs and a single dot_general produces s1 and s2 together.
            s12 = jf.mat_mul_mont(m, jnp.stack([kl, lagk], axis=2))
            s1, s2 = s12[:, :, 0], s12[:, :, 1]
        else:
            s1 = jf.sum(jf.mont_mul(m, kl[:, :, None, :]), axis=1)  # (B, chunk, n)
            s2 = jf.sum(jf.mont_mul(m, lagk[:, :, None, :]), axis=1)
        evens = jf.mont_mul(s1, r_ch)
        odds = jf.sub(s2, ccorr[:, None, :])
        se = jf.mont_mul(seeds, lag0[:, None, :])  # (B, arity, n)
        return self._zip_wires(jf, evens, odds, se)

    def v(self, jf, gk, meas_m, jr_m, consts):
        meas_sum = jf.sum(meas_m, axis=1)  # (B, n)
        return self.v_from_meas_sum(jf, gk, meas_sum, jr_m, consts)

    def v_from_meas_sum(self, jf, gk, meas_sum, jr_m, consts):
        """v given a precomputed meas sum (planar path computes it lazily)."""
        range_check = jf.sum(gk, axis=1)
        sum_check = jf.sub(
            meas_sum, jnp.broadcast_to(consts["shares_inv_c"], meas_sum.shape)
        )
        jr1 = jr_m[:, 1]
        return jf.add(
            jf.mont_mul(jr1, range_check),
            jf.mont_mul(jf.mont_mul(jr1, jr1), sum_check),
        )

    def planar_coeffs(self, jf, jr_m, lag, consts):
        """Per-report coefficient tensors for the planar wire kernel.

        Exactly the scalars wire_evals folds into its fused contraction:
        (kl (B,calls,n), lagk (B,calls,n), lag0 (B,n), ccorr (B,n),
        r_ch (B,chunk,n)) — same formulas, so kernel output limbs are
        byte-identical to the row-major path.
        """
        B = jr_m.shape[0]
        lag0, lagk = lag[:, 0], lag[:, 1:]
        r = jr_m[:, 0]
        r_ch = jf.pow_range_mont(r, self.chunk)  # r^(u+1), u < chunk
        rc = r_ch[:, -1]
        ones = jf.mont_one()[None, None, :]
        if self.calls > 1:
            tail = jf.cumprod_mont(
                jnp.broadcast_to(rc[:, None, :], (B, self.calls - 1, jf.n)), axis=1
            )
            r_call = jnp.concatenate(
                [jnp.broadcast_to(ones, (B, 1, jf.n)), tail], axis=1
            )
        else:
            r_call = jnp.broadcast_to(ones, (B, 1, jf.n))
        kl = jf.mont_mul(r_call, lagk)
        lag_sum = jf.sum(lagk, axis=1)
        c = jnp.broadcast_to(consts["shares_inv_c"], lag_sum.shape)
        ccorr = jf.mont_mul(c, lag_sum)
        return kl, lagk, lag0, ccorr, r_ch

    def truncate(self, jf, meas_m, consts, ml=None):
        return meas_m


class _DFixedPointL2(_DChunked):
    """Device twin of FixedPointBoundedL2VecSum — the first TWO-gadget
    circuit on the device plane (the jax_graft gradient-sum workload).

    Gadget 0 is the SumVec-pattern bit-range check over all MEAS_LEN
    positions (per-call joint-rand weights, power resetting each call);
    gadget 1 is the entry-squares ParallelSum(Mul) whose inputs are the
    fixed-point entries RECOMPOSED IN-GRAPH from the bit planes
    (X_i = sum_b 2^b * meas[i*n + b]) — no entry tensor ever crosses the
    host boundary.  The norm-equality affine combination and the
    Schwartz-Zippel fold live in ``v_multi``.  Under canonical padding
    (vdaf/canonical.py) every length-dependent site is per-row: the entry
    count d derives from ``ml``, padded entries mask to zero (the columns
    past a row's entry region hold its NORM bits — live data), the
    claimed-norm bits gather at the row's own offset d*n, and the
    Schwartz-Zippel combiner r_n selects joint_rand[bit_calls(row)].
    """

    def __init__(self, valid, mxu: bool = False):
        super().__init__(valid, mxu)  # chunk + gadget-0 pad over MEAS_LEN
        self.nbits = valid.bits_per_entry
        self.entries = valid.entries
        self.norm_bits = valid.bits_for_norm
        self.pad_len1 = self.plans[1].calls * self.chunk - valid.entries

    # -- canonical-shape helpers ----------------------------------------
    def entries_from_meas_len(self, ml):
        return (ml - self.norm_bits) // self.nbits

    def calls_live_list(self, ml):
        chunk = self.chunk
        return [
            (ml + chunk - 1) // chunk,
            (self.entries_from_meas_len(ml) + chunk - 1) // chunk,
        ]

    def _entries_from_meas(self, jf, meas_m, consts, entries_live=None):
        """(B, entries, n) canonical X_i = sum_b 2^b * meas[i*n + b].

        ``entries_live`` (B,) zeroes entries at/past the row's own count:
        a canonical-padded row's columns past its entry region hold its
        norm bits, so the recomposition there is garbage that must not
        reach the squares gadget, the norm sums, or the out share."""
        B = meas_m.shape[0]
        m = meas_m[:, : self.entries * self.nbits].reshape(
            B, self.entries, self.nbits, jf.n
        )
        w = consts["pow2_m"]  # (nbits, n) Montgomery
        if self.mxu:
            x = jf.dot_mont(jnp.swapaxes(m, 1, 2), w)  # (B, entries, n)
        else:
            x = jf.sum(jf.mont_mul(m, w[None, None]), axis=2)
        if entries_live is not None:
            e = jnp.arange(self.entries, dtype=jnp.int32)[None, :]
            x = jnp.where((e < entries_live[:, None])[:, :, None], x, 0)
        return x

    # -- per-gadget wire evaluations ------------------------------------
    def wire_evals_g(self, gi, jf, meas_m, jr_m, lag, seeds, consts, ml=None):
        if gi == 0:
            return self._wire_evals_bits(jf, meas_m, jr_m, lag, seeds, consts)
        return self._wire_evals_squares(
            jf, meas_m, lag, seeds, consts, ml=ml
        )

    def _wire_evals_bits(self, jf, meas_m, jr_m, lag, seeds, consts):
        """Fused SumVec-pattern wires: evens[u] = sum_k lag_{k+1} * m[k,u]
        * jr_k^(u+1) (jr slice: one weight per bit chunk), odds/seed via
        the shared _DChunked machinery.  Identical math to _DSumVec."""
        B = meas_m.shape[0]
        calls0 = self.plans[0].calls
        m = self._pad(jf, meas_m).reshape(B, calls0, self.chunk, jf.n)
        lag0, lagk = lag[:, 0], lag[:, 1:]
        jr_b = jnp.broadcast_to(jr_m[:, :calls0, None, :], m.shape)
        r_pows = jf.cumprod_mont(jr_b, axis=2)  # jr_k^(u+1) * R
        rl = jf.mont_mul(r_pows, jnp.broadcast_to(lagk[:, :, None, :], m.shape))
        evens = jf.sum(jf.mont_mul(m, rl), axis=1)  # (B, chunk, n)
        odds, se = self._odds_and_seed(jf, m, lagk, lag0, seeds, consts)
        return self._zip_wires(jf, evens, odds, se)

    def _wire_evals_squares(self, jf, meas_m, lag, seeds, consts, ml=None):
        """Gadget-1 wires: both wires of pair u evaluate to
        seed*lag_0 + sum_k X[k,u]*lag_{k+1} — the (X_i, X_i) input pairs
        share one contraction, emitted to the even AND odd slots."""
        B = meas_m.shape[0]
        calls1 = self.plans[1].calls
        el = self.entries_from_meas_len(ml) if ml is not None else None
        x = self._entries_from_meas(jf, meas_m, consts, entries_live=el)
        if self.pad_len1:
            x = jnp.concatenate(
                [x, jnp.zeros((B, self.pad_len1, jf.n), dtype=_U32)], axis=1
            )
        xm = x.reshape(B, calls1, self.chunk, jf.n)
        lag0, lagk = lag[:, 0], lag[:, 1:]
        if self.mxu:
            s = jf.dot_mont(xm, lagk)  # (B, chunk, n)
        else:
            s = jf.sum(jf.mont_mul(xm, lagk[:, :, None, :]), axis=1)
        se = jf.mont_mul(seeds, lag0[:, None, :])  # (B, arity, n)
        pair = jnp.stack([s, s], axis=2).reshape(B, 2 * self.chunk, jf.n)
        return jf.add(se, pair)

    # -- circuit output ---------------------------------------------------
    def v_multi(self, jf, gks, meas_m, jr_m, consts, ml=None):
        gk_bits, gk_sq = gks
        B = meas_m.shape[0]
        bit_check = jf.sum(gk_bits, axis=1)  # (B, n) canonical
        sumsq = jf.sum(gk_sq, axis=1)
        el = self.entries_from_meas_len(ml) if ml is not None else None
        x = self._entries_from_meas(jf, meas_m, consts, entries_live=el)
        sum_x = jf.sum(x, axis=1)
        # claimed norm: the (2n-2)-bit decomposition at the row's offset.
        w = consts["pow2_norm_m"]  # (norm_bits, n) Montgomery
        if ml is None:
            norm_m = meas_m[:, self.entries * self.nbits :]
        else:
            cols = (el * self.nbits)[:, None] + jnp.arange(
                self.norm_bits, dtype=jnp.int32
            )[None, :]
            norm_m = jnp.take_along_axis(meas_m, cols[:, :, None], axis=1)
        if self.mxu:
            claimed = jnp.squeeze(jf.dot_mont(norm_m[:, :, None, :], w), axis=1)
        else:
            claimed = jf.sum(jf.mont_mul(norm_m, w[None]), axis=1)
        # computed = sumsq - 2^n * sum_x + shares_inv * d * 2^(2n-2)
        two_n = jnp.broadcast_to(consts["pow2n_m"], sum_x.shape)
        if ml is None:
            off = jnp.broadcast_to(consts["offset_sq_c"], sum_x.shape)
        else:
            d_limbs = jnp.concatenate(
                [
                    el.astype(_U32)[:, None],
                    jnp.zeros((B, jf.n - 1), dtype=_U32),
                ],
                axis=1,
            )
            off = jf.mont_mul(d_limbs, jnp.broadcast_to(consts["offsq_m"], d_limbs.shape))
        computed = jf.add(jf.sub(sumsq, jf.mont_mul(sum_x, two_n)), off)
        norm_check = jf.sub(computed, claimed)
        # Schwartz-Zippel: r_n = joint_rand[bit_calls] (per-row index under
        # canonical padding — the row's OWN stream position).
        if ml is None:
            rn = jr_m[:, self.plans[0].calls]
        else:
            cl0 = (ml + self.chunk - 1) // self.chunk
            rn = jnp.squeeze(
                jnp.take_along_axis(jr_m, cl0[:, None, None], axis=1), axis=1
            )
        return jf.add(
            jf.mont_mul(rn, bit_check),
            jf.mont_mul(jf.mont_mul(rn, rn), norm_check),
        )

    def truncate(self, jf, meas_m, consts, ml=None):
        el = self.entries_from_meas_len(ml) if ml is not None else None
        return self._entries_from_meas(jf, meas_m, consts, entries_live=el)


def _device_circuit(valid, mxu: bool = False) -> _DeviceCircuit:
    if isinstance(valid, Count):
        return _DCount(valid, mxu)
    if isinstance(valid, Sum):
        return _DSum(valid, mxu)
    if isinstance(valid, SumVec):
        return _DSumVec(valid, mxu)
    if isinstance(valid, Histogram):
        return _DHistogram(valid, mxu)
    if isinstance(valid, FixedPointBoundedL2VecSum):
        return _DFixedPointL2(valid, mxu)
    raise NotImplementedError(f"no device circuit for {type(valid).__name__}")


class BatchedPrio3:
    """Device-batched prepare for one Prio3 instance (TurboSHAKE XOF only).

    All shapes are static per instance; the batch axis is the report axis.
    Outputs are canonical u32 limb tensors / u8 byte tensors that are
    byte-identical to the CPU oracle.
    """

    def __init__(
        self,
        prio3: Prio3,
        ntt_min_p: int = 64,
        require_device_xof: bool = True,
        field_backend: str = "vpu",
    ):
        #: TurboSHAKE has device (Pallas) kernels; other XOFs (the HMAC
        #: multiproof variant) run on the HOST and feed query_batch — the
        #: hybrid split in vdaf/backend.py HybridXofBackend.
        self.device_xof = prio3.xof is XofTurboShake128
        if require_device_xof and not self.device_xof:
            raise NotImplementedError("device path requires XofTurboShake128")
        if field_backend not in ("vpu", "mxu"):
            raise ValueError(f"unknown field_backend {field_backend!r}")
        #: "vpu" (default): scalar-lane CIOS mont_mul chains, limb-planar
        #: Pallas fast paths.  "mxu": the K-axis field contractions (wire
        #: Lagrange evaluation, gadget Vandermonde evaluation, weighted
        #: truncates, joint-rand folds) run as limb-plane dot_generals
        #: (JField.mat_mul_mont) on the row-major path — identical limbs,
        #: matmul-shaped compute for the matrix units.
        self.field_backend = field_backend
        self.prio3 = prio3
        self.flp = prio3.flp
        self.jf = JField(self.flp.field)
        self.circ = _device_circuit(self.flp.valid, mxu=field_backend == "mxu")
        jf, circ, field = self.jf, self.circ, self.flp.field
        p = field.MODULUS

        def mont_np(x: int) -> np.ndarray:
            return jf._int_to_limbs_np((x % p) * (1 << (32 * jf.n)) % p)

        self.consts: Dict[str, jnp.ndarray] = {}
        # Canonical: subtracted from / compared with canonical tensors.
        self.consts["shares_inv_c"] = jnp.asarray(
            jf._int_to_limbs_np(pow(prio3.num_shares, p - 2, p))
        )
        # Host-precomputed PER-GADGET Montgomery constants: each gadget g
        # has its own interpolation modulus P_g, hence its own root of
        # unity, alpha powers, barycentric weights, and (optionally) NTT
        # twiddles.  Single-gadget circuits see exactly the constants the
        # pre-multi-gadget code built.
        #
        # Gadget-poly evaluation strategy per gadget: the verifier needs
        # gpoly(alpha^k) for k=1..calls, alpha a P-th root of unity.  For
        # small P a Horner scan over the glen coefficients is cheapest;
        # for the wide-vector circuits (P >= 64, e.g. SumVec len=100k
        # chunk=316 -> P=512, glen=1023) Horner costs calls*glen
        # multiplies per report while a fold to P coefficients + P-point
        # NTT costs P*log2(P)/2 — ~70x fewer.  Both produce identical
        # limbs (exact integer math).  ``ntt_min_p`` exists so parity
        # tests can force this branch at tiny P and check it
        # byte-for-byte against the oracle.
        self._gc: List[Dict[str, object]] = []
        for plan in circ.plans:
            w = field.root(plan.P)
            p_inv = pow(plan.P, p - 2, p)
            gc: Dict[str, object] = {
                # alpha^k for k=1..calls (gadget poly eval points).
                "alpha_pows_m": jnp.asarray(
                    np.stack(
                        [mont_np(pow(w, k, p)) for k in range(1, plan.calls + 1)]
                    )
                ),
                # Barycentric constants w^k / P for k=0..calls.
                "bary_c_m": jnp.asarray(
                    np.stack(
                        [
                            mont_np(pow(w, k, p) * p_inv % p)
                            for k in range(plan.calls + 1)
                        ]
                    )
                ),
                "roots_m": jnp.asarray(
                    np.stack([mont_np(pow(w, k, p)) for k in range(plan.calls + 1)])
                ),
                # ALL P root differences feed the inversion-free
                # barycentric weights (prod over j != k of (t - w^k)
                # spans every P-th root, used or not).
                "roots_all_m": jnp.asarray(
                    np.stack([mont_np(pow(w, k, p)) for k in range(plan.P)])
                ),
                "log2_P": plan.P.bit_length() - 1,
                "ntt": None,
            }
            if plan.P >= ntt_min_p:
                P = plan.P
                logp = P.bit_length() - 1
                bitrev = np.zeros(P, dtype=np.int32)
                for i in range(P):
                    bitrev[i] = int(format(i, f"0{logp}b")[::-1], 2)
                tw_stages = []
                m = 2
                while m <= P:
                    w_m = pow(w, P // m, p)
                    tw_stages.append(
                        jnp.asarray(
                            np.stack(
                                [mont_np(pow(w_m, j, p)) for j in range(m // 2)]
                            )
                        )
                    )
                    m *= 2
                gc["ntt"] = (bitrev, tw_stages)
            self._gc.append(gc)
        # Gadget-0 aliases: the planar Pallas paths (single-gadget
        # circuits only) read these under the historical names.
        gc0 = self._gc[0]
        self.alpha_pows_m = gc0["alpha_pows_m"]
        self.bary_c_m = gc0["bary_c_m"]
        self.roots_m = gc0["roots_m"]
        self.roots_all_m = gc0["roots_all_m"]
        self._log2_P = gc0["log2_P"]
        self._ntt = gc0["ntt"]
        self._alpha_mat_cache: Dict[int, np.ndarray] = {}

        valid = self.flp.valid
        if hasattr(valid, "bits"):
            bits = valid.bits
            self.consts["pow2_m"] = jnp.asarray(
                np.stack([mont_np(1 << b) for b in range(bits)])
            )
        if isinstance(valid, FixedPointBoundedL2VecSum):
            nb = valid.bits_per_entry
            shares_inv = pow(prio3.num_shares, p - 2, p)
            # entry-bit recomposition weights 2^b (b < bits_per_entry)
            self.consts["pow2_m"] = jnp.asarray(
                np.stack([mont_np(1 << b) for b in range(nb)])
            )
            # claimed-norm decomposition weights 2^b (b < 2n-2)
            self.consts["pow2_norm_m"] = jnp.asarray(
                np.stack([mont_np(1 << b) for b in range(valid.bits_for_norm)])
            )
            # 2^n (the cross-term weight of the norm expansion)
            self.consts["pow2n_m"] = jnp.asarray(mont_np(1 << nb))
            # shares_inv * 2^(2n-2): multiplied by the per-row entry count
            # d on canonical graphs (offset term of the norm identity)
            self.consts["offsq_m"] = jnp.asarray(
                mont_np(shares_inv * (1 << (2 * nb - 2)))
            )
            # the exact-shape constant offset shares_inv * d * 2^(2n-2)
            self.consts["offset_sq_c"] = jnp.asarray(
                jf._int_to_limbs_np(
                    shares_inv * (valid.entries % p) * (1 << (2 * nb - 2)) % p
                )
            )

    # -- XOF helpers ----------------------------------------------------
    def _dst(self, usage: int) -> bytes:
        return self.prio3._dst(usage)

    def _expand_vec(self, seed_u8, dst, binder_u8, length) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """XOF -> (canonical limbs (B, length, n), ok (B,))."""
        return xof_next_vec_batch(self.jf, seed_u8, dst, binder_u8, length)

    def _xof_seed(self, seed_u8, dst, binder_u8) -> jnp.ndarray:
        """XOF -> one seed-sized output (B, SEED)."""
        from .keccak_pallas import pallas_enabled, xof_words_pallas

        seed_size = self.prio3.xof.SEED_SIZE
        if seed_u8.ndim == 2 and pallas_enabled(seed_u8.shape[0]) and seed_size % 4 == 0:
            words = xof_words_pallas(seed_u8, dst, binder_u8, seed_size // 4)
            return words_to_bytes(words)
        return xof_turboshake128_batch(seed_u8, dst, binder_u8, seed_size)

    # -- share expansion (helper side) ----------------------------------
    def helper_shares(self, agg_id: int, share_seeds_u8: jnp.ndarray):
        """Expand a helper's (meas, proofs) shares from its seed.

        Oracle twin: Prio3._helper_meas_share / _helper_proofs_share.
        Returns (meas (B,MEAS_LEN,n), proofs (B,num_proofs*PROOF_LEN,n), ok (B,)).
        """
        B = share_seeds_u8.shape[0]
        binder = jnp.broadcast_to(
            jnp.asarray(np.array([agg_id], dtype=np.uint8)), (B, 1)
        )
        meas, ok1 = self._expand_vec(
            share_seeds_u8, self._dst(USAGE_MEAS_SHARE), binder, self.flp.MEAS_LEN
        )
        proofs, ok2 = self._expand_vec(
            share_seeds_u8,
            self._dst(USAGE_PROOF_SHARE),
            binder,
            self.flp.PROOF_LEN * self.prio3.num_proofs,
        )
        return meas, proofs, ok1 & ok2

    def _lagrange_coeffs(self, t_m, gi: int = 0):
        """Barycentric Lagrange coefficients at t over gadget ``gi``'s
        P-th roots.

        Inversion-free form: z/(t - w^k) = prod_{j != k} (t - w^j) exactly
        (t^P - 1 factors over ALL P roots), so the coefficients need only
        exclusive prefix/suffix products — this removes a Fermat inversion
        whose 2x(32n)-step sequential scan dominated the query's serial
        sections.  Rows with t on a root have z == 0 and are flagged via
        t_ok for host recompute, as before.
        Returns (lag (B, calls+1, n) Montgomery, t_ok (B,)).
        """
        jf = self.jf
        plan, gc = self.circ.plans[gi], self._gc[gi]
        t_pow = t_m
        for _ in range(gc["log2_P"]):
            t_pow = jf.mont_mul(t_pow, t_pow)
        z = jf.sub(t_pow, jnp.broadcast_to(jf.mont_one(), t_pow.shape))  # t^P - 1
        t_ok = ~jf.is_zero(z)
        K = plan.calls + 1
        denom_all = jf.sub(t_m[:, None, :], gc["roots_all_m"][None])  # (B, P, n)
        others = jf.mutual_products_mont(denom_all, axis=1)
        lag = jf.mont_mul(others[:, :K], gc["bary_c_m"][None])  # (B, K, n)
        return lag, t_ok

    def _gpoly_at(self, gpoly, t_m):
        """Gadget polynomial at t.  Wide polynomials (the 100k-element
        SumVec has glen=1023) use baby-step/giant-step evaluation —
        Horner's glen-step serial chain is the launch's critical path.
        Under mxu both bsgs contractions run as dot_generals."""
        jf = self.jf
        if self.field_backend == "mxu":
            return jf.poly_eval_dot(gpoly, t_m)
        if gpoly.shape[1] >= 64:
            return jf.poly_eval_mont(gpoly, t_m)
        return jf.horner_mont(gpoly, t_m)

    def _gadget_outputs(self, gpoly, B, gi: int = 0):
        """gk (B, calls, n): gadget ``gi``'s polynomial at alpha^1..alpha^calls."""
        jf = self.jf
        plan, gc = self.circ.plans[gi], self._gc[gi]
        if self.field_backend == "mxu":
            # Vandermonde-style matmul: gk[b, k] = sum_j gpoly[b, j] * w^(kj)
            # with the alpha-power table a host-precomputed Montgomery
            # constant shared by every report — ONE dot_general across calls
            # replaces the NTT butterfly stages / the Horner scan, and the
            # canonical residues are identical (exact integer math).
            amat = self._alpha_mat_m(gi)  # (calls, glen, n) Montgomery, host
            w = jnp.asarray(np.ascontiguousarray(amat.transpose(1, 0, 2)))
            return jnp.squeeze(jf.mat_mul_mont(gpoly[:, :, None, :], w), axis=1)
        if gc["ntt"] is not None:
            P = plan.P
            hi = gpoly[:, P:]
            hi = jnp.concatenate(
                [hi, jnp.zeros((B, P - hi.shape[1], jf.n), dtype=_U32)], axis=1
            )
            folded = jf.add(gpoly[:, :P], hi)
            evals = jf.ntt_eval_mont(folded, *gc["ntt"])
            return evals[:, 1 : plan.calls + 1]

        def horner_step(acc, c):
            return (
                jf.add(
                    jf.mont_mul(acc, gc["alpha_pows_m"][None]), c[:, None, :]
                ),
                None,
            )

        coeffs_rev = jnp.moveaxis(jnp.flip(gpoly, axis=1), 1, 0)
        acc0 = jnp.zeros((B, plan.calls, jf.n), dtype=_U32)
        gk, _ = lax.scan(horner_step, acc0, coeffs_rev)
        return _scan_fence(gk)

    # -- FLP query (one proof) ------------------------------------------
    def _query_one(self, meas_m, proof_m, jr_m, t_m, calls_live=None, ml=None):
        """Device FLP query for one proof, over EVERY gadget.

        meas_m (B,MEAS_LEN,n) CANONICAL, proof_m (B,PROOF_LEN,n) CANONICAL,
        jr_m (B,JR_LEN,n) Montgomery, t_m (B,QUERY_RAND_LEN,n) Montgomery
        (one query point per gadget) ->
        (verifier (B,VERIFIER_LEN,n) CANONICAL, t_ok (B,)).
        Every mont_mul pairs one canonical bulk tensor with one Montgomery
        scalar/constant, so products stay canonical (see module docstring).
        The proof splits into per-gadget segments (wire seeds + gadget
        polynomial) and the verifier concatenates [v] + per-gadget
        [wire evals, gpoly(t)] — exactly the scalar FlpGeneric.query
        layout.  Oracle twin: FlpGeneric.query.

        ``calls_live`` (canonical masking, vdaf/canonical.py) is a
        PER-GADGET list of (B,) i32 mask boundaries: this graph is
        compiled for the BUCKET's call counts, and rows from a shorter
        task zero their padded calls out of (a) each gadget-output fold —
        an adversarial gadget polynomial is NOT zero at unused evaluation
        points, so gk must be masked before v — and (b) each barycentric
        coefficient vector, which reproduces the actual circuit's wire
        polynomial exactly (its values at unused P-th roots are zero BY
        DEFINITION, and every fused wire path consumes lag downstream of
        this mask).  ``ml`` (B,) i32 is the row's true measurement length
        for length-dependent gadget inputs (the fixed-point entry
        recomposition and norm fold).
        """
        jf, circ = self.jf, self.circ
        B = meas_m.shape[0]
        ok = jnp.ones((B,), dtype=bool)
        gks = []
        segs = []
        idx = 0
        for gi, plan in enumerate(circ.plans):
            seeds = proof_m[:, idx : idx + plan.arity]  # (B, arity_g, n)
            gpoly = proof_m[:, idx + plan.arity : idx + plan.arity + plan.glen]
            idx += plan.arity + plan.glen

            gk = self._gadget_outputs(gpoly, B, gi=gi)  # (B, calls_g, n)
            cl = calls_live[gi] if calls_live is not None else None
            if cl is not None:
                k = jnp.arange(plan.calls, dtype=jnp.int32)[None, :]
                gk = jnp.where((k < cl[:, None])[:, :, None], gk, 0)
            gks.append(gk)

            # Wire evaluations at t_g via barycentric Lagrange on the
            # gadget's own P-th roots.
            t_g = t_m[:, gi]
            lag, t_ok = self._lagrange_coeffs(t_g, gi=gi)
            ok = ok & t_ok
            if cl is not None:
                k = jnp.arange(plan.calls + 1, dtype=jnp.int32)[None, :]
                lag = jnp.where((k <= cl[:, None])[:, :, None], lag, 0)
            wire_evals = circ.wire_evals_g(
                gi, jf, meas_m, jr_m, lag, seeds, self.consts, ml=ml
            )
            gp_t = self._gpoly_at(gpoly, t_g)  # (B, n)
            segs.append((wire_evals, gp_t))

        v = circ.v_multi(jf, gks, meas_m, jr_m, self.consts, ml=ml)  # (B, n)
        parts = [v[:, None]]
        for wire_evals, gp_t in segs:
            parts.extend([wire_evals, gp_t[:, None]])
        verifier = jnp.concatenate(parts, axis=1)  # (B, VERIFIER_LEN, n)
        return verifier, ok

    # -- prep init ------------------------------------------------------
    def prep_init(
        self,
        agg_id: int,
        verify_key,  # bytes, or (SEED,) u8 array (traced — per-task data)
        nonces_u8: jnp.ndarray,
        *,
        share_seeds_u8: Optional[jnp.ndarray] = None,
        meas_limbs: Optional[jnp.ndarray] = None,
        proofs_limbs: Optional[jnp.ndarray] = None,
        blinds_u8: Optional[jnp.ndarray] = None,
        public_parts_u8: Optional[jnp.ndarray] = None,
        meas_len_u32: Optional[jnp.ndarray] = None,
    ) -> Dict[str, jnp.ndarray]:
        """Batched Prio3.prep_init for one aggregator.

        Leader (agg_id=0) passes canonical ``meas_limbs``/``proofs_limbs``;
        helpers pass ``share_seeds_u8``.  ``public_parts_u8`` is (B, S, SEED)
        when the circuit uses joint randomness.  Returns canonical tensors:
        out_share (B,OUT,n), verifiers (B,num_proofs*VER,n),
        joint_rand_part/corrected_seed (B,SEED) u8 (if applicable), and
        ok (B,) flagging rows needing host fallback.

        ``meas_len_u32`` (B,) engages canonical-shape masking
        (vdaf/canonical.py): this instance is the BUCKET's padded twin and
        each row carries its task's true MEAS_LEN.  Measurement columns at
        or past it are zeroed (the helper XOF expands the bucket width —
        its stream is prefix-stable, but the tail is live data that must
        not reach the wires), the joint-rand-part XOF absorbs the row's
        true ``enc(meas)`` byte length via the length-selected sponge, and
        the gadget-call masks flow into _query_one.  Outputs are
        byte-identical to the row's own unpadded oracle.

        Oracle twin: Prio3.prep_init (janus_tpu/vdaf/prio3.py).
        """
        prio3, flp, jf = self.prio3, self.flp, self.jf
        B = nonces_u8.shape[0]
        ok = jnp.ones((B,), dtype=bool)
        if agg_id == 0:
            meas, proofs = meas_limbs, proofs_limbs
        else:
            meas, proofs, ok_h = self.helper_shares(agg_id, share_seeds_u8)
            ok = ok & ok_h
        ml = calls_live = None
        if meas_len_u32 is not None:
            ml = meas_len_u32.astype(jnp.int32)
            calls_live = self.circ.calls_live_list(ml)
            col = jnp.arange(flp.MEAS_LEN, dtype=jnp.int32)[None, :]
            meas = jnp.where((col < ml[:, None])[:, :, None], meas, 0)

        if isinstance(verify_key, (bytes, bytearray)):
            verify_key = jnp.asarray(np.frombuffer(bytes(verify_key), dtype=np.uint8))
        vk = jnp.broadcast_to(verify_key, (B, verify_key.shape[-1]))
        qr, ok_q = self._expand_vec(
            vk,
            self._dst(USAGE_QUERY_RANDOMNESS),
            nonces_u8,
            flp.QUERY_RAND_LEN * prio3.num_proofs,
        )
        ok = ok & ok_q

        out: Dict[str, jnp.ndarray] = {}
        jr = None
        if flp.JOINT_RAND_LEN > 0:
            # joint_rand_part = XOF(blind, dst, agg_id || nonce || enc(meas))
            agg_b = jnp.broadcast_to(
                jnp.asarray(np.array([agg_id], dtype=np.uint8)), (B, 1)
            )
            meas_bytes = limbs_to_bytes(meas)
            part_binder = jnp.concatenate([agg_b, nonces_u8, meas_bytes], axis=-1)
            if ml is None:
                part = self._xof_seed(
                    blinds_u8, self._dst(USAGE_JOINT_RAND_PART), part_binder
                )
            else:
                # Canonical padding: the binder embeds enc(meas), whose true
                # byte length is per-row — absorb with the length-selected
                # sponge (the padded tail bytes are zero by the mask above,
                # which the select absorb's pad construction requires).
                from .keccak_jax import xof_turboshake128_batch_select

                binder_len = 1 + nonces_u8.shape[-1] + ml * (4 * jf.n)
                part = xof_turboshake128_batch_select(
                    blinds_u8,
                    self._dst(USAGE_JOINT_RAND_PART),
                    part_binder,
                    prio3.xof.SEED_SIZE,
                    binder_len,
                )
            # corrected joint rand seed over parts with ours substituted.
            S = prio3.num_shares
            pieces = []
            if agg_id > 0:
                pieces.append(public_parts_u8[:, :agg_id].reshape(B, -1))
            pieces.append(part)
            if agg_id < S - 1:
                pieces.append(public_parts_u8[:, agg_id + 1 :].reshape(B, -1))
            seed_binder = jnp.concatenate(pieces, axis=-1)
            zero_seed = jnp.zeros((B, prio3.xof.SEED_SIZE), dtype=jnp.uint8)
            corrected = self._xof_seed(zero_seed, self._dst(USAGE_JOINT_RAND_SEED), seed_binder)
            jr_vec, ok_j = self._expand_vec(
                corrected,
                self._dst(USAGE_JOINT_RANDOMNESS),
                jnp.zeros((B, 0), dtype=jnp.uint8),
                flp.JOINT_RAND_LEN * prio3.num_proofs,
            )
            ok = ok & ok_j
            jr = jr_vec
            out["joint_rand_part"] = part
            out["corrected_seed"] = corrected

        # Bulk tensors stay canonical; only the per-report multipliers (joint
        # rand, query point t) go to Montgomery form — a handful of elements
        # vs MEAS_LEN + PROOF_LEN full-width conversion passes.
        jr_m = jf.to_mont(jr) if jr is not None else None

        verifiers = []
        for i in range(prio3.num_proofs):
            pm = proofs[:, i * flp.PROOF_LEN : (i + 1) * flp.PROOF_LEN]
            # one query point per gadget: the full QUERY_RAND_LEN segment
            ti = jf.to_mont(
                qr[:, i * flp.QUERY_RAND_LEN : (i + 1) * flp.QUERY_RAND_LEN]
            )
            ji = (
                jr_m[:, i * flp.JOINT_RAND_LEN : (i + 1) * flp.JOINT_RAND_LEN]
                if jr_m is not None
                else jnp.zeros((B, 0, jf.n), dtype=_U32)
            )
            ver, t_ok = self._query_one(
                meas, pm, ji, ti, calls_live=calls_live, ml=ml
            )
            ok = ok & t_ok
            verifiers.append(ver)

        out["verifiers"] = jnp.concatenate(verifiers, axis=1)
        out["out_share"] = self.circ.truncate(jf, meas, self.consts, ml=ml)
        out["ok"] = ok
        return out

    def query_batch(
        self,
        meas_limbs: jnp.ndarray,
        proofs_limbs: jnp.ndarray,
        jr_limbs: Optional[jnp.ndarray],
        qr_limbs: jnp.ndarray,
    ) -> Dict[str, jnp.ndarray]:
        """FLP query ONLY — every XOF output precomputed by the caller.

        The device half of the hybrid path for host-XOF VDAFs (the
        HMAC-SHA256-AES128 multiproof variant, reference:
        core/src/vdaf.rs:178-195): meas (B, MEAS_LEN, n), proofs
        (B, num_proofs*PROOF_LEN, n), jr (B, num_proofs*JR_LEN, n) or None,
        qr (B, num_proofs*QUERY_RAND_LEN, n), all canonical.  Returns
        verifiers (B, num_proofs*VER, n), out_share (B, OUT, n), and ok
        (rows whose query point hit an interpolation root).  Identical
        field math to prep_init's verifier loop — byte parity with the
        oracle's FlpGeneric.query per proof.
        """
        prio3, flp, jf = self.prio3, self.flp, self.jf
        B = meas_limbs.shape[0]
        ok = jnp.ones((B,), dtype=bool)
        jr_m = jf.to_mont(jr_limbs) if jr_limbs is not None else None
        verifiers = []
        for i in range(prio3.num_proofs):
            pm = proofs_limbs[:, i * flp.PROOF_LEN : (i + 1) * flp.PROOF_LEN]
            ti = jf.to_mont(
                qr_limbs[:, i * flp.QUERY_RAND_LEN : (i + 1) * flp.QUERY_RAND_LEN]
            )
            ji = (
                jr_m[:, i * flp.JOINT_RAND_LEN : (i + 1) * flp.JOINT_RAND_LEN]
                if jr_m is not None
                else jnp.zeros((B, 0, jf.n), dtype=_U32)
            )
            ver, t_ok = self._query_one(meas_limbs, pm, ji, ti)
            ok = ok & t_ok
            verifiers.append(ver)
        return {
            "verifiers": jnp.concatenate(verifiers, axis=1),
            "out_share": self.circ.truncate(jf, meas_limbs, self.consts),
            "ok": ok,
        }

    def decide_batch(self, combined_verifiers: jnp.ndarray) -> jnp.ndarray:
        """Decide from the COMBINED (summed) verifier tensor — the field
        half of prep_shares_to_prep, XOF-free for the hybrid backend."""
        prio3, flp, jf, circ = self.prio3, self.flp, self.jf, self.circ
        B = combined_verifiers.shape[0]
        decide = jnp.ones((B,), dtype=bool)
        for i in range(prio3.num_proofs):
            ver = combined_verifiers[
                :, i * flp.VERIFIER_LEN : (i + 1) * flp.VERIFIER_LEN
            ]
            decide = decide & jf.is_zero(ver[:, 0])
            idx = 1
            for gi, plan in enumerate(circ.plans):
                x = ver[:, idx : idx + plan.arity]
                y_scaled = jf.from_mont(ver[:, idx + plan.arity])
                g = circ.gadget_eval_scaled_g(gi, jf, x)
                decide = decide & jf.eq(g, y_scaled)
                idx += plan.arity + 1
        return decide

    # -- planar (limb-plane) helper prep --------------------------------
    def planar_eligible(self, agg_id: int, batch: int) -> bool:
        """True when the limb-planar Pallas fast path serves this prep."""
        from .keccak_pallas import pallas_enabled

        if self.field_backend == "mxu":
            # The MXU layer lives on the row-major path: its contractions
            # want (batch x K) matrices feeding dot_general, not lane-planar
            # tensors feeding the VPU Pallas kernels.  field_backend is the
            # A/B seam between the two accelerated layouts.
            return False
        if isinstance(self.circ, _DHistogram):
            # u16-half lazy meas_sum is exact only up to 65535 terms.
            circuit_ok = self.flp.MEAS_LEN <= 65535
        elif isinstance(self.circ, _DSumVec):
            # bits > 1 would need a planar truncate (out_share != meas).
            circuit_ok = self.flp.valid.bits == 1
        else:
            # Count/Sum ride the all-planes small-circuit path.
            circuit_ok = isinstance(self.circ, (_DCount, _DSum))
        return (
            circuit_ok
            and self.prio3.num_proofs == 1
            # planar aggregate's lazy batch sum is exact to 65535 terms.
            and batch <= 65535
            and pallas_enabled(batch)
        )

    def _planar_ok(self, stream, num_elems):
        """Canonicality of stream-ordered element words -> ok (B,) row-major."""
        jf = self.jf
        el = stream[: num_elems * jf.n].reshape(num_elems, jf.n, *stream.shape[1:])
        borrow = jnp.zeros(el.shape[0:1] + el.shape[2:], dtype=_U32)
        from .field_jax import _sbb

        for i in range(jf.n):
            _, borrow = _sbb(el[:, i], jnp.asarray(np.uint32(jf.p_np[i])), borrow)
        valid = jnp.all(borrow == 1, axis=0)  # (R, 128)
        return valid.reshape(-1)

    def _rows_to_planes_small(self, rows3):
        """(B, L, n) row-major limbs -> (R, n, L, 128) planes (narrow L)."""
        B, L, n = rows3.shape
        return rows3.reshape(B // 128, 128, L, n).transpose(0, 3, 2, 1)

    def _ones_planes(self, R):
        jf = self.jf
        return [jnp.broadcast_to(jf.mont_one()[l], (R, 128)) for l in range(jf.n)]

    def _pow_range_planes(self, x_pl, count):
        """x^1..x^count on limb-list planes via baby-step/giant-step.

        x_pl: n arrays (R, 128) Montgomery -> n arrays (R, count, 128).
        Exact Montgomery identities (byte parity with cumprod)."""
        import math

        jf = self.jf
        n = jf.n
        R = x_pl[0].shape[0]
        bs = max(1, math.isqrt(count))
        gs = -(-count // bs)
        baby = [x_pl]
        for _ in range(bs - 1):
            baby.append(jf.mont_mul_limbs(baby[-1], x_pl))
        giant = [self._ones_planes(R)]
        for _ in range(gs - 1):
            giant.append(jf.mont_mul_limbs(giant[-1], baby[-1]))
        baby_t = [jnp.stack([b[l] for b in baby], axis=1) for l in range(n)]
        giant_t = [jnp.stack([g[l] for g in giant], axis=1) for l in range(n)]
        outer = jf.mont_mul_limbs(
            [g[:, :, None, :] for g in giant_t], [b[:, None, :, :] for b in baby_t]
        )
        return [o.reshape(R, gs * bs, 128)[:, :count] for o in outer]

    def _gpoly_at_planes(self, gp, t_pl):
        """gpoly(t) on limb-list planes (baby-step/giant-step).

        gp: n arrays (R, glen, 128) canonical coefficients, t_pl: n arrays
        (R, 128) Montgomery -> n arrays (R, 128) canonical."""
        import math

        jf = self.jf
        glen = gp[0].shape[1]
        R = gp[0].shape[0]
        bs = max(1, math.isqrt(glen))
        gs = -(-glen // bs)
        one = self._ones_planes(R)
        baby = [one]  # t^j for j in 0..bs-1
        for _ in range(bs - 1):
            baby.append(jf.mont_mul_limbs(baby[-1], t_pl))
        tbs = jf.mont_mul_limbs(baby[-1], t_pl)  # t^bs
        giant = [one]
        for _ in range(gs - 1):
            giant.append(jf.mont_mul_limbs(giant[-1], tbs))
        gpt = None
        for g in range(gs):
            inner = None
            for j in range(bs):
                idx = g * bs + j
                if idx >= glen:
                    break
                term = jf.mont_mul_limbs([x[:, idx] for x in gp], baby[j])
                inner = term if inner is None else jf.add_limbs(inner, term)
            outer = jf.mont_mul_limbs(inner, giant[g])
            gpt = outer if gpt is None else jf.add_limbs(gpt, outer)
        return gpt

    def _lagrange_planes(self, t_pl):
        """Planar twin of _lagrange_coeffs.

        t_pl: limb list of (R, 128) Montgomery -> (lag_pl (R, n, K, 128)
        Montgomery, t_ok (R, 128) bool).  Same inversion-free barycentric
        construction (z/(t - w^k) = prod_{j != k} (t - w^j)); prefix/suffix
        chains are lane-wide multiplies instead of T(1,128) row passes.
        Byte parity follows from exact Montgomery identities.
        """
        jf, circ = self.jf, self.circ
        n = jf.n
        R = t_pl[0].shape[0]
        P = circ.P
        K = circ.calls + 1
        one = [jnp.broadcast_to(jf.mont_one()[l], (R, 128)) for l in range(n)]

        tp = t_pl
        for _ in range(self._log2_P):
            tp = jf.mont_mul_limbs(tp, tp)
        z = jf.sub_limbs(tp, one)  # t^P - 1
        nz = z[0]
        for l in range(1, n):
            nz = nz | z[l]
        t_ok = nz != 0

        roots = self.roots_all_m  # (P, n) Montgomery
        denom = [
            jf.sub_limbs(
                t_pl,
                [jnp.broadcast_to(roots[k, l], (R, 128)) for l in range(n)],
            )
            for k in range(P)
        ]
        prefix = [one]
        for k in range(1, P):
            prefix.append(jf.mont_mul_limbs(prefix[-1], denom[k - 1]))
        suffix = [one] * P
        for k in range(P - 2, -1, -1):
            suffix[k] = jf.mont_mul_limbs(suffix[k + 1], denom[k + 1])
        bary = self.bary_c_m  # (K, n) Montgomery
        lag_cols = []
        for k in range(K):
            others = jf.mont_mul_limbs(prefix[k], suffix[k])
            lag_cols.append(
                jf.mont_mul_limbs(
                    others,
                    [jnp.broadcast_to(bary[k, l], (R, 128)) for l in range(n)],
                )
            )
        lag_pl = jnp.stack(
            [jnp.stack([col[l] for col in lag_cols], axis=1) for l in range(n)],
            axis=1,
        )  # (R, n, K, 128)
        return lag_pl, t_ok

    def _alpha_mat_m(self, gi: int = 0):
        """Constant w^{k*j} Montgomery table (calls, glen, n) per gadget for
        the direct-sum / Vandermonde gadget evaluation (lazy)."""
        mat = self._alpha_mat_cache.get(gi)
        if mat is None:
            field, jf = self.flp.field, self.jf
            plan = self.circ.plans[gi]
            p = field.MODULUS
            w = field.root(plan.P)

            def mont_np(x: int) -> np.ndarray:
                return jf._int_to_limbs_np((x % p) * (1 << (32 * jf.n)) % p)

            # Cached as a HOST array: a jnp constant created inside one jit
            # trace must not be cached across traces (tracer leak).
            mat = np.stack(
                [
                    np.stack(
                        [mont_np(pow(w, k * j, p)) for j in range(plan.glen)]
                    )
                    for k in range(1, plan.calls + 1)
                ]
            )  # (calls, glen, n)
            self._alpha_mat_cache[gi] = mat
        return mat

    def _gadget_planes(self, gp_pl, t_pl):
        """Planar gadget-polynomial evaluations.

        gp_pl (R, n, glen, 128) canonical coefficient planes, t_pl limb list
        of (R, 128) Montgomery -> (gk planes (R, n, calls, 128) canonical,
        gpoly(t) limb list of (R, 128) canonical).  gk[k] = gpoly(alpha^k)
        as the DIRECT sum over coefficients times constant w^{kj} powers —
        the same residue the row path's Horner chain produces, and canonical
        limbs are unique, so byte parity holds while the glen-step serial
        chain over T(1,128) row tensors disappears.
        """
        import math

        jf, circ = self.jf, self.circ
        n = jf.n
        R = gp_pl.shape[0]
        glen = gp_pl.shape[2]
        gp = [gp_pl[:, l] for l in range(n)]  # (R, glen, 128)
        amat = self._alpha_mat_m()  # (calls, glen, n)
        gk_cols = []
        for k in range(circ.calls):
            c = [
                jnp.broadcast_to(amat[k, :, l][None, :, None], (R, glen, 128))
                for l in range(n)
            ]
            terms = jf.mont_mul_limbs(gp, c)
            acc = [t[:, 0] for t in terms]
            for j in range(1, glen):
                acc = jf.add_limbs(acc, [t[:, j] for t in terms])
            gk_cols.append(acc)
        gk_pl = jnp.stack(
            [jnp.stack([col[l] for col in gk_cols], axis=1) for l in range(n)],
            axis=1,
        )  # (R, n, calls, 128)
        return gk_pl, self._gpoly_at_planes(gp, t_pl)

    def _histogram_coeff_planes(self, jr_m, lag_pl, cp):
        """Planar twin of _DHistogram.planar_coeffs.

        Generates every wire-kernel coefficient tensor DIRECTLY in plane
        layout with limb-list Montgomery ops (lanes = reports), so no
        full-width row-major (B, chunk, n) pass exists — XLA lays those out
        T(1,128) (batch minor) at several times the planar cost.  The chunk
        power table r^(u+1) uses baby-step/giant-step (two ~sqrt(cp)
        sequential chains of lane-wide multiplies + one wide outer product).
        Every step is an exact Montgomery identity, so the values are
        byte-identical to planar_coeffs (tests/test_prepare.py planar
        parity).  Returns (rch_pl (R,n,cp,128), kl_pl (R,n,calls,128),
        lagk_pl, lag0_pl (R,n,128), ccorr_pl (R,n,128)).

        Pad columns u in [chunk, cp) get REAL powers r^(u+1) rather than
        planar_coeffs' zero padding — sound because the measurement pad
        columns are zero, so those wire outputs are garbage either way and
        the consumers mask/slice them.
        """
        import math

        jf, circ = self.jf, self.circ
        n = jf.n
        calls = circ.calls
        jr_pl = self._rows_to_planes_small(jr_m)  # (R, n, JR, 128)
        R = jr_pl.shape[0]
        one = self._ones_planes(R)
        r = [jr_pl[:, l, 0] for l in range(n)]
        rch = self._pow_range_planes(r, cp)
        rc = [l_[:, circ.chunk - 1] for l_ in rch]  # r^chunk
        r_call = [one]
        for _ in range(calls - 1):
            r_call.append(jf.mont_mul_limbs(r_call[-1], rc))
        r_call_t = [jnp.stack([c[l] for c in r_call], axis=1) for l in range(n)]
        lagk_t = [lag_pl[:, l, 1 : 1 + calls] for l in range(n)]
        kl = jf.mont_mul_limbs(r_call_t, lagk_t)

        lag_sum = [lagk_t[l][:, 0] for l in range(n)]
        for k in range(1, calls):
            lag_sum = jf.add_limbs(lag_sum, [lagk_t[l][:, k] for l in range(n)])
        c = self.consts["shares_inv_c"]
        c_pl = [jnp.broadcast_to(c[l], (R, 128)) for l in range(n)]
        ccorr = jf.mont_mul_limbs(c_pl, lag_sum)

        return (
            jnp.stack(rch, axis=1),  # (R, n, cp, 128)
            jnp.stack(kl, axis=1),  # (R, n, calls, 128)
            jnp.stack(lagk_t, axis=1),  # (R, n, calls, 128)
            lag_pl[:, :, 0],  # (R, n, 128)
            jnp.stack(ccorr, axis=1),  # (R, n, 128)
        )

    def _jr_part_planes(self, agg_id, blinds_u8, nonces_u8, meas_stream):
        """Joint-rand-part XOF with the 16 KB meas binder built in-plane.

        The message is  len(dst) || dst || blind || agg_id || nonce ||
        meas_bytes || padding.  meas_bytes already exist as the XOF squeeze
        planes; a 16/8/24-bit funnel shift aligns them into message words,
        replacing a byte-level concat plus a full-batch lane transpose.
        Byte-identical to the row-major absorb (tests/test_prepare.py).
        """
        from .keccak_pallas import (
            RATE,
            RATE_WORDS,
            absorb_planes_pallas,
            rows_to_planes,
        )
        from .keccak_jax import bytes_to_words

        jf = self.jf
        B = nonces_u8.shape[0]
        R = B // 128
        dst = self._dst(USAGE_JOINT_RAND_PART)
        W_m = meas_stream.shape[0]
        hb_len = 1 + len(dst) + blinds_u8.shape[-1] + 1 + nonces_u8.shape[-1]
        q, rm = divmod(hb_len, 4)
        msg_len = hb_len + 4 * W_m
        nblocks = msg_len // RATE + 1
        msg_words = nblocks * RATE_WORDS

        # Head: constant prefix + per-report blind/agg_id/nonce, padded to a
        # word boundary, as (ceil(hb_len/4), R, 128) planes.
        prefix = np.frombuffer(bytes([len(dst)]) + dst, dtype=np.uint8)
        agg_b = jnp.broadcast_to(jnp.asarray(np.array([agg_id], dtype=np.uint8)), (B, 1))
        head_pad = (-hb_len) % 4
        head_parts = [
            jnp.broadcast_to(jnp.asarray(prefix), (B, len(prefix))),
            blinds_u8,
            agg_b,
            nonces_u8,
        ]
        if head_pad:
            head_parts.append(jnp.zeros((B, head_pad), dtype=jnp.uint8))
        head_words = bytes_to_words(jnp.concatenate(head_parts, axis=-1))
        head_planes = rows_to_planes(head_words)  # (q or q+1, R, 128)

        # Tail: TurboSHAKE padding bytes (constant), as extension words so
        # the funnel below can treat meas+pad as one stream.  The funnel
        # consumes msg_words - q extension words total; the meas stream
        # provides W_m, so (rm + pad_len)/4 constant words complete it
        # (exact: 4*msg_words = 4*q + rm + 4*W_m + pad_len).
        pad_len = nblocks * RATE - msg_len
        pad_words_needed = (rm + pad_len) // 4
        pad = np.zeros(pad_words_needed * 4, dtype=np.uint8)
        pad[0] = 0x01
        pad[pad_len - 1] ^= 0x80
        pad_words_np = pad.view("<u4").astype(np.uint32)
        ext_const = jnp.broadcast_to(
            jnp.asarray(pad_words_np)[:, None, None], (pad_words_needed, R, 128)
        )
        ext = jnp.concatenate([meas_stream, ext_const], axis=0)

        if rm == 0:
            body = ext[: msg_words - q]
            msg = jnp.concatenate([head_planes[:q], body], axis=0)
        else:
            sh = 8 * rm
            boundary = head_planes[q] | (ext[0] << sh)
            nbody = msg_words - q - 1
            body = (ext[:nbody] >> (32 - sh)) | (ext[1 : nbody + 1] << sh)
            msg = jnp.concatenate([head_planes[:q], boundary[None], body], axis=0)

        seed_words = self.prio3.xof.SEED_SIZE // 4
        return absorb_planes_pallas(msg, seed_words)  # (seed_words, R, 128)

    def prep_init_planar(
        self,
        agg_id: int,
        verify_key,
        nonces_u8: jnp.ndarray,
        *,
        share_seeds_u8: Optional[jnp.ndarray] = None,
        meas_limbs: Optional[jnp.ndarray] = None,
        proofs_limbs: Optional[jnp.ndarray] = None,
        blinds_u8: Optional[jnp.ndarray] = None,
        public_parts_u8: Optional[jnp.ndarray] = None,
        keep_planar: bool = False,
    ) -> Dict[str, jnp.ndarray]:
        """Prep in the limb-planar layout (histogram family), either side.

        Helpers (agg_id > 0) pass ``share_seeds_u8`` and the meas/proof
        streams come from the planar XOF squeeze; the leader (agg_id == 0)
        passes its explicit ``meas_limbs``/``proofs_limbs`` row-major and
        they are lane-transposed into the same stream planes (no XOF
        expansion and no canonicality recheck — reference leader prep:
        aggregator/src/aggregator/aggregation_job_driver.rs:397-449).

        Same outputs as prep_init except ``out_share`` stays limb-planar
        (R, n, OUTPUT_LEN, 128) — ``aggregate`` consumes either layout.  The
        stream planes feed the Pallas wire kernel directly; nothing
        batch-wide is lane-transposed except the (small) verifier tensor.
        """
        if isinstance(self.circ, (_DCount, _DSum)):
            return self.prep_init_planar_small(
                agg_id,
                verify_key,
                nonces_u8,
                share_seeds_u8=share_seeds_u8,
                meas_limbs=meas_limbs,
                proofs_limbs=proofs_limbs,
                blinds_u8=blinds_u8,
                public_parts_u8=public_parts_u8,
            )
        from .keccak_jax import words_to_bytes
        from .keccak_pallas import rows_to_planes, xof_planes_pallas
        from .flp_pallas import pad_chunk, wire_evals_planar, _pallas_interpret

        prio3, flp, jf, circ = self.prio3, self.flp, self.jf, self.circ
        B = nonces_u8.shape[0]
        R = B // 128
        n = jf.n

        if agg_id == 0:
            # Leader: explicit shares -> stream planes (word w of element e,
            # limb l at stream position e*n + l, little-endian — the same
            # order the XOF squeeze emits).
            meas_st = rows_to_planes(meas_limbs.reshape(B, flp.MEAS_LEN * n))
            proofs_st = rows_to_planes(
                proofs_limbs.reshape(B, flp.PROOF_LEN * n)
            )
            ok = jnp.ones((B,), dtype=bool)
        else:
            binder = jnp.broadcast_to(
                jnp.asarray(np.array([agg_id], dtype=np.uint8)), (B, 1)
            )
            meas_st = xof_planes_pallas(
                share_seeds_u8, self._dst(USAGE_MEAS_SHARE), binder, flp.MEAS_LEN * n
            )  # (MEAS_LEN*n, R, 128)
            proofs_st = xof_planes_pallas(
                share_seeds_u8, self._dst(USAGE_PROOF_SHARE), binder, flp.PROOF_LEN * n
            )
            ok = self._planar_ok(meas_st, flp.MEAS_LEN) & self._planar_ok(
                proofs_st, flp.PROOF_LEN
            )

        # Limb-planar views: lanes stay report-indexed throughout.  The
        # histogram wire kernel reads the RAW streams (one transpose each —
        # circuit padding / per-call splitting / seed de-interleaving happen
        # in-register); only the SumVec slab path still builds the padded
        # chunk layout.
        cp = pad_chunk(circ.chunk)
        m_el = meas_st.reshape(flp.MEAS_LEN, n, R, 128)
        m_lp = m_el.transpose(2, 1, 0, 3)  # (R, n, MEAS_LEN, 128)
        p_el = proofs_st.reshape(flp.PROOF_LEN, n, R, 128)
        p_lp = p_el.transpose(2, 1, 0, 3)  # (R, n, PROOF_LEN, 128)
        gpoly = (
            p_el[circ.arity :].transpose(2, 3, 0, 1).reshape(B, circ.glen, n)
        )  # small row-major

        # Joint randomness: part from the in-plane absorb, the rest row-major.
        part_planes = self._jr_part_planes(agg_id, blinds_u8, nonces_u8, meas_st)
        from .keccak_pallas import planes_to_rows

        part = words_to_bytes(planes_to_rows(part_planes))  # (B, SEED)
        S = prio3.num_shares
        pieces = []
        if agg_id > 0:
            pieces.append(public_parts_u8[:, :agg_id].reshape(B, -1))
        pieces.append(part)
        if agg_id < S - 1:
            pieces.append(public_parts_u8[:, agg_id + 1 :].reshape(B, -1))
        seed_binder = jnp.concatenate(pieces, axis=-1)
        zero_seed = jnp.zeros((B, prio3.xof.SEED_SIZE), dtype=jnp.uint8)
        corrected = self._xof_seed(zero_seed, self._dst(USAGE_JOINT_RAND_SEED), seed_binder)
        jr_vec, ok_j = self._expand_vec(
            corrected,
            self._dst(USAGE_JOINT_RANDOMNESS),
            jnp.zeros((B, 0), dtype=jnp.uint8),
            flp.JOINT_RAND_LEN,
        )
        if isinstance(verify_key, (bytes, bytearray)):
            verify_key = jnp.asarray(np.frombuffer(bytes(verify_key), dtype=np.uint8))
        vk = jnp.broadcast_to(verify_key, (B, verify_key.shape[-1]))
        qr, ok_q = self._expand_vec(
            vk, self._dst(USAGE_QUERY_RANDOMNESS), nonces_u8, flp.QUERY_RAND_LEN
        )
        ok = ok & ok_j & ok_q

        jr_m = jf.to_mont(jr_vec)
        t_m = jf.to_mont(qr[:, 0])

        ev_pl = od_pl = None
        if isinstance(circ, _DHistogram):
            from .flp_pallas import _grid_chunk

            t_planes_a = self._rows_to_planes_small(t_m[:, None, :])[:, :, 0]
            t_pl = [t_planes_a[:, l] for l in range(n)]
            lag_pl, t_ok_pl = self._lagrange_planes(t_pl)
            ok = ok & t_ok_pl.reshape(B)
            NJc, UCc = _grid_chunk(circ.chunk)
            rch_pl, kl_pl, lagk_pl, lag0_pl, ccorr_pl = self._histogram_coeff_planes(
                jr_m, lag_pl, NJc * UCc
            )
            ev_pl, od_pl = wire_evals_planar(
                jf,
                flp.MEAS_LEN,
                circ.chunk,
                m_lp,
                p_lp,
                rch_pl,
                kl_pl,
                lagk_pl,
                lag0_pl,
                ccorr_pl,
                interpret=_pallas_interpret(),
            )  # each (R, n, chunk, 128)
            # Gadget polynomial: planar direct-sum evaluation (no glen-step
            # row-major Horner chain); gk back to rows only for the tiny
            # (B, calls, n) v computation.
            gk_pl, gpt_limbs = self._gadget_planes(p_lp[:, :, circ.arity :], t_pl)
            gk = gk_pl.transpose(0, 3, 2, 1).reshape(B, circ.calls, n)
            gp_t = (
                jnp.stack(gpt_limbs, axis=1).transpose(0, 2, 1).reshape(B, n)
            )
            # v from the lazily-summed measurement (see JField._sum_lazy).
            slo = jnp.sum(m_lp & np.uint32(0xFFFF), axis=2)  # (R, n, 128)
            shi = jnp.sum(m_lp >> 16, axis=2)
            meas_sum = jf.lazy_fold(
                slo.transpose(0, 2, 1).reshape(B, n),
                shi.transpose(0, 2, 1).reshape(B, n),
            )
            v = circ.v_from_meas_sum(jf, gk, meas_sum, jr_m, self.consts)
        else:  # _DSumVec: padded chunk layout for the call-slab kernels
            lag, t_ok = self._lagrange_coeffs(t_m)
            ok = ok & t_ok
            if circ.pad_len:
                m_pad = jnp.concatenate(
                    [m_lp, jnp.zeros((R, n, circ.pad_len, 128), dtype=_U32)],
                    axis=2,
                )
            else:
                m_pad = m_lp
            m_pl = m_pad.reshape(R, n, circ.calls, circ.chunk, 128)
            if cp != circ.chunk:
                m_pl = jnp.pad(
                    m_pl, ((0, 0), (0, 0), (0, 0), (0, cp - circ.chunk), (0, 0))
                )
            swe_pl = p_lp[:, :, 0 : circ.arity : 2]
            swo_pl = p_lp[:, :, 1 : circ.arity : 2]
            if cp != circ.chunk:
                hpad = ((0, 0), (0, 0), (0, cp - circ.chunk), (0, 0))
                swe_pl = jnp.pad(swe_pl, hpad)
                swo_pl = jnp.pad(swo_pl, hpad)
            wire = self._sumvec_wires_planar(m_pl, swe_pl, swo_pl, jr_m, lag, cp)
            gk = self._gadget_outputs(gpoly, B)
            v = jf.sum(gk, axis=1)
            gp_t = self._gpoly_at(gpoly, t_m)

        out = {
            "out_share": m_lp,  # planar; aggregate() accepts this layout
            "ok": ok,
            "joint_rand_part": part,
            "corrected_seed": corrected,
        }
        if ev_pl is not None and keep_planar:
            # Planar-combine consumers: wires stay in plane layout; only the
            # tiny v / gpoly(t) rows leave it.  No row-major verifier is
            # materialized (prep_shares_to_prep_planar pairs the planes
            # directly).
            out.update(wire_ev_pl=ev_pl, wire_od_pl=od_pl, v_row=v, gpt_row=gp_t)
            return out
        if ev_pl is not None:
            wire = self._zip_planes_to_rows(ev_pl, od_pl)[:, : circ.arity]
        out["verifiers"] = jnp.concatenate([v[:, None], wire, gp_t[:, None]], axis=1)
        return out

    def _stream_to_limb_planes(self, stream, num_elems):
        """(L*n, R, 128) stream words -> limb list of n arrays (R, L, 128)."""
        jf = self.jf
        el = stream[: num_elems * jf.n].reshape(num_elems, jf.n, -1, 128)
        return [el[:, l].transpose(1, 0, 2) for l in range(jf.n)]

    def prep_init_planar_small(
        self,
        agg_id: int,
        verify_key,
        nonces_u8: jnp.ndarray,
        *,
        share_seeds_u8: Optional[jnp.ndarray] = None,
        meas_limbs: Optional[jnp.ndarray] = None,
        proofs_limbs: Optional[jnp.ndarray] = None,
        blinds_u8: Optional[jnp.ndarray] = None,
        public_parts_u8: Optional[jnp.ndarray] = None,
    ) -> Dict[str, jnp.ndarray]:
        """Count/Sum prep entirely in plane layout (no wire Pallas kernel).

        These circuits move a few dozen field elements per report, so the
        whole FLP query fits in limb-list ops over (R, L, 128) planes —
        which XLA tiles (8, 128) and fuses well, unlike the row-major
        T(1,128) emission this path replaces.  The XOF expansion/absorb
        still runs in the planar Keccak kernels.  Outputs and byte parity
        match prep_init exactly (tests/test_prepare.py); out_share stays
        planar (R, n, OUTPUT_LEN, 128) like prep_init_planar's.

        Reference twins: leader aggregation_job_driver.rs:397-449, helper
        aggregator.rs:2101 — both sides of the small-circuit VDAFs ride the
        same accelerated path as the histogram family.
        """
        from .keccak_jax import words_to_bytes
        from .keccak_pallas import rows_to_planes, xof_planes_pallas

        prio3, flp, jf, circ = self.prio3, self.flp, self.jf, self.circ
        B = nonces_u8.shape[0]
        R = B // 128
        n = jf.n
        has_jr = flp.JOINT_RAND_LEN > 0

        if agg_id == 0:
            meas_st = rows_to_planes(meas_limbs.reshape(B, flp.MEAS_LEN * n))
            proofs_st = rows_to_planes(proofs_limbs.reshape(B, flp.PROOF_LEN * n))
            ok = jnp.ones((B,), dtype=bool)
        else:
            binder = jnp.broadcast_to(
                jnp.asarray(np.array([agg_id], dtype=np.uint8)), (B, 1)
            )
            meas_st = xof_planes_pallas(
                share_seeds_u8, self._dst(USAGE_MEAS_SHARE), binder, flp.MEAS_LEN * n
            )
            proofs_st = xof_planes_pallas(
                share_seeds_u8, self._dst(USAGE_PROOF_SHARE), binder, flp.PROOF_LEN * n
            )
            ok = self._planar_ok(meas_st, flp.MEAS_LEN) & self._planar_ok(
                proofs_st, flp.PROOF_LEN
            )

        m = self._stream_to_limb_planes(meas_st, flp.MEAS_LEN)  # n x (R, MEAS, 128)
        p = self._stream_to_limb_planes(proofs_st, flp.PROOF_LEN)
        sw = [x[:, : circ.arity] for x in p]
        gp = [x[:, circ.arity :] for x in p]

        out: Dict[str, jnp.ndarray] = {}
        if has_jr:
            part_planes = self._jr_part_planes(agg_id, blinds_u8, nonces_u8, meas_st)
            from .keccak_pallas import planes_to_rows

            part = words_to_bytes(planes_to_rows(part_planes))
            S = prio3.num_shares
            pieces = []
            if agg_id > 0:
                pieces.append(public_parts_u8[:, :agg_id].reshape(B, -1))
            pieces.append(part)
            if agg_id < S - 1:
                pieces.append(public_parts_u8[:, agg_id + 1 :].reshape(B, -1))
            seed_binder = jnp.concatenate(pieces, axis=-1)
            zero_seed = jnp.zeros((B, prio3.xof.SEED_SIZE), dtype=jnp.uint8)
            corrected = self._xof_seed(
                zero_seed, self._dst(USAGE_JOINT_RAND_SEED), seed_binder
            )
            jr_vec, ok_j = self._expand_vec(
                corrected,
                self._dst(USAGE_JOINT_RANDOMNESS),
                jnp.zeros((B, 0), dtype=jnp.uint8),
                flp.JOINT_RAND_LEN,
            )
            ok = ok & ok_j
            out["joint_rand_part"] = part
            out["corrected_seed"] = corrected
            jr_m = jf.to_mont(jr_vec)
            jr_planes = self._rows_to_planes_small(jr_m)
            jr_pl = [jr_planes[:, l, 0] for l in range(n)]  # (R, 128) limbs

        if isinstance(verify_key, (bytes, bytearray)):
            verify_key = jnp.asarray(np.frombuffer(bytes(verify_key), dtype=np.uint8))
        vk = jnp.broadcast_to(verify_key, (B, verify_key.shape[-1]))
        qr, ok_q = self._expand_vec(
            vk, self._dst(USAGE_QUERY_RANDOMNESS), nonces_u8, flp.QUERY_RAND_LEN
        )
        ok = ok & ok_q
        t_m = jf.to_mont(qr[:, 0])
        t_planes = self._rows_to_planes_small(t_m[:, None, :])[:, :, 0]
        t_pl = [t_planes[:, l] for l in range(n)]
        lag_pl, t_ok_pl = self._lagrange_planes(t_pl)
        ok = ok & t_ok_pl.reshape(B)
        lag0 = [lag_pl[:, l, 0] for l in range(n)]
        lagk = [lag_pl[:, l, 1:] for l in range(n)]  # (R, calls, 128)

        # gadget outputs gk at alpha^1..alpha^calls
        if self._ntt is not None:
            P = circ.P
            folded = [
                jf.add_limbs(
                    [x[:, :P] for x in gp],
                    [
                        jnp.concatenate(
                            [
                                x[:, P:],
                                jnp.zeros(
                                    (R, 2 * P - circ.glen, 128), dtype=_U32
                                ),
                            ],
                            axis=1,
                        )
                        for x in gp
                    ],
                )[l]
                for l in range(n)
            ]
            evals = jf.ntt_eval_mont_limbs(folded, *self._ntt)
            gk = [e[:, 1 : circ.calls + 1] for e in evals]
        else:
            amat = self._alpha_mat_m()  # (calls, glen, n)
            gk_cols = []
            for k in range(circ.calls):
                c = [
                    jnp.broadcast_to(
                        amat[k, :, l][None, :, None], (R, circ.glen, 128)
                    )
                    for l in range(n)
                ]
                terms = jf.mont_mul_limbs(gp, c)
                acc = [t[:, 0] for t in terms]
                for j in range(1, circ.glen):
                    acc = jf.add_limbs(acc, [t[:, j] for t in terms])
                gk_cols.append(acc)
            gk = [
                jnp.stack([col[l] for col in gk_cols], axis=1) for l in range(n)
            ]  # (R, calls, 128)

        if isinstance(circ, _DCount):
            # v = gk[0] - m[0]; wires w0 = w1 = sw_i*lag0 + m0*lag1
            v = jf.sub_limbs(
                [g[:, 0] for g in gk], [x[:, 0] for x in m]
            )
            m0lag1 = jf.mont_mul_limbs(
                [x[:, 0] for x in m], [lk[:, 0] for lk in lagk]
            )
            wires = []
            for i in range(2):
                se = jf.mont_mul_limbs([x[:, i] for x in sw], lag0)
                wires.append(jf.add_limbs(se, m0lag1))
        else:  # _DSum
            # v = sum_k r^(k+1) * gk[k]
            r_pows = self._pow_range_planes(jr_pl, circ.calls)  # (R, calls, 128)
            vk_terms = jf.mont_mul_limbs(r_pows, gk)
            v = [t[:, 0] for t in vk_terms]
            for k in range(1, circ.calls):
                v = jf.add_limbs(v, [t[:, k] for t in vk_terms])
            # single wire: sw0*lag0 + sum_k m[k]*lag_{k+1}
            mk = jf.mont_mul_limbs(m, lagk)
            s = [t[:, 0] for t in mk]
            for k in range(1, circ.calls):
                s = jf.add_limbs(s, [t[:, k] for t in mk])
            se = jf.mont_mul_limbs([x[:, 0] for x in sw], lag0)
            wires = [jf.add_limbs(se, s)]

        gpt = self._gpoly_at_planes(gp, t_pl)

        # verifier rows (B, VERIFIER_LEN, n): tiny stack + transpose
        cols = [v] + wires + [gpt]  # each: n x (R, 128)
        ver_pl = jnp.stack(
            [jnp.stack([col[l] for col in cols], axis=1) for l in range(n)],
            axis=1,
        )  # (R, n, VER, 128)
        out["verifiers"] = ver_pl.transpose(0, 3, 2, 1).reshape(B, len(cols), n)

        # out_share planar (R, n, OUTPUT_LEN, 128)
        if isinstance(circ, _DCount):
            osh = [x[:, 0:1] for x in m]
        else:
            w = self.consts["pow2_m"]  # (bits, n) Montgomery
            terms = jf.mont_mul_limbs(
                m,
                [
                    jnp.broadcast_to(w[:, l][None, :, None], (R, circ.calls, 128))
                    for l in range(n)
                ],
            )
            acc = [t[:, 0] for t in terms]
            for k in range(1, circ.calls):
                acc = jf.add_limbs(acc, [t[:, k] for t in terms])
            osh = [a[:, None, :] for a in acc]
        out["out_share"] = jnp.stack(osh, axis=1)  # (R, n, OUT, 128)
        out["ok"] = ok
        return out

    @staticmethod
    def _zip_planes_to_rows(ev_pl, od_pl):
        """Interleave even/odd wire planes -> row-major (B, 2*cp, n)."""
        R, n, cp, _ = ev_pl.shape
        zipped = jnp.stack([ev_pl, od_pl], axis=3)  # (R, n, cp, 2, 128)
        return zipped.transpose(0, 4, 2, 3, 1).reshape(R * 128, 2 * cp, n)

    @staticmethod
    def planar_out_share_to_rows(osp):
        """(R, n, L, 128) planar out shares -> row-major (B, L, n).

        The single place that knows the planar out_share layout outside the
        planar pipeline itself (report b lives at (b // 128, ..., b % 128)).
        """
        R, n, L, _ = osp.shape
        return osp.transpose(0, 3, 2, 1).reshape(R * 128, L, n)

    def _planar_add(self, a, b):
        """Modular add on (R, n, ..., 128) planar tensors (limb axis 1)."""
        jf = self.jf
        return jnp.stack(
            jf.add_limbs([a[:, l] for l in range(jf.n)], [b[:, l] for l in range(jf.n)]),
            axis=1,
        )

    def _sumvec_wires_planar(self, m_pl, swe_pl, swo_pl, jr_m, lag, cp):
        """SumVec wire evaluations via per-call-slab Pallas contractions.

        evens[u] = sum_k m[k,u] * jr_k^(u+1) * lag_{k+1};
        odds[u]  = sum_k m[k,u] * lag_{k+1}  -  ccorr;
        wire     = seeds * lag_0 + zip(evens, odds).

        The evens coefficient klu = jr_k^(u+1) * lag_{k+1} varies over BOTH
        axes (per-call joint rand, power resetting each call), so unlike the
        histogram it cannot fold into a per-call scalar.  It is generated
        and consumed slab-by-slab over the calls axis (lax.scan) so the
        wide-vector circuits — calls=317 for the 100k-element SumVec —
        never materialize a meas-sized coefficient tensor, and each slab's
        contraction runs in the limb-planar kernel.  Exact mod-p identities
        throughout: limbs match the row path (tests/test_prepare.py).
        """
        from .flp_pallas import _pallas_interpret, sumvec_partial_planar

        jf, circ = self.jf, self.circ
        R, n, calls, _, _ = m_pl.shape
        B = R * 128
        lag0, lagk = lag[:, 0], lag[:, 1:]
        lag_sum = jf.sum(lagk, axis=1)
        c = jnp.broadcast_to(self.consts["shares_inv_c"], lag_sum.shape)
        ccorr = jf.mont_mul(c, lag_sum)

        KC = min(calls, 8)
        calls_pad = -(-calls // KC) * KC
        if calls_pad != calls:
            pad = calls_pad - calls
            # zero meas + zero lagk make pad calls contribute exactly 0.
            m_pl = jnp.pad(m_pl, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
            lagk = jnp.pad(lagk, ((0, 0), (0, pad), (0, 0)))
            jr_m = jnp.pad(jr_m, ((0, 0), (0, pad), (0, 0)))
        NS = calls_pad // KC
        interpret = _pallas_interpret()

        def slab(s):
            m_slab = lax.dynamic_slice_in_dim(m_pl, s * KC, KC, axis=2)
            jr_s = lax.dynamic_slice_in_dim(jr_m, s * KC, KC, axis=1)
            lagk_s = lax.dynamic_slice_in_dim(lagk, s * KC, KC, axis=1)
            r_pows = jf.pow_range_mont(jr_s, circ.chunk)  # jr_k^(u+1) * R
            klu = jf.mont_mul(
                r_pows, jnp.broadcast_to(lagk_s[:, :, None, :], r_pows.shape)
            )
            if cp != circ.chunk:
                klu = jnp.pad(klu, ((0, 0), (0, 0), (0, cp - circ.chunk), (0, 0)))
            klu_pl = klu.reshape(R, 128, KC, cp, jf.n).transpose(0, 4, 2, 3, 1)
            lagk_pl = self._rows_to_planes_small(lagk_s)
            return sumvec_partial_planar(
                jf, m_slab, klu_pl, lagk_pl, interpret=interpret
            )

        ev, od = slab(0)
        if NS > 1:
            def body(carry, s):
                ev_c, od_c = carry
                ev_p, od_p = slab(s)
                return (
                    self._planar_add(ev_c, ev_p),
                    self._planar_add(od_c, od_p),
                ), None

            (ev, od), _ = lax.scan(body, (ev, od), jnp.arange(1, NS))

        evens_row = ev.transpose(0, 3, 2, 1).reshape(B, cp, n)[:, : circ.chunk]
        odds_row = od.transpose(0, 3, 2, 1).reshape(B, cp, n)[:, : circ.chunk]
        odds_row = jf.sub(odds_row, jnp.broadcast_to(ccorr[:, None, :], odds_row.shape))
        swe_row = swe_pl.transpose(0, 3, 2, 1).reshape(B, cp, n)[:, : circ.chunk]
        swo_row = swo_pl.transpose(0, 3, 2, 1).reshape(B, cp, n)[:, : circ.chunk]
        sw_row = jnp.stack([swe_row, swo_row], axis=2).reshape(B, circ.arity, n)
        se = jf.mont_mul(sw_row, jnp.broadcast_to(lag0[:, None, :], sw_row.shape))
        pair = jnp.stack([evens_row, odds_row], axis=2).reshape(B, circ.arity, n)
        return jf.add(se, pair)

    # -- prep shares -> prep message ------------------------------------
    def prep_shares_to_prep(
        self,
        verifier_shares: List[jnp.ndarray],
        joint_rand_parts_u8: Optional[List[jnp.ndarray]] = None,
    ) -> Dict[str, jnp.ndarray]:
        """Combine verifier shares and decide; derive the joint-rand seed.

        verifier_shares: num_shares tensors (B, num_proofs*VER_LEN, n) canonical.
        Returns {"decide": (B,) bool, "prep_msg_seed": (B,SEED) u8 (if joint rand)}.
        Oracle twin: Prio3.prep_shares_to_prep.
        """
        prio3, flp, jf, circ = self.prio3, self.flp, self.jf, self.circ
        combined = verifier_shares[0]
        for vs in verifier_shares[1:]:
            combined = jf.add(combined, vs)
        B = combined.shape[0]
        decide = jnp.ones((B,), dtype=bool)
        for i in range(prio3.num_proofs):
            ver = combined[:, i * flp.VERIFIER_LEN : (i + 1) * flp.VERIFIER_LEN]
            decide = decide & jf.is_zero(ver[:, 0])
            idx = 1
            for gi, plan in enumerate(circ.plans):
                x = ver[:, idx : idx + plan.arity]  # canonical wire evals
                # Compare g*R^-1 == y*R^-1 (R invertible => same predicate
                # as g == y) to skip the to_mont pass over the arity wires.
                y_scaled = jf.from_mont(ver[:, idx + plan.arity])
                g = circ.gadget_eval_scaled_g(gi, jf, x)
                decide = decide & jf.eq(g, y_scaled)
                idx += plan.arity + 1
        out: Dict[str, jnp.ndarray] = {"decide": decide}
        if flp.JOINT_RAND_LEN > 0:
            binder = jnp.concatenate(list(joint_rand_parts_u8), axis=-1)
            zero_seed = jnp.zeros((B, prio3.xof.SEED_SIZE), dtype=jnp.uint8)
            out["prep_msg_seed"] = self._xof_seed(
                zero_seed, self._dst(USAGE_JOINT_RAND_SEED), binder
            )
        return out

    def prep_shares_to_prep_planar(
        self,
        own: Dict[str, jnp.ndarray],
        peer_verifiers: jnp.ndarray,
        joint_rand_parts_u8: Optional[List[jnp.ndarray]] = None,
    ) -> Dict[str, jnp.ndarray]:
        """Combine + decide with OUR verifier still in plane layout.

        ``own`` is a prep_init_planar(keep_planar=True) result (wire_ev_pl /
        wire_od_pl planes + v_row / gpt_row); ``peer_verifiers`` is the other
        aggregator's share, row-major (B, VERIFIER_LEN, n) canonical as it
        arrives off the wire.  The gadget contraction over the combined
        wires runs in the planar Pallas kernel (combine_decide_planar);
        only v / gpoly(t) / the folded gadget sum touch row layout (tiny).
        Exact mod-p identities throughout — ``decide`` and the derived
        prep-message seed are bit-identical to prep_shares_to_prep
        (tests/test_prepare.py).  num_proofs == 1 (planar_eligible).
        """
        from .flp_pallas import _pallas_interpret, combine_decide_planar

        prio3, flp, jf, circ = self.prio3, self.flp, self.jf, self.circ
        ev_pl, od_pl = own["wire_ev_pl"], own["wire_od_pl"]
        B = peer_verifiers.shape[0]
        # One transpose puts the peer's whole verifier in plane layout; the
        # kernel de-interleaves its zipped wires in-register.
        pv_pl = self._rows_to_planes_small(peer_verifiers)
        g_parts = combine_decide_planar(
            jf, circ.chunk, ev_pl, od_pl, pv_pl,
            interpret=_pallas_interpret(),
        )  # (R, n, 8, 128) partial sums
        R, n, S8, _ = g_parts.shape
        g = jf.sum(g_parts.transpose(0, 3, 2, 1).reshape(B, S8, n), axis=1)

        v = jf.add(own["v_row"], peer_verifiers[:, 0])
        y = jf.add(own["gpt_row"], peer_verifiers[:, 1 + circ.arity])
        # g is (a*b)*R^-1-scaled (gadget_eval_scaled); compare against
        # y*R^-1 — R invertible, so the predicate equals g == y.
        decide = jf.is_zero(v) & jf.eq(g, jf.from_mont(y))
        out: Dict[str, jnp.ndarray] = {"decide": decide}
        if flp.JOINT_RAND_LEN > 0:
            binder = jnp.concatenate(list(joint_rand_parts_u8), axis=-1)
            zero_seed = jnp.zeros((B, prio3.xof.SEED_SIZE), dtype=jnp.uint8)
            out["prep_msg_seed"] = self._xof_seed(
                zero_seed, self._dst(USAGE_JOINT_RAND_SEED), binder
            )
        return out

    # -- aggregation -----------------------------------------------------
    def aggregate(self, out_shares: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        """Masked modular sum of out shares over the batch axis.

        out_shares (B, OUTPUT_LEN, n) canonical — or limb-planar
        (n, OUTPUT_LEN, R, 128) from prep_init_planar — with mask (B,) bool
        -> (OUTPUT_LEN, n).  TPU analog of sharded batch-aggregation
        accumulation (reference:
        aggregator/src/aggregator/aggregation_job_writer.rs:591-698).
        """
        if out_shares.ndim == 4:  # planar (R, n, L, 128): lazy u16 lane reduce
            R, n, L, _ = out_shares.shape
            maskp = mask.reshape(R, 128)
            masked = jnp.where(
                maskp[:, None, None], out_shares, jnp.zeros_like(out_shares)
            )
            slo = jnp.sum(masked & np.uint32(0xFFFF), axis=(0, 3))  # (n, L)
            shi = jnp.sum(masked >> 16, axis=(0, 3))
            return self.jf.lazy_fold(slo.T, shi.T)
        masked = jnp.where(mask[:, None, None], out_shares, jnp.zeros_like(out_shares))
        return self.jf.sum(masked, axis=0)
