"""Vectorized TurboSHAKE128 on u32 lane pairs (JAX, TPU-friendly).

Keccak-p[1600,12] with each 64-bit lane held as two u32s (lo, hi) — TPU has no
64-bit integer registers, and all rotations/xors decompose exactly onto u32
lanes.  The batch axis broadcasts over reports: one call absorbs/squeezes the
XOF streams for a whole aggregation job (the reference runs the scalar
equivalent per report inside rayon tasks; SURVEY.md §2.3 P1).

Message layouts are static per VDAF configuration, so padding is baked at
trace time.  Byte streams are u8 tensors; lane packing is explicit arithmetic
(no bitcasts) for backend-independent determinism.

Bit-exact against the oracle in janus_tpu.xof (tests/test_ops_keccak.py).
"""

from __future__ import annotations

from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp

from .field_jax import _eager_jit, _scan_fence
import numpy as np
from jax import lax

from ..xof import ROUND_CONSTANTS, _RHO

_U32 = jnp.uint32
RATE = 168  # bytes
RATE_WORDS = RATE // 4  # 42 u32 words = 21 lanes
_ROUNDS = 12

# Per-round constants as (lo, hi) u32 pairs for the final 12 rounds.
_RC_PAIRS = np.array(
    [[rc & 0xFFFFFFFF, rc >> 32] for rc in ROUND_CONSTANTS[24 - _ROUNDS :]],
    dtype=np.uint32,
)


def _rotl_pair(lo, hi, r: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Rotate a 64-bit lane (as u32 lo/hi) left by static amount r."""
    r = r % 64
    if r == 0:
        return lo, hi
    if r == 32:
        return hi, lo
    if r < 32:
        return (
            (lo << r) | (hi >> (32 - r)),
            (hi << r) | (lo >> (32 - r)),
        )
    s = r - 32
    return (
        (hi << s) | (lo >> (32 - s)),
        (lo << s) | (hi >> (32 - s)),
    )


def _keccak_round(state: jnp.ndarray, rc_pair: jnp.ndarray) -> jnp.ndarray:
    """One Keccak round on state (..., 50) u32 (lane i = pairs 2i, 2i+1)."""
    lanes = [(state[..., 2 * i], state[..., 2 * i + 1]) for i in range(25)]
    # theta
    c = []
    for x in range(5):
        lo = lanes[x][0] ^ lanes[x + 5][0] ^ lanes[x + 10][0] ^ lanes[x + 15][0] ^ lanes[x + 20][0]
        hi = lanes[x][1] ^ lanes[x + 5][1] ^ lanes[x + 10][1] ^ lanes[x + 15][1] ^ lanes[x + 20][1]
        c.append((lo, hi))
    d = []
    for x in range(5):
        rl, rh = _rotl_pair(*c[(x + 1) % 5], 1)
        d.append((c[(x - 1) % 5][0] ^ rl, c[(x - 1) % 5][1] ^ rh))
    lanes = [(lanes[i][0] ^ d[i % 5][0], lanes[i][1] ^ d[i % 5][1]) for i in range(25)]
    # rho + pi
    b: List = [None] * 25
    for x in range(5):
        for y in range(5):
            src = x + 5 * y
            b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl_pair(*lanes[src], _RHO[src])
    # chi
    lanes = [
        (
            b[i][0] ^ (~b[(i % 5 + 1) % 5 + 5 * (i // 5)][0] & b[(i % 5 + 2) % 5 + 5 * (i // 5)][0]),
            b[i][1] ^ (~b[(i % 5 + 1) % 5 + 5 * (i // 5)][1] & b[(i % 5 + 2) % 5 + 5 * (i // 5)][1]),
        )
        for i in range(25)
    ]
    # iota
    lanes[0] = (lanes[0][0] ^ rc_pair[0], lanes[0][1] ^ rc_pair[1])
    flat = []
    for i in range(25):
        flat.append(lanes[i][0])
        flat.append(lanes[i][1])
    return jnp.stack(flat, axis=-1)


def keccak_p_batch(state: jnp.ndarray) -> jnp.ndarray:
    """Keccak-p[1600,12] on state (..., 50) u32: lane i = (state[2i], state[2i+1]).

    Rounds run under lax.scan (they are sequential by construction) so each
    XOF site contributes one round body to the graph, not twelve — an order
    of magnitude off XLA compile time for the prepare pipelines.
    """

    def body(s, rc_pair):
        return _keccak_round(s, rc_pair), None

    out, _ = lax.scan(body, state, jnp.asarray(_RC_PAIRS))
    return _scan_fence(out)


def bytes_to_words(b: jnp.ndarray) -> jnp.ndarray:
    """(..., 4k) u8 -> (..., k) u32, little-endian."""
    shape = b.shape[:-1] + (b.shape[-1] // 4, 4)
    w = b.reshape(shape).astype(_U32)
    return w[..., 0] | (w[..., 1] << 8) | (w[..., 2] << 16) | (w[..., 3] << 24)


def words_to_bytes(w: jnp.ndarray) -> jnp.ndarray:
    """(..., k) u32 -> (..., 4k) u8, little-endian."""
    parts = jnp.stack(
        [
            (w & 0xFF).astype(jnp.uint8),
            ((w >> 8) & 0xFF).astype(jnp.uint8),
            ((w >> 16) & 0xFF).astype(jnp.uint8),
            ((w >> 24) & 0xFF).astype(jnp.uint8),
        ],
        axis=-1,
    )
    return parts.reshape(w.shape[:-1] + (w.shape[-1] * 4,))


def _pad_message(msg: jnp.ndarray, domain: int) -> jnp.ndarray:
    """TurboSHAKE pad: append D, zero-fill to the rate, xor 0x80 into the last
    byte of the final block.  msg: (..., L) u8 with static L."""
    L = msg.shape[-1]
    nblocks = L // RATE + 1
    pad_len = nblocks * RATE - L
    pad = np.zeros(pad_len, dtype=np.uint8)
    pad[0] = domain
    pad[-1] ^= 0x80
    pad_arr = jnp.broadcast_to(jnp.asarray(pad), msg.shape[:-1] + (pad_len,))
    return jnp.concatenate([msg, pad_arr], axis=-1)


@_eager_jit(static_argnums=(1, 2))
def turboshake128_batch(msg: jnp.ndarray, domain: int, out_len: int) -> jnp.ndarray:
    """One-shot TurboSHAKE128 over a batch: msg (..., L) u8 -> (..., out_len) u8.

    L and out_len are static.  Matches janus_tpu.xof.turboshake128 exactly.
    """
    padded = _pad_message(msg, domain)
    batch_shape = padded.shape[:-1]
    nblocks = padded.shape[-1] // RATE
    words = bytes_to_words(padded).reshape(batch_shape + (nblocks, RATE_WORDS))
    state0 = jnp.zeros(batch_shape + (50,), dtype=_U32)

    # absorb: xor each block into the rate words, permute
    blocks = jnp.moveaxis(words, -2, 0)  # (nblocks, ..., 42)

    def absorb(state, block):
        rate_part = state[..., :RATE_WORDS] ^ block
        state = jnp.concatenate([rate_part, state[..., RATE_WORDS:]], axis=-1)
        return keccak_p_batch(state), None

    out_blocks = (out_len + RATE - 1) // RATE

    def squeeze(state, _):
        out = state[..., :RATE_WORDS]
        return keccak_p_batch(state), out

    # Small static block counts are unrolled as Python loops: a lax.scan
    # here would nest the 12-round permutation scan inside another while
    # loop, and XLA:CPU's thunk runtime charges a large per-iteration
    # penalty to any loop whose body is not a single fusion (an inner loop
    # never is).  Unrolling keeps the rounds scan the only loop at each XOF
    # site.  Long squeezes (wide-vector share expansion) keep the scan so
    # the graph stays one permutation body regardless of stream length.
    _UNROLL = 8
    if nblocks <= _UNROLL:
        state = state0
        for i in range(nblocks):
            state, _ = absorb(state, blocks[i])
    else:
        state, _ = lax.scan(absorb, state0, blocks)

    if out_blocks <= _UNROLL:
        outs_list = []
        for _ in range(out_blocks):
            state, out = squeeze(state, None)
            outs_list.append(out)
        outs = jnp.stack(outs_list, axis=0)
    else:
        state, outs = lax.scan(squeeze, state, None, length=out_blocks)
    outs = jnp.moveaxis(outs, 0, -2)  # (..., out_blocks, 42)
    out_bytes = words_to_bytes(outs.reshape(batch_shape + (out_blocks * RATE_WORDS,)))
    return out_bytes[..., :out_len]


@_eager_jit(static_argnums=(1, 2))
def turboshake128_batch_select(
    msg: jnp.ndarray, domain: int, out_len: int, msg_len: jnp.ndarray
) -> jnp.ndarray:
    """TurboSHAKE128 over PER-ROW message lengths (canonical shape padding).

    ``msg`` is (..., Lmax) u8 with every byte at or past the row's
    ``msg_len`` (..., i32) equal to ZERO — the canonical-shape marshal
    zero-masks its pad columns, which is what lets the TurboSHAKE pad
    (domain byte at msg_len, 0x80 into the last byte of the row's final
    RATE block) be written with static-shape where/iota masks.  The
    absorb runs over ALL Lmax blocks and keeps, per row, the sponge
    state after the row's own final block — every block before it is
    byte-identical to the row's true absorb, so the selected state (and
    the squeeze from it) matches ``turboshake128_batch(msg[:msg_len])``
    exactly.  ``out_len`` must fit one squeeze block (a seed does).

    Exactness asserted row-for-row against the host oracle in
    tests/test_shape_canonical.py.
    """
    if out_len > RATE:
        raise NotImplementedError("select squeeze serves seed-sized outputs")
    Lmax = msg.shape[-1]
    batch_shape = msg.shape[:-1]
    nblocks = Lmax // RATE + 1
    total = nblocks * RATE
    buf = jnp.concatenate(
        [msg, jnp.zeros(batch_shape + (total - Lmax,), dtype=jnp.uint8)], axis=-1
    )
    ml = msg_len.astype(jnp.int32)[..., None]
    idx = lax.broadcasted_iota(jnp.int32, buf.shape, buf.ndim - 1)
    # domain byte lands on a zero; 0x80 xors into the row's final block's
    # last byte (they coincide exactly when the true pad is one byte).
    buf = jnp.where(idx == ml, jnp.uint8(domain), buf)
    last = (ml // RATE + 1) * RATE - 1
    buf = buf ^ jnp.where(idx == last, jnp.uint8(0x80), jnp.uint8(0))
    words = bytes_to_words(buf).reshape(batch_shape + (nblocks, RATE_WORDS))
    blocks = jnp.moveaxis(words, -2, 0)  # (nblocks, ..., 42)
    target = (msg_len.astype(jnp.int32) // RATE)[..., None]  # row's final block

    state = jnp.zeros(batch_shape + (50,), dtype=_U32)
    selected = state

    def absorb_select(state, selected, block, i):
        rate_part = state[..., :RATE_WORDS] ^ block
        state = keccak_p_batch(
            jnp.concatenate([rate_part, state[..., RATE_WORDS:]], axis=-1)
        )
        return state, jnp.where(target == i, state, selected)

    # mirror turboshake128_batch: unroll short messages, scan long ones
    # (the scan keeps ONE permutation body in the graph)
    _UNROLL = 8
    if nblocks <= _UNROLL:
        for i in range(nblocks):
            state, selected = absorb_select(state, selected, blocks[i], i)
    else:

        def body(carry, xs):
            block, i = xs
            return absorb_select(*carry, block, i), None

        (state, selected), _ = lax.scan(
            body, (state, selected), (blocks, jnp.arange(nblocks, dtype=jnp.int32))
        )
        selected = _scan_fence(selected)
    out_bytes = words_to_bytes(selected[..., :RATE_WORDS])
    return out_bytes[..., :out_len]


@_eager_jit(static_argnums=(1, 3))
def xof_turboshake128_batch_select(
    seed: jnp.ndarray,
    dst: bytes,
    binder: jnp.ndarray,
    out_len: int,
    binder_len: jnp.ndarray,
) -> jnp.ndarray:
    """``xof_turboshake128_batch`` with a PER-ROW binder length: binder is
    (..., Bmax) u8, zero past each row's ``binder_len`` (..., i32).  The
    fixed head (len(dst) || dst || seed) absorbs identically for every
    row; only the binder tail varies, via the length-selected sponge."""
    prefix = np.frombuffer(bytes([len(dst)]) + dst, dtype=np.uint8)
    batch_shape = seed.shape[:-1]
    head = len(prefix) + seed.shape[-1]
    msg = jnp.concatenate(
        [jnp.broadcast_to(jnp.asarray(prefix), batch_shape + (len(prefix),)), seed, binder],
        axis=-1,
    )
    return turboshake128_batch_select(
        msg, 0x01, out_len, binder_len.astype(jnp.int32) + head
    )


@_eager_jit(static_argnums=(1, 3))
def xof_turboshake128_batch(
    seed: jnp.ndarray, dst: bytes, binder: jnp.ndarray, out_len: int
) -> jnp.ndarray:
    """Batched XofTurboShake128 (draft-irtf-cfrg-vdaf-08 §6.2.1): message is
    len(dst) || dst || seed || binder with domain byte 0x01.

    seed: (..., 16) u8; binder: (..., B) u8 (static B, may be 0); dst: host bytes.
    """
    prefix = np.frombuffer(bytes([len(dst)]) + dst, dtype=np.uint8)
    batch_shape = seed.shape[:-1]
    parts = [jnp.broadcast_to(jnp.asarray(prefix), batch_shape + (len(prefix),)), seed]
    if binder.shape[-1]:
        parts.append(binder)
    msg = jnp.concatenate(parts, axis=-1)
    return turboshake128_batch(msg, 0x01, out_len)
