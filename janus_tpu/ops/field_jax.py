"""Batched prime-field arithmetic on u32 limb tensors (JAX, TPU-friendly).

A field element is a little-endian vector of u32 limbs along the trailing
axis: shape (..., n_limbs).  Canonical form = integer < MODULUS; Montgomery
form = x * R mod p with R = 2^(32 n).  ``mont_mul`` is CIOS Montgomery
multiplication built from 16-bit half-limb products (TPU has no 64-bit
integer multiply; 16x16->32 products are exact in u32).

Bit-exactness: all ops are exact integer arithmetic mod p — there is no
rounding or reassociation hazard — so any algebraically-equal formula yields
identical limbs.  Tests compare against janus_tpu.fields on random and edge
values.
"""

from __future__ import annotations

import functools
import math
from functools import partial
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_U32 = jnp.uint32
_MASK16 = np.uint32(0xFFFF)
_MASK8 = np.uint32(0xFF)

#: Max contraction length for one ``mat_mul_mont`` dot pass.  The layer
#: contracts base-2^8 digit planes, so every per-digit-pair partial sum
#: P[d,e] = sum_k a_d[k] * b_e[k] is bounded by K * 255^2 and must stay
#: exact in the u32 dot accumulator: K <= floor((2^32-1)/255^2) = 66051.
#: 65536 keeps a round power of two and matches the u16-half lazy-sum cap
#: (JField.sum / planar aggregate) used across the prepare pipeline.
#: Longer contractions split into exact modular-added chunks.
DOT_MAX_K = 65536


def _eager_jit(static_argnums=(0,)):
    """Jit for EAGER callers only; inline when already under a trace.

    Wrapping these methods in plain jax.jit made eager tests fast but
    embedded hundreds of nested pjit calls into every prepare trace, which
    blew XLA CPU compile times from tens of seconds to tens of minutes.
    Tracing callers get the original inlined body; eager callers (tests,
    oracle fallbacks) get a cached compiled version.
    """

    def deco(fn):
        jitted = partial(jax.jit, static_argnums=static_argnums)(fn)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if any(isinstance(a, jax.core.Tracer) for a in args) or any(
                isinstance(v, jax.core.Tracer) for v in kwargs.values()
            ):
                return fn(*args, **kwargs)
            return jitted(*args, **kwargs)

        return wrapper

    return deco


def _u32(x: int):
    return jnp.asarray(np.uint32(x), dtype=_U32)


def _scan_fence(x):
    """Fence a scan's output from its consumers on XLA:CPU.

    XLA:CPU fuses cheap consumers *into* a while-loop body; once the body
    spans multiple fusions the thunk runtime pays a per-iteration
    scheduling penalty that grows with executable size (measured: a
    127-iteration Fermat-inversion scan inside the histogram prepare graph
    went from milliseconds standalone to minutes composed).  An
    optimization_barrier on the scan output keeps the loop body a single
    fused kernel.  TPU keeps the fusion (it's profitable there), so the
    barrier is trace-time conditional on the backend.
    """
    # Keyed on the jax_platforms *config* (set by tests/conftest.py and the
    # multichip dryrun, which pin "cpu"), NOT jax.default_backend(): reading
    # the default backend at trace time runs the platform election and
    # would initialize the out-of-process TPU plugin from contexts that
    # must never touch it (see __graft_entry__.dryrun_multichip).
    platforms = jax.config.jax_platforms or ""
    if platforms.split(",")[0] == "cpu":
        return lax.optimization_barrier(x)
    return x


def _mul32(a, b) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact 32x32 -> 64 multiply as (hi, lo) u32 pairs via 16-bit halves."""
    al = a & _MASK16
    ah = a >> 16
    bl = b & _MASK16
    bh = b >> 16
    ll = al * bl
    lh = al * bh
    hl = ah * bl
    hh = ah * bh
    mid = (ll >> 16) + (lh & _MASK16) + (hl & _MASK16)  # < 2^18, no overflow
    lo = (ll & _MASK16) | ((mid & _MASK16) << 16)
    hi = hh + (lh >> 16) + (hl >> 16) + (mid >> 16)
    return hi, lo


def _adc(a, b, carry_in):
    """a + b + carry_in (carry_in in {0,1}) -> (sum, carry_out in {0,1})."""
    s = a + b
    c1 = (s < a).astype(_U32)
    s2 = s + carry_in
    c2 = (s2 < s).astype(_U32)
    return s2, c1 | c2


def _sbb(a, b, borrow_in):
    """a - b - borrow_in -> (diff, borrow_out in {0,1})."""
    d = a - b
    b1 = (a < b).astype(_U32)
    d2 = d - borrow_in
    b2 = (d < borrow_in).astype(_U32)
    return d2, b1 | b2


def _mac(a, b, acc, carry):
    """a*b + acc + carry -> (hi, lo); fits exactly in 64 bits."""
    hi, lo = _mul32(a, b)
    lo, c = _adc(lo, acc, _u32(0))
    hi = hi + c
    lo, c = _adc(lo, carry, _u32(0))
    hi = hi + c
    return hi, lo


class JField:
    """JAX batched ops for one of the oracle fields (janus_tpu.fields).

    Instances are hashable/equal by their oracle field so the jitted method
    wrappers below share one compilation cache across instances (tests and
    pipelines construct JField freely).
    """

    def __hash__(self):
        return hash(self.oracle)

    def __eq__(self, other):
        return isinstance(other, JField) and other.oracle is self.oracle

    def __init__(self, oracle_field: type):
        self.oracle = oracle_field
        p = oracle_field.MODULUS
        self.p = p
        self.n = oracle_field.ENCODED_SIZE // 4  # u32 limbs per element
        bits = 32 * self.n
        r = (1 << bits) % p
        self.p_np = self._int_to_limbs_np(p)
        self.r2_np = self._int_to_limbs_np(r * r % p)
        self.one_np = self._int_to_limbs_np(1)
        self.n_prime = np.uint32((-pow(p, -1, 1 << 32)) % (1 << 32))
        # p - 2 bits (MSB first) for Fermat inversion.
        self._inv_exp_bits = np.array(
            [int(b) for b in bin(p - 2)[2:]], dtype=np.uint32
        )

    # --- host-side conversions ----------------------------------------
    def _int_to_limbs_np(self, x: int) -> np.ndarray:
        return np.array(
            [(x >> (32 * i)) & 0xFFFFFFFF for i in range(self.n)], dtype=np.uint32
        )

    def to_limbs(self, values: Sequence[int]) -> np.ndarray:
        """Host: python ints -> (..., n) u32 canonical limbs."""
        flat = np.empty((len(values), self.n), dtype=np.uint32)
        for i, v in enumerate(values):
            for j in range(self.n):
                flat[i, j] = (v >> (32 * j)) & 0xFFFFFFFF
        return flat

    def from_limbs(self, limbs: np.ndarray) -> List[int]:
        """Host: (..., n) u32 canonical limbs -> python ints (flattened)."""
        arr = np.asarray(limbs, dtype=np.uint32).reshape(-1, self.n)
        out = []
        for row in arr:
            v = 0
            for j in range(self.n):
                v |= int(row[j]) << (32 * j)
            out.append(v)
        return out

    def const(self, value: int) -> jnp.ndarray:
        """Canonical constant as a device limb vector."""
        return jnp.asarray(self._int_to_limbs_np(value % self.p))

    def mont_const(self, value: int) -> jnp.ndarray:
        """Constant already converted to Montgomery form (host-side)."""
        bits = 32 * self.n
        return jnp.asarray(self._int_to_limbs_np((value % self.p) * (1 << bits) % self.p))

    # --- device ops (operate on (..., n) u32; canonical in, canonical out
    #     for add/sub; Montgomery domain for mont_mul chains) -----------
    def zeros(self, shape) -> jnp.ndarray:
        return jnp.zeros(tuple(shape) + (self.n,), dtype=_U32)

    def _split(self, a):
        return [a[..., i] for i in range(self.n)]

    def _join(self, limbs):
        return jnp.stack(limbs, axis=-1)

    def _cond_sub_p(self, limbs, extra_bit):
        """limbs (list of n) + extra_bit*2^(32n); subtract p if >= p."""
        p = [ _u32(int(x)) for x in self.p_np ]
        d = []
        borrow = _u32(0)
        for i in range(self.n):
            di, borrow = _sbb(limbs[i], p[i], borrow)
            d.append(di)
        # subtract if extra_bit set or no borrow (value >= p)
        take = (extra_bit | (1 - borrow)).astype(jnp.bool_)
        return [jnp.where(take, d[i], limbs[i]) for i in range(self.n)]

    def add_limbs(self, aa: List, bb: List) -> List:
        """Canonical modular addition on limb lists (shared XLA/Pallas core)."""
        s = []
        carry = _u32(0)
        for i in range(self.n):
            si, carry = _adc(aa[i], bb[i], carry)
            s.append(si)
        return self._cond_sub_p(s, carry)

    @_eager_jit(static_argnums=(0,))
    def add(self, a, b):
        """Canonical modular addition."""
        return self._join(self.add_limbs(self._split(a), self._split(b)))

    def sub_limbs(self, aa: List, bb: List) -> List:
        """Canonical modular subtraction on limb lists (shared XLA/Pallas core)."""
        d = []
        borrow = _u32(0)
        for i in range(self.n):
            di, borrow = _sbb(aa[i], bb[i], borrow)
            d.append(di)
        # add p back when we borrowed
        p = [ _u32(int(x)) for x in self.p_np ]
        s = []
        carry = _u32(0)
        for i in range(self.n):
            si, carry = _adc(d[i], p[i], carry)
            s.append(si)
        use_add = borrow.astype(jnp.bool_)
        return [jnp.where(use_add, s[i], d[i]) for i in range(self.n)]

    @_eager_jit(static_argnums=(0,))
    def sub(self, a, b):
        """Canonical modular subtraction."""
        return self._join(self.sub_limbs(self._split(a), self._split(b)))

    def neg(self, a):
        return self.sub(self.zeros(a.shape[:-1]), a)

    def _mont_m(self, t0):
        """m = t0 * n_prime mod 2^32; free negation when n_prime == -1.

        Every field whose modulus is 1 mod 2^32 (Field64 = 2^64-2^32+1,
        Field128 = 2^128-7*2^66+1) has n_prime = 0xFFFFFFFF.
        """
        if int(self.n_prime) == 0xFFFFFFFF:
            return jnp.zeros_like(t0) - t0
        return t0 * _u32(int(self.n_prime))

    def _mac_p(self, j: int, m, acc, carry):
        """(hi, lo) of m * p[j] + acc + carry, specialized on the host-known
        limb value of the modulus.  The VDAF fields' moduli have limbs drawn
        from {0, 1, 0xFFFFFFFF, <one odd limb>}, which turns most of the
        CIOS reduction multiplies into adds/negations (~1.4x fewer VPU ops
        per mont_mul; exact same integer result)."""
        pj = int(self.p_np[j])
        zero = jnp.zeros_like(m)
        if pj == 0:
            lo, c = _adc(acc, carry, zero)
            return c, lo
        if pj == 1:
            lo, c1 = _adc(m, acc, zero)
            lo, c2 = _adc(lo, carry, zero)
            return c1 + c2, lo
        if pj == 0xFFFFFFFF:
            # m*(2^32-1) + acc + carry = m*2^32 + (acc + carry - m)
            s1, c1 = _adc(acc, carry, zero)
            d, borrow = _sbb(s1, m, zero)
            return m + c1 - borrow, d
        return _mac(m, _u32(pj), acc, carry)

    def mont_mul_limbs(self, aa: List, bb: List) -> List:
        """CIOS core on limb lists: a*b*R^-1 mod p (shared XLA/Pallas)."""
        n = self.n
        zero = jnp.zeros_like(aa[0] | bb[0])
        t = [zero] * (n + 2)
        for i in range(n):
            carry = zero
            for j in range(n):
                hi, lo = _mac(aa[i], bb[j], t[j], carry)
                t[j] = lo
                carry = hi
            s, c = _adc(t[n], carry, zero)
            t[n] = s
            t[n + 1] = t[n + 1] + c
            m = self._mont_m(t[0])
            hi, _lo = self._mac_p(0, m, t[0], zero)
            carry = hi
            for j in range(1, n):
                hi, lo = self._mac_p(j, m, t[j], carry)
                t[j - 1] = lo
                carry = hi
            s, c = _adc(t[n], carry, zero)
            t[n - 1] = s
            t[n] = t[n + 1] + c
            t[n + 1] = zero
        return self._cond_sub_p(t[:n], t[n])

    @_eager_jit(static_argnums=(0,))
    def mont_mul(self, a, b):
        """CIOS Montgomery multiplication: returns a*b*R^-1 mod p, canonical."""
        return self._join(self.mont_mul_limbs(self._split(a), self._split(b)))

    @_eager_jit(static_argnums=(0,))
    def to_mont(self, a):
        r2 = jnp.asarray(self.r2_np)
        return self.mont_mul(a, jnp.broadcast_to(r2, a.shape))

    @_eager_jit(static_argnums=(0,))
    def from_mont(self, a):
        one = jnp.asarray(self.one_np)
        return self.mont_mul(a, jnp.broadcast_to(one, a.shape))

    def mont_one(self):
        bits = 32 * self.n
        return jnp.asarray(self._int_to_limbs_np((1 << bits) % self.p))

    def _fermat_inv_mont(self, a):
        """Fermat inversion in Montgomery domain: a^(p-2).  inv(0) = 0.

        Two single-multiply scans instead of one square-and-multiply scan:
        phase 1 stacks the squares chain a^(2^i); phase 2 multiplies the
        squares selected by the bits of p-2.  Same exact integer result
        (modular multiplication is associative/commutative), but each scan
        body stays one fused kernel — XLA:CPU's while-loop runtime pays a
        ~0.3 s/iteration scheduling penalty the moment a body spans more
        than one fusion, which turned the old 2-multiply body into a
        63 s dispatch for a (4,) batch (observed; 35 ms this way).
        """
        bits = jnp.asarray(self._inv_exp_bits[::-1].copy())  # LSB-first

        def sq(acc, _):
            return self.mont_mul(acc, acc), acc

        _, squares = lax.scan(sq, a, None, length=bits.shape[0])
        squares = _scan_fence(squares)

        one = jnp.broadcast_to(self.mont_one(), a.shape)

        def mulsel(acc, si_b):
            si, bit = si_b
            return self.mont_mul(acc, jnp.where(bit == 1, si, one)), None

        acc, _ = lax.scan(mulsel, one, (squares, bits))
        return _scan_fence(acc)

    @_eager_jit(static_argnums=(0,))
    def inv_mont(self, a):
        """Inversion in Montgomery domain; inv(0) = 0.

        A single element runs the Fermat square-and-multiply chain
        (``_fermat_inv_mont``).  Any BATCHED input runs Montgomery batch
        inversion instead: the whole batch collapses through one prefix
        product, ONE Fermat chain inverts the single total, and two
        prefix/suffix passes fan the inverse back out — so the
        127-iteration sequential scan (the thing ``_scan_fence`` exists to
        protect on XLA:CPU) runs over ONE field element instead of the
        full tensor, and the deepest sequential chain a vector call site
        pays drops from 2*127 tensor-wide multiplies to one scalar chain
        plus log-depth prefix scans.  Zero entries are substituted with 1
        before the product (a zero would annihilate it) and masked back to
        0 after, preserving inv(0) = 0 exactly.  The inverse of a nonzero
        element is unique and canonical limbs are unique, so the result is
        limb-identical to the per-element Fermat chain.
        """
        batch_elems = 1
        for d in a.shape[:-1]:
            batch_elems *= d
        if batch_elems <= 1:
            return self._fermat_inv_mont(a)
        flat = a.reshape((-1, self.n))
        z = jnp.all(flat == 0, axis=-1)
        one = jnp.broadcast_to(self.mont_one(), flat.shape)
        safe = jnp.where(z[:, None], one, flat)
        inv = self._batch_inv_nonzero(safe, 0)
        inv = jnp.where(z[:, None], jnp.zeros_like(inv), inv)
        return inv.reshape(a.shape)

    @_eager_jit(static_argnums=(0,))
    def eq(self, a, b):
        """Elementwise equality of canonical limb vectors -> bool (...)."""
        return jnp.all(a == b, axis=-1)

    @_eager_jit(static_argnums=(0,))
    def is_zero(self, a):
        return jnp.all(a == 0, axis=-1)

    @_eager_jit(static_argnums=(0, 2))
    def sum(self, a, axis: int):
        """Exact modular reduction along an element axis.

        Long axes use a lazy 16-bit-half accumulation: limbs are split into
        u16 halves, summed with plain (exact, < 2^32) integer reduces, and
        reduced mod p ONCE at the end — replacing length-1 full modular adds
        (carry chain + conditional subtract each) with plain adds.  Exact
        integer math, so the result is limb-identical to the add tree, which
        short axes still use (the lazy path's fixed cost: a digit
        carry-propagation plus one tiny mont_mul).
        """
        axis = axis % (a.ndim - 1)  # never the limb axis
        length = a.shape[axis]
        if 16 <= length <= 65535:
            return self._sum_lazy(a, axis)
        while length > 1:
            half = length // 2
            lo = lax.slice_in_dim(a, 0, half, axis=axis)
            hi = lax.slice_in_dim(a, half, 2 * half, axis=axis)
            rest = lax.slice_in_dim(a, 2 * half, length, axis=axis)
            a = jnp.concatenate([self.add(lo, hi), rest], axis=axis)
            length = half + (length - 2 * half)
        return jnp.squeeze(a, axis=axis)

    def _sum_lazy(self, a, axis: int):
        """Lazy-reduction sum: u16-half accumulate, one mod-p fold at the end.

        Requires a.shape[axis] <= 65535 so each half-column sum stays below
        2^16 * 65535 < 2^32 (exact in u32).
        """
        slo = jnp.sum(a & _MASK16, axis=axis)  # (..., n) each < 2^32
        shi = jnp.sum(a >> 16, axis=axis)
        return self.lazy_fold(slo, shi)

    def lazy_fold(self, slo, shi):
        """(..., n) u16-half column sums -> canonical limbs (..., n).

        Base-2^16 digit stream D[2i] = slo_i, D[2i+1] = shi_i is carry-
        normalized; the overflow beyond 2^(32n) (carry < 2^17) folds back
        via one tiny mont_mul with R^2 (= 2^(32n)*R mod p).  Exact integer
        math — shared by the row-major and limb-planar lazy sums.
        """
        n = self.n
        carry = jnp.zeros_like(slo[..., 0])
        digits = []
        for i in range(n):
            t = slo[..., i] + carry
            digits.append(t & _MASK16)
            carry = t >> 16
            t = shi[..., i] + carry
            digits.append(t & _MASK16)
            carry = t >> 16
        limbs = self._join(
            [digits[2 * j] | (digits[2 * j + 1] << 16) for j in range(n)]
        )
        r2 = jnp.asarray(self.r2_np)
        hi_limbs = self._join([carry] + [jnp.zeros_like(carry)] * (n - 1))
        corr = self.mont_mul(hi_limbs, jnp.broadcast_to(r2, hi_limbs.shape))
        # limbs < 2^(32n) < 2p but may exceed p: add(x, 0) canonicalizes.
        limbs = self.add(limbs, jnp.zeros_like(limbs))
        return self.add(limbs, corr)

    @_eager_jit(static_argnums=(0, 2))
    def mutual_products_mont(self, a, axis: int):
        """For each k along the axis: prod_{j != k} a_j (Montgomery domain).

        Exclusive prefix x exclusive suffix products — the inversion-free
        core of barycentric Lagrange on roots of unity, where
        (t^P - 1)/(t - w^k) = prod_{j != k} (t - w^j) exactly.
        """
        axis = axis % (a.ndim - 1)
        L = a.shape[axis]
        prefix = self.cumprod_mont(a, axis)
        ones = jnp.broadcast_to(
            self.mont_one(), lax.slice_in_dim(a, 0, 1, axis=axis).shape
        )
        prefix_excl = jnp.concatenate(
            [ones, lax.slice_in_dim(prefix, 0, L - 1, axis=axis)], axis=axis
        )
        rev = jnp.flip(a, axis=axis)
        suffix_incl_rev = self.cumprod_mont(rev, axis)
        suffix_excl = jnp.concatenate(
            [
                jnp.flip(
                    lax.slice_in_dim(suffix_incl_rev, 0, L - 1, axis=axis), axis=axis
                ),
                ones,
            ],
            axis=axis,
        )
        return self.mont_mul(prefix_excl, suffix_excl)

    @_eager_jit(static_argnums=(0, 2))
    def cumprod_mont(self, a, axis: int):
        """Inclusive cumulative product (Montgomery domain) along an axis."""
        axis = axis % (a.ndim - 1)
        return _scan_fence(lax.associative_scan(self.mont_mul, a, axis=axis))

    @_eager_jit(static_argnums=(0, 2))
    def pow_range_mont(self, x, count: int):
        """x^1..x^count as (..., count, n), x Montgomery -> Montgomery.

        Baby-step/giant-step: two short sequential chains (~2*sqrt(count)
        tiny multiplies) plus ONE wide multiply — where cumprod_mont's
        associative scan costs log2(count) full-width passes over the
        (batch, count, n) tensor.  Exact Montgomery identities
        (mont_mul(aR, bR) = abR), so the limbs are byte-identical to the
        cumulative-product form (tests/test_ops_field.py)."""
        bs = max(1, math.isqrt(count))
        gs = -(-count // bs)
        baby = [x]  # baby[i] = x^(i+1) * R for i in 0..bs-1
        for _ in range(bs - 1):
            baby.append(self.mont_mul(baby[-1], x))
        giant = [jnp.broadcast_to(self.mont_one(), x.shape)]
        for _ in range(gs - 1):  # giant[g] = x^(bs*g) * R
            giant.append(self.mont_mul(giant[-1], baby[-1]))
        baby_t = jnp.stack(baby, axis=-2)  # (..., bs, n)
        giant_t = jnp.stack(giant, axis=-2)  # (..., gs, n)
        out = self.mont_mul(giant_t[..., :, None, :], baby_t[..., None, :, :])
        return out.reshape(x.shape[:-1] + (gs * bs, self.n))[..., :count, :]

    @_eager_jit(static_argnums=(0,))
    def poly_eval_mont(self, coeffs, x):
        """Polynomial evaluation via baby-step/giant-step powers.

        coeffs (..., C, n) canonical low-order-first, x (..., n) Montgomery
        -> (..., n) canonical.  Horner's C sequential tiny multiplies become
        ~2*sqrt(C) sequential ones plus C wide parallel ones — the serial
        depth is what dominates wide gadget polynomials (C = 1023 for the
        100k-element SumVec).  Exact integer math: limb-identical to
        horner_mont (tests/test_ops_field.py
        test_poly_eval_bsgs_matches_horner_wide, slow tier).
        """
        C = coeffs.shape[-2]
        bs = max(1, math.isqrt(C))
        gs = -(-C // bs)
        pad = bs * gs - C
        if pad:
            coeffs = jnp.concatenate(
                [coeffs, self.zeros(coeffs.shape[:-2] + (pad,))], axis=-2
            )
        one = jnp.broadcast_to(self.mont_one(), x.shape)
        baby = [one]  # x^i * R for i in 0..bs-1
        for _ in range(bs - 1):
            baby.append(self.mont_mul(baby[-1], x))
        xbs = self.mont_mul(baby[-1], x)  # x^bs * R
        giant = [one]  # x^(bs*g) * R
        for _ in range(gs - 1):
            giant.append(self.mont_mul(giant[-1], xbs))
        baby_t = jnp.stack(baby, axis=-2)  # (..., bs, n)
        giant_t = jnp.stack(giant, axis=-2)  # (..., gs, n)
        cg = coeffs.reshape(coeffs.shape[:-2] + (gs, bs, self.n))
        # c_j * x^(j%bs): canonical; sum over the baby axis, then * giant.
        t = self.mont_mul(cg, baby_t[..., None, :, :])
        inner = self.sum(t, axis=t.ndim - 2)  # (..., gs, n)
        outer = self.mont_mul(inner, giant_t)
        return self.sum(outer, axis=outer.ndim - 2)

    @_eager_jit(static_argnums=(0,))
    def horner_mont(self, coeffs, x):
        """Evaluate poly with coeff tensor (..., n_coeffs, n_limbs) at x (..., n_limbs).

        Low-order-first coefficients (matching the oracle); Montgomery domain.
        """
        rev = jnp.flip(coeffs, axis=-2)
        # scan over coefficient axis
        cs = jnp.moveaxis(rev, -2, 0)

        def body(acc, c):
            return self.add(self.mont_mul(acc, x), c), None

        acc0 = jnp.zeros_like(x)
        acc, _ = lax.scan(body, acc0, cs)
        return _scan_fence(acc)

    def ntt_eval_mont(self, coeffs, bitrev_idx, tw_stages):
        """Evaluate a polynomial at ALL P-th roots of unity (iterative NTT).

        coeffs (..., P, n) canonical -> values (..., P, n) canonical, value
        j = poly(w^j) in natural order.  ``bitrev_idx`` (P,) host-precomputed
        bit-reversal permutation; ``tw_stages`` list of per-stage twiddle
        tables (m/2, n) in Montgomery form (w^(P/m)^j).  Cooley-Tukey DIT:
        log2(P) stages of m/2 butterflies; each butterfly is one
        mont_mul(odd_canonical, twiddle_montgomery) -> canonical plus an
        add/sub, so the whole tensor stays canonical.  Exact integer math —
        identical limbs to per-point Horner evaluation, at O(P log P) cost
        instead of O(P * deg) (the wide-vector FLP evaluates a ~2P-coeff
        gadget polynomial at ~P points; reference circuit params
        core/src/vdaf.rs:220-236).
        """
        P = coeffs.shape[-2]
        x = jnp.take(coeffs, jnp.asarray(bitrev_idx), axis=-2)
        m = 2
        for tw in tw_stages:
            xr = x.reshape(x.shape[:-2] + (P // m, m, self.n))
            even = xr[..., : m // 2, :]
            odd = xr[..., m // 2 :, :]
            t = self.mont_mul(odd, jnp.broadcast_to(tw, odd.shape))
            xr = jnp.concatenate([self.add(even, t), self.sub(even, t)], axis=-2)
            x = xr.reshape(x.shape)
            m *= 2
        return x

    def ntt_eval_mont_limbs(self, coeffs: List, bitrev_idx, tw_stages) -> List:
        """Planar twin of ntt_eval_mont on limb lists.

        coeffs: n arrays (R, P, 128) canonical -> values, same shapes.  The
        butterfly schedule is identical op-for-op (one mont_mul + add/sub
        per butterfly, same order), so outputs are byte-identical to the
        row form — the lanes just hold reports instead of T(1,128) rows.
        """
        P = coeffs[0].shape[1]
        idx = jnp.asarray(bitrev_idx)
        x = [jnp.take(c, idx, axis=1) for c in coeffs]
        R = x[0].shape[0]
        m = 2
        for tw in tw_stages:  # (m/2, n) Montgomery twiddles
            xr = [c.reshape(R, P // m, m, 128) for c in x]
            even = [c[:, :, : m // 2] for c in xr]
            odd = [c[:, :, m // 2 :] for c in xr]
            twl = [
                jnp.broadcast_to(tw[:, l][None, None, :, None], odd[0].shape)
                for l in range(self.n)
            ]
            t = self.mont_mul_limbs(odd, twl)
            hi = self.add_limbs(even, t)
            lo = self.sub_limbs(even, t)
            x = [
                jnp.concatenate([h, l_], axis=2).reshape(R, P, 128)
                for h, l_ in zip(hi, lo)
            ]
            m *= 2
        return x

    def _batch_inv_nonzero(self, a, axis: int):
        """Montgomery-trick core: inv(a_k) = inv(prod_j a_j) * prod_{j != k}
        a_j — one Fermat inversion of the single total plus the exclusive
        mutual products.  All entries along the axis must be nonzero."""
        total = jnp.squeeze(
            lax.slice_in_dim(
                self.cumprod_mont(a, axis), a.shape[axis] - 1, a.shape[axis], axis=axis
            ),
            axis=axis,
        )
        inv_total = self._fermat_inv_mont(total)
        others = self.mutual_products_mont(a, axis)
        inv_b = jnp.expand_dims(inv_total, axis=axis)
        return _scan_fence(self.mont_mul(others, jnp.broadcast_to(inv_b, a.shape)))

    @_eager_jit(static_argnums=(0, 2))
    def batch_inv_mont(self, a, axis: int):
        """Montgomery-trick batched inversion along an axis (all nonzero)."""
        return self._batch_inv_nonzero(a, axis % (a.ndim - 1))

    # -- MXU contraction layer (limb-plane dot_general) -----------------
    def _digits8(self, x):
        """(..., n) u32 limbs -> (..., 4n) u32 base-2^8 digit planes.

        Little-endian, limb-major: digit d of an element has weight
        2^(8d).  Digits are held in u32 (not u8) so the contraction's
        dot_general accumulates in u32 — on TPU, XLA decomposes the
        integer matmul into MXU-native narrow passes; on CPU it stays one
        exact integer ``dot``.
        """
        parts = jnp.stack([(x >> (8 * i)) & _MASK8 for i in range(4)], axis=-1)
        return parts.reshape(x.shape[:-1] + (4 * self.n,))

    @_eager_jit(static_argnums=(0,))
    def mat_mul_mont(self, a, b):
        """Modular matmul with ONE Montgomery reduction per output element.

        a (*B, K, M, n) x b (*B, K, N, n) -> (*B, M, N, n) with
        out[m, v] = sum_k a[k, m] * b[k, v] * R^-1 mod p — exactly
        sum_k mont_mul(a_k, b_k), so it composes with the prepare
        pipeline's domain convention (one canonical operand times one
        Montgomery operand yields a canonical result) the same way a
        mont_mul/sum chain does.  ``b`` may omit the batch dims
        ((K, N, n)): a host-constant matrix (e.g. the gadget Vandermonde
        table) shared by every batch element.

        The contraction runs on base-2^8 digit planes as a single batched
        ``lax.dot_general`` with u32 accumulation (the MXU path named by
        the multi-precision-systolic-NTT recipe in PAPERS.md): all 4n x 4n
        cross-digit partial products for a whole output tile come out of
        one integer matmul, and carry propagation + modular reduction are
        DEFERRED to a single pass per output tile (``_lazy_reduce_digits``).
        Contractions longer than DOT_MAX_K split into exact modular-added
        chunks.  Every step is exact integer arithmetic, so outputs are
        limb-identical to the mont_mul/sum form (tests/test_mxu_field.py
        fuzzes random and adversarial operands against the oracle field).
        """
        K = a.shape[-3]
        if K <= DOT_MAX_K:
            return self._mat_mul_dot(a, b)
        out = None
        for s in range(0, K, DOT_MAX_K):
            part = self._mat_mul_dot(
                a[..., s : s + DOT_MAX_K, :, :], b[..., s : s + DOT_MAX_K, :, :]
            )
            out = part if out is None else self.add(out, part)
        return out

    def _mat_mul_dot(self, a, b):
        """Single-chunk core of mat_mul_mont (K <= DOT_MAX_K)."""
        n = self.n
        D = 4 * n
        K, M = a.shape[-3], a.shape[-2]
        N = b.shape[-2]
        batch = a.shape[:-3]
        nb = len(batch)
        shared_rhs = b.ndim == 3 and nb > 0
        lhs = jnp.moveaxis(self._digits8(a), -3, -1).reshape(batch + (M * D, K))
        if shared_rhs:
            rhs = self._digits8(b).reshape(K, N * D)
            dn = (((nb + 1,), (0,)), ((), ()))
        else:
            rhs = self._digits8(b).reshape(batch + (K, N * D))
            dn = (((nb + 1,), (nb,)), (tuple(range(nb)), tuple(range(nb))))
        prod = lax.dot_general(lhs, rhs, dn, preferred_element_type=_U32)
        return self._lazy_reduce_digits(
            prod.reshape(batch + (M, D, N, D)), batch + (M, N)
        )

    def _lazy_reduce_digits(self, P, out_shape):
        """(..., M, D, N, D) digit-pair partial sums -> canonical (..., M, N, n).

        The deferred half of the MXU contraction — one pass per output
        tile.  Lazy-carry bounds (all exact in u32):

        * each partial sum P[d, e] <= K * 255^2 < 2^32 for K <= DOT_MAX_K;
        * P splits into u16 halves before the diagonal fold, so a base-2^8
          digit column S[g] accumulates at most 2D addends each < 2^16 —
          S[g] < 2^21 regardless of K (the same trick as JField._sum_lazy);
        * the sequential carry pass keeps carry < 2^14.

        The normalized integer U < K * 2^(64n) <= 2^(64n+16) packs into
        2n+1 u32 limbs U = U0 + R*U1 + R^2*U2 (R = 2^(32n)), and
        U*R^-1 mod p folds with the existing primitives:
        from_mont(U0) + canonicalize(U1) + mont_mul(U2, R^2).  Each piece
        is the unique canonical residue of the same value mod p, so the
        result is limb-identical to the multiply/add tree it replaces.
        """
        n = self.n
        D = 4 * n
        lo = P & _MASK16
        hi = P >> 16
        zero = jnp.zeros(out_shape, dtype=_U32)
        # S[g]: base-2^8 digit column g — lo[d,e] lands at d+e, hi at d+e+2.
        S = [zero] * (2 * D + 1)
        for d in range(D):
            for e in range(D):
                f = d + e
                S[f] = S[f] + lo[..., d, :, e]
                S[f + 2] = S[f + 2] + hi[..., d, :, e]
        L = 2 * n + 1
        digits = []
        carry = zero
        for g in range(4 * L):
            t = (S[g] if g < len(S) else zero) + carry
            digits.append(t & _MASK8)
            carry = t >> 8
        # carry == 0 here: U < 2^(64n+16) and 4L digits span 2^(64n+32).
        U = jnp.stack(
            [
                digits[4 * j]
                | (digits[4 * j + 1] << 8)
                | (digits[4 * j + 2] << 16)
                | (digits[4 * j + 3] << 24)
                for j in range(L)
            ],
            axis=-1,
        )  # (..., M, N, L)
        U0 = U[..., :n]
        U1 = U[..., n : 2 * n]
        U2 = jnp.concatenate(
            [U[..., 2 * n :], jnp.zeros(U.shape[:-1] + (n - 1,), dtype=_U32)],
            axis=-1,
        )
        r2 = jnp.asarray(self.r2_np)
        res = self.add(self.from_mont(U0), self.add(U1, jnp.zeros_like(U1)))
        return self.add(res, self.mont_mul(U2, jnp.broadcast_to(r2, U2.shape)))

    @_eager_jit(static_argnums=(0,))
    def dot_mont(self, a, b):
        """Contraction form of mat_mul_mont: sum_k mont_mul(a_k, b_k).

        a (*B, K, M, n) x b (*B, K, n) -> (*B, M, n): the wire-evaluation
        shape (per-report Lagrange coefficients contracted against a
        per-report wire tensor).  One batched dot_general under the hood.
        """
        return jnp.squeeze(self.mat_mul_mont(a, b[..., :, None, :]), axis=-2)

    @_eager_jit(static_argnums=(0,))
    def poly_eval_dot(self, coeffs, x):
        """MXU twin of poly_eval_mont: baby-step/giant-step powers with
        BOTH contractions (per-giant coefficient fold, giant fold) run as
        mat_mul_mont dot_generals instead of mont_mul/sum trees.

        coeffs (..., C, n) canonical low-order-first, x (..., n) Montgomery
        -> (..., n) canonical.  Same residues stage for stage as
        poly_eval_mont (exact integer math), so limbs are identical.
        """
        C = coeffs.shape[-2]
        bs = max(1, math.isqrt(C))
        gs = -(-C // bs)
        pad = bs * gs - C
        if pad:
            coeffs = jnp.concatenate(
                [coeffs, self.zeros(coeffs.shape[:-2] + (pad,))], axis=-2
            )
        one = jnp.broadcast_to(self.mont_one(), x.shape)
        baby = [one]  # x^i * R for i in 0..bs-1
        for _ in range(bs - 1):
            baby.append(self.mont_mul(baby[-1], x))
        xbs = self.mont_mul(baby[-1], x)  # x^bs * R
        giant = [one]  # x^(bs*g) * R
        for _ in range(gs - 1):
            giant.append(self.mont_mul(giant[-1], xbs))
        baby_t = jnp.stack(baby, axis=-2)  # (..., bs, n)
        giant_t = jnp.stack(giant, axis=-2)  # (..., gs, n)
        cg = coeffs.reshape(coeffs.shape[:-2] + (gs, bs, self.n))
        inner = self.dot_mont(jnp.swapaxes(cg, -3, -2), baby_t)  # (..., gs, n)
        return jnp.squeeze(
            self.dot_mont(inner[..., :, None, :], giant_t), axis=-2
        )
