"""Device-side XOF field-vector expansion with exact rejection sampling.

Mirrors janus_tpu.xof.Xof.next_vec (draft-irtf-cfrg-vdaf-08 §6.2.1): the XOF
stream is consumed in ENCODED_SIZE-byte candidates, little-endian; candidates
>= MODULUS are skipped.  Rejections are vanishingly rare (~2^-32 per candidate
for Field64, ~2^-62 for Field128), so the kernel samples exactly ``length``
candidates and takes them verbatim; when every candidate is canonical — the
overwhelmingly common case — that is byte-identical to the oracle (no
candidate was skipped, so the oracle takes the same bytes).  Any rejection
clears the row's ``ok`` flag and the caller recomputes that row on the host
oracle (janus_tpu/vdaf/backend.py prep_init_batch).

An earlier version over-sampled a margin and compacted valid candidates with
a stable argsort; on TPU the batched sort cost ~2x the TurboSHAKE expansion
it post-processed (bitonic sort is O(n log^2 n) compares), for an event that
happens less than once per ~10^9 batches per Field64 job and essentially
never for Field128.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .field_jax import JField, _sbb, _u32
from .keccak_jax import RATE, bytes_to_words, xof_turboshake128_batch


def limbs_from_stream(jf: JField, stream: jnp.ndarray, num_elems: int) -> jnp.ndarray:
    """(..., num_elems * 4n) u8 -> (..., num_elems, n) u32 little-endian."""
    words = bytes_to_words(stream)
    return words.reshape(words.shape[:-1] + (num_elems, jf.n))


def _is_canonical(jf: JField, limbs: jnp.ndarray) -> jnp.ndarray:
    """True where the limb value is < MODULUS.  limbs: (..., n) -> (...)."""
    borrow = _u32(0)
    p = jf.p_np
    for i in range(jf.n):
        _, borrow = _sbb(limbs[..., i], jnp.asarray(np.uint32(p[i])), borrow)
    return borrow == 1


from .field_jax import _eager_jit as __eager_jit


@__eager_jit(static_argnums=(0, 2, 4))
def xof_next_vec_batch(
    jf: JField, seed: jnp.ndarray, dst: bytes, binder: jnp.ndarray, length: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Batched XofTurboShake128(...).next_vec(field, length).

    seed (..., 16) u8, binder (..., B) u8 -> (canonical limbs (..., length, n),
    ok (...) bool).  ``ok`` False means the stream contained a rejected
    candidate and the affected batch row must be recomputed on the host
    oracle.
    """
    from .keccak_pallas import pallas_enabled, xof_words_pallas

    elem_size = 4 * jf.n
    msg_len = 1 + len(dst) + seed.shape[-1] + binder.shape[-1]
    if seed.ndim == 2 and pallas_enabled(seed.shape[0]) and msg_len < RATE:
        words = xof_words_pallas(seed, dst, binder, length * jf.n)
        cand = words.reshape(words.shape[:-1] + (length, jf.n))
    else:
        stream = xof_turboshake128_batch(seed, dst, binder, length * elem_size)
        cand = limbs_from_stream(jf, stream, length)  # (..., length, n)
    valid = _is_canonical(jf, cand)  # (..., length)
    ok = jnp.all(valid, axis=-1)
    return cand, ok
