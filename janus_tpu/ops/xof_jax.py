"""Device-side XOF field-vector expansion with exact rejection sampling.

Mirrors janus_tpu.xof.Xof.next_vec (draft-irtf-cfrg-vdaf-08 §6.2.1): the XOF
stream is consumed in ENCODED_SIZE-byte candidates, little-endian; candidates
>= MODULUS are skipped.  Rejections are vanishingly rare (~2^-32 per candidate
for Field64, ~2^-62 for Field128) but must be handled exactly for
byte-compatibility with the oracle, so the kernel over-samples a margin and
compacts valid candidates with a stable sort; an ``ok`` mask flags the
(astronomically unlikely) case that the margin was insufficient, for host
fallback.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .field_jax import JField, _sbb, _u32
from .keccak_jax import RATE, bytes_to_words, xof_turboshake128_batch


def limbs_from_stream(jf: JField, stream: jnp.ndarray, num_elems: int) -> jnp.ndarray:
    """(..., num_elems * 4n) u8 -> (..., num_elems, n) u32 little-endian."""
    words = bytes_to_words(stream)
    return words.reshape(words.shape[:-1] + (num_elems, jf.n))


def _is_canonical(jf: JField, limbs: jnp.ndarray) -> jnp.ndarray:
    """True where the limb value is < MODULUS.  limbs: (..., n) -> (...)."""
    borrow = _u32(0)
    p = jf.p_np
    for i in range(jf.n):
        _, borrow = _sbb(limbs[..., i], jnp.asarray(np.uint32(p[i])), borrow)
    return borrow == 1


from .field_jax import _eager_jit as __eager_jit


@__eager_jit(static_argnums=(0, 2, 4))
def xof_next_vec_batch(
    jf: JField, seed: jnp.ndarray, dst: bytes, binder: jnp.ndarray, length: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Batched XofTurboShake128(...).next_vec(field, length).

    seed (..., 16) u8, binder (..., B) u8 -> (canonical limbs (..., length, n),
    ok (...) bool).  ``ok`` False means rejections exceeded the margin and the
    affected batch row must be recomputed on the host oracle.
    """
    elem_size = 4 * jf.n
    margin = max(2, RATE // elem_size)
    total = length + margin
    stream = xof_turboshake128_batch(seed, dst, binder, total * elem_size)
    cand = limbs_from_stream(jf, stream, total)  # (..., total, n)
    valid = _is_canonical(jf, cand)  # (..., total)
    # Stable-compact valid candidates to the front, preserving stream order.
    order = jnp.argsort(~valid, axis=-1, stable=True)  # valid-first
    taken = jnp.take_along_axis(cand, order[..., :length, None], axis=-2)
    ok = jnp.sum(valid.astype(jnp.int32), axis=-1) >= length
    return taken, ok
