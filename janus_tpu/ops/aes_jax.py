"""Fixed-key AES-128-ECB as a JAX kernel: the device half of the IDPF walk.

The Poplar1 tree walk's bulk compute is AES-128 over (N, 16) u8 blocks
(xof.XofFixedKeyAes128 hash_block, draft-irtf-cfrg-vdaf-08 §6.2.2).  The
host path runs it on AES-NI (``cryptography``) or numpy table AES
(utils/softaes.py); this module re-expresses the same table-based layout
as jitted jnp ops — u8 byte planes, S-box/xtime gathers, ShiftRows as a
static column permutation — so the walk can run where the sketch math
already lives and the per-level frontier never round-trips host memory.
The NTT-on-matrix-unit playbook (PAPERS.md: Low-Cost Multi-Precision
Systolic Arrays; Hermes) is the blueprint: byte-granular modular
arithmetic mapped onto wide integer units, exactly the limb-plane trick
ops/field_jax.py uses for field matmuls.

Two call forms:

* :class:`JaxAes128Ecb` — duck-type of ``Cipher(AES(key), ECB()).encryptor()``
  (``.update(bytes) -> bytes``), selected by the ``poplar_backend: jax``
  seam in ``utils.softaes.aes128_ecb_encryptor``.
* :func:`encrypt_blocks_multikey` — the batched walk form: per-REPORT
  round keys (B, 11, 16) over (B, K, 16) blocks in ONE vmapped launch,
  with K padded to a power of two so a whole tree walk compiles O(log)
  executables instead of one per frontier width.

Correctness is anchored to the FIPS-197 appendix C.1 vector at import
time (like softaes: a table or layout bug must fail loudly, never walk a
wrong tree) and fuzzed against softaes in tests/test_aes_jax.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# The host tables are generated from the GF(2^8) construction in softaes
# (no transcription risk); this module only re-hosts them as device
# constants.  _expand_key is reused verbatim — key schedules are tiny and
# per-report, host territory.
from ..utils.softaes import _MUL2, _MUL3, _SBOX, _SHIFT, _expand_key

__all__ = [
    "JaxAes128Ecb",
    "encrypt_blocks_jax",
    "encrypt_blocks_multikey",
    "expand_keys",
]

_J_SBOX = jnp.asarray(_SBOX)
_J_MUL2 = jnp.asarray(_MUL2)
_J_MUL3 = jnp.asarray(_MUL3)
#: ShiftRows as a flat gather over the 16-byte state (softaes layout:
#: byte i sits at (row = i % 4, col = i // 4)).
_J_SHIFT = jnp.asarray(np.asarray(_SHIFT, dtype=np.int32))


def _sub_shift(s):
    """SubBytes + ShiftRows on (..., 16) u8 state."""
    return _J_SBOX[s][..., _J_SHIFT]


def _mix_columns(s):
    """MixColumns on (..., 16) u8 state, reshaped (..., 4 cols, 4 rows)."""
    a = s.reshape(s.shape[:-1] + (4, 4))
    a0, a1, a2, a3 = a[..., 0], a[..., 1], a[..., 2], a[..., 3]
    out = jnp.stack(
        [
            _J_MUL2[a0] ^ _J_MUL3[a1] ^ a2 ^ a3,
            a0 ^ _J_MUL2[a1] ^ _J_MUL3[a2] ^ a3,
            a0 ^ a1 ^ _J_MUL2[a2] ^ _J_MUL3[a3],
            _J_MUL3[a0] ^ a1 ^ a2 ^ _J_MUL2[a3],
        ],
        axis=-1,
    )
    return out.reshape(s.shape)


def _encrypt_core(round_keys, blocks):
    """AES-128 over (..., 16) u8 blocks with (11, 16) u8 round keys.

    The round loop is unrolled (10 rounds is a fixed, tiny depth) so the
    whole cipher fuses into one executable of table gathers + XORs.
    """
    s = blocks ^ round_keys[0]
    for rnd in range(1, 10):
        s = _sub_shift(s)
        s = _mix_columns(s) ^ round_keys[rnd]
    return _sub_shift(s) ^ round_keys[10]


@jax.jit
def encrypt_blocks_jax(round_keys, blocks):
    """Single-key form: (11, 16) u8 round keys, (N, 16) u8 blocks."""
    return _encrypt_core(round_keys, blocks)


@jax.jit
def encrypt_blocks_multikey(round_keys, blocks):
    """Per-report form: (B, 11, 16) round keys over (B, K, 16) blocks —
    the IDPF walk's shape (two key schedules per report, every frontier
    node of every report in one launch)."""
    return jax.vmap(_encrypt_core)(round_keys, blocks)


def expand_keys(keys) -> np.ndarray:
    """(B, 11, 16) u8 round-key schedules for a sequence of 16-byte keys."""
    return np.stack([_expand_key(bytes(k)) for k in keys])


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def encrypt_blocks_multikey_padded(round_keys, blocks):
    """The walk's dispatch face: pads the block axis (and the batch axis)
    to powers of two before the jitted multikey launch, so a level-by-level
    walk with growing frontiers compiles O(log) executables, then slices
    the result back.  Accepts numpy or jax arrays; returns a DEVICE array
    (callers keep the frontier resident across levels)."""
    rks = jnp.asarray(round_keys, dtype=jnp.uint8)
    blk = jnp.asarray(blocks, dtype=jnp.uint8)
    b, k = blk.shape[0], blk.shape[1]
    pb, pk = _next_pow2(b), _next_pow2(k)
    if pb != b or pk != k:
        blk = jnp.pad(blk, ((0, pb - b), (0, pk - k), (0, 0)))
        if pb != b:
            rks = jnp.pad(rks, ((0, pb - b), (0, 0), (0, 0)))
    out = encrypt_blocks_multikey(rks, blk)
    return out[:b, :k, :]


class JaxAes128Ecb:
    """Duck-type of ``Cipher(AES(key), ECB()).encryptor()`` over the jitted
    kernel: stateless ECB, ``update`` encrypts every 16-byte block.  The
    per-call host<->device byte round trip makes this the API-compat face
    only — the batched walk uses the array forms above directly."""

    def __init__(self, key: bytes):
        self._rk = jnp.asarray(_expand_key(key))

    def update(self, data: bytes) -> bytes:
        if len(data) % 16:
            raise ValueError("ECB input must be a multiple of 16 bytes")
        if not data:
            return b""
        blocks = np.frombuffer(data, dtype=np.uint8).reshape(-1, 16)
        return np.asarray(encrypt_blocks_jax(self._rk, blocks)).tobytes()


# -- import-time anchor (FIPS-197 C.1) ---------------------------------------
_vec = JaxAes128Ecb(bytes(range(16))).update(
    bytes.fromhex("00112233445566778899aabbccddeeff")
)
if _vec != bytes.fromhex("69c4e0d86a7b0430d8cdb78070b4c55a"):  # pragma: no cover
    raise AssertionError("aes_jax self-test failed (table/layout corruption)")
del _vec
