"""Pallas TPU kernel for the chunked-circuit wire evaluations (limb-planar).

The FLP query's dominant cost is the wire-polynomial evaluation over the
measurement: for every chunk column u,

    evens[u] = (sum_k m[k,u] * kl[k]) * r_ch[u]
    odds[u]  =  sum_k m[k,u] * lagk[k]  -  ccorr
    wire     =  seeds * lag0  +  zip(evens, odds)

(~3.5 * MEAS_LEN Montgomery multiplies per report for histogram1024).  XLA
emits this as dozens of partially-fused elementwise kernels at ~2x the raw
op cost (profiled); this kernel hand-schedules the whole contraction with
every tensor in the limb-planar layout — tensors are (R, n_limbs, elems,
128) with the 128 lanes indexing reports (report b lives at (b // 128,
..., b % 128)) — so each VPU op is full-width and the measurement block is
read from HBM exactly once.

The chunk axis is zero-padded to a multiple of 16 so block shapes satisfy
the TPU (8, 128) tiling rule; pad columns compute garbage wires that the
caller slices off (no cross-column dataflow exists).

Field arithmetic is field_jax.JField's limb-list CIOS core (mont_mul_limbs),
so device results are byte-identical to the row-major path and the oracle
by construction (tests/test_prepare.py).

Reference hot loop analog: aggregator/src/aggregator/aggregation_job_driver.rs:397-449.
"""

from __future__ import annotations

from functools import partial
from typing import List

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .field_jax import JField


def _pallas_interpret() -> bool:
    from .keccak_pallas import _pallas_mode

    return _pallas_mode() == "interpret"


def pad_chunk(chunk: int) -> int:
    """Chunk axis padded so both it and its half are sublane (8) multiples."""
    return -(-chunk // 16) * 16


def _uchunks(chunk_pad: int) -> int:
    """Grid subdivision of the chunk axis keeping blocks comfortably in VMEM."""
    return 2 if chunk_pad > 160 else 1


def _grid_chunk(chunk: int):
    """(NJ, UC) for chunk-axis grid splitting: UC-column steps with UC a
    sublane (8) multiple — Mosaic requires block minor-2 dims divisible by 8
    — and NJ*UC >= chunk (the <=7-column ragged tail is masked/clipped)."""
    NJ = -(-chunk // 96)
    UC = 8 * (-(-chunk // (8 * NJ)))
    return NJ, UC


def _wire_kernel(jf: JField, meas_len: int, chunk: int, calls: int, UC: int,
                 m_ref, p_ref, rch_ref, kl_ref, lagk_ref, lag0_ref,
                 ccorr_ref, ev_ref, od_ref):
    """Histogram wire evals straight off the RAW limb-planar streams.

    m_ref block (1, n, meas_len, 128): the measurement-share squeeze planes
    with NO padding — the circuit's zero padding of positions
    meas_len..calls*chunk-1 is applied in-register (mask on the last call's
    tail), and per-call columns are unaligned static slices (Mosaic handles
    non-tile-aligned slices on the sublane axis).  p_ref block
    (1, n, PROOF_LEN, 128): the raw proof planes; the zipped wire seeds
    [a0, b0, a1, b1, ...] are de-interleaved in-register via a sublane
    reshape.  This removes every XLA-side pad / de-interleave / calls
    reshape pass (~100s of MB per launch) between the XOF and the wires.

    The chunk axis is processed in UC-column grid steps (minor grid dim) to
    bound the Mosaic VMEM stack; the stream blocks' index maps ignore that
    dim, so they are fetched once per R row.
    """
    n = jf.n
    j = pl.program_id(1)

    def scal(ref, *idx):
        return jnp.broadcast_to(ref[idx].reshape(1, 128), (UC, 128))

    s1: List = None
    s2: List = None
    for k in range(calls):
        lo = k * chunk  # + j*UC dynamically below
        lim_full = meas_len - k * chunk  # valid columns in this call
        mk = [
            m_ref[0, l, pl.dslice(lo + j * UC, UC), :] for l in range(n)
        ]
        if lim_full < chunk:
            # circuit zero padding for the final partial call: column
            # j*UC + i is valid iff j*UC + i < lim_full.
            upos = jax.lax.broadcasted_iota(jnp.uint32, (UC, 128), 0) + j * UC
            keep = upos < lim_full
            zero = jnp.zeros((UC, 128), dtype=jnp.uint32)
            mk = [jnp.where(keep, x, zero) for x in mk]
        t1 = jf.mont_mul_limbs(mk, [scal(kl_ref, 0, l, k) for l in range(n)])
        s1 = t1 if s1 is None else jf.add_limbs(s1, t1)
        t2 = jf.mont_mul_limbs(mk, [scal(lagk_ref, 0, l, k) for l in range(n)])
        s2 = t2 if s2 is None else jf.add_limbs(s2, t2)
    rch = [rch_ref[0, l, :, :] for l in range(n)]
    evens = jf.mont_mul_limbs(s1, rch)
    odds = jf.sub_limbs(s2, [scal(ccorr_ref, 0, l) for l in range(n)])
    lag0 = [scal(lag0_ref, 0, l) for l in range(n)]
    sw = [
        p_ref[0, l, pl.dslice(2 * j * UC, 2 * UC), :].reshape(UC, 2, 128)
        for l in range(n)
    ]
    swe = [s[:, 0, :] for s in sw]
    swo = [s[:, 1, :] for s in sw]
    ev = jf.add_limbs(jf.mont_mul_limbs(swe, lag0), evens)
    od = jf.add_limbs(jf.mont_mul_limbs(swo, lag0), odds)
    for l in range(n):
        ev_ref[0, l, :, :] = ev[l]
        od_ref[0, l, :, :] = od[l]


def _sumvec_partial_kernel(jf: JField, kc: int, m_ref, klu_ref, lagk_ref,
                           ev_ref, od_ref):
    """Per-call-slab contraction for the SumVec circuit:

        evens_part[u] = sum_k m[k,u] * klu[k,u]
        odds_part[u]  = sum_k m[k,u] * lagk[k]

    klu[k,u] = jr_k^(u+1) * lag_{k+1} varies over BOTH axes (the joint rand
    is per-call and its power resets each call), so unlike the histogram
    kernel the evens coefficient is a full tensor, computed slab-by-slab by
    the caller so the 100k-element circuits never materialize it whole.
    """
    n = jf.n
    UC = m_ref.shape[3]
    shape = (UC, 128)
    ev: List = None
    od: List = None
    for k in range(kc):
        mk = [m_ref[0, l, k, :, :] for l in range(n)]
        kluk = [klu_ref[0, l, k, :, :] for l in range(n)]
        t1 = jf.mont_mul_limbs(mk, kluk)
        ev = t1 if ev is None else jf.add_limbs(ev, t1)
        lgk = [
            jnp.broadcast_to(lagk_ref[0, l, k, :].reshape(1, 128), shape)
            for l in range(n)
        ]
        t2 = jf.mont_mul_limbs(mk, lgk)
        od = t2 if od is None else jf.add_limbs(od, t2)
    for l in range(n):
        ev_ref[0, l, :, :] = ev[l]
        od_ref[0, l, :, :] = od[l]


def sumvec_partial_planar(
    jf: JField,
    m_slab: jnp.ndarray,     # (R, n, KC, chunk_pad, 128) canonical
    klu_slab: jnp.ndarray,   # (R, n, KC, chunk_pad, 128) Montgomery
    lagk_slab: jnp.ndarray,  # (R, n, KC, 128) Montgomery
    *,
    interpret: bool = False,
):
    """One slab's (evens_part, odds_part), each (R, n, chunk_pad, 128)."""
    R, n, kc, chunk_pad, _ = m_slab.shape
    NJ = _uchunks(chunk_pad)
    UC = chunk_pad // NJ
    grid = (R, NJ)
    kern = partial(_sumvec_partial_kernel, jf, kc)
    out_shape = jax.ShapeDtypeStruct((R, n, chunk_pad, 128), jnp.uint32)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, n, kc, UC, 128), lambda r, j: (r, 0, 0, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, kc, UC, 128), lambda r, j: (r, 0, 0, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, kc, 128), lambda r, j: (r, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, n, UC, 128), lambda r, j: (r, 0, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, UC, 128), lambda r, j: (r, 0, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[out_shape, out_shape],
        interpret=interpret,
    )(m_slab, klu_slab, lagk_slab)


def wire_evals_planar(
    jf: JField,
    meas_len: int,
    chunk: int,
    m_pl: jnp.ndarray,      # (R, n, MEAS_LEN, 128) canonical (raw planes)
    proof_pl: jnp.ndarray,  # (R, n, PROOF_LEN, 128) canonical (raw planes)
    rch_pl: jnp.ndarray,    # (R, n, chunk, 128) Montgomery r^(u+1)
    kl_pl: jnp.ndarray,     # (R, n, calls, 128) Montgomery
    lagk_pl: jnp.ndarray,   # (R, n, calls, 128) Montgomery
    lag0_pl: jnp.ndarray,   # (R, n, 128) Montgomery
    ccorr_pl: jnp.ndarray,  # (R, n, 128) canonical
    *,
    interpret: bool = False,
):
    """Histogram-family wire evals off the raw streams, kept as separate
    even/odd planes (w_{2u} and w_{2u+1}) -> two (R, n, chunk, 128)
    canonical tensors.  Circuit zero-padding, per-call splitting, and wire
    seed de-interleaving all happen in-register (see _wire_kernel)."""
    R, n, L, _ = m_pl.shape
    cp2 = rch_pl.shape[2]
    calls = kl_pl.shape[2]
    plen = proof_pl.shape[2]
    # UC-column grid steps bound the Mosaic stack; the stream blocks span
    # the whole row.  Blocks may exceed the array (ragged NJ*UC tails, the
    # m tail past meas_len): that region is Mosaic edge padding, read only
    # under the zero mask / in out columns >= chunk which consumers clip.
    NJ, UC = _grid_chunk(chunk)
    assert cp2 == NJ * UC, (cp2, NJ, UC)

    def blk8(dim: int, array_dim: int) -> int:
        return dim if dim == array_dim else 8 * (-(-dim // 8))

    mblk = blk8(max((calls - 1) * chunk + NJ * UC, L), L)
    pblk = blk8(max(plen, 2 * NJ * UC), plen)
    grid = (R, NJ)
    kern = partial(_wire_kernel, jf, meas_len, chunk, calls, UC)
    out_shape = jax.ShapeDtypeStruct((R, n, cp2, 128), jnp.uint32)
    uc_spec = pl.BlockSpec((1, n, UC, 128), lambda r, j: (r, 0, j, 0),
                           memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, n, mblk, 128), lambda r, j: (r, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, pblk, 128), lambda r, j: (r, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            uc_spec,
            pl.BlockSpec((1, n, calls, 128), lambda r, j: (r, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, calls, 128), lambda r, j: (r, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, 128), lambda r, j: (r, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, 128), lambda r, j: (r, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[uc_spec, uc_spec],
        out_shape=[out_shape, out_shape],
        interpret=interpret,
    )(m_pl, proof_pl, rch_pl, kl_pl, lagk_pl, lag0_pl, ccorr_pl)


def _combine_decide_kernel(jf: JField, chunk: int, UC: int, he_ref, ho_ref,
                           pv_ref, g_ref):
    """Combined-verifier gadget sum for one (R, UC-columns) grid step:

        g_part = sum_u mont_mul(he[u] + pe[u], ho[u] + po[u])

    he/ho are our even/odd wire planes; pv is the peer's verifier in plane
    layout as it came off the wire (row 0 = v, rows 1..2*chunk = zipped
    wires, row 2*chunk+1 = gpoly(t)) — the zipped wires are de-interleaved
    in-register.  Output: 8-sublane partial sums (1, n, 8, 128) per j step;
    the caller folds sublanes and steps with add_limbs (tiny)."""
    n = jf.n
    j = pl.program_id(1)
    pv = [
        pv_ref[0, l, pl.dslice(1 + 2 * j * UC, 2 * UC), :].reshape(UC, 2, 128)
        for l in range(n)
    ]
    xe = jf.add_limbs([he_ref[0, l] for l in range(n)],
                      [p[:, 0, :] for p in pv])
    xo = jf.add_limbs([ho_ref[0, l] for l in range(n)],
                      [p[:, 1, :] for p in pv])
    prod = jf.mont_mul_limbs(xe, xo)
    # columns past chunk in the final step are he/ho edge padding: zero them
    upos = jax.lax.broadcasted_iota(jnp.uint32, (UC, 128), 0) + j * UC
    keep = upos < chunk
    zero = jnp.zeros((UC, 128), dtype=jnp.uint32)
    prod = [jnp.where(keep, p, zero) for p in prod]
    # fold UC -> 8 sublanes (zero-pad the ragged tail slab)
    slabs = -(-UC // 8)
    if UC < slabs * 8:
        prod = [jnp.pad(p, ((0, slabs * 8 - UC), (0, 0))) for p in prod]
    acc = [p[:8] for p in prod]
    for i in range(1, slabs):
        acc = jf.add_limbs(acc, [p[8 * i : 8 * (i + 1)] for p in prod])
    for l in range(n):
        g_ref[0, l] = acc[l]


def combine_decide_planar(
    jf: JField,
    chunk: int,
    he_pl: jnp.ndarray,  # (R, n, chunk, 128) canonical even wires (ours)
    ho_pl: jnp.ndarray,  # (R, n, chunk, 128) canonical odd wires (ours)
    pv_pl: jnp.ndarray,  # (R, n, VERIFIER_LEN, 128) canonical (peer, zipped)
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """ParallelSum(Mul) gadget over the COMBINED wires -> g (R, n, 8*NJ, 128)
    partial sums (caller folds the sublane axis).  This is the decide step's
    hot contraction — chunk Montgomery multiplies per report — which XLA
    otherwise emits as unfused (B, chunk, n) passes at several times the
    kernel's cost."""
    R, n, chunk_c, _ = he_pl.shape
    vlen = pv_pl.shape[2]
    NJ, UC = _grid_chunk(chunk)
    assert chunk_c == NJ * UC, (chunk_c, NJ, UC)
    vblk = max(vlen, 1 + 2 * NJ * UC)
    if vblk != vlen:
        vblk = 8 * (-(-vblk // 8))
    grid = (R, NJ)
    kern = partial(_combine_decide_kernel, jf, chunk, UC)
    uc_spec = pl.BlockSpec((1, n, UC, 128), lambda r, j: (r, 0, j, 0),
                           memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            uc_spec,
            uc_spec,
            # Block may exceed vlen when NJ*UC is ragged: the excess is
            # Mosaic edge padding, only ever read under the zero mask.
            pl.BlockSpec((1, n, vblk, 128), lambda r, j: (r, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, n, 8, 128), lambda r, j: (r, 0, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, n, 8 * NJ, 128), jnp.uint32),
        interpret=interpret,
    )(he_pl, ho_pl, pv_pl)
