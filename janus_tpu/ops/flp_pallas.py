"""Pallas TPU kernel for the chunked-circuit wire evaluations (limb-planar).

The FLP query's dominant cost is the wire-polynomial evaluation over the
measurement: for every chunk column u,

    evens[u] = (sum_k m[k,u] * kl[k]) * r_ch[u]
    odds[u]  =  sum_k m[k,u] * lagk[k]  -  ccorr
    wire     =  seeds * lag0  +  zip(evens, odds)

(~3.5 * MEAS_LEN Montgomery multiplies per report for histogram1024).  XLA
emits this as dozens of partially-fused elementwise kernels at ~2x the raw
op cost (profiled); this kernel hand-schedules the whole contraction with
every tensor in the limb-planar layout — tensors are (R, n_limbs, elems,
128) with the 128 lanes indexing reports (report b lives at (b // 128,
..., b % 128)) — so each VPU op is full-width and the measurement block is
read from HBM exactly once.

The chunk axis is zero-padded to a multiple of 16 so block shapes satisfy
the TPU (8, 128) tiling rule; pad columns compute garbage wires that the
caller slices off (no cross-column dataflow exists).

Field arithmetic is field_jax.JField's limb-list CIOS core (mont_mul_limbs),
so device results are byte-identical to the row-major path and the oracle
by construction (tests/test_prepare.py).

Reference hot loop analog: aggregator/src/aggregator/aggregation_job_driver.rs:397-449.
"""

from __future__ import annotations

from functools import partial
from typing import List

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .field_jax import JField


def _pallas_interpret() -> bool:
    from .keccak_pallas import _pallas_mode

    return _pallas_mode() == "interpret"


def pad_chunk(chunk: int) -> int:
    """Chunk axis padded so both it and its half are sublane (8) multiples."""
    return -(-chunk // 16) * 16


def _uchunks(chunk_pad: int) -> int:
    """Grid subdivision of the chunk axis keeping blocks comfortably in VMEM."""
    return 2 if chunk_pad > 160 else 1


def _wire_kernel(jf: JField, calls: int, m_ref, sw_ref, rch_ref, kl_ref,
                 lagk_ref, lag0_ref, ccorr_ref, out_ref):
    n = jf.n
    UC = m_ref.shape[3]
    shape = (UC, 128)

    def scal(ref, *idx):
        return jnp.broadcast_to(ref[idx].reshape(1, 128), shape)

    s1: List = None
    s2: List = None
    for k in range(calls):
        mk = [m_ref[0, l, k, :, :] for l in range(n)]
        t1 = jf.mont_mul_limbs(mk, [scal(kl_ref, 0, l, k) for l in range(n)])
        s1 = t1 if s1 is None else jf.add_limbs(s1, t1)
        t2 = jf.mont_mul_limbs(mk, [scal(lagk_ref, 0, l, k) for l in range(n)])
        s2 = t2 if s2 is None else jf.add_limbs(s2, t2)
    rch = [rch_ref[0, l, :, :] for l in range(n)]
    evens = jf.mont_mul_limbs(s1, rch)
    odds = jf.sub_limbs(s2, [scal(ccorr_ref, 0, l) for l in range(n)])
    sshape = (2 * UC, 128)
    sw = [sw_ref[0, l, :, :] for l in range(n)]
    lag0 = [
        jnp.broadcast_to(lag0_ref[0, l].reshape(1, 128), sshape) for l in range(n)
    ]
    se = jf.mont_mul_limbs(sw, lag0)
    eo = [jnp.stack([evens[l], odds[l]], axis=1).reshape(sshape) for l in range(n)]
    wire = jf.add_limbs(se, eo)
    for l in range(n):
        out_ref[0, l, :, :] = wire[l]


def _sumvec_partial_kernel(jf: JField, kc: int, m_ref, klu_ref, lagk_ref,
                           ev_ref, od_ref):
    """Per-call-slab contraction for the SumVec circuit:

        evens_part[u] = sum_k m[k,u] * klu[k,u]
        odds_part[u]  = sum_k m[k,u] * lagk[k]

    klu[k,u] = jr_k^(u+1) * lag_{k+1} varies over BOTH axes (the joint rand
    is per-call and its power resets each call), so unlike the histogram
    kernel the evens coefficient is a full tensor, computed slab-by-slab by
    the caller so the 100k-element circuits never materialize it whole.
    """
    n = jf.n
    UC = m_ref.shape[3]
    shape = (UC, 128)
    ev: List = None
    od: List = None
    for k in range(kc):
        mk = [m_ref[0, l, k, :, :] for l in range(n)]
        kluk = [klu_ref[0, l, k, :, :] for l in range(n)]
        t1 = jf.mont_mul_limbs(mk, kluk)
        ev = t1 if ev is None else jf.add_limbs(ev, t1)
        lgk = [
            jnp.broadcast_to(lagk_ref[0, l, k, :].reshape(1, 128), shape)
            for l in range(n)
        ]
        t2 = jf.mont_mul_limbs(mk, lgk)
        od = t2 if od is None else jf.add_limbs(od, t2)
    for l in range(n):
        ev_ref[0, l, :, :] = ev[l]
        od_ref[0, l, :, :] = od[l]


def sumvec_partial_planar(
    jf: JField,
    m_slab: jnp.ndarray,     # (R, n, KC, chunk_pad, 128) canonical
    klu_slab: jnp.ndarray,   # (R, n, KC, chunk_pad, 128) Montgomery
    lagk_slab: jnp.ndarray,  # (R, n, KC, 128) Montgomery
    *,
    interpret: bool = False,
):
    """One slab's (evens_part, odds_part), each (R, n, chunk_pad, 128)."""
    R, n, kc, chunk_pad, _ = m_slab.shape
    NJ = _uchunks(chunk_pad)
    UC = chunk_pad // NJ
    grid = (R, NJ)
    kern = partial(_sumvec_partial_kernel, jf, kc)
    out_shape = jax.ShapeDtypeStruct((R, n, chunk_pad, 128), jnp.uint32)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, n, kc, UC, 128), lambda r, j: (r, 0, 0, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, kc, UC, 128), lambda r, j: (r, 0, 0, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, kc, 128), lambda r, j: (r, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, n, UC, 128), lambda r, j: (r, 0, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, UC, 128), lambda r, j: (r, 0, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[out_shape, out_shape],
        interpret=interpret,
    )(m_slab, klu_slab, lagk_slab)


def wire_evals_planar(
    jf: JField,
    m_pl: jnp.ndarray,      # (R, n, calls, chunk_pad, 128) canonical
    sw_pl: jnp.ndarray,     # (R, n, 2*chunk_pad, 128) canonical
    rch_pl: jnp.ndarray,    # (R, n, chunk_pad, 128) Montgomery r^(u+1)
    kl_pl: jnp.ndarray,     # (R, n, calls, 128) Montgomery
    lagk_pl: jnp.ndarray,   # (R, n, calls, 128) Montgomery
    lag0_pl: jnp.ndarray,   # (R, n, 128) Montgomery
    ccorr_pl: jnp.ndarray,  # (R, n, 128) canonical
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """Histogram-family wire evals -> (R, n, 2*chunk_pad, 128) canonical."""
    R, n, calls, chunk_pad, _ = m_pl.shape
    NJ = _uchunks(chunk_pad)
    UC = chunk_pad // NJ
    grid = (R, NJ)
    kern = partial(_wire_kernel, jf, calls)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, n, calls, UC, 128), lambda r, j: (r, 0, 0, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, 2 * UC, 128), lambda r, j: (r, 0, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, UC, 128), lambda r, j: (r, 0, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, calls, 128), lambda r, j: (r, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, calls, 128), lambda r, j: (r, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, 128), lambda r, j: (r, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, 128), lambda r, j: (r, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, n, 2 * UC, 128), lambda r, j: (r, 0, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, n, 2 * chunk_pad, 128), jnp.uint32),
        interpret=interpret,
    )(m_pl, sw_pl, rch_pl, kl_pl, lagk_pl, lag0_pl, ccorr_pl)
