"""Pallas TPU kernels for batched TurboSHAKE128 (Keccak-p[1600,12]).

The XLA graph version (keccak_jax.py) runs the permutation as ~5k scalar u32
HLOs on (B, 50)-shaped tensors and reaches ~2% of VPU peak.  These kernels
hold the sponge state in VMEM scratch as 100 u32 lane-words of shape (8, 128)
— one full VPU tile of 1024 reports per lane-word — so every xor/rot/and in
the permutation is a single full-width VPU op, and the squeeze/absorb block
loop rides the Pallas grid, overlapping the per-block HBM DMA with the next
permutation.

Layout convention ("planar"): a batch of B reports (B % 1024 == 0) is carried
as u32 word-planes of shape (W, B // 128, 128); plane w holds stream word w
of every report.  Lane l of the Keccak state is planes (2l, 2l+1) =
(lo, hi) of the 64-bit lane, identical to keccak_jax.

Replaces the rayon-parallel scalar Keccak of the reference's prio crate
(reference: aggregator/src/aggregator.rs:2101 ships the per-report scalar
loops to rayon; SURVEY.md §2.3 P1).  Bit-exact vs janus_tpu.xof.turboshake128
(tests/test_ops_keccak.py, interpret mode on CPU + real kernels on TPU).
"""

from __future__ import annotations

import os
from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..xof import ROUND_CONSTANTS, _RHO

RATE = 168
RATE_WORDS = 42
_ROUNDS = 12
_RC = [(rc & 0xFFFFFFFF, rc >> 32) for rc in ROUND_CONSTANTS[24 - _ROUNDS :]]


def _pallas_mode() -> str:
    """'on' | 'off' | 'interpret' — resolved at trace time.

    auto: real kernels when the default backend is TPU, else off (the CPU
    test mesh and the oracle paths use the XLA graph version).
    """
    mode = os.environ.get("JANUS_TPU_PALLAS", "auto")
    if mode in ("0", "off"):
        return "off"
    if mode == "interpret":
        return "interpret"
    if mode in ("1", "on"):
        return "on"
    return "on" if jax.default_backend() == "tpu" else "off"


def pallas_enabled(batch: int) -> bool:
    """True when the planar kernels apply: TPU (or interpret) and full tiles."""
    return batch % 1024 == 0 and _pallas_mode() != "off"


# -- the permutation on (lo, hi) u32 tile pairs -----------------------------

def _rotl(lo, hi, r: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    r %= 64
    if r == 0:
        return lo, hi
    if r == 32:
        return hi, lo
    if r < 32:
        return (lo << r) | (hi >> (32 - r)), (hi << r) | (lo >> (32 - r))
    s = r - 32
    return (hi << s) | (lo >> (32 - s)), (lo << s) | (hi >> (32 - s))


def _permute_pingpong(a_ref, b_ref):
    """Keccak-p[1600,12] on a (100, 8, 128) VMEM state, result in a_ref.

    Register-pressure-aware schedule: holding all 25 lanes of a 1024-report
    tile in registers (50 live (8,128) tiles + temporaries) overflows the
    VPU register file and Mosaic spills every round.  Instead each round
    streams through VMEM — theta columns, then rho+pi+chi fused per output
    row — reading the round input from one buffer and writing the round
    output to the other (ping-pong, so sources are never clobbered).  At
    most ~25 tiles are live and every state word is loaded twice / stored
    once per round.  Measured ~6x faster than the all-lanes-in-registers
    form on v5e.  12 rounds = even count, so the result lands back in a_ref.
    """
    for rnd, (rc_lo, rc_hi) in enumerate(_RC):
        src_ref, dst_ref = (a_ref, b_ref) if rnd % 2 == 0 else (b_ref, a_ref)
        # theta: column xors c[x], then d[x] = c[x-1] ^ rotl(c[x+1], 1)
        c = []
        for x in range(5):
            lo = src_ref[2 * x] ^ src_ref[2 * (x + 5)] ^ src_ref[2 * (x + 10)] ^ src_ref[2 * (x + 15)] ^ src_ref[2 * (x + 20)]
            hi = src_ref[2 * x + 1] ^ src_ref[2 * (x + 5) + 1] ^ src_ref[2 * (x + 10) + 1] ^ src_ref[2 * (x + 15) + 1] ^ src_ref[2 * (x + 20) + 1]
            c.append((lo, hi))
        d = []
        for x in range(5):
            rl, rh = _rotl(*c[(x + 1) % 5], 1)
            d.append((c[(x - 1) % 5][0] ^ rl, c[(x - 1) % 5][1] ^ rh))
        # rho+pi+chi fused per output row: b[x_b + 5*y_b] = rotl(a[src] ^
        # d[x_src], RHO[src]) with src = x_src + 5*x_b, x_src = (3*y_b +
        # x_b) % 5 (inverse of the b-index map y + 5*((2x + 3y) % 5)); the
        # chi row needs only the 5 freshly built b lanes.
        for y_b in range(5):
            row = []
            for x_b in range(5):
                x_src = (3 * y_b + x_b) % 5
                src = x_src + 5 * x_b
                lo = src_ref[2 * src] ^ d[x_src][0]
                hi = src_ref[2 * src + 1] ^ d[x_src][1]
                row.append(_rotl(lo, hi, _RHO[src]))
            for x_b in range(5):
                lo = row[x_b][0] ^ (~row[(x_b + 1) % 5][0] & row[(x_b + 2) % 5][0])
                hi = row[x_b][1] ^ (~row[(x_b + 1) % 5][1] & row[(x_b + 2) % 5][1])
                if x_b == 0 and y_b == 0:
                    lo = lo ^ jnp.uint32(rc_lo)
                    hi = hi ^ jnp.uint32(rc_hi)
                dst_ref[2 * (5 * y_b + x_b)] = lo
                dst_ref[2 * (5 * y_b + x_b) + 1] = hi


# -- squeeze kernel: one absorbed block -> NB output blocks -----------------

def _squeeze_kernel(in_ref, out_ref, state_ref, tmp_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        for w in range(RATE_WORDS):
            state_ref[w] = in_ref[w]
        zero = jnp.zeros((8, 128), dtype=jnp.uint32)
        for w in range(RATE_WORDS, 100):
            state_ref[w] = zero

    _permute_pingpong(state_ref, tmp_ref)
    for w in range(RATE_WORDS):
        out_ref[0, w] = state_ref[w]


def _squeeze_call(planar: jnp.ndarray, nb: int, interpret: bool) -> jnp.ndarray:
    """(42, R, 128) padded single-block messages -> (nb, 42, R, 128) stream."""
    R = planar.shape[1]
    grid = (R // 8, nb)
    return pl.pallas_call(
        _squeeze_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((RATE_WORDS, 8, 128), lambda i, j: (0, i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec(
            (1, RATE_WORDS, 8, 128), lambda i, j: (j, 0, i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((nb, RATE_WORDS, R, 128), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((100, 8, 128), jnp.uint32),
            pltpu.VMEM((100, 8, 128), jnp.uint32),
        ],
        interpret=interpret,
    )(planar)


# -- absorb kernel: NA message blocks -> 42-word (one block) output ---------

def _absorb_kernel(in_ref, out_ref, state_ref, tmp_ref):
    j = pl.program_id(1)
    first = j == 0
    zero = jnp.zeros((8, 128), dtype=jnp.uint32)

    @pl.when(first)
    def _():
        for w in range(RATE_WORDS, 100):
            state_ref[w] = zero

    # xor the message block into the rate words (state is zero at j==0).
    for w in range(RATE_WORDS):
        prev = jnp.where(first, zero, state_ref[w])
        state_ref[w] = prev ^ in_ref[w]

    _permute_pingpong(state_ref, tmp_ref)

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        for w in range(RATE_WORDS):
            out_ref[w] = state_ref[w]


def _absorb_call(planar: jnp.ndarray, na: int, interpret: bool) -> jnp.ndarray:
    """(na*42, R, 128) padded message blocks -> (42, R, 128) first out block."""
    R = planar.shape[1]
    grid = (R // 8, na)
    return pl.pallas_call(
        _absorb_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((RATE_WORDS, 8, 128), lambda i, j: (j, i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec(
            (RATE_WORDS, 8, 128), lambda i, j: (0, i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((RATE_WORDS, R, 128), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((100, 8, 128), jnp.uint32),
            pltpu.VMEM((100, 8, 128), jnp.uint32),
        ],
        interpret=interpret,
    )(planar)


# -- host/XLA-side planar plumbing ------------------------------------------

def _to_planar(words: jnp.ndarray) -> jnp.ndarray:
    """(B, W) u32 -> (W, B//128, 128) word planes."""
    B, W = words.shape
    return words.reshape(B // 128, 128, W).transpose(2, 0, 1)


def _pad_words(msg_u8: jnp.ndarray, domain: int) -> jnp.ndarray:
    """(B, L) u8 message -> (B, nblocks*42) u32 padded stream words."""
    from .keccak_jax import bytes_to_words

    B, L = msg_u8.shape
    nblocks = L // RATE + 1
    pad_len = nblocks * RATE - L
    pad = np.zeros(pad_len, dtype=np.uint8)
    pad[0] = domain
    pad[-1] ^= 0x80
    padded = jnp.concatenate(
        [msg_u8, jnp.broadcast_to(jnp.asarray(pad), (B, pad_len))], axis=-1
    )
    return bytes_to_words(padded)


def xof_planes_pallas(
    seed: jnp.ndarray, dst: bytes, binder: jnp.ndarray, out_words: int
) -> jnp.ndarray:
    """Batched XofTurboShake128 -> PLANE-ordered stream words (W, B//128, 128).

    Same computation as xof_words_pallas for a single-block message, but the
    result stays in the kernels' native planar layout (plane w = stream word
    w of every report) — the limb-planar FLP pipeline consumes this directly,
    skipping the 100+ MB lane transpose that (B, W) row-major output costs.
    """
    interpret = _pallas_mode() == "interpret"
    prefix = np.frombuffer(bytes([len(dst)]) + dst, dtype=np.uint8)
    B = seed.shape[0]
    parts = [jnp.broadcast_to(jnp.asarray(prefix), (B, len(prefix))), seed]
    if binder.shape[-1]:
        parts.append(binder)
    msg = jnp.concatenate(parts, axis=-1)
    words = _pad_words(msg, 0x01)
    if words.shape[1] != RATE_WORDS:
        raise NotImplementedError("xof_planes_pallas requires a single-block message")
    nb = -(-out_words // RATE_WORDS)
    planes = _squeeze_call(_to_planar(words), nb, interpret)  # (nb, 42, R, 128)
    R = planes.shape[2]
    return planes.reshape(nb * RATE_WORDS, R, 128)[:out_words]


def absorb_planes_pallas(msg_planes: jnp.ndarray, out_words: int) -> jnp.ndarray:
    """Absorb a pre-built planar padded message -> (out_words, R, 128).

    msg_planes: (na*42, R, 128) plane-ordered padded message words (the
    caller applies TurboSHAKE padding).  Used by the joint-rand-part XOF,
    whose 16 KB-per-report binder is assembled by funnel-shifting the
    measurement-share planes instead of a byte-level concat + transpose.
    """
    interpret = _pallas_mode() == "interpret"
    if out_words > RATE_WORDS:
        raise NotImplementedError("multi-block squeeze after absorb")
    na = msg_planes.shape[0] // RATE_WORDS
    planes = _absorb_call(msg_planes, na, interpret)  # (42, R, 128)
    return planes[:out_words]


def planes_to_rows(planes: jnp.ndarray) -> jnp.ndarray:
    """(W, R, 128) planar words -> (B, W) row-major words (small W only)."""
    W, R, _ = planes.shape
    return planes.transpose(1, 2, 0).reshape(R * 128, W)


def rows_to_planes(words: jnp.ndarray) -> jnp.ndarray:
    """(B, W) row-major words -> (W, B//128, 128) planes (small W only)."""
    return _to_planar(words)


def xof_words_pallas(
    seed: jnp.ndarray, dst: bytes, binder: jnp.ndarray, out_words: int
) -> jnp.ndarray:
    """Batched XofTurboShake128 via the planar kernels -> (B, out_words) u32.

    Chooses the squeeze kernel (single-block message) or absorb kernel
    (multi-block message, out_words <= 42) based on static shapes; the caller
    must have checked pallas_enabled(B).
    """
    interpret = _pallas_mode() == "interpret"
    prefix = np.frombuffer(bytes([len(dst)]) + dst, dtype=np.uint8)
    B = seed.shape[0]
    parts = [jnp.broadcast_to(jnp.asarray(prefix), (B, len(prefix))), seed]
    if binder.shape[-1]:
        parts.append(binder)
    msg = jnp.concatenate(parts, axis=-1)
    words = _pad_words(msg, 0x01)
    nblocks = words.shape[1] // RATE_WORDS
    if nblocks == 1:
        nb = -(-out_words // RATE_WORDS)
        planes = _squeeze_call(_to_planar(words), nb, interpret)
        # (nb, 42, R, 128) -> (B, nb*42): batch-major stream words.
        R = planes.shape[2]
        stream = planes.transpose(2, 3, 0, 1).reshape(B, nb * RATE_WORDS)
        return stream[:, :out_words]
    if out_words > RATE_WORDS:
        raise NotImplementedError("multi-block absorb + multi-block squeeze")
    planes = _absorb_call(_to_planar(words), nblocks, interpret)
    stream = planes.transpose(1, 2, 0).reshape(B, RATE_WORDS)
    return stream[:, :out_words]
