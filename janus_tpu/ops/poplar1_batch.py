"""Batched Poplar1 preparation: host AES tree walk + device sketch math.

Poplar1's prepare cost splits into two very different halves:

* the IDPF tree walk — per (report, prefix) chains of fixed-key-AES
  extend/convert steps (draft-irtf-cfrg-vdaf-08 §8).  AES-128 belongs on
  the host (AES-NI runs at GB/s; a TPU VPU has no S-box and would emulate
  it at hundreds of ops per byte), but the ORACLE walks it one XOF object
  per tree node in Python.  This module walks the whole batch level-
  synchronously: one numpy pass for the xor/select logic per level and one
  cipher.update per (report, usage) covering every node at that level —
  thousands of Python-object round trips become a handful of bulk calls.
* the sketch arithmetic — z/zs inner products over the per-prefix values
  with the verify randomness, then the σ share.  Pure field math over a
  (B, prefixes) tensor: device territory, batched with JField limb ops
  (Field64 n=2 / Field255 n=8) exactly like the Prio3 pipeline.

Byte parity with the oracle (janus_tpu/vdaf/poplar1.py) is asserted in
tests/test_poplar1_batch.py; the backend seam exposes this as the device
path for Poplar1 (vdaf/backend.py Poplar1Backend), closing the
"heavy-hitters is CPU-only" gap (reference: core/src/vdaf.rs:96 —
Poplar1 is the reference's second production VDAF and runs the same
accelerated dispatch as Prio3).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..vdaf.idpf import KEY_SIZE, _dst
from ..vdaf.prio3 import VdafError
from ..xof import _fixed_key_aes128


def _ciphers_for(nonces: Sequence[bytes]):
    """Per-report ECB encryptors for the two IDPF usages (extend/convert).

    The fixed key depends on (dst, nonce) only — two key schedules per
    report for the WHOLE walk.  Encryptors resolve through the softaes
    seam: `cryptography` (AES-NI) when present, numpy soft-AES otherwise."""
    from ..utils.softaes import aes128_ecb_encryptor

    enc = []
    for nonce in nonces:
        pair = []
        for usage in (0, 1):
            key = _fixed_key_aes128(_dst(usage), nonce)
            pair.append(aes128_ecb_encryptor(key))
        enc.append(pair)
    return enc


def _hash_blocks(enc, blocks: np.ndarray) -> np.ndarray:
    """Davies-Meyer-style hash over (K, 16) u8 blocks with one AES call.

    hash(x) = AES(k, sigma(x)) ^ sigma(x),  sigma(xL||xR) = xR || (xL^xR).
    """
    sigma = np.concatenate([blocks[:, 8:], blocks[:, :8] ^ blocks[:, 8:]], axis=1)
    ct = np.frombuffer(enc.update(sigma.tobytes()), dtype=np.uint8).reshape(
        sigma.shape
    )
    return ct ^ sigma


def _xof_stream(enc, seeds: np.ndarray, nblocks: int) -> np.ndarray:
    """XofFixedKeyAes128 stream for (K, 16) seeds -> (K, nblocks*16) bytes.

    Block i hashes (seed ^ le128(i)); all K seeds for all indices go
    through ONE AES call."""
    K = seeds.shape[0]
    idx = np.zeros((nblocks, 16), dtype=np.uint8)
    for i in range(nblocks):
        idx[i, :8] = np.frombuffer(int(i).to_bytes(8, "little"), dtype=np.uint8)
    blocks = (seeds[:, None, :] ^ idx[None, :, :]).reshape(K * nblocks, 16)
    out = _hash_blocks(enc, blocks)
    return out.reshape(K, nblocks * 16)


class BatchedPoplar1:
    """Level-synchronous batched IDPF eval + device sketch for one Poplar1."""

    def __init__(self, poplar1):
        self.vdaf = poplar1
        self.idpf = poplar1.idpf
        self._jf: Dict[type, object] = {}

    def _jfield(self, field):
        jf = self._jf.get(field)
        if jf is None:
            from .field_jax import JField

            jf = JField(field)
            self._jf[field] = jf
        return jf

    # -- batched IDPF eval ------------------------------------------------
    def eval_batch(
        self,
        agg_id: int,
        public_shares: Sequence,  # per report: List[IdpfCorrectionWord]
        keys: Sequence[bytes],
        level: int,
        prefixes: Sequence[int],
        nonces: Sequence[bytes],
    ) -> np.ndarray:
        """Per-report, per-prefix value shares -> (B, P) Python-int array.

        Walks the prefix tree level-by-level over the whole batch: the
        node frontier at level l is the set of distinct l-bit ancestors of
        ``prefixes`` (shared-prefix memoization, same trick as the oracle's
        per-report memo, but across the batch)."""
        B = len(keys)
        P = len(prefixes)
        bits = self.idpf.BITS
        if not 0 <= level < bits:
            raise VdafError("level out of range")
        for p in prefixes:
            if p >> (level + 1):
                raise VdafError("prefix out of range for level")
        enc = _ciphers_for(nonces)

        # ancestor frontiers per level (shared across reports)
        frontier = [
            sorted({p >> (level - l) for p in prefixes}) for l in range(level + 1)
        ]
        ok = np.ones(B, dtype=bool)  # False: rejection-sampled value, redo on oracle
        # level-0 parents: the key itself
        parent_seed = {(-1, 0): np.frombuffer(b"".join(keys), dtype=np.uint8).reshape(B, 16)}
        parent_ctrl = {(-1, 0): np.full((B,), agg_id, dtype=np.uint8)}

        out_vals: Dict[int, List[int]] = {}
        for l in range(level + 1):
            field = self.idpf.field_at(l)
            elem = field.ENCODED_SIZE
            conv_blocks = -(-(KEY_SIZE + elem) // 16)
            # correction words at this level, per report
            seed_cw = np.stack(
                [
                    np.frombuffer(ps[l].seed_cw, dtype=np.uint8)
                    for ps in public_shares
                ]
            )  # (B, 16)
            ctrl_cw = np.array(
                [[ps[l].ctrl_cw[0], ps[l].ctrl_cw[1]] for ps in public_shares],
                dtype=np.uint8,
            )  # (B, 2)
            w_cw = [int(ps[l].w_cw[0]) for ps in public_shares]  # (B,) ints

            # distinct parent nodes feeding this level's frontier
            parents = sorted({node >> 1 for node in frontier[l]})
            # extend every parent for every report: gather parent seeds
            pseed = np.stack(
                [parent_seed[(l - 1, par)] for par in parents], axis=1
            )  # (B, NP, 16)
            pctrl = np.stack(
                [parent_ctrl[(l - 1, par)] for par in parents], axis=1
            )  # (B, NP)
            NP = len(parents)
            ext = np.empty((B, NP, 32), dtype=np.uint8)
            for b in range(B):
                ext[b] = _xof_stream(enc[b][0], pseed[b], 2)
            s = ext.reshape(B, NP, 2, 16).copy()  # [.., i, :] = seed_i
            t = (s[:, :, :, 0] & 1).astype(np.uint8)  # (B, NP, 2)
            s[:, :, :, 0] &= 0xFE
            # correction by parent ctrl
            applied = pctrl[:, :, None, None].astype(bool)
            s = np.where(applied, s ^ seed_cw[:, None, None, :], s)
            t = np.where(
                pctrl[:, :, None].astype(bool), t ^ ctrl_cw[:, None, :], t
            )

            # convert the kept child for each frontier node
            new_seed: Dict[Tuple[int, int], np.ndarray] = {}
            new_ctrl: Dict[Tuple[int, int], np.ndarray] = {}
            for node in frontier[l]:
                par = node >> 1
                pi = parents.index(par)
                bit = node & 1
                x = s[:, pi, bit, :]  # (B, 16)
                ctrl = t[:, pi, bit]  # (B,)
                conv = np.empty((B, conv_blocks * 16), dtype=np.uint8)
                for b in range(B):
                    conv[b] = _xof_stream(enc[b][1], x[b : b + 1], conv_blocks)[0]
                new_seed[(l, node)] = conv[:, :KEY_SIZE].copy()
                new_ctrl[(l, node)] = ctrl
                if l == level:
                    # value share: masked rejection sample (xof.next_vec);
                    # a rejected first candidate flags the report for the
                    # oracle (astronomically rare, but exact).
                    from ..fields import next_power_of_2

                    mask = next_power_of_2(field.MODULUS) - 1
                    raw = conv[:, KEY_SIZE : KEY_SIZE + elem]
                    vals = []
                    for b in range(B):
                        w = int.from_bytes(raw[b].tobytes(), "little") & mask
                        if w >= field.MODULUS:
                            ok[b] = False
                            w %= field.MODULUS  # placeholder; row redone
                        if ctrl[b]:
                            w = field.add(w, w_cw[b])
                        if agg_id == 1:
                            w = field.neg(w)
                        vals.append(w)
                    out_vals[node] = vals
            parent_seed = {**{(l, k[1]): v for k, v in new_seed.items()}}
            parent_ctrl = {**{(l, k[1]): v for k, v in new_ctrl.items()}}

        y = np.empty((B, P), dtype=object)
        for j, p in enumerate(prefixes):
            col = out_vals[p]
            for b in range(B):
                y[b, j] = col[b]
        return y, ok

    # -- batched sketch ---------------------------------------------------
    def sketch_batch(
        self,
        verify_key,  # bytes, or a per-report Sequence[bytes]
        agg_id: int,
        agg_param,
        nonces: Sequence[bytes],
        y: np.ndarray,  # (B, P) object ints
        abc: Sequence[Tuple[int, int, int]],
    ):
        """(z, zs) shares per report via one device launch.

        z = a + Σ r_i y_i ;  zs = b + Σ r_i² y_i — the (B, P) double inner
        product runs as JField limb math on the accelerator; the verify
        randomness r comes from the host TurboSHAKE oracle (tiny, per
        report).  ``verify_key`` may be per-REPORT (a sequence): the
        executor's poplar_init mega-batches carry rows from multiple tasks,
        each with its own key — exactly the per-row traced-verify-key trick
        the Prio3 mega-batches use.  Byte parity: exact mod-p identities.
        """
        import jax.numpy as jnp

        vdaf = self.vdaf
        field = vdaf.field_for_agg_param(agg_param)
        jf = self._jfield(field)
        B, P = y.shape
        vks = (
            verify_key
            if not isinstance(verify_key, (bytes, bytearray))
            else [verify_key] * B
        )
        rs = [
            vdaf._verify_rands(vk, nonce, agg_param)
            for vk, nonce in zip(vks, nonces)
        ]  # (B, P) ints
        y_l = jnp.asarray(
            jf.to_limbs([int(v) for row in y for v in row]).reshape(B, P, jf.n)
        )
        r_l = jnp.asarray(
            jf.to_limbs([int(v) for row in rs for v in row]).reshape(B, P, jf.n)
        )
        a_l = jnp.asarray(
            jf.to_limbs([int(a) for (a, _, _) in abc]).reshape(B, jf.n)
        )
        b_l = jnp.asarray(
            jf.to_limbs([int(b) for (_, b, _) in abc]).reshape(B, jf.n)
        )
        r_m = jf.to_mont(r_l)
        ry = jf.mont_mul(r_m, y_l)  # r_i * y_i canonical
        z = jf.add(a_l, jf.sum(ry, axis=1))
        rry = jf.mont_mul(r_m, ry)  # r_i^2 * y_i
        zs = jf.add(b_l, jf.sum(rry, axis=1))
        z_ints = jf.from_limbs(np.asarray(z))
        zs_ints = jf.from_limbs(np.asarray(zs))
        return list(zip(z_ints, zs_ints))

    # -- the full batched round-0 prep ------------------------------------
    def prep_init_batch(
        self,
        verify_key: bytes,
        agg_id: int,
        agg_param,
        reports: Sequence[Tuple[bytes, object, object]],
    ):
        """Batched Poplar1.prep_init over (nonce, public_share, input_share).

        Returns per-report (Poplar1PrepareState, Poplar1PrepareShare),
        byte-identical to the oracle's prep_init.
        """
        return self._prep_rows(
            [verify_key] * len(reports), agg_id, agg_param, reports
        )

    def _prep_rows(
        self,
        verify_keys: Sequence[bytes],
        agg_id: int,
        agg_param,
        reports: Sequence[Tuple[bytes, object, object]],
    ):
        """The per-row-verify-key core: ONE bulk-AES tree walk + ONE device
        sketch launch for rows that may span multiple tasks (each row uses
        its own verify key for the sketch randomness)."""
        from ..vdaf.poplar1 import (
            Poplar1PrepareShare,
            Poplar1PrepareState,
            _field_tag,
        )

        vdaf = self.vdaf
        level = agg_param.level
        prefixes = list(agg_param.prefixes)
        field = vdaf.field_for_agg_param(agg_param)
        nonces = [r[0] for r in reports]
        pubs = [r[1] for r in reports]
        keys = [r[2].idpf_key for r in reports]

        y, ok = self.eval_batch(agg_id, pubs, keys, level, prefixes, nonces)

        abc = []
        for nonce, _pub, share in reports:
            if share.corr_seed is not None:
                inner, leaf = vdaf._corr_triples(share.corr_seed, nonce, 1)
            else:
                inner, leaf = share.corr_inner, share.corr_leaf
            abc.append(leaf if level == vdaf.bits - 1 else inner[level])

        zzs = self.sketch_batch(verify_keys, agg_id, agg_param, nonces, y, abc)
        out = []
        for b, ((z, zs), (a, bb, c)) in enumerate(zip(zzs, abc)):
            if not ok[b]:
                # Exact-path fallback: first rejection-sampling candidate
                # for some tree value was non-canonical.
                out.append(
                    vdaf.prep_init(
                        verify_keys[b], agg_id, agg_param,
                        reports[b][0], reports[b][1], reports[b][2],
                    )
                )
                continue
            state = Poplar1PrepareState(
                agg_id=agg_id,
                level=level,
                round=0,
                y_flat=[int(v) for v in y[b]],
                a=a,
                b=bb,
                c=c,
                zs_share=zs,
            )
            out.append((state, Poplar1PrepareShare(_field_tag(field), [z, zs])))
        return out

    def prep_init_multi(
        self,
        agg_id: int,
        requests: Sequence[Tuple[bytes, object, Sequence[Tuple[bytes, object, object]]]],
    ):
        """ONE walk serving rows from MULTIPLE jobs/tasks: the executor's
        poplar_init mega-batch form.

        ``requests``: (verify_key, agg_param, reports) per submission.
        Submissions sharing an aggregation parameter — different jobs of
        one task at one tree level, the multi-round collection steady state
        — are concatenated into ONE bulk-AES tree walk + ONE device sketch
        launch with per-row verify keys.  Distinct parameters at the same
        level (different tasks, or different prefix sets) run one walk per
        parameter within the flush: the IDPF frontier and the sketch
        randomness binder are parameter-shaped, so merging them would
        change bytes.  Results return per request, byte-identical to
        separate prep_init_batch calls.
        """
        if not requests:
            return []
        groups: Dict[object, List[int]] = {}
        for i, (_vk, agg_param, _reports) in enumerate(requests):
            groups.setdefault(agg_param, []).append(i)
        results: List[Optional[list]] = [None] * len(requests)
        for agg_param, idxs in groups.items():
            vks: List[bytes] = []
            rows: List[Tuple[bytes, object, object]] = []
            for i in idxs:
                vk, _p, reports = requests[i]
                vks.extend([vk] * len(reports))
                rows.extend(reports)
            outs = self._prep_rows(vks, agg_id, agg_param, rows) if rows else []
            start = 0
            for i in idxs:
                n = len(requests[i][2])
                results[i] = outs[start : start + n]
                start += n
        return results
