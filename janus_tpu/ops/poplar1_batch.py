"""Batched Poplar1 preparation: host AES tree walk + device sketch math.

Poplar1's prepare cost splits into two very different halves:

* the IDPF tree walk — per (report, prefix) chains of fixed-key-AES
  extend/convert steps (draft-irtf-cfrg-vdaf-08 §8).  AES-128 belongs on
  the host (AES-NI runs at GB/s; a TPU VPU has no S-box and would emulate
  it at hundreds of ops per byte), but the ORACLE walks it one XOF object
  per tree node in Python.  This module walks the whole batch level-
  synchronously: one numpy pass for the xor/select logic per level and one
  cipher.update per (report, usage) covering every node at that level —
  thousands of Python-object round trips become a handful of bulk calls.
* the sketch arithmetic — z/zs inner products over the per-prefix values
  with the verify randomness, then the σ share.  Pure field math over a
  (B, prefixes) tensor: device territory, batched with JField limb ops
  (Field64 n=2 / Field255 n=8) exactly like the Prio3 pipeline.

Byte parity with the oracle (janus_tpu/vdaf/poplar1.py) is asserted in
tests/test_poplar1_batch.py; the backend seam exposes this as the device
path for Poplar1 (vdaf/backend.py Poplar1Backend), closing the
"heavy-hitters is CPU-only" gap (reference: core/src/vdaf.rs:96 —
Poplar1 is the reference's second production VDAF and runs the same
accelerated dispatch as Prio3).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..vdaf.idpf import KEY_SIZE, _dst
from ..vdaf.prio3 import VdafError
from ..xof import _fixed_key_aes128


def _ciphers_for(nonces: Sequence[bytes], backend: Optional[str] = None):
    """Per-report ECB encryptors for the two IDPF usages (extend/convert).

    The fixed key depends on (dst, nonce) only — two key schedules per
    report for the WHOLE walk.  ``backend`` is the ``poplar_backend:
    jax|host`` seam (None resolves the process default): host encryptors
    resolve through softaes (`cryptography`/AES-NI when present, numpy
    soft-AES otherwise); "jax" returns ONE :class:`_JaxWalkKeys` carrying
    the whole batch's round-key schedules for the jitted device kernel
    (ops/aes_jax.py) — the walk then runs every report in one launch per
    level instead of per-report ``update`` calls."""
    from ..utils.softaes import aes128_ecb_encryptor, poplar_backend

    if (backend or poplar_backend()) == "jax":
        try:
            return _JaxWalkKeys(nonces)
        except Exception:  # pragma: no cover - jax-less host
            import logging

            logging.getLogger("janus_tpu.poplar1_batch").warning(
                "poplar_backend=jax unavailable; walking on host", exc_info=True
            )
    enc = []
    for nonce in nonces:
        pair = []
        for usage in (0, 1):
            key = _fixed_key_aes128(_dst(usage), nonce)
            pair.append(aes128_ecb_encryptor(key, backend="host"))
        enc.append(pair)
    return enc


class _JaxWalkKeys:
    """The batch's AES round-key schedules for the device walk: (B, 11, 16)
    u8 per IDPF usage (0 = extend, 1 = convert).  Key derivation stays on
    host (one cached TurboSHAKE per (usage, nonce), tiny); only the bulk
    block cipher moves onto the device."""

    def __init__(self, nonces: Sequence[bytes]):
        from .aes_jax import expand_keys  # proves the jax kernel imports

        self.rk = [
            expand_keys([_fixed_key_aes128(_dst(usage), n) for n in nonces])
            for usage in (0, 1)
        ]


@dataclass
class _WalkResult:
    """One agg-param group's staged walk: the per-(report, prefix) value
    shares plus everything the sketch launch needs.  Under the jax walk
    the values stay DEVICE-RESIDENT limbs (``y_limbs``, (B, P, n) u32) —
    ``y_host`` is materialized lazily and counted as sketch readback."""

    ok: np.ndarray  # (B,) — False: rejection-sampled value, redo on oracle
    abc: List[Tuple[int, int, int]]
    field: type
    y_host: Optional[np.ndarray] = None  # (B, P) object ints (host walk)
    y_limbs: Optional[object] = None  # (B, P, n) u32 device array (jax walk)
    jf: Optional[object] = None


@dataclass
class _StagedPoplar:
    """A staged poplar mega-batch: per-agg-param groups with their walks
    done, awaiting the sketch launch (the executor's stage/launch seam —
    walk k+1 overlaps sketch k on the stage/launch threads)."""

    agg_id: int
    n_requests: int
    #: (agg_param, idxs, per-request row counts, vks, rows, _WalkResult|None)
    groups: List[tuple]


class _PoplarSketchPlane:
    """The accumulator store's minting-backend face for device-resident
    sketch vectors: per-(field, prefix-count) psum/readback launches over
    (B, P, n) u32 limb matrices, mirroring TpuBackend.accumulate_rows /
    read_accum_buffer for Prio3 out shares.  Level fencing is the bucket
    key's job (it carries the encoded agg param), so one plane instance
    serves every flush of its (field, P) shape."""

    def __init__(self, jf, field: type, prefixes_len: int):
        self.jf = jf
        #: drain-time field for the store (accumulator._evict / drain_all)
        self.accum_field = field
        self.prefixes_len = prefixes_len
        #: resident-byte accounting for the store's budget
        self.accum_buffer_nbytes = prefixes_len * jf.n * 4

    def accumulate_rows(self, buffer, matrix, mask):
        import jax.numpy as jnp

        m = jnp.asarray(matrix)  # host mirror after eviction device_puts back
        sel = jnp.where(jnp.asarray(mask)[:, None, None], m, jnp.zeros_like(m))
        delta = self.jf.sum(sel, axis=0)  # (P, n) canonical
        return delta if buffer is None else self.jf.add(buffer, delta)

    def read_accum_buffer(self, buffer) -> List[int]:
        return self.jf.from_limbs(np.asarray(buffer))


def _hash_blocks(enc, blocks: np.ndarray) -> np.ndarray:
    """Davies-Meyer-style hash over (K, 16) u8 blocks with one AES call.

    hash(x) = AES(k, sigma(x)) ^ sigma(x),  sigma(xL||xR) = xR || (xL^xR).
    """
    sigma = np.concatenate([blocks[:, 8:], blocks[:, :8] ^ blocks[:, 8:]], axis=1)
    ct = np.frombuffer(enc.update(sigma.tobytes()), dtype=np.uint8).reshape(
        sigma.shape
    )
    return ct ^ sigma


def _xof_stream(enc, seeds: np.ndarray, nblocks: int) -> np.ndarray:
    """XofFixedKeyAes128 stream for (K, 16) seeds -> (K, nblocks*16) bytes.

    Block i hashes (seed ^ le128(i)); all K seeds for all indices go
    through ONE AES call."""
    K = seeds.shape[0]
    idx = np.zeros((nblocks, 16), dtype=np.uint8)
    for i in range(nblocks):
        idx[i, :8] = np.frombuffer(int(i).to_bytes(8, "little"), dtype=np.uint8)
    blocks = (seeds[:, None, :] ^ idx[None, :, :]).reshape(K * nblocks, 16)
    out = _hash_blocks(enc, blocks)
    return out.reshape(K, nblocks * 16)


class BatchedPoplar1:
    """Level-synchronous batched IDPF eval + device sketch for one Poplar1.

    ``poplar_backend`` selects the AES-walk backend ("host" | "jax"; None
    resolves the process default from utils/softaes).  The jax walk keeps
    the per-level frontier (seeds + control bits) and the final value
    shares device-resident — the sketch consumes the (B, P, n) limb
    matrix in place, and with a retain store attached the prepare states
    carry ResidentRefs instead of host vectors (zero sketch readback)."""

    def __init__(self, poplar1, poplar_backend: Optional[str] = None):
        self.vdaf = poplar1
        self.idpf = poplar1.idpf
        self._jf: Dict[type, object] = {}
        self._planes: Dict[tuple, _PoplarSketchPlane] = {}
        self._poplar_backend = poplar_backend
        #: rows whose device-walked sketch vectors were materialized back
        #: to host (bench/acceptance counter: the device-resident path
        #: keeps this at 0 — states carry refs, drains read ONE vector)
        self.sketch_readback_rows = 0

    @property
    def walk_backend(self) -> str:
        from ..utils.softaes import poplar_backend

        return self._poplar_backend or poplar_backend()

    def _jfield(self, field):
        jf = self._jf.get(field)
        if jf is None:
            from .field_jax import JField

            jf = JField(field)
            self._jf[field] = jf
        return jf

    def _plane(self, field, prefixes_len: int) -> _PoplarSketchPlane:
        key = (field, prefixes_len)
        plane = self._planes.get(key)
        if plane is None:
            plane = _PoplarSketchPlane(self._jfield(field), field, prefixes_len)
            self._planes[key] = plane
        return plane

    # -- batched IDPF eval ------------------------------------------------
    def eval_batch(
        self,
        agg_id: int,
        public_shares: Sequence,  # per report: List[IdpfCorrectionWord]
        keys: Sequence[bytes],
        level: int,
        prefixes: Sequence[int],
        nonces: Sequence[bytes],
    ) -> np.ndarray:
        """Per-report, per-prefix value shares -> (B, P) Python-int array.

        Walks the prefix tree level-by-level over the whole batch: the
        node frontier at level l is the set of distinct l-bit ancestors of
        ``prefixes`` (shared-prefix memoization, same trick as the oracle's
        per-report memo, but across the batch)."""
        enc = _ciphers_for(nonces, backend=self.walk_backend)
        if isinstance(enc, _JaxWalkKeys):
            y_limbs, ok, jf = self._eval_batch_dev(
                agg_id, public_shares, keys, level, prefixes, enc
            )
            return self._materialize_y(y_limbs, jf), ok
        return self._eval_batch_host(
            agg_id, public_shares, keys, level, prefixes, nonces, enc
        )

    def _eval_batch_host(
        self, agg_id, public_shares, keys, level, prefixes, nonces, enc
    ):
        """The numpy/host-AES walk (the original eval_batch body)."""
        B = len(keys)
        P = len(prefixes)
        bits = self.idpf.BITS
        if not 0 <= level < bits:
            raise VdafError("level out of range")
        for p in prefixes:
            if p >> (level + 1):
                raise VdafError("prefix out of range for level")

        # ancestor frontiers per level (shared across reports)
        frontier = [
            sorted({p >> (level - l) for p in prefixes}) for l in range(level + 1)
        ]
        ok = np.ones(B, dtype=bool)  # False: rejection-sampled value, redo on oracle
        # level-0 parents: the key itself
        parent_seed = {(-1, 0): np.frombuffer(b"".join(keys), dtype=np.uint8).reshape(B, 16)}
        parent_ctrl = {(-1, 0): np.full((B,), agg_id, dtype=np.uint8)}

        out_vals: Dict[int, List[int]] = {}
        for l in range(level + 1):
            field = self.idpf.field_at(l)
            elem = field.ENCODED_SIZE
            conv_blocks = -(-(KEY_SIZE + elem) // 16)
            # correction words at this level, per report
            seed_cw = np.stack(
                [
                    np.frombuffer(ps[l].seed_cw, dtype=np.uint8)
                    for ps in public_shares
                ]
            )  # (B, 16)
            ctrl_cw = np.array(
                [[ps[l].ctrl_cw[0], ps[l].ctrl_cw[1]] for ps in public_shares],
                dtype=np.uint8,
            )  # (B, 2)
            w_cw = [int(ps[l].w_cw[0]) for ps in public_shares]  # (B,) ints

            # distinct parent nodes feeding this level's frontier
            parents = sorted({node >> 1 for node in frontier[l]})
            # extend every parent for every report: gather parent seeds
            pseed = np.stack(
                [parent_seed[(l - 1, par)] for par in parents], axis=1
            )  # (B, NP, 16)
            pctrl = np.stack(
                [parent_ctrl[(l - 1, par)] for par in parents], axis=1
            )  # (B, NP)
            NP = len(parents)
            ext = np.empty((B, NP, 32), dtype=np.uint8)
            for b in range(B):
                ext[b] = _xof_stream(enc[b][0], pseed[b], 2)
            s = ext.reshape(B, NP, 2, 16).copy()  # [.., i, :] = seed_i
            t = (s[:, :, :, 0] & 1).astype(np.uint8)  # (B, NP, 2)
            s[:, :, :, 0] &= 0xFE
            # correction by parent ctrl
            applied = pctrl[:, :, None, None].astype(bool)
            s = np.where(applied, s ^ seed_cw[:, None, None, :], s)
            t = np.where(
                pctrl[:, :, None].astype(bool), t ^ ctrl_cw[:, None, :], t
            )

            # convert the kept child for each frontier node
            new_seed: Dict[Tuple[int, int], np.ndarray] = {}
            new_ctrl: Dict[Tuple[int, int], np.ndarray] = {}
            for node in frontier[l]:
                par = node >> 1
                pi = parents.index(par)
                bit = node & 1
                x = s[:, pi, bit, :]  # (B, 16)
                ctrl = t[:, pi, bit]  # (B,)
                conv = np.empty((B, conv_blocks * 16), dtype=np.uint8)
                for b in range(B):
                    conv[b] = _xof_stream(enc[b][1], x[b : b + 1], conv_blocks)[0]
                new_seed[(l, node)] = conv[:, :KEY_SIZE].copy()
                new_ctrl[(l, node)] = ctrl
                if l == level:
                    # value share: masked rejection sample (xof.next_vec);
                    # a rejected first candidate flags the report for the
                    # oracle (astronomically rare, but exact).
                    from ..fields import next_power_of_2

                    mask = next_power_of_2(field.MODULUS) - 1
                    raw = conv[:, KEY_SIZE : KEY_SIZE + elem]
                    vals = []
                    for b in range(B):
                        w = int.from_bytes(raw[b].tobytes(), "little") & mask
                        if w >= field.MODULUS:
                            ok[b] = False
                            w %= field.MODULUS  # placeholder; row redone
                        if ctrl[b]:
                            w = field.add(w, w_cw[b])
                        if agg_id == 1:
                            w = field.neg(w)
                        vals.append(w)
                    out_vals[node] = vals
            parent_seed = {**{(l, k[1]): v for k, v in new_seed.items()}}
            parent_ctrl = {**{(l, k[1]): v for k, v in new_ctrl.items()}}

        y = np.empty((B, P), dtype=object)
        for j, p in enumerate(prefixes):
            col = out_vals[p]
            for b in range(B):
                y[b, j] = col[b]
        return y, ok

    def _materialize_y(self, y_limbs, jf) -> np.ndarray:
        """Read a device-walked (B, P, n) limb matrix back to host ints —
        the readback the resident path exists to avoid; counted so the
        bench row can assert 0 on the device-resident path."""
        B, P = int(y_limbs.shape[0]), int(y_limbs.shape[1])
        ints = jf.from_limbs(np.asarray(y_limbs))
        y = np.empty((B, P), dtype=object)
        for b in range(B):
            for j in range(P):
                y[b, j] = ints[b * P + j]
        self.sketch_readback_rows += B
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.poplar_sketch_readback_rows.inc(B)
        return y

    # -- device-resident IDPF walk (poplar_backend: jax) -------------------
    def _eval_batch_dev(
        self,
        agg_id: int,
        public_shares: Sequence,
        keys: Sequence[bytes],
        level: int,
        prefixes: Sequence[int],
        walk_keys: "_JaxWalkKeys",
    ):
        """The jax twin of :meth:`eval_batch`: same level-synchronous walk,
        but the frontier seeds/controls live as device arrays across
        levels and the final values come out as a (B, P, n) u32 canonical
        limb matrix — the sketch (and the resident store) consume it in
        place.  Bit-exact with the host walk: identical AES stream,
        identical rejection-sample masking (a rejected first candidate
        flags the row for the oracle), identical correction-word and sign
        handling.  Returns (y_limbs, ok, jf)."""
        import jax.numpy as jnp

        from ..fields import next_power_of_2
        from .aes_jax import encrypt_blocks_multikey_padded
        from .field_jax import _sbb, _u32

        B = len(keys)
        bits = self.idpf.BITS
        if not 0 <= level < bits:
            raise VdafError("level out of range")
        for p in prefixes:
            if p >> (level + 1):
                raise VdafError("prefix out of range for level")
        frontier = [
            sorted({p >> (level - l) for p in prefixes}) for l in range(level + 1)
        ]

        def xof_blocks(rks, seeds, nblocks: int):
            """XofFixedKeyAes128 stream for (B, K, 16) seeds -> hashed
            (B, K, nblocks, 16): block i = hash(seed ^ le128(i)), the
            whole frontier in ONE padded multikey AES launch."""
            idx = np.zeros((nblocks, 16), dtype=np.uint8)
            for i in range(nblocks):
                idx[i, :8] = np.frombuffer(
                    int(i).to_bytes(8, "little"), dtype=np.uint8
                )
            blocks = seeds[:, :, None, :] ^ jnp.asarray(idx)[None, None, :, :]
            k = blocks.shape[1]
            blocks = blocks.reshape(B, k * nblocks, 16)
            sigma = jnp.concatenate(
                [blocks[..., 8:], blocks[..., :8] ^ blocks[..., 8:]], axis=-1
            )
            out = encrypt_blocks_multikey_padded(rks, sigma) ^ sigma
            return out.reshape(B, k, nblocks, 16)

        def cond_sub_p(jf, w):
            """(w mod p, w >= p) for masked w < 2^(32 n) < 2 p."""
            limbs = [w[..., i] for i in range(jf.n)]
            pl = [_u32(int(x)) for x in jf.p_np]
            borrow = _u32(0)
            d = []
            for i in range(jf.n):
                di, borrow = _sbb(limbs[i], pl[i], borrow)
                d.append(di)
            geq = borrow == 0
            out = jnp.stack(
                [jnp.where(geq, d[i], limbs[i]) for i in range(jf.n)], axis=-1
            )
            return out, geq

        parent_seed = {
            (-1, 0): jnp.asarray(
                np.frombuffer(b"".join(keys), dtype=np.uint8).reshape(B, 16)
            )
        }
        parent_ctrl = {(-1, 0): jnp.full((B,), agg_id, dtype=jnp.uint8)}
        y_limbs = ok_dev = jf = None
        for l in range(level + 1):
            field = self.idpf.field_at(l)
            elem = field.ENCODED_SIZE
            conv_blocks = -(-(KEY_SIZE + elem) // 16)
            seed_cw = jnp.asarray(
                np.stack(
                    [np.frombuffer(ps[l].seed_cw, dtype=np.uint8) for ps in public_shares]
                )
            )  # (B, 16)
            ctrl_cw = jnp.asarray(
                np.array(
                    [[ps[l].ctrl_cw[0], ps[l].ctrl_cw[1]] for ps in public_shares],
                    dtype=np.uint8,
                )
            )  # (B, 2)
            w_cw = [int(ps[l].w_cw[0]) for ps in public_shares]

            parents = sorted({node >> 1 for node in frontier[l]})
            pseed = jnp.stack(
                [parent_seed[(l - 1, par)] for par in parents], axis=1
            )  # (B, NP, 16)
            pctrl = jnp.stack(
                [parent_ctrl[(l - 1, par)] for par in parents], axis=1
            )  # (B, NP)
            ext = xof_blocks(walk_keys.rk[0], pseed, 2)  # (B, NP, 2, 16)
            t = ext[..., 0] & 1  # (B, NP, 2)
            s = ext.at[..., 0].set(ext[..., 0] & 0xFE)
            applied = pctrl.astype(bool)[:, :, None, None]
            s = jnp.where(applied, s ^ seed_cw[:, None, None, :], s)
            t = jnp.where(pctrl.astype(bool)[:, :, None], t ^ ctrl_cw[:, None, :], t)

            nodes = frontier[l]
            pi = np.array([parents.index(n >> 1) for n in nodes])
            bit = np.array([n & 1 for n in nodes])
            x = s[:, pi, bit, :]  # (B, NF, 16)
            ctrl = t[:, pi, bit]  # (B, NF)
            conv = xof_blocks(walk_keys.rk[1], x, conv_blocks).reshape(
                B, len(nodes), conv_blocks * 16
            )
            parent_seed = {
                (l, node): conv[:, i, :KEY_SIZE] for i, node in enumerate(nodes)
            }
            parent_ctrl = {(l, node): ctrl[:, i] for i, node in enumerate(nodes)}
            if l == level:
                jf = self._jfield(field)
                raw = conv[:, :, KEY_SIZE : KEY_SIZE + elem]  # (B, NF, elem)
                r = raw.astype(jnp.uint32).reshape(B, len(nodes), jf.n, 4)
                limbs = (
                    r[..., 0]
                    | (r[..., 1] << 8)
                    | (r[..., 2] << 16)
                    | (r[..., 3] << 24)
                )
                mask = next_power_of_2(field.MODULUS) - 1
                mask_l = jnp.asarray(
                    np.array(
                        [(mask >> (32 * i)) & 0xFFFFFFFF for i in range(jf.n)],
                        dtype=np.uint32,
                    )
                )
                limbs = limbs & mask_l
                w, geq = cond_sub_p(jf, limbs)
                corrected = jf.add(w, jnp.asarray(jf.to_limbs(w_cw))[:, None, :])
                w = jnp.where(ctrl.astype(bool)[..., None], corrected, w)
                if agg_id == 1:
                    w = jf.neg(w)
                colmap = {node: i for i, node in enumerate(nodes)}
                sel = np.array([colmap[p] for p in prefixes])
                y_limbs = w[:, sel, :]
                ok_dev = ~jnp.any(geq, axis=1)
        return y_limbs, np.asarray(ok_dev).copy(), jf

    # -- batched sketch ---------------------------------------------------
    def sketch_batch(
        self,
        verify_key,  # bytes, or a per-report Sequence[bytes]
        agg_id: int,
        agg_param,
        nonces: Sequence[bytes],
        y: np.ndarray,  # (B, P) object ints; or None with y_limbs
        abc: Sequence[Tuple[int, int, int]],
        y_limbs=None,  # (B, P, n) u32 device limbs (jax walk): consumed
        # in place — the y vectors never leave the device
    ):
        """(z, zs) shares per report via one device launch.

        z = a + Σ r_i y_i ;  zs = b + Σ r_i² y_i — the (B, P) double inner
        product runs as JField limb math on the accelerator; the verify
        randomness r comes from the host TurboSHAKE oracle (tiny, per
        report).  ``verify_key`` may be per-REPORT (a sequence): the
        executor's poplar_init mega-batches carry rows from multiple tasks,
        each with its own key — exactly the per-row traced-verify-key trick
        the Prio3 mega-batches use.  Byte parity: exact mod-p identities.
        """
        import jax.numpy as jnp

        vdaf = self.vdaf
        field = vdaf.field_for_agg_param(agg_param)
        jf = self._jfield(field)
        B, P = (y.shape if y is not None else y_limbs.shape[:2])
        vks = (
            verify_key
            if not isinstance(verify_key, (bytes, bytearray))
            else [verify_key] * B
        )
        rs = [
            vdaf._verify_rands(vk, nonce, agg_param)
            for vk, nonce in zip(vks, nonces)
        ]  # (B, P) ints
        y_l = (
            jnp.asarray(y_limbs)
            if y_limbs is not None
            else jnp.asarray(
                jf.to_limbs([int(v) for row in y for v in row]).reshape(B, P, jf.n)
            )
        )
        r_l = jnp.asarray(
            jf.to_limbs([int(v) for row in rs for v in row]).reshape(B, P, jf.n)
        )
        a_l = jnp.asarray(
            jf.to_limbs([int(a) for (a, _, _) in abc]).reshape(B, jf.n)
        )
        b_l = jnp.asarray(
            jf.to_limbs([int(b) for (_, b, _) in abc]).reshape(B, jf.n)
        )
        r_m = jf.to_mont(r_l)
        ry = jf.mont_mul(r_m, y_l)  # r_i * y_i canonical
        z = jf.add(a_l, jf.sum(ry, axis=1))
        rry = jf.mont_mul(r_m, ry)  # r_i^2 * y_i
        zs = jf.add(b_l, jf.sum(rry, axis=1))
        z_ints = jf.from_limbs(np.asarray(z))
        zs_ints = jf.from_limbs(np.asarray(zs))
        return list(zip(z_ints, zs_ints))

    # -- the full batched round-0 prep ------------------------------------
    def prep_init_batch(
        self,
        verify_key: bytes,
        agg_id: int,
        agg_param,
        reports: Sequence[Tuple[bytes, object, object]],
    ):
        """Batched Poplar1.prep_init over (nonce, public_share, input_share).

        Returns per-report (Poplar1PrepareState, Poplar1PrepareShare),
        byte-identical to the oracle's prep_init.
        """
        return self._prep_rows(
            [verify_key] * len(reports), agg_id, agg_param, reports
        )

    def _walk_rows(self, agg_id: int, agg_param, reports) -> _WalkResult:
        """The WALK half: the bulk-AES IDPF eval (host or jax per the
        ``poplar_backend`` seam) plus the host correlated-randomness
        triples — everything the sketch launch half consumes.  Under the
        jax backend the value shares come back as device-resident limbs."""
        vdaf = self.vdaf
        level = agg_param.level
        prefixes = list(agg_param.prefixes)
        nonces = [r[0] for r in reports]
        pubs = [r[1] for r in reports]
        keys = [r[2].idpf_key for r in reports]
        field = vdaf.field_for_agg_param(agg_param)

        abc = []
        for nonce, _pub, share in reports:
            if share.corr_seed is not None:
                inner, leaf = vdaf._corr_triples(share.corr_seed, nonce, 1)
            else:
                inner, leaf = share.corr_inner, share.corr_leaf
            abc.append(leaf if level == vdaf.bits - 1 else inner[level])

        enc = _ciphers_for(nonces, backend=self.walk_backend)
        from ..core.metrics import GLOBAL_METRICS

        if isinstance(enc, _JaxWalkKeys):
            if GLOBAL_METRICS.registry is not None:
                GLOBAL_METRICS.poplar_walk_rows.labels(backend="jax").inc(
                    len(reports)
                )
            y_limbs, ok, jf = self._eval_batch_dev(
                agg_id, pubs, keys, level, prefixes, enc
            )
            return _WalkResult(ok=ok, abc=abc, field=field, y_limbs=y_limbs, jf=jf)
        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.poplar_walk_rows.labels(backend="host").inc(len(reports))
        y, ok = self._eval_batch_host(
            agg_id, pubs, keys, level, prefixes, nonces, enc
        )
        return _WalkResult(ok=ok, abc=abc, field=field, y_host=y)

    def _sketch_rows(
        self,
        agg_id: int,
        agg_param,
        verify_keys: Sequence[bytes],
        reports,
        walk: _WalkResult,
        retain_store=None,
    ):
        """The SKETCH half: one device launch for the (z, z*) inner
        products over the staged walk, then per-row state assembly.  With
        ``retain_store`` attached and a device-walked group, the (B, P, n)
        value matrix is adopted by the store and the prepare states carry
        :class:`~janus_tpu.executor.accumulator.ResidentRef` rows instead
        of host vectors — the sketch y values never leave the device (the
        commit psums rows in place; the drain reads ONE vector per
        bucket)."""
        from ..vdaf.poplar1 import (
            Poplar1PrepareShare,
            Poplar1PrepareState,
            _field_tag,
        )

        vdaf = self.vdaf
        level = agg_param.level
        P = len(agg_param.prefixes)
        nonces = [r[0] for r in reports]
        B = len(reports)
        field = walk.field
        zzs = self.sketch_batch(
            verify_keys, agg_id, agg_param, nonces, walk.y_host, walk.abc,
            y_limbs=walk.y_limbs,
        )
        fid = None
        if retain_store is not None and walk.y_limbs is not None:
            plane = self._plane(field, P)
            fid = retain_store.retain_flush(
                plane, walk.y_limbs, rows=B, nbytes=B * plane.accum_buffer_nbytes
            )
        y_host = walk.y_host
        if fid is None and y_host is None:
            y_host = self._materialize_y(walk.y_limbs, walk.jf)
        if fid is not None:
            from ..executor.accumulator import ResidentRef
        out = []
        dead = []
        try:
            for b, ((z, zs), (a, bb, c)) in enumerate(zip(zzs, walk.abc)):
                if not walk.ok[b]:
                    # Exact-path fallback: first rejection-sampling
                    # candidate for some tree value was non-canonical.
                    # Its retained row is never referenced — release it
                    # so the matrix can free.
                    if fid is not None:
                        dead.append(ResidentRef(fid, b))
                    out.append(
                        vdaf.prep_init(
                            verify_keys[b], agg_id, agg_param,
                            reports[b][0], reports[b][1], reports[b][2],
                        )
                    )
                    continue
                y_val = (
                    ResidentRef(fid, b)
                    if fid is not None
                    else [int(v) for v in y_host[b]]
                )
                state = Poplar1PrepareState(
                    agg_id=agg_id,
                    level=level,
                    round=0,
                    y_flat=y_val,
                    a=a,
                    b=bb,
                    c=c,
                    zs_share=zs,
                )
                out.append(
                    (state, Poplar1PrepareShare(_field_tag(field), [z, zs]))
                )
        except BaseException:
            # a post-retain failure (e.g. the oracle fallback raising)
            # must not pin the whole retained matrix: no caller ever saw
            # these refs, so release every row before surfacing
            if fid is not None:
                retain_store.release_refs(
                    [ResidentRef(fid, b) for b in range(B)]
                )
            raise
        if dead:
            retain_store.release_refs(dead)
        return out

    def _prep_rows(
        self,
        verify_keys: Sequence[bytes],
        agg_id: int,
        agg_param,
        reports: Sequence[Tuple[bytes, object, object]],
        retain_store=None,
    ):
        """The per-row-verify-key core: ONE bulk-AES tree walk + ONE device
        sketch launch for rows that may span multiple tasks (each row uses
        its own verify key for the sketch randomness)."""
        walk = self._walk_rows(agg_id, agg_param, reports)
        return self._sketch_rows(
            agg_id, agg_param, verify_keys, reports, walk, retain_store=retain_store
        )

    def stage_init_multi(self, agg_id: int, requests) -> _StagedPoplar:
        """The WALK half of :meth:`prep_init_multi`: group the flush's
        submissions by aggregation parameter and run each group's bulk-AES
        tree walk, leaving the value shares staged (device-resident under
        the jax backend) for the sketch launch.  The executor runs this on
        its STAGING thread so walk k+1 overlaps sketch launch k — the
        Prio3 marshal/launch double-buffering, applied to heavy hitters."""
        groups_idx: Dict[object, List[int]] = {}
        for i, (_vk, agg_param, _reports) in enumerate(requests):
            groups_idx.setdefault(agg_param, []).append(i)
        groups = []
        for agg_param, idxs in groups_idx.items():
            vks: List[bytes] = []
            rows: List[Tuple[bytes, object, object]] = []
            counts: List[int] = []
            for i in idxs:
                vk, _p, reports = requests[i]
                vks.extend([vk] * len(reports))
                rows.extend(reports)
                counts.append(len(reports))
            walk = self._walk_rows(agg_id, agg_param, rows) if rows else None
            groups.append((agg_param, idxs, counts, vks, rows, walk))
        return _StagedPoplar(agg_id, len(requests), groups)

    def launch_init_multi(self, staged: _StagedPoplar, retain_store=None):
        """The SKETCH half: per-group device sketch launches + per-row
        state assembly over an already-staged walk.  Results return per
        request, byte-identical to separate prep_init_batch calls.  A
        later group's failure releases every EARLIER group's retained
        rows (their refs were never handed to any caller, so nothing
        else would ever free those matrices) before re-raising — the
        flush then fails uniformly and redelivery re-mints."""
        results: List[Optional[list]] = [None] * staged.n_requests
        try:
            for agg_param, idxs, counts, vks, rows, walk in staged.groups:
                outs = (
                    self._sketch_rows(
                        staged.agg_id, agg_param, vks, rows, walk,
                        retain_store=retain_store,
                    )
                    if rows
                    else []
                )
                start = 0
                for i, n in zip(idxs, counts):
                    results[i] = outs[start : start + n]
                    start += n
        except BaseException:
            if retain_store is not None:
                from ..executor.accumulator import ResidentRef

                refs = [
                    st.y_flat
                    for outs in results
                    if outs
                    for st, _sh in (o for o in outs if isinstance(o, tuple))
                    if isinstance(st.y_flat, ResidentRef)
                ]
                if refs:
                    retain_store.release_refs(refs)
            raise
        return results

    def prep_init_multi(
        self,
        agg_id: int,
        requests: Sequence[Tuple[bytes, object, Sequence[Tuple[bytes, object, object]]]],
        retain_store=None,
    ):
        """ONE walk serving rows from MULTIPLE jobs/tasks: the executor's
        poplar_init mega-batch form.

        ``requests``: (verify_key, agg_param, reports) per submission.
        Submissions sharing an aggregation parameter — different jobs of
        one task at one tree level, the multi-round collection steady state
        — are concatenated into ONE bulk-AES tree walk + ONE device sketch
        launch with per-row verify keys.  Distinct parameters at the same
        level (different tasks, or different prefix sets) run one walk per
        parameter within the flush: the IDPF frontier and the sketch
        randomness binder are parameter-shaped, so merging them would
        change bytes.  Results return per request, byte-identical to
        separate prep_init_batch calls.
        """
        if not requests:
            return []
        return self.launch_init_multi(
            self.stage_init_multi(agg_id, requests), retain_store=retain_store
        )
