"""JAX/TPU kernels: u32-limb field arithmetic, vmapped Keccak, batched prepare.

These are the TPU-native re-expression of the reference's CPU-bound VDAF hot
loop (reference: aggregator/src/aggregator/aggregation_job_driver.rs:449,
aggregator/src/aggregator.rs:2101 — per-report serial loops on a rayon pool).
Every kernel must agree bit-for-bit with the oracle in janus_tpu.{fields,xof,
flp,vdaf}; tests enforce byte equality.

TPU notes: there is no native 64-bit integer path on TPU, so field elements are
little-endian u32 limb vectors (2 limbs for Field64, 4 for Field128) and
multiplication uses 16-bit half-limb products that fit exactly in u32
multiplies.  Field multiplication is Montgomery (CIOS); values are kept in
Montgomery form between boundary conversions.  All shapes are static per VDAF
configuration; batching over reports is jax.vmap-style broadcasting over the
leading axis.
"""
